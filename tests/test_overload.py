"""Overload control plane (docs/OVERLOAD.md): per-tenant weighted-fair
admission (serve/admission.py), adaptive brownout with hysteresis
(resilience/brownout.py), per-plan-class circuit breakers
(resilience/breaker.py), the MV112 verifier pass, the overload obs
roll-up — and the off-by-default contracts: no tenants + brownout off
+ breakers off must construct ZERO controller/breaker objects and keep
admission bit-identical FIFO."""

import queue
import threading
import time

import numpy as np
import pytest

from matrel_tpu.config import MatrelConfig, parse_tenant_weights
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.resilience import breaker as breaker_lib
from matrel_tpu.resilience import brownout as brownout_lib
from matrel_tpu.resilience import errors as rerrors
from matrel_tpu.resilience.breaker import BreakerRegistry, CircuitBreaker
from matrel_tpu.resilience.brownout import LoadController
from matrel_tpu.resilience.retry import Deadline
from matrel_tpu.serve.admission import AdmissionQueue
from matrel_tpu.session import MatrelSession


def _mat(rng, n, m, mesh):
    return BlockMatrix.from_numpy(
        rng.standard_normal((n, m)).astype(np.float32), mesh=mesh)


def _sess(mesh, **cfg):
    return MatrelSession(mesh=mesh, config=MatrelConfig(**cfg))


def _entry(expr=None, fut=None, deadline=None, sla="default",
           tenant="", staleness=None):
    from concurrent.futures import Future
    return (expr, fut if fut is not None else Future(),
            time.perf_counter(), sla, deadline, tenant, staleness)


#: Aggressive-but-valid brownout knobs for controller unit tests.
BROWNOUT = dict(brownout_enable=True, brownout_window=8,
                brownout_dwell=2, brownout_wait_high_ms=100.0,
                brownout_wait_low_ms=10.0, brownout_depth_high=10,
                brownout_depth_low=2, brownout_miss_high=0.5,
                brownout_miss_low=0.05)


class _StubController:
    """A brownout controller pinned at one rung — rung-action tests
    must not depend on driving real load through thresholds."""

    def __init__(self, rung):
        self._rung = rung
        self.samples = []

    def rung(self):
        return self._rung

    def observe(self, depth, waits_ms=(), misses=0, admitted=0):
        self.samples.append((depth, tuple(waits_ms), misses, admitted))
        return self._rung

    def snapshot(self):
        return {"rung": self._rung, "max_rung_seen": self._rung,
                "entered": 0, "exited": 0}


# ---------------------------------------------------------------------------
# config validation


class TestConfigValidation:
    def test_tenant_weights_parse(self):
        assert parse_tenant_weights("") == {}
        assert parse_tenant_weights("gold:4,silver:2,bronze:1") == {
            "gold": 4.0, "silver": 2.0, "bronze": 1.0}
        assert parse_tenant_weights(" a : 1.5 ") == {"a": 1.5}

    @pytest.mark.parametrize("bad", [
        "bad", "a:", ":2", "a:0", "a:-1", "a:x", "a:1,a:2", ","])
    def test_tenant_weights_reject_malformed(self, bad):
        with pytest.raises(ValueError):
            parse_tenant_weights(bad)

    def test_config_validates_tenant_weights_at_construction(self):
        with pytest.raises(ValueError):
            MatrelConfig(serve_tenant_weights="a:0")
        with pytest.raises(ValueError):
            MatrelConfig(serve_tenant_queue_max=-1)

    @pytest.mark.parametrize("kw", [
        dict(brownout_wait_low_ms=300.0),      # low >= high
        dict(brownout_depth_low=64, brownout_depth_high=64),
        dict(brownout_miss_low=0.3, brownout_miss_high=0.2),
        dict(brownout_miss_high=1.5),
        dict(brownout_window=0),
        dict(brownout_dwell=0),
    ])
    def test_brownout_thresholds_validated(self, kw):
        with pytest.raises(ValueError):
            MatrelConfig(**kw)

    @pytest.mark.parametrize("kw", [
        dict(breaker_threshold=-1),
        dict(breaker_cooldown_ms=0.0),
        dict(breaker_half_open_probes=0),
    ])
    def test_breaker_knobs_validated(self, kw):
        with pytest.raises(ValueError):
            MatrelConfig(**kw)


# ---------------------------------------------------------------------------
# weighted-fair admission queue


class TestAdmissionQueue:
    def test_implicit_tenant_is_fifo(self):
        q = AdmissionQueue(MatrelConfig())
        for i in range(6):
            q.put(_entry(expr=i))
        got = [q.get_nowait()[0] for i in range(6)]
        assert got == [0, 1, 2, 3, 4, 5]   # bit-identical FIFO order

    def test_weighted_fair_pop_is_proportional(self):
        q = AdmissionQueue(MatrelConfig(
            serve_tenant_weights="a:3,b:1"))
        for i in range(12):
            q.put(_entry(expr=("a", i), tenant="a"), "a")
            q.put(_entry(expr=("b", i), tenant="b"), "b")
        # over any backlogged window of 8 pops, service is 6:2
        first8 = [q.get_nowait()[0][0] for _ in range(8)]
        assert first8.count("a") == 6
        assert first8.count("b") == 2

    def test_fair_batch_formation(self):
        # the worker's coalescing loop is just repeated pops: a batch
        # of 4 over a backlog cannot be monopolized by the chatty
        # tenant (weights 3:1 -> 3 a's + 1 b per 4)
        q = AdmissionQueue(MatrelConfig(
            serve_tenant_weights="a:3,b:1"))
        for i in range(20):
            q.put(_entry(expr=("a", i), tenant="a"), "a")
        for i in range(20):
            q.put(_entry(expr=("b", i), tenant="b"), "b")
        batch = [q.get_nowait()[0][0] for _ in range(4)]
        assert batch.count("a") == 3 and batch.count("b") == 1

    def test_tenant_order_within_tenant_is_fifo(self):
        q = AdmissionQueue(MatrelConfig(
            serve_tenant_weights="a:2,b:1"))
        for i in range(4):
            q.put(_entry(expr=("a", i)), "a")
        seq = []
        while True:
            try:
                seq.append(q.get_nowait()[0])
            except queue.Empty:
                break
        assert [i for t, i in seq if t == "a"] == [0, 1, 2, 3]

    def test_tenant_quota_sheds_before_global(self):
        q = AdmissionQueue(MatrelConfig(
            serve_tenant_weights="a:2,b:1",
            serve_tenant_queue_max=2, serve_queue_max=100))
        q.put(_entry(), "a")
        q.put(_entry(), "a")
        with pytest.raises(rerrors.AdmissionShed) as ei:
            q.put(_entry(), "a")
        assert ei.value.tenant == "a"
        assert ei.value.scope == "tenant"
        # the OTHER tenant's share is untouched
        q.put(_entry(), "b")
        assert q.counters()["sheds"] == {"a": 1}

    def test_global_bound_sheds_typed(self):
        q = AdmissionQueue(MatrelConfig(serve_queue_max=2))
        q.put(_entry())
        q.put(_entry())
        with pytest.raises(rerrors.AdmissionShed) as ei:
            q.put(_entry())
        assert ei.value.scope == "queue"

    def test_full_of_expired_queue_admits_fresh(self):
        # the ride-along regression (ISSUE 12 satellite 1): dead
        # entries used to hold their slots until the worker reached
        # them, shedding LIVE traffic from a queue of corpses
        q = AdmissionQueue(MatrelConfig(serve_queue_max=3))
        dead = []
        for _ in range(3):
            e = _entry(deadline=Deadline(0.0))   # expired immediately
            dead.append(e[1])
            q.put(e)
        time.sleep(0.005)
        live = _entry()
        q.put(live)              # purge at the shed decision point
        assert q.qsize() == 1
        for fut in dead:
            assert isinstance(fut.exception(timeout=1),
                              rerrors.DeadlineExceeded)
        assert q.counters()["purged_expired"] == 3
        # live future untouched, drain accounting consistent
        assert not live[1].done()
        assert q.unfinished_tasks == 1

    def test_tenant_quota_purges_expired_first(self):
        q = AdmissionQueue(MatrelConfig(
            serve_tenant_weights="a:2,b:1",
            serve_tenant_queue_max=2))
        q.put(_entry(deadline=Deadline(0.0)), "a")
        q.put(_entry(deadline=Deadline(0.0)), "a")
        time.sleep(0.01)
        q.put(_entry(), "a")      # admits: both corpses purged
        assert q.tenant_depths() == {"a": 1}

    def test_idle_tenant_banks_no_credit(self):
        q = AdmissionQueue(MatrelConfig(
            serve_tenant_weights="a:1,b:1"))
        for i in range(8):
            q.put(_entry(expr=("a", i)), "a")
        for _ in range(6):
            q.get_nowait()
        # b goes active LATE: it re-enters at the current virtual
        # time, not at 0 — it must not get 6 make-up pops in a row
        q.put(_entry(expr=("b", 0)), "b")
        q.put(_entry(expr=("b", 1)), "b")
        got = [q.get_nowait()[0][0] for _ in range(4)]
        assert got.count("b") <= 2 and got.count("a") >= 2

    def test_lowest_weight_tenant_set(self):
        q = AdmissionQueue(MatrelConfig(
            serve_tenant_weights="a:4,b:1"))
        assert q.lowest_weight_tenant("b") is True
        assert q.lowest_weight_tenant("a") is False
        # unknown tenants carry implicit weight 1.0 — the bottom
        assert q.lowest_weight_tenant("zzz") is True
        # no weights / all-equal weights: nobody is lowest
        assert AdmissionQueue(
            MatrelConfig()).lowest_weight_tenant("x") is False
        assert AdmissionQueue(MatrelConfig(
            serve_tenant_weights="a:2,b:2")).lowest_weight_tenant(
                "a") is False


# ---------------------------------------------------------------------------
# brownout controller


class TestLoadController:
    def _ctl(self, **kw):
        return LoadController(MatrelConfig(**{**BROWNOUT, **kw}))

    def test_enters_under_sustained_wait_pressure(self):
        ctl = self._ctl()
        for _ in range(3):
            ctl.observe(depth=0, waits_ms=[500.0] * 4, admitted=4)
        assert ctl.rung() >= 1
        assert ctl.snapshot()["entered"] >= 1

    def test_hysteresis_band_holds_the_rung(self):
        ctl = self._ctl()
        for _ in range(4):
            ctl.observe(depth=0, waits_ms=[500.0] * 8, admitted=8)
        r = ctl.rung()
        assert r >= 1
        # waits BETWEEN low (10) and high (100): neither hot nor cold
        # — the rung must hold exactly where it is, indefinitely
        for _ in range(20):
            ctl.observe(depth=0, waits_ms=[50.0] * 8, admitted=8)
        assert ctl.rung() == r

    def test_exits_only_when_every_signal_cold(self):
        ctl = self._ctl()
        for _ in range(4):
            ctl.observe(depth=20, waits_ms=[500.0] * 8, admitted=8)
        assert ctl.rung() >= 1
        # waits cold but DEPTH still hot: no exit
        for _ in range(6):
            ctl.observe(depth=20, waits_ms=[1.0] * 8, admitted=8)
        assert ctl.rung() >= 1
        # everything cold: descends to 0 (and counts the exits)
        for _ in range(30):
            ctl.observe(depth=0, waits_ms=[1.0] * 8, admitted=8)
        assert ctl.rung() == 0
        snap = ctl.snapshot()
        assert snap["exited"] >= 1
        assert snap["max_rung_seen"] >= 1

    def test_dwell_bounds_climb_rate(self):
        ctl = self._ctl(brownout_dwell=5)
        for _ in range(4):
            ctl.observe(depth=100, waits_ms=[999.0] * 8, admitted=8)
        # 4 hot samples with dwell 5: at most ONE move has happened
        assert ctl.rung() <= 1

    def test_climbs_to_max_rung_and_saturates(self):
        ctl = self._ctl(brownout_dwell=1)
        for _ in range(12):
            ctl.observe(depth=100, waits_ms=[999.0] * 8, admitted=8)
        assert ctl.rung() == brownout_lib.MAX_RUNG

    def test_miss_rate_signal(self):
        ctl = self._ctl(brownout_dwell=1)
        for _ in range(4):
            ctl.observe(depth=0, waits_ms=[1.0] * 2, misses=3,
                        admitted=1)
        assert ctl.rung() >= 1

    def test_from_config_off_constructs_nothing(self, monkeypatch):
        def poisoned(self, *a, **k):
            raise AssertionError(
                "LoadController constructed with brownout off")
        monkeypatch.setattr(LoadController, "__init__", poisoned)
        assert brownout_lib.from_config(MatrelConfig()) is None

    def test_downshift_stamp_authorizing_rungs(self):
        assert brownout_lib.downshift_stamp() == {
            "rung": brownout_lib.TIER_RUNG, "sla": "fast"}
        st = brownout_lib.downshift_stamp(2000.0)
        assert st["rung"] == brownout_lib.STALE_RUNG
        # the CLAIM rides the stamp, never the caller's raw tolerance:
        # the stamp forms the plan key, and per-value stamps would
        # compile one plan per distinct tolerance for byte-identical
        # programs
        assert st["stale_ok"] is True
        assert "staleness_ms" not in st
        assert (brownout_lib.downshift_stamp(100.0)
                == brownout_lib.downshift_stamp(9999.0))


# ---------------------------------------------------------------------------
# brownout rung actions through the serve pipeline


class TestBrownoutActions:
    def test_rung1_downshifts_default_sla(self, mesh8, rng):
        sess = _sess(mesh8, **BROWNOUT)
        sess._brownout = _StubController(1)
        A = _mat(rng, 32, 32, mesh8)
        an = A.to_numpy()
        fut = sess.submit(A.expr().multiply(A.expr()))
        got = fut.result(timeout=60).to_numpy()
        scale = float(np.max(np.abs(an @ an)))
        assert np.max(np.abs(got - an @ an)) <= 2e-2 * max(scale, 1.0)
        # the downshifted plan compiled under the fast-SLA-isolated
        # key prefix — it can never answer a default-SLA query later
        assert any(k.startswith("prec:fast|") or "prec:fast|" in k
                   for k in sess._plan_cache)

    def test_rung1_leaves_explicit_sla_alone(self, mesh8, rng):
        sess = _sess(mesh8, **BROWNOUT)
        sess._brownout = _StubController(1)
        A = _mat(rng, 32, 32, mesh8)
        an = A.to_numpy()
        fut = sess.submit(A.expr().multiply(A.expr()),
                          precision="exact")
        got = fut.result(timeout=60).to_numpy()
        # an explicit accuracy ask is an ask: full fidelity
        np.testing.assert_allclose(got, an @ an, rtol=1e-5, atol=1e-5)
        assert not any("prec:fast|" in k for k in sess._plan_cache)

    def test_rung2_serves_stale_to_tolerant_queries(self, mesh8, rng):
        sess = _sess(mesh8, result_cache_max_bytes=64 << 20,
                     **BROWNOUT)
        sess._brownout = _StubController(2)
        a_old = rng.standard_normal((32, 32)).astype(np.float32)
        A_old = BlockMatrix.from_numpy(a_old, mesh=mesh8)
        sess.register("A", A_old)
        e = A_old.expr().multiply_scalar(2.0)
        old = sess.run(e)                      # cached
        # catalog rebind: the entry is STALE now, not gone (a brownout
        # controller exists)
        sess.register("A", _mat(rng, 32, 32, mesh8))
        assert sess.result_cache_info()["stale_entries"] == 1
        # a tolerant query gets the stale answer with zero compute
        fut = sess.submit(e, staleness_ms=60_000.0)
        assert fut.result(timeout=60) is old
        assert sess.result_cache_info()["stale_hits"] == 1
        # an intolerant query recomputes (fresh result, not the ghost)
        fut2 = sess.submit(e)
        np.testing.assert_allclose(fut2.result(timeout=60).to_numpy(),
                                   a_old * 2.0, rtol=1e-5, atol=1e-5)

    def test_stale_age_respects_tolerance(self, mesh8, rng):
        sess = _sess(mesh8, result_cache_max_bytes=64 << 20,
                     **BROWNOUT)
        sess._brownout = _StubController(2)
        a_old = rng.standard_normal((32, 32)).astype(np.float32)
        A_old = BlockMatrix.from_numpy(a_old, mesh=mesh8)
        sess.register("A", A_old)
        e = A_old.expr().multiply_scalar(3.0)
        sess.run(e)
        sess.register("A", _mat(rng, 32, 32, mesh8))
        time.sleep(0.03)
        # tolerance smaller than the entry's age: recompute
        fut = sess.submit(e, staleness_ms=1.0)
        np.testing.assert_allclose(fut.result(timeout=60).to_numpy(),
                                   a_old * 3.0, rtol=1e-5, atol=1e-5)
        assert sess.result_cache_info()["stale_hits"] == 0

    def test_below_stale_rung_never_serves_stale(self, mesh8, rng):
        sess = _sess(mesh8, result_cache_max_bytes=64 << 20,
                     **BROWNOUT)
        sess._brownout = _StubController(1)   # rung 1 < STALE_RUNG
        a_old = rng.standard_normal((32, 32)).astype(np.float32)
        A_old = BlockMatrix.from_numpy(a_old, mesh=mesh8)
        sess.register("A", A_old)
        e = A_old.expr().multiply_scalar(4.0)
        sess.run(e)
        sess.register("A", _mat(rng, 32, 32, mesh8))
        fut = sess.submit(e, staleness_ms=60_000.0)
        fut.result(timeout=60)
        assert sess.result_cache_info()["stale_hits"] == 0

    def test_stale_graveyard_byte_bounded(self, mesh8, rng):
        # stale ghosts stay device-pinned: the graveyard is bounded by
        # the live cache's own byte budget, so repeated rebinds can
        # never retain more device memory than the cache is allowed
        from matrel_tpu.serve.result_cache import (CacheEntry,
                                                   ResultCache)
        rc = ResultCache()
        budget = 1000
        for i in range(8):
            key = f"k{i}"
            m = object()
            ent = CacheEntry(key_hash=key, result=None, pins=(),
                             dep_ids=frozenset({id(m)}), layout="rep",
                             dtype="float32", nbytes=400)
            rc._entries[key] = ent
            rc._bytes += ent.nbytes
            rc.invalidate_deps({id(m)}, keep_stale=True,
                               stale_max=256, stale_max_bytes=budget)
        info = rc.info()
        assert info["stale_bytes"] <= budget
        assert info["stale_entries"] == 2      # 2 x 400 <= 1000

    def test_default_config_drops_stale_on_rebind(self, mesh8, rng):
        # no brownout controller -> invalidation drops entries exactly
        # as before (the bit-identity contract: no graveyard grows)
        sess = _sess(mesh8, result_cache_max_bytes=64 << 20)
        A_old = _mat(rng, 32, 32, mesh8)
        sess.register("A", A_old)
        sess.run(A_old.expr().multiply_scalar(2.0))
        sess.register("A", _mat(rng, 32, 32, mesh8))
        info = sess.result_cache_info()
        assert info["stale_entries"] == 0

    def test_rung3_sheds_lowest_weight_tenant(self, mesh8, rng):
        sess = _sess(mesh8,
                     serve_tenant_weights="gold:4,bronze:1",
                     **BROWNOUT)
        sess._brownout = _StubController(3)
        A = _mat(rng, 32, 32, mesh8)
        e = A.expr().multiply_scalar(2.0)
        with pytest.raises(rerrors.AdmissionShed) as ei:
            sess.submit(e, tenant="bronze")
        assert ei.value.scope == "brownout"
        assert ei.value.tenant == "bronze"
        # the high-weight tenant still admits and completes
        fut = sess.submit(e, tenant="gold")
        fut.result(timeout=60)

    def test_rung3_single_implicit_tenant_sheds_nobody(self, mesh8,
                                                       rng):
        sess = _sess(mesh8, **BROWNOUT)
        sess._brownout = _StubController(3)
        A = _mat(rng, 32, 32, mesh8)
        fut = sess.submit(A.expr().multiply_scalar(2.0))
        fut.result(timeout=60)    # no tenants configured: no shed set


# ---------------------------------------------------------------------------
# circuit breakers


class TestCircuitBreaker:
    def _reg(self, clock, threshold=2, cooldown_ms=1000.0, probes=1):
        return BreakerRegistry(threshold, cooldown_ms, probes,
                               clock=clock)

    def test_state_machine_transitions(self):
        t = [0.0]
        reg = self._reg(lambda: t[0])
        cls = "matmul:<=64"
        reg.admit(cls)
        reg.record(cls, False)
        reg.admit(cls)                  # one failure: still closed
        reg.record(cls, False)          # second consecutive: OPEN
        with pytest.raises(rerrors.CircuitOpen) as ei:
            reg.admit(cls)
        assert ei.value.plan_class == cls
        assert 0 < ei.value.retry_after_ms <= 1000.0
        # cooldown elapses: half-open admits exactly one probe
        t[0] = 1.1
        reg.admit(cls)                  # the probe
        with pytest.raises(rerrors.CircuitOpen):
            reg.admit(cls)              # probe budget spent
        # probe success closes; failures reset
        reg.record(cls, True)
        assert reg.state(cls) == "closed"
        reg.admit(cls)
        snap = reg.snapshot()
        assert snap["transitions"] == {"open": 1, "half_open": 1,
                                       "close": 1}

    def test_probe_failure_reopens_with_fresh_cooldown(self):
        t = [0.0]
        reg = self._reg(lambda: t[0])
        cls = "matmul:<=64"
        for _ in range(2):
            reg.admit(cls)
            reg.record(cls, False)
        t[0] = 1.1
        reg.admit(cls)                  # half-open probe
        reg.record(cls, False)          # probe fails: re-open
        assert reg.state(cls) == "open"
        with pytest.raises(rerrors.CircuitOpen):
            reg.admit(cls)
        t[0] = 1.5                      # old cooldown would be over;
        with pytest.raises(rerrors.CircuitOpen):
            reg.admit(cls)              # the RESTARTED one is not
        t[0] = 2.2
        reg.admit(cls)
        reg.record(cls, True)
        assert reg.state(cls) == "closed"

    def test_success_resets_consecutive_failures(self):
        t = [0.0]
        reg = self._reg(lambda: t[0], threshold=2)
        cls = "c"
        reg.admit(cls)
        reg.record(cls, False)
        reg.admit(cls)
        reg.record(cls, True)           # streak broken
        reg.admit(cls)
        reg.record(cls, False)          # 1 consecutive again
        reg.admit(cls)                  # still closed

    def test_none_outcome_releases_probe_slot(self):
        t = [0.0]
        reg = self._reg(lambda: t[0])
        cls = "c"
        for _ in range(2):
            reg.admit(cls)
            reg.record(cls, False)
        t[0] = 1.1
        reg.admit(cls)                  # probe out
        reg.record(cls, None)           # deadline/shed: says nothing
        reg.admit(cls)                  # slot released: probe again
        reg.record(cls, True)
        assert reg.state(cls) == "closed"

    def test_counts_as_failure_taxonomy(self):
        assert not breaker_lib.counts_as_failure(
            rerrors.DeadlineExceeded(1.0, 2.0))
        assert not breaker_lib.counts_as_failure(
            rerrors.AdmissionShed(4))
        assert not breaker_lib.counts_as_failure(
            rerrors.CircuitOpen("c", 10.0))
        assert not breaker_lib.counts_as_failure(
            rerrors.QueryAborted("x"))
        assert breaker_lib.counts_as_failure(ValueError("boom"))
        assert breaker_lib.counts_as_failure(
            rerrors.InjectedFault("execute", "fatal", 1))

    def test_plan_class_is_kind_plus_shape_bucket(self, mesh8, rng):
        A = _mat(rng, 48, 64, mesh8)
        e = A.expr().multiply(A.expr().t())
        assert breaker_lib.plan_class(e) == "matmul:<=64"

    def test_from_config_off_constructs_nothing(self, monkeypatch):
        def poisoned(self, *a, **k):
            raise AssertionError(
                "CircuitBreaker constructed with breakers off")
        monkeypatch.setattr(CircuitBreaker, "__init__", poisoned)
        assert BreakerRegistry.from_config(MatrelConfig()) is None
        # and a default-config session serves without one
        from matrel_tpu.core import mesh as mesh_lib
        sess = MatrelSession(mesh=mesh_lib.make_mesh((2, 4)),
                             config=MatrelConfig())
        assert sess._breakers is None


class TestBreakerSessionWiring:
    def _poison(self, mesh8, rng):
        """A deterministically-failing query class: mixed-mesh
        multiply raises ValueError at compile, every attempt."""
        import jax
        from matrel_tpu.core import mesh as mesh_lib
        other = mesh_lib.make_mesh((1, 1), devices=jax.devices()[:1])
        P = BlockMatrix.from_numpy(
            rng.standard_normal((256, 256)).astype(np.float32),
            mesh=mesh8)
        M = BlockMatrix.from_numpy(
            rng.standard_normal((256, 256)).astype(np.float32),
            mesh=other)
        return P.expr().multiply(M.expr())

    def test_run_fails_fast_after_threshold(self, mesh8, rng):
        sess = _sess(mesh8, breaker_threshold=2,
                     breaker_cooldown_ms=80.0)
        poison = self._poison(mesh8, rng)
        for _ in range(2):
            with pytest.raises(ValueError):
                sess.run(poison)
        # third call: typed fast-fail, no compile attempted
        with pytest.raises(rerrors.CircuitOpen):
            sess.run(poison)
        # a DIFFERENT class (other shape bucket) is unaffected
        A = _mat(rng, 32, 32, mesh8)
        sess.run(A.expr().multiply(A.expr()))
        # cooldown over: the probe runs (and fails again, re-opening)
        time.sleep(0.1)
        with pytest.raises(ValueError):
            sess.run(poison)
        assert sess._breakers.state("matmul:<=256") == "open"

    def test_breaker_closes_after_class_heals(self, mesh8, rng):
        sess = _sess(mesh8, breaker_threshold=2,
                     breaker_cooldown_ms=40.0,
                     fault_inject="execute:fatal:n=1;execute:fatal:n=2",
                     fault_inject_seed=7)
        from matrel_tpu.resilience import faults
        faults.reset()
        A = _mat(rng, 32, 32, mesh8)
        an = A.to_numpy()
        e = A.expr().multiply(A.expr())
        for _ in range(2):
            with pytest.raises(rerrors.InjectedFault):
                sess.run(e)
        with pytest.raises(rerrors.CircuitOpen):
            sess.run(e)
        time.sleep(0.06)
        # fault window over (both n-rules fired): the probe SUCCEEDS
        # and closes the breaker — the class is healthy again
        got = sess.run(e).to_numpy()
        np.testing.assert_allclose(got, an @ an, rtol=1e-4, atol=1e-4)
        assert sess._breakers.state(
            breaker_lib.plan_class(e)) == "closed"
        snap = sess._breakers.snapshot()
        assert snap["transitions"]["close"] == 1
        faults.reset()

    def test_serve_open_class_fails_future_fast(self, mesh8, rng):
        sess = _sess(mesh8, breaker_threshold=1,
                     breaker_cooldown_ms=60_000.0)
        poison = self._poison(mesh8, rng)
        f1 = sess.submit(poison)
        assert isinstance(f1.exception(timeout=60), ValueError)
        # the class is open now: the next submission fails typed at
        # BATCH FORMATION — no compile, no bisection, no retry burn
        f2 = sess.submit(poison)
        assert isinstance(f2.exception(timeout=60),
                          rerrors.CircuitOpen)
        # a healthy class rides through the same worker untouched
        A = _mat(rng, 32, 32, mesh8)
        f3 = sess.submit(A.expr().multiply(A.expr()))
        f3.result(timeout=60)

    def test_deadline_outcomes_do_not_trip_breakers(self, mesh8, rng):
        sess = _sess(mesh8, breaker_threshold=1,
                     breaker_cooldown_ms=60_000.0)
        A = _mat(rng, 32, 32, mesh8)
        e = A.expr().multiply(A.expr())
        with pytest.raises(rerrors.DeadlineExceeded):
            sess.run(e, deadline_ms=1e-6)
        # starvation says nothing about the class: still closed
        sess.run(e)


# ---------------------------------------------------------------------------
# MV112


class TestMV112:
    def _verify(self, e, mesh, cfg):
        from matrel_tpu import analysis
        from matrel_tpu.ir import rules
        from matrel_tpu.parallel import planner
        from matrel_tpu.core import mesh as mesh_lib
        grid = mesh_lib.mesh_grid_shape(mesh)
        opt = planner.annotate_strategies(
            rules.optimize(e, cfg, grid=grid, mesh=mesh), mesh, cfg)
        return [d for d in analysis.verify_plan(opt, mesh, cfg)
                if d.code == "MV112"]

    def test_fresh_plans_quiet(self, mesh8, rng):
        A = _mat(rng, 32, 32, mesh8)
        e = A.expr().multiply(A.expr())
        assert self._verify(e, mesh8, MatrelConfig()) == []

    def test_worker_stamp_verifies_clean(self, mesh8, rng):
        # exactly what the serve worker produces at rung >= 1: the
        # downshift stamp on a plan compiled under the fast SLA with
        # brownout on. Epilogue-rooted tree: stamps ride expr attrs,
        # and the rewrite pass RECONSTRUCTS bare matmul roots (the
        # stamp drops with the node — the conservative direction, see
        # the pass docstring), so the positive fixtures use the root
        # kinds real downshifted dashboard queries end in.
        A = _mat(rng, 32, 32, mesh8)
        e = A.expr().multiply(A.expr()).multiply_scalar(2.0).with_attrs(
            brownout=brownout_lib.downshift_stamp())
        cfg = MatrelConfig(precision_sla="fast", **BROWNOUT)
        assert self._verify(e, mesh8, cfg) == []

    def test_bad_rung_flagged(self, mesh8, rng):
        A = _mat(rng, 32, 32, mesh8)
        e = A.expr().multiply(A.expr()).multiply_scalar(2.0).with_attrs(
            brownout={"rung": 9, "sla": "fast"})
        cfg = MatrelConfig(precision_sla="fast", **BROWNOUT)
        diags = self._verify(e, mesh8, cfg)
        assert diags and "rung 9" in diags[0].message

    def test_sla_mismatch_flagged(self, mesh8, rng):
        # stamp claims a downshift the plan's config does not run
        A = _mat(rng, 32, 32, mesh8)
        e = A.expr().multiply(A.expr()).multiply_scalar(2.0).with_attrs(
            brownout=brownout_lib.downshift_stamp())
        cfg = MatrelConfig(**BROWNOUT)     # compiles at "default"
        diags = self._verify(e, mesh8, cfg)
        assert diags and "disagree" in diags[0].message

    def test_staleness_below_stale_rung_flagged(self, mesh8, rng):
        A = _mat(rng, 32, 32, mesh8)
        e = A.expr().multiply(A.expr()).multiply_scalar(2.0).with_attrs(
            brownout={"rung": 1, "sla": "fast",
                      "staleness_ms": 500.0})
        cfg = MatrelConfig(precision_sla="fast", **BROWNOUT)
        diags = self._verify(e, mesh8, cfg)
        assert diags and "staleness" in diags[0].message

    def test_stamp_with_controller_off_flagged(self, mesh8, rng):
        A = _mat(rng, 32, 32, mesh8)
        e = A.expr().multiply(A.expr()).multiply_scalar(2.0).with_attrs(
            brownout=brownout_lib.downshift_stamp())
        cfg = MatrelConfig(precision_sla="fast")   # brownout OFF
        diags = self._verify(e, mesh8, cfg)
        assert diags and "OFF" in diags[0].message
        assert all(d.severity == "warning" for d in diags)


# ---------------------------------------------------------------------------
# obs: overload events, tenant tags, history roll-up


class TestOverloadObs:
    def test_overload_events_and_rollup(self, mesh8, rng, tmp_path):
        log = tmp_path / "events.jsonl"
        sess = _sess(mesh8, obs_level="on", obs_event_log=str(log),
                     serve_tenant_weights="gold:4,bronze:1",
                     serve_tenant_queue_max=4,
                     breaker_threshold=4, **BROWNOUT)
        A = _mat(rng, 32, 32, mesh8)
        e = A.expr().multiply_scalar(2.0)
        futs = [sess.submit(e, tenant=("gold" if i % 2 else "bronze"))
                for i in range(8)]
        sess.serve_drain(timeout=60)
        for f in futs:
            f.result(timeout=60)
        from matrel_tpu.obs.events import read_events
        from matrel_tpu.obs.history import render_summary, summarize
        events = read_events(str(log))
        ov = [ev for ev in events if ev.get("kind") == "overload"]
        assert ov, "no overload events from an active control plane"
        rec = ov[0]
        assert {"rung", "queue_depth", "tenant_depths", "admitted",
                "tenant_waits_ms", "sheds", "purged_expired",
                "stale_served", "brownout", "breakers"} <= set(rec)
        s = summarize(events)
        assert s["overload"] is not None
        assert s["overload"]["cycles"] == len(ov)
        tenants = s["overload"]["tenants"]
        assert set(tenants) >= {"gold", "bronze"}
        assert sum(t["admitted"] for t in tenants.values()) == 8
        text = render_summary(events)
        assert "overload:" in text
        assert "gold" in text

    def test_serve_events_carry_tenant_census(self, mesh8, rng,
                                              tmp_path):
        log = tmp_path / "events.jsonl"
        sess = _sess(mesh8, obs_level="on", obs_event_log=str(log),
                     serve_tenant_weights="a:2,b:1")
        A = _mat(rng, 32, 32, mesh8)
        sess.submit(A.expr().multiply_scalar(2.0),
                    tenant="a").result(timeout=60)
        sess.serve_drain(timeout=60)
        from matrel_tpu.obs.events import read_events
        sv = read_events(str(log), kinds=("serve",))
        assert sv and sv[-1].get("tenants") == {"a": 1}

    def test_query_event_carries_tenant_tag(self, mesh8, rng,
                                            tmp_path):
        log = tmp_path / "events.jsonl"
        sess = _sess(mesh8, obs_level="on", obs_event_log=str(log))
        A = _mat(rng, 32, 32, mesh8)
        sess.run(A.expr().multiply_scalar(2.0), tenant="team-x")
        from matrel_tpu.obs.events import read_events
        qs = read_events(str(log), kinds=("query",))
        assert qs and qs[-1].get("tenant") == "team-x"

    def test_default_serve_emits_no_overload_events(self, mesh8, rng,
                                                    tmp_path):
        # control plane inactive (no tenants/brownout/breakers): obs
        # on must see ZERO overload records — historical logs unchanged
        log = tmp_path / "events.jsonl"
        sess = _sess(mesh8, obs_level="on", obs_event_log=str(log))
        A = _mat(rng, 32, 32, mesh8)
        sess.submit(A.expr().multiply_scalar(2.0)).result(timeout=60)
        sess.serve_drain(timeout=60)
        from matrel_tpu.obs.events import read_events
        assert read_events(str(log), kinds=("overload",)) == []


# ---------------------------------------------------------------------------
# default-config bit-identity


class TestOffContracts:
    def test_default_session_owns_no_controllers(self, mesh8):
        sess = _sess(mesh8)
        assert sess._brownout is None
        assert sess._breakers is None

    def test_default_serve_constructs_no_controller_objects(
            self, mesh8, rng, monkeypatch):
        def poisoned(self, *a, **k):
            raise AssertionError("controller built on default path")
        monkeypatch.setattr(LoadController, "__init__", poisoned)
        monkeypatch.setattr(CircuitBreaker, "__init__", poisoned)
        sess = _sess(mesh8)
        A = _mat(rng, 32, 32, mesh8)
        fut = sess.submit(A.expr().multiply_scalar(2.0))
        an = A.to_numpy()
        np.testing.assert_allclose(fut.result(timeout=60).to_numpy(),
                                   an * 2.0, rtol=1e-6, atol=1e-6)
        sess.serve_drain(timeout=60)

    def test_legacy_short_entries_still_served(self, mesh8, rng):
        # white-box callers enqueue 3-tuples straight into the queue;
        # the worker right-pads to the 7-tuple shape
        from concurrent.futures import Future
        sess = _sess(mesh8)
        A = _mat(rng, 32, 32, mesh8)
        fut = sess.submit(A.expr().multiply_scalar(2.0))
        fut.result(timeout=60)
        pl = sess._serve
        f = Future()
        pl._q.put((A.expr().multiply_scalar(3.0), f,
                   time.perf_counter()))
        deadline = time.time() + 30
        while not f.done() and time.time() < deadline:
            time.sleep(0.01)
        np.testing.assert_allclose(f.result(timeout=1).to_numpy(),
                                   A.to_numpy() * 3.0,
                                   rtol=1e-6, atol=1e-6)

    def test_weighted_queue_preserves_drain_contract(self, mesh8,
                                                     rng):
        sess = _sess(mesh8, serve_tenant_weights="a:2,b:1")
        A = _mat(rng, 32, 32, mesh8)
        futs = [sess.submit(A.expr().multiply_scalar(float(i + 1)),
                            tenant=("a" if i % 2 else "b"))
                for i in range(6)]
        sess.serve_drain(timeout=60)
        an = A.to_numpy()
        for i, f in enumerate(futs):
            np.testing.assert_allclose(
                f.result(timeout=1).to_numpy(), an * (i + 1),
                rtol=1e-5, atol=1e-5)
