"""Physical-strategy tests: each of BMM/CPMM/RMM/SUMMA must (a) match the
numpy oracle on a real multi-device mesh and (b) lower to the collectives
its reference analogue implies — the HLO-inspection analogue of the
reference's Catalyst plan assertions (SURVEY.md §4 "plan shape")."""

import jax
import numpy as np
import pytest

from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir.expr import leaf, matmul
from matrel_tpu.parallel import planner, strategies
from matrel_tpu import executor


def _run(strategy, a, b, mesh):
    A = BlockMatrix.from_numpy(a, mesh=mesh)
    B = BlockMatrix.from_numpy(b, mesh=mesh)
    f = jax.jit(lambda x, y: strategies.run_matmul(strategy, x, y, mesh, None))
    out = np.asarray(f(A.data, B.data))
    return out[: a.shape[0], : b.shape[1]]


ALL = ["bmm_left", "bmm_right", "cpmm", "rmm", "xla"]


@pytest.mark.parametrize("strategy", ALL)
def test_strategy_numerics_2x4(strategy, mesh8, rng):
    a = rng.standard_normal((16, 24)).astype(np.float32)
    b = rng.standard_normal((24, 32)).astype(np.float32)
    np.testing.assert_allclose(_run(strategy, a, b, mesh8), a @ b,
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("strategy", ALL + ["summa"])
def test_strategy_numerics_square_mesh(strategy, mesh_square, rng):
    a = rng.standard_normal((12, 20)).astype(np.float32)
    b = rng.standard_normal((20, 8)).astype(np.float32)
    np.testing.assert_allclose(_run(strategy, a, b, mesh_square), a @ b,
                               rtol=1e-4, atol=1e-4)


def test_summa_on_rect_mesh_falls_back(mesh8, rng):
    a = rng.standard_normal((16, 16)).astype(np.float32)
    b = rng.standard_normal((16, 16)).astype(np.float32)
    np.testing.assert_allclose(_run("summa", a, b, mesh8), a @ b,
                               rtol=1e-4, atol=1e-4)


class TestHloCollectives:
    """CPMM must reduce-scatter; RMM must all-gather with no reduce-scatter;
    SUMMA must ride a ppermute ring (collective-permute)."""

    def _hlo(self, strategy, mesh, shape=(16, 16)):
        a = BlockMatrix.random(shape, mesh=mesh, seed=0)
        b = BlockMatrix.random(shape, mesh=mesh, seed=1)
        f = jax.jit(lambda x, y: strategies.run_matmul(strategy, x, y, mesh, None))
        return f.lower(a.data, b.data).compile().as_text()

    def test_cpmm_reduce_scatter(self, mesh8):
        hlo = self._hlo("cpmm", mesh8)
        assert "reduce-scatter" in hlo

    def test_rmm_all_gather_only(self, mesh8):
        hlo = self._hlo("rmm", mesh8)
        assert "all-gather" in hlo
        assert "reduce-scatter" not in hlo

    def test_summa_collective_permute(self, mesh_square):
        hlo = self._hlo("summa", mesh_square)
        assert "collective-permute" in hlo

    def test_bmm_no_execution_collectives_after_reshard(self, mesh8):
        # BMM: the only comm is the input broadcast (all-gather of B);
        # no reduce-scatter / collective-permute anywhere.
        hlo = self._hlo("bmm_right", mesh8)
        assert "reduce-scatter" not in hlo
        assert "collective-permute" not in hlo


class TestPlannerChoice:
    def _mk(self, n, k, m, mesh, nnz_a=None, nnz_b=None):
        """Planner only reads shapes/stats, so fabricate metadata-true,
        data-tiny leaves: a small zero matrix with an overridden shape."""
        import dataclasses
        a_small = BlockMatrix.from_numpy(
            np.zeros((8, 8), dtype=np.float32), mesh=mesh)
        b_small = BlockMatrix.from_numpy(
            np.zeros((8, 8), dtype=np.float32), mesh=mesh)
        a = dataclasses.replace(a_small, shape=(n, k), nnz=nnz_a)
        b = dataclasses.replace(b_small, shape=(k, m), nnz=nnz_b)
        return matmul(leaf(a), leaf(b))

    def test_small_rhs_broadcasts(self, mesh8):
        # Classic BMM case: big side already row-partitioned (co-partitioned
        # input — zero shuffle of it), tiny RHS broadcast. The reference's
        # canonical broadcast-join situation.
        import dataclasses
        from jax.sharding import PartitionSpec as P
        a_small = BlockMatrix.from_numpy(
            np.zeros((8, 8), dtype=np.float32), mesh=mesh8,
            spec=P(("x", "y"), None))
        b_small = BlockMatrix.from_numpy(
            np.zeros((8, 8), dtype=np.float32), mesh=mesh8)
        a = dataclasses.replace(a_small, shape=(100_000, 512))
        b = dataclasses.replace(b_small, shape=(512, 64))
        node = matmul(leaf(a), leaf(b))
        assert planner.choose_strategy(node, mesh8) == "bmm_right"

    def test_2d_input_large_output_prefers_cpmm_over_bmm(self, mesh8):
        # With A in canonical 2D layout, broadcasting would pay to reshard
        # the big side row-wise; CPMM leaves A in place and reduce-scatters
        # the (smaller) output — the cost model must see that.
        node = self._mk(100_000, 512, 64, mesh8)
        assert planner.choose_strategy(node, mesh8) == "cpmm"

    def test_large_contraction_uses_cpmm(self, mesh8):
        cfg = MatrelConfig(broadcast_threshold_bytes=1024)
        node = self._mk(4096, 65536, 4096, mesh8)
        assert planner.choose_strategy(node, mesh8, cfg) == "cpmm"

    def test_square_large_not_bmm(self, mesh8):
        cfg = MatrelConfig(broadcast_threshold_bytes=1024)
        s = planner.choose_strategy(self._mk(8192, 8192, 8192, mesh8), mesh8, cfg)
        assert s in ("rmm", "cpmm", "summa")

    def test_single_device_is_xla(self):
        import jax as j
        from matrel_tpu.core import mesh as mesh_lib
        m1 = mesh_lib.make_mesh((1, 1), devices=j.devices()[:1])
        node = self._mk(1024, 1024, 1024, m1)
        assert planner.choose_strategy(node, m1) == "xla"

    def test_override(self, mesh8):
        cfg = MatrelConfig(strategy_override="rmm")
        node = self._mk(512, 512, 512, mesh8)
        assert planner.choose_strategy(node, mesh8, cfg) == "rmm"

    def test_annotation_recorded_in_plan(self, mesh8):
        node = self._mk(100_000, 512, 64, mesh8)
        plan = executor.compile_expr(node, mesh8)
        assert "strategy" in plan.optimized.attrs


def test_compiled_plan_collectives_summary(mesh8):
    import dataclasses
    cfg = MatrelConfig(broadcast_threshold_bytes=1024, strategy_override="cpmm")
    a = BlockMatrix.random((64, 64), mesh=mesh8, seed=0)
    b = BlockMatrix.random((64, 64), mesh=mesh8, seed=1)
    plan = executor.compile_expr(matmul(leaf(a), leaf(b)), mesh8, cfg)
    cols = plan.collectives()
    assert cols.get("reduce-scatter", 0) >= 1
    assert "strategy=cpmm" in plan.explain()


class TestBmmLeft:
    def test_bmm_left_hlo_no_reduce_scatter(self, mesh8):
        # near-symmetric pin to the bmm_right HLO test: with the LEFT
        # operand replicated there is no contraction-time
        # reduce-scatter. (B's 2d→col reshard MAY lower to a
        # collective-permute — input movement, not execution comm — so
        # only the reduce-scatter absence is pinned.)
        import jax
        rng = np.random.default_rng(3)
        a = BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32), mesh=mesh8)
        b = BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32), mesh=mesh8)
        f = jax.jit(lambda x, y: strategies.run_matmul(
            "bmm_left", x, y, mesh8, MatrelConfig()))
        hlo = f.lower(a.data, b.data).compile().as_text()
        assert "reduce-scatter" not in hlo
        got = np.asarray(f(a.data, b.data))[:16, :16]
        np.testing.assert_allclose(got, a.to_numpy() @ b.to_numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_small_lhs_broadcasts_left(self, mesh8):
        # mirror of test_small_rhs_broadcasts: tiny LEFT operand against
        # a big col-partitioned RHS → the planner must flip to bmm_left
        import dataclasses
        from jax.sharding import PartitionSpec as P
        a_small = BlockMatrix.from_numpy(
            np.zeros((8, 8), dtype=np.float32), mesh=mesh8)
        b_small = BlockMatrix.from_numpy(
            np.zeros((8, 8), dtype=np.float32), mesh=mesh8,
            spec=P(None, ("x", "y")))
        a = dataclasses.replace(a_small, shape=(64, 512))
        b = dataclasses.replace(b_small, shape=(512, 100_000))
        node = matmul(leaf(a), leaf(b))
        assert planner.choose_strategy(node, mesh8) == "bmm_left"
