"""Physical-strategy tests: each of BMM/CPMM/RMM/SUMMA must (a) match the
numpy oracle on a real multi-device mesh and (b) lower to the collectives
its reference analogue implies — the HLO-inspection analogue of the
reference's Catalyst plan assertions (SURVEY.md §4 "plan shape")."""

import jax
import numpy as np
import pytest

from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir.expr import leaf, matmul
from matrel_tpu.parallel import planner, strategies
from matrel_tpu import executor


def _run(strategy, a, b, mesh):
    A = BlockMatrix.from_numpy(a, mesh=mesh)
    B = BlockMatrix.from_numpy(b, mesh=mesh)
    f = jax.jit(lambda x, y: strategies.run_matmul(strategy, x, y, mesh, None))
    out = np.asarray(f(A.data, B.data))
    return out[: a.shape[0], : b.shape[1]]


ALL = ["bmm_left", "bmm_right", "cpmm", "rmm", "xla"]


@pytest.mark.parametrize("strategy", ALL)
def test_strategy_numerics_2x4(strategy, mesh8, rng):
    a = rng.standard_normal((16, 24)).astype(np.float32)
    b = rng.standard_normal((24, 32)).astype(np.float32)
    np.testing.assert_allclose(_run(strategy, a, b, mesh8), a @ b,
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("strategy", ALL + ["summa"])
def test_strategy_numerics_square_mesh(strategy, mesh_square, rng):
    a = rng.standard_normal((12, 20)).astype(np.float32)
    b = rng.standard_normal((20, 8)).astype(np.float32)
    np.testing.assert_allclose(_run(strategy, a, b, mesh_square), a @ b,
                               rtol=1e-4, atol=1e-4)


def test_summa_on_rect_mesh_falls_back(mesh8, rng):
    a = rng.standard_normal((16, 16)).astype(np.float32)
    b = rng.standard_normal((16, 16)).astype(np.float32)
    np.testing.assert_allclose(_run("summa", a, b, mesh8), a @ b,
                               rtol=1e-4, atol=1e-4)


class TestHloCollectives:
    """CPMM must reduce-scatter; RMM must all-gather with no reduce-scatter;
    SUMMA must ride a ppermute ring (collective-permute)."""

    def _hlo(self, strategy, mesh, shape=(16, 16)):
        a = BlockMatrix.random(shape, mesh=mesh, seed=0)
        b = BlockMatrix.random(shape, mesh=mesh, seed=1)
        f = jax.jit(lambda x, y: strategies.run_matmul(strategy, x, y, mesh, None))
        return f.lower(a.data, b.data).compile().as_text()

    def test_cpmm_reduce_scatter(self, mesh8):
        hlo = self._hlo("cpmm", mesh8)
        assert "reduce-scatter" in hlo

    def test_rmm_all_gather_only(self, mesh8):
        hlo = self._hlo("rmm", mesh8)
        assert "all-gather" in hlo
        assert "reduce-scatter" not in hlo

    def test_summa_collective_permute(self, mesh_square):
        hlo = self._hlo("summa", mesh_square)
        assert "collective-permute" in hlo

    def test_bmm_no_execution_collectives_after_reshard(self, mesh8):
        # BMM: the only comm is the input broadcast (all-gather of B);
        # no reduce-scatter / collective-permute anywhere.
        hlo = self._hlo("bmm_right", mesh8)
        assert "reduce-scatter" not in hlo
        assert "collective-permute" not in hlo


class TestPlannerChoice:
    def _mk(self, n, k, m, mesh, nnz_a=None, nnz_b=None):
        """Planner only reads shapes/stats, so fabricate metadata-true,
        data-tiny leaves: a small zero matrix with an overridden shape."""
        import dataclasses
        a_small = BlockMatrix.from_numpy(
            np.zeros((8, 8), dtype=np.float32), mesh=mesh)
        b_small = BlockMatrix.from_numpy(
            np.zeros((8, 8), dtype=np.float32), mesh=mesh)
        a = dataclasses.replace(a_small, shape=(n, k), nnz=nnz_a)
        b = dataclasses.replace(b_small, shape=(k, m), nnz=nnz_b)
        return matmul(leaf(a), leaf(b))

    def test_small_rhs_broadcasts(self, mesh8):
        # Classic BMM case: big side already row-partitioned (co-partitioned
        # input — zero shuffle of it), tiny RHS broadcast. The reference's
        # canonical broadcast-join situation.
        import dataclasses
        from jax.sharding import PartitionSpec as P
        a_small = BlockMatrix.from_numpy(
            np.zeros((8, 8), dtype=np.float32), mesh=mesh8,
            spec=P(("x", "y"), None))
        b_small = BlockMatrix.from_numpy(
            np.zeros((8, 8), dtype=np.float32), mesh=mesh8)
        a = dataclasses.replace(a_small, shape=(100_000, 512))
        b = dataclasses.replace(b_small, shape=(512, 64))
        node = matmul(leaf(a), leaf(b))
        assert planner.choose_strategy(node, mesh8) == "bmm_right"

    def test_2d_input_large_output_prefers_cpmm_over_bmm(self, mesh8):
        # With A in canonical 2D layout, broadcasting would pay to reshard
        # the big side row-wise; CPMM leaves A in place and reduce-scatters
        # the (smaller) output — the cost model must see that.
        node = self._mk(100_000, 512, 64, mesh8)
        assert planner.choose_strategy(node, mesh8) == "cpmm"

    def test_large_contraction_uses_cpmm(self, mesh8):
        cfg = MatrelConfig(broadcast_threshold_bytes=1024)
        node = self._mk(4096, 65536, 4096, mesh8)
        assert planner.choose_strategy(node, mesh8, cfg) == "cpmm"

    def test_square_large_not_bmm(self, mesh8):
        cfg = MatrelConfig(broadcast_threshold_bytes=1024)
        s = planner.choose_strategy(self._mk(8192, 8192, 8192, mesh8), mesh8, cfg)
        assert s in ("rmm", "cpmm", "summa")

    def test_single_device_is_xla(self):
        import jax as j
        from matrel_tpu.core import mesh as mesh_lib
        m1 = mesh_lib.make_mesh((1, 1), devices=j.devices()[:1])
        node = self._mk(1024, 1024, 1024, m1)
        assert planner.choose_strategy(node, m1) == "xla"

    def test_override(self, mesh8):
        cfg = MatrelConfig(strategy_override="rmm")
        node = self._mk(512, 512, 512, mesh8)
        assert planner.choose_strategy(node, mesh8, cfg) == "rmm"

    def test_annotation_recorded_in_plan(self, mesh8):
        node = self._mk(100_000, 512, 64, mesh8)
        plan = executor.compile_expr(node, mesh8)
        assert "strategy" in plan.optimized.attrs


def test_compiled_plan_collectives_summary(mesh8):
    import dataclasses
    cfg = MatrelConfig(broadcast_threshold_bytes=1024, strategy_override="cpmm")
    a = BlockMatrix.random((64, 64), mesh=mesh8, seed=0)
    b = BlockMatrix.random((64, 64), mesh=mesh8, seed=1)
    plan = executor.compile_expr(matmul(leaf(a), leaf(b)), mesh8, cfg)
    cols = plan.collectives()
    assert cols.get("reduce-scatter", 0) >= 1
    assert "strategy=cpmm" in plan.explain()


class TestBmmLeft:
    def test_bmm_left_hlo_no_reduce_scatter(self, mesh8):
        # near-symmetric pin to the bmm_right HLO test: with the LEFT
        # operand replicated there is no contraction-time
        # reduce-scatter. (B's 2d→col reshard MAY lower to a
        # collective-permute — input movement, not execution comm — so
        # only the reduce-scatter absence is pinned.)
        import jax
        rng = np.random.default_rng(3)
        a = BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32), mesh=mesh8)
        b = BlockMatrix.from_numpy(
            rng.standard_normal((16, 16)).astype(np.float32), mesh=mesh8)
        f = jax.jit(lambda x, y: strategies.run_matmul(
            "bmm_left", x, y, mesh8, MatrelConfig()))
        hlo = f.lower(a.data, b.data).compile().as_text()
        assert "reduce-scatter" not in hlo
        got = np.asarray(f(a.data, b.data))[:16, :16]
        np.testing.assert_allclose(got, a.to_numpy() @ b.to_numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_small_lhs_broadcasts_left(self, mesh8):
        # mirror of test_small_rhs_broadcasts: tiny LEFT operand against
        # a big col-partitioned RHS → the planner must flip to bmm_left
        import dataclasses
        from jax.sharding import PartitionSpec as P
        a_small = BlockMatrix.from_numpy(
            np.zeros((8, 8), dtype=np.float32), mesh=mesh8)
        b_small = BlockMatrix.from_numpy(
            np.zeros((8, 8), dtype=np.float32), mesh=mesh8,
            spec=P(None, ("x", "y")))
        a = dataclasses.replace(a_small, shape=(64, 512))
        b = dataclasses.replace(b_small, shape=(512, 100_000))
        node = matmul(leaf(a), leaf(b))
        assert planner.choose_strategy(node, mesh8) == "bmm_left"


def _fab(mesh, n, m, spec=None):
    """Metadata-true, data-tiny leaf (see TestPlannerChoice._mk)."""
    import dataclasses
    small = BlockMatrix.from_numpy(np.zeros((8, 8), dtype=np.float32),
                                   mesh=mesh, spec=spec)
    return leaf(dataclasses.replace(small, shape=(n, m)))


class TestLayoutInference:
    """infer_layout (VERDICT r4 "what's missing" #2): the bottom-up
    layout pass mirroring the executor's actual sharding behaviour, so
    the co-partitioning credit reaches INTERIOR nodes — the analogue of
    the reference's partitioner-aware planning (SURVEY.md §2
    "Partitioners")."""

    def test_leaf_layouts(self, mesh8):
        from jax.sharding import PartitionSpec as P
        assert planner.infer_layout(
            _fab(mesh8, 64, 64), mesh8) == "2d"
        assert planner.infer_layout(
            _fab(mesh8, 64, 64, spec=P(("x", "y"), None)), mesh8) == "row"
        assert planner.infer_layout(
            _fab(mesh8, 64, 64, spec=P(None, ("x", "y"))), mesh8) == "col"
        assert planner.infer_layout(
            _fab(mesh8, 64, 64, spec=P(None, None)), mesh8) == "rep"

    def test_matmul_layout_follows_strategy(self, mesh8):
        # the strategies' shard_map out_specs (strategies.py): bmm_right
        # emits P((x,y), None), bmm_left P(None, (x,y)), the rest P(x,y)
        node = matmul(_fab(mesh8, 64, 64), _fab(mesh8, 64, 64))
        for strat, want in (("bmm_right", "row"), ("bmm_left", "col"),
                            ("cpmm", "2d"), ("rmm", "2d"),
                            ("summa", "2d"), ("xla", "2d")):
            stamped = node.with_attrs(strategy=strat)
            assert planner.infer_layout(stamped, mesh8) == want, strat
        # un-annotated: conservative 2d
        assert planner.infer_layout(node, mesh8) == "2d"

    def test_transpose_swaps_elemwise_preserves(self, mesh8):
        from jax.sharding import PartitionSpec as P
        from matrel_tpu.ir.expr import elemwise, scalar_op, transpose
        row = _fab(mesh8, 64, 64, spec=P(("x", "y"), None))
        rep = _fab(mesh8, 64, 64, spec=P(None, None))
        two_d = _fab(mesh8, 64, 64)
        assert planner.infer_layout(transpose(row), mesh8) == "col"
        assert planner.infer_layout(
            transpose(transpose(row)), mesh8) == "row"
        assert planner.infer_layout(
            scalar_op("mul", row, 2.0), mesh8) == "row"
        assert planner.infer_layout(
            elemwise("add", row, row), mesh8) == "row"
        # one replicated operand: XLA computes on the other's layout
        assert planner.infer_layout(
            elemwise("add", row, rep), mesh8) == "row"
        # disagreeing layouts: conservative 2d
        assert planner.infer_layout(
            elemwise("add", row, two_d), mesh8) == "2d"

    def test_agg_layouts(self, mesh8):
        from jax.sharding import PartitionSpec as P
        from matrel_tpu.ir.expr import agg
        row = _fab(mesh8, 64, 64, spec=P(("x", "y"), None))
        assert planner.infer_layout(agg(row, "sum", "all"), mesh8) == "rep"
        assert planner.infer_layout(agg(row, "sum", "row"), mesh8) == "row"
        assert planner.infer_layout(agg(row, "sum", "col"), mesh8) == "2d"

    def test_align_join_layout(self, mesh8):
        from matrel_tpu.relational import ops as R
        a = BlockMatrix.random((64, 8), mesh=mesh8, seed=0)
        b = BlockMatrix.random((64, 8), mesh=mesh8, seed=1)
        je = R.join_on_rows(a, b, "mul").with_attrs(replicate="align")
        assert planner.infer_layout(je, mesh8) == "row"
        jl = R.join_on_rows(a, b, "mul").with_attrs(replicate="left")
        # left replicated -> output inherits the kept (right) side: 2d
        assert planner.infer_layout(jl, mesh8) == "2d"


class TestInteriorLayoutCredit:
    """The round-5 flip tests: a producer's output layout changes its
    consumer's pick (chain interior) and a join consumes a bmm output's
    layout in place."""

    # shapes tuned for the (2,4) grid: with the producer's output
    # assumed canonical-2D the model picks cpmm/rmm for the outer
    # multiply (bmm_right pays an extra a/8 * 3/4 reshard); with the
    # producer KNOWN row-sharded that reshard is free and bmm_right
    # wins (7b/8 = 0.875 MB vs 0.969 MB for cpmm/rmm at these dims)
    N, K, M = 1152, 512, 512

    def test_interior_pick_flips_on_producer_layout(self, mesh8):
        inner = matmul(_fab(mesh8, self.N, self.K),
                       _fab(mesh8, self.K, self.K))
        outer_ctl = matmul(inner.with_attrs(strategy="rmm"),
                           _fab(mesh8, self.K, self.M))
        outer_row = matmul(inner.with_attrs(strategy="bmm_right"),
                           _fab(mesh8, self.K, self.M))
        ctl = planner.choose_strategy(outer_ctl, mesh8)
        got = planner.choose_strategy(outer_row, mesh8)
        assert ctl in ("cpmm", "rmm"), ctl
        assert got == "bmm_right", got

    def test_end_to_end_chain_credit(self, mesh8):
        # no planted strategies: A row-sharded makes the inner multiply
        # bmm_right naturally, and its row-sharded OUTPUT then flips the
        # outer multiply to bmm_right too — the credit firing on an
        # interior node through annotate_strategies. N2 puts k/n in the
        # band where bmm survives the ROOT canonical-output reshard
        # charge too (1/4 < k/n < 3/8 on the (2,4) grid)
        from jax.sharding import PartitionSpec as P
        N2 = 1600
        a = _fab(mesh8, N2, self.K, spec=P(("x", "y"), None))
        chain = matmul(matmul(a, _fab(mesh8, self.K, self.K)),
                       _fab(mesh8, self.K, self.M))
        ann = planner.annotate_strategies(chain, mesh8)
        assert ann.children[0].attrs["strategy"] == "bmm_right"
        assert ann.attrs["strategy"] == "bmm_right"

    def test_join_consumes_interior_bmm_output(self, mesh8):
        # join_rows(bmm_right output, small 2d): with the producer
        # assumed 2D the align scheme pays to re-lay BOTH operands and
        # replicating the small side wins; with the producer KNOWN
        # row-sharded its reshard term is zero and align wins
        from matrel_tpu.relational import ops as R
        inner = matmul(_fab(mesh8, self.N, self.K),
                       _fab(mesh8, self.K, self.K))
        other = _fab(mesh8, self.N, 32)
        j_ctl = R.join_on_rows(inner.with_attrs(strategy="rmm"), other,
                               "mul")
        j_row = R.join_on_rows(inner.with_attrs(strategy="bmm_right"),
                               other, "mul")
        assert planner.choose_join_scheme(j_ctl, mesh8) == "right"
        assert planner.choose_join_scheme(j_row, mesh8) == "align"


class TestConsumerAwareJoinTiebreak:
    """VERDICT r4 #7: among near-tie schemes, prefer the one whose
    output layout the PARENT consumes in place."""

    def test_matmul_parent_flips_zero_cost_tie_to_align(self, mesh8):
        # both operands replicated: left/right/align all cost 0. A
        # standalone join resolves the tie to "left" (argmin order);
        # under a matmul parent the hint ("row" for its left operand)
        # picks align, whose row-sharded output bmm_right consumes free
        from jax.sharding import PartitionSpec as P
        from matrel_tpu.relational import ops as R
        a = _fab(mesh8, 64, 8, spec=P(None, None))
        b = _fab(mesh8, 64, 4, spec=P(None, None))
        je = R.join_on_rows(a, b, "mul")
        standalone = planner.annotate_strategies(je, mesh8)
        assert standalone.attrs["replicate"] == "left"
        consumed = planner.annotate_strategies(
            matmul(R.join_on_rows(a, b, "mul"), _fab(mesh8, 32, 16)),
            mesh8)
        assert consumed.children[0].attrs["replicate"] == "align"

    def test_hint_never_overrides_clear_winner(self, mesh8):
        # a >10% cost gap must ignore the hint: big 2d left operand vs
        # tiny right — replicating the tiny side wins outright even
        # under a matmul parent
        from matrel_tpu.relational import ops as R
        big = _fab(mesh8, 4096, 512)
        tiny = _fab(mesh8, 4096, 1, spec=None)
        node = matmul(R.join_on_rows(big, tiny, "mul"),
                      _fab(mesh8, 512, 16))
        ann = planner.annotate_strategies(node, mesh8)
        assert ann.children[0].attrs["replicate"] == "right"


class TestAutotuneLayoutGate:
    """VERDICT r4 "what's missing" #3: the measured table is consulted
    only for canonically-2D operands — the layouts it measures. A
    non-2D operand falls back to the byte model's per-layout credit."""

    def _planted(self, mesh, tmp_path, node):
        import json
        from matrel_tpu.parallel import autotune
        from matrel_tpu.core import mesh as mesh_lib
        gx, gy = mesh_lib.mesh_grid_shape(mesh)
        path = str(tmp_path / "tuned.json")
        json.dump({autotune._table_key(64, gx, gy, "float32"):
                   {"best": "rmm", "times": {"rmm": 1e-6}}},
                  open(path, "w"))
        autotune._CACHE.clear()
        cfg = MatrelConfig(autotune=True, autotune_table_path=path)
        return planner.choose_strategy_ex(node, mesh, cfg)

    def test_2d_operands_consult_table(self, mesh8, tmp_path):
        node = matmul(_fab(mesh8, 64, 64), _fab(mesh8, 64, 64))
        strat, source = self._planted(mesh8, tmp_path, node)
        assert (strat, source) == ("rmm", "measured")

    def test_row_sharded_operand_skips_table(self, mesh8, tmp_path):
        from jax.sharding import PartitionSpec as P
        node = matmul(_fab(mesh8, 64, 64, spec=P(("x", "y"), None)),
                      _fab(mesh8, 64, 64))
        _, source = self._planted(mesh8, tmp_path, node)
        assert source == "model"

    def test_interior_bmm_output_skips_table(self, mesh8, tmp_path):
        inner = matmul(_fab(mesh8, 64, 64),
                       _fab(mesh8, 64, 64)).with_attrs(
                           strategy="bmm_right")
        node = matmul(inner, _fab(mesh8, 64, 64))
        _, source = self._planted(mesh8, tmp_path, node)
        assert source == "model"


def test_explain_prints_interior_layouts(mesh8):
    # observability: the physical EXPLAIN shows infer_layout's verdicts
    # next to the strategy provenance they drive
    from jax.sharding import PartitionSpec as P
    import dataclasses
    rng = np.random.default_rng(7)
    a = BlockMatrix.from_numpy(
        rng.standard_normal((64, 16)).astype(np.float32), mesh=mesh8,
        spec=P(("x", "y"), None))
    b = BlockMatrix.from_numpy(
        rng.standard_normal((16, 16)).astype(np.float32), mesh=mesh8)
    node = matmul(leaf(a), leaf(b)).with_attrs(strategy="bmm_right",
                                               strategy_source="model")
    plan = executor.compile_expr(node, mesh8)
    text = plan.explain()
    assert "layout=row" in text          # the row-sharded leaf AND the
    assert "strategy=bmm_right" in text  # bmm output both annotated


def test_infer_layout_matches_compiled_output_shardings(mesh8):
    # the ground-truth pin for infer_layout's matmul rule: classify the
    # REAL compiled output sharding of every strategy and compare with
    # the planner's claim (summa needs a square grid — covered by the
    # mapping test at the out_specs level)
    a = BlockMatrix.random((16, 16), mesh=mesh8, seed=0)
    b = BlockMatrix.random((16, 16), mesh=mesh8, seed=1)
    node = matmul(leaf(a), leaf(b))

    def classify(spec):
        row = spec[0] if len(spec) > 0 else None
        col = spec[1] if len(spec) > 1 else None
        flat = ("x", "y")
        if row in (flat, ("y", "x")) and col is None:
            return "row"
        if row is None and col in (flat, ("y", "x")):
            return "col"
        if row is None and col is None:
            return "rep"
        return "2d"

    for s in strategies.STRATEGIES:
        if s == "summa":
            continue
        f = jax.jit(lambda x, y, s=s: strategies.run_matmul(
            s, x, y, mesh8, None))
        (out,) = f.lower(a.data, b.data).compile().output_shardings,
        got = classify(out.spec)
        want = planner.infer_layout(node.with_attrs(strategy=s), mesh8)
        assert got == want, (s, out.spec, want)


class TestLayoutOtherAndCooRep:
    """Review r5 follow-ups: partial shardings classify as "other" (real
    placements the autotune table never measured), and the COO matmul's
    "rep" claim holds only where the lowering pins it."""

    def test_partial_sharding_is_other_not_2d(self, mesh8):
        from jax.sharding import PartitionSpec as P
        # P(x, None) on a matrix whose canonical spec is P(x, y): a real
        # non-canonical placement
        n = _fab(mesh8, 64, 64, spec=P("x", None))
        assert planner.infer_layout(n, mesh8) == "other"
        # but P(x, None) IS canonical for a column vector — still "2d"
        v = _fab(mesh8, 64, 1, spec=P("x", None))
        assert planner.infer_layout(v, mesh8) == "2d"

    def test_other_layout_skips_measured_winner(self, mesh8, tmp_path):
        import json
        from jax.sharding import PartitionSpec as P
        from matrel_tpu.parallel import autotune
        path = str(tmp_path / "tuned.json")
        json.dump({autotune._table_key(64, 2, 4, "float32"):
                   {"best": "rmm", "times": {"rmm": 1e-6}}},
                  open(path, "w"))
        autotune._CACHE.clear()
        cfg = MatrelConfig(autotune=True, autotune_table_path=path)
        node = matmul(_fab(mesh8, 64, 64, spec=P("x", None)),
                      _fab(mesh8, 64, 64))
        _, source = planner.choose_strategy_ex(node, mesh8, cfg)
        assert source == "model"

    def test_coo_rep_only_where_pinned(self, mesh8):
        from matrel_tpu.core.coo import COOMatrix
        rng = np.random.default_rng(0)
        A = COOMatrix.from_edges(rng.integers(0, 64, 100),
                                 rng.integers(0, 64, 100), shape=(64, 64))
        x = BlockMatrix.from_numpy(
            rng.standard_normal((64, 2)).astype(np.float32), mesh=mesh8)
        e = A.multiply(x.expr())
        # pallas interpret on: the compact sharded path (out_specs=P())
        # really runs -> "rep"
        cfg_p = MatrelConfig(pallas_interpret=True)
        assert planner.infer_layout(e, mesh8, config=cfg_p) == "rep"
        # pallas off on a multi-device mesh: expanded XLA path, GSPMD
        # decides -> no replication claim
        cfg_np = MatrelConfig(use_pallas=False)
        assert planner.infer_layout(e, mesh8, config=cfg_np) == "2d"
        # autotune on: a measured "expanded" winner could reroute the
        # dispatch onto the GSPMD-decided XLA path -> no claim either
        cfg_at = MatrelConfig(pallas_interpret=True, autotune=True)
        assert planner.infer_layout(e, mesh8, config=cfg_at) == "2d"


class TestRootOutputReshardTerm:
    """Round 5: the executor re-lays ROOT outputs to the canonical
    sharding (Lowerer.lower_multi), so a root-level bmm pays a
    row/col->2d move the interior never does. The model charges it for
    the root only."""

    def test_root_pick_flips_away_from_bmm(self, mesh8):
        # k/n = 0.32 on the (2,4) grid: bmm_right wins as an interior
        # (7b/8 + 3a/32 beats rmm/cpmm) but the extra 3c/32 root charge
        # flips the ROOT pick to a 2d-emitting strategy
        node = matmul(_fab(mesh8, 1600, 512), _fab(mesh8, 512, 512))
        interior, _ = planner.choose_strategy_ex(node, mesh8)
        root, _ = planner.choose_strategy_ex(node, mesh8,
                                             root_output=True)
        assert interior == "bmm_right", interior
        assert root in ("rmm", "cpmm"), root

    def test_rootness_flows_through_entrywise_wrappers(self, mesh8):
        # a scalar wrapper does NOT shield the multiply from the root
        # charge (the canonical constraint re-lays the scalar's output,
        # whose layout is the multiply's); a consuming MATMUL does —
        # its own cost model sees the producer's layout instead
        from matrel_tpu.ir.expr import scalar_op
        inner = matmul(_fab(mesh8, 1600, 512), _fab(mesh8, 512, 512))
        wrapped = planner.annotate_strategies(
            scalar_op("mul", inner, 2.0), mesh8)
        assert wrapped.children[0].attrs["strategy"] in ("rmm", "cpmm")
        chain = planner.annotate_strategies(
            matmul(matmul(_fab(mesh8, 1600, 512),
                          _fab(mesh8, 512, 512)),
                   _fab(mesh8, 512, 64)), mesh8)
        assert chain.children[0].attrs["strategy"] == "bmm_right"


class TestReviewR5FollowUps:
    """Third review pass: plan-refusal honoured in the COO layout
    claim, transpose-swapped root charge, config-faithful EXPLAIN."""

    def test_coo_plan_refusal_drops_rep_claim(self, mesh8, monkeypatch):
        from matrel_tpu import executor as ex
        from matrel_tpu.core.coo import COOMatrix
        rng = np.random.default_rng(0)
        A = COOMatrix.from_edges(rng.integers(0, 64, 100),
                                 rng.integers(0, 64, 100), shape=(64, 64))
        x = BlockMatrix.from_numpy(
            rng.standard_normal((64, 2)).astype(np.float32), mesh=mesh8)
        e = A.multiply(x.expr())
        cfg = MatrelConfig(pallas_interpret=True)
        assert planner.infer_layout(e, mesh8, config=cfg) == "rep"
        # the executor refusing the plan (densify fallback, 2d output)
        # must drop the replication claim — the predicate is shared
        monkeypatch.setattr(ex, "_coo_dispatch_plan", lambda n: None)
        assert planner.infer_layout(e, mesh8, config=cfg) == "2d"

    def test_transpose_swaps_root_charge_axis(self, mesh8):
        # k/n = 512/1896 = 0.27 on the (2,4) grid: the row->2d re-lay
        # (factor 3/4) sinks bmm at a bare root, but under a root
        # TRANSPOSE the output arrives col-sharded and re-lays along
        # the cheaper axis (factor 1/2) — bmm survives
        from matrel_tpu.ir.expr import transpose
        bare = planner.annotate_strategies(
            matmul(_fab(mesh8, 1896, 512), _fab(mesh8, 512, 512)),
            mesh8)
        assert bare.attrs["strategy"] in ("rmm", "cpmm")
        under_t = planner.annotate_strategies(
            transpose(matmul(_fab(mesh8, 1896, 512),
                             _fab(mesh8, 512, 512))), mesh8)
        assert under_t.children[0].attrs["strategy"] == "bmm_right"

    def test_explain_uses_plan_config_for_layouts(self, mesh8):
        from matrel_tpu.core.coo import COOMatrix
        rng = np.random.default_rng(1)
        A = COOMatrix.from_edges(rng.integers(0, 64, 100),
                                 rng.integers(0, 64, 100), shape=(64, 64))
        x = BlockMatrix.from_numpy(
            rng.standard_normal((64, 2)).astype(np.float32), mesh=mesh8)
        cfg = MatrelConfig(pallas_interpret=True)
        plan = executor.compile_expr(A.multiply(x.expr()), mesh8, cfg)
        # the plan's config claims "rep" (compact sharded path); the
        # DEFAULT config on this CPU backend would claim nothing —
        # explain must print the planner's view, not default_config's
        assert "layout=rep" in plan.explain()


class TestSymmetricLayoutTerms:
    """Round 5: every comm_cost branch reads operand layouts, not just
    the bmm ones — a replicated operand gathers for free under rmm/cpmm
    too, and a 1D-sharded operand pays its way back to the 2D tiling
    cpmm consumes."""

    def test_replicated_A_flips_cpmm_to_rmm(self, mesh8):
        # big replicated A (over the bcast threshold, so bmm_left is
        # out), k > m: rmm's A-gather is now free and beats cpmm's
        # C reduce-scatter; with the old layout-blind rmm term cpmm won
        from jax.sharding import PartitionSpec as P
        cfg = MatrelConfig(broadcast_threshold_bytes=1024)
        a_rep = _fab(mesh8, 4096, 4096, spec=P(None, None))
        b = _fab(mesh8, 4096, 1024)
        got = planner.choose_strategy(matmul(a_rep, b), mesh8, cfg)
        assert got == "rmm", got
        ctl = planner.choose_strategy(
            matmul(_fab(mesh8, 4096, 4096), b), mesh8, cfg)
        assert ctl == "cpmm", ctl

    def test_row_sharded_A_charges_cpmm_relay(self, mesh8):
        # 3a/4 < c < a band on the (2,4) grid: cpmm wins for 2D A, but
        # a row-sharded A must pay its re-lay to P(x, y) and rmm takes
        # over (bmm excluded by the threshold)
        from jax.sharding import PartitionSpec as P
        cfg = MatrelConfig(broadcast_threshold_bytes=1024)
        b = _fab(mesh8, 1024, 896)
        ctl = planner.choose_strategy(
            matmul(_fab(mesh8, 8192, 1024), b), mesh8, cfg)
        assert ctl == "cpmm", ctl
        got = planner.choose_strategy(
            matmul(_fab(mesh8, 8192, 1024, spec=P(("x", "y"), None)), b),
            mesh8, cfg)
        assert got == "rmm", got


class TestConsumerAwareStrategyTiebreak:
    """The matmul analogue of the join-scheme tiebreak (round 5): a
    near-tied strategy pick flips toward the output layout the parent
    consumes in place."""

    def _inner(self, mesh, m):
        # (2048x512)·(512xm) on the (2,4) grid: at m=800 rmm beats
        # bmm_right by ~4% (within the tie band); at m=1024 by ~21%
        return matmul(_fab(mesh, 2048, 512), _fab(mesh, 512, m))

    def test_left_child_hint_flips_to_bmm_right(self, mesh8):
        standalone, _ = planner.choose_strategy_ex(self._inner(mesh8,
                                                               800),
                                                   mesh8)
        assert standalone == "rmm", standalone
        ann = planner.annotate_strategies(
            matmul(self._inner(mesh8, 800), _fab(mesh8, 800, 64)),
            mesh8)
        assert ann.children[0].attrs["strategy"] == "bmm_right"

    def test_hint_never_overrides_clear_winner(self, mesh8):
        ann = planner.annotate_strategies(
            matmul(self._inner(mesh8, 1024), _fab(mesh8, 1024, 64)),
            mesh8)
        assert ann.children[0].attrs["strategy"] == "rmm"


def test_hint_gated_by_parent_bmm_admissibility(mesh8):
    # review r5: a parent whose broadcast side exceeds the threshold
    # can never run the bmm that would consume the hinted layout — no
    # hint is emitted, so a near-tied child keeps its cheapest pick
    cfg = MatrelConfig(broadcast_threshold_bytes=1024)
    inner = matmul(_fab(mesh8, 2048, 512), _fab(mesh8, 512, 800))
    ann = planner.annotate_strategies(
        matmul(inner, _fab(mesh8, 800, 800)), mesh8, cfg)
    assert ann.children[0].attrs["strategy"] == "rmm"


def test_measured_bmm_winner_not_applied_at_root(mesh8, tmp_path):
    # review r5: autotune probes never pay the root canonical-output
    # re-lay, so a measured 1D-emitting winner doesn't cover the root
    # context — the model (which charges _root_reshard_cost) decides;
    # a 2d-emitting measured winner still applies at the root
    import json
    from matrel_tpu.parallel import autotune
    node = matmul(_fab(mesh8, 64, 64), _fab(mesh8, 64, 64))
    for planted, want_src in (("bmm_right", "model"), ("rmm", "measured")):
        path = str(tmp_path / f"t_{planted}.json")
        json.dump({autotune._table_key(64, 2, 4, "float32"):
                   {"best": planted, "times": {planted: 1e-6}}},
                  open(path, "w"))
        autotune._CACHE.clear()
        cfg = MatrelConfig(autotune=True, autotune_table_path=path)
        _, src = planner.choose_strategy_ex(node, mesh8, cfg,
                                            root_output=True)
        assert src == want_src, (planted, src)
        _, src_int = planner.choose_strategy_ex(node, mesh8, cfg)
        assert src_int == "measured", planted   # interior: always applies


def test_no_hint_for_sparse_dispatch_parents(mesh8):
    # review r5: a parent matmul dispatching the COO SpMV path cannot
    # consume any hinted layout — no hint reaches its children
    from matrel_tpu.core.coo import COOMatrix
    rng = np.random.default_rng(0)
    A = COOMatrix.from_edges(rng.integers(0, 64, 100),
                             rng.integers(0, 64, 100), shape=(64, 64))
    parent = A.multiply(matmul(_fab(mesh8, 64, 32), _fab(mesh8, 32, 2)))
    assert planner._child_layout_hints(parent) == (None, None)
    dense = matmul(_fab(mesh8, 64, 64), _fab(mesh8, 64, 2))
    assert planner._child_layout_hints(dense) == ("row", "col")


def test_planner_works_with_custom_axis_names(rng):
    # robustness: nothing in the layout machinery may assume the
    # default ("x", "y") axis names — infer_layout, the strategies'
    # shard_map specs and the align lowering all read mesh.axis_names
    import jax
    from jax.sharding import PartitionSpec as P
    from matrel_tpu.core import mesh as mesh_lib
    mesh = mesh_lib.make_mesh((2, 4), axis_names=("rows", "cols"))
    a = rng.standard_normal((64, 32)).astype(np.float32)
    b = rng.standard_normal((32, 16)).astype(np.float32)
    A = BlockMatrix.from_numpy(a, mesh=mesh,
                               spec=P(("rows", "cols"), None))
    B = BlockMatrix.from_numpy(b, mesh=mesh)
    node = matmul(leaf(A), leaf(B))
    assert planner.infer_layout(node.children[0], mesh) == "row"
    ann = planner.annotate_strategies(node, mesh)
    assert "strategy" in ann.attrs
    plan = executor.compile_expr(node, mesh)
    np.testing.assert_allclose(plan.run().to_numpy(), a @ b,
                               rtol=1e-4, atol=1e-4)


# -- topology-weighted comm model (round 7) ---------------------------------


def _legacy_comm_cost(strategy, n, k, m, da, db, gx, gy, itemsize=4,
                      a_layout="2d", b_layout="2d", alpha_bytes=0.0):
    """VERBATIM copy of the pre-topology flat comm_cost — the round-7
    acceptance oracle: weights (1.0, 1.0) must reproduce these floats
    bit for bit (same closed forms, same summation order)."""
    def _b(shape, density, isz=4):
        return shape[0] * shape[1] * isz * max(density, 0.0)

    def _to2d(bytes_, layout):
        p_ = max(gx * gy, 1)
        if layout == "rep":
            return 0.0
        if layout == "row":
            return (bytes_ / p_) * (1 - 1 / gy)
        if layout == "col":
            return (bytes_ / p_) * (1 - 1 / gx)
        return 0.0

    a_bytes = _b((n, k), da, itemsize)
    b_bytes = _b((k, m), db, itemsize)
    c_bytes = _b((n, m), 1.0, itemsize)
    p = gx * gy

    def total(*terms, extra_steps=0):
        steps = sum(1 for t in terms if t > 0.0) + extra_steps
        return sum(terms) + alpha_bytes * steps

    if strategy == "bmm_right":
        bcast = 0.0 if b_layout == "rep" else b_bytes * (p - 1) / p
        reshard_a = (0.0 if a_layout in ("row", "rep")
                     else (a_bytes / p) * (1 - 1 / gy))
        return total(bcast, reshard_a)
    if strategy == "bmm_left":
        bcast = 0.0 if a_layout == "rep" else a_bytes * (p - 1) / p
        reshard_b = (0.0 if b_layout in ("col", "rep")
                     else (b_bytes / p) * (1 - 1 / gx))
        return total(bcast, reshard_b)
    if strategy == "cpmm":
        reshard_a = _to2d(a_bytes, a_layout)
        reshard_b = (0.0 if b_layout == "rep"
                     else (b_bytes / gy) * (gx - 1) / gx)
        rs_c = (c_bytes / gx) * (gy - 1) / gy
        return total(reshard_a, reshard_b, rs_c)
    if strategy in ("rmm", "xla"):
        ag_a = (0.0 if a_layout == "rep"
                else (a_bytes / gx) * (gy - 1) / gy)
        ag_b = (0.0 if b_layout == "rep"
                else (b_bytes / gy) * (gx - 1) / gx)
        return total(ag_a, ag_b)
    if strategy == "summa":
        g = max(gx, gy)
        ring = (a_bytes / p + b_bytes / p) * (g - 1)
        return ring + total(_to2d(a_bytes, a_layout),
                            _to2d(b_bytes, b_layout),
                            extra_steps=2 * (g - 1))
    if strategy == "spgemm":
        return 0.0
    raise ValueError(strategy)


class TestTopologyWeightedModel:
    """Round 7: per-axis inverse-bandwidth weights (core/mesh.
    MeshTopology) thread through every costing path — default weights
    are bit-identical to the flat model, non-uniform weights bill each
    collective leg on the axis it rides."""

    def test_default_weights_bit_identical_across_vocabulary(self):
        # the round-7 acceptance oracle: comm_cost at (1.0, 1.0) ==
        # the pre-topology flat model, EXACTLY, for every strategy x
        # shape x layout x grid x alpha on a grid of shapes
        rng = np.random.default_rng(23)
        layouts = ("2d", "row", "col", "rep", "other")
        for _ in range(50):
            n, k, m = (int(rng.integers(1, 3000)) for _ in range(3))
            da = float(rng.choice([1.0, 1.0, 0.3, 0.02]))
            db = float(rng.choice([1.0, 1.0, 0.3, 0.02]))
            gx, gy = [int(v) for v in
                      rng.choice([(1, 8), (8, 1), (2, 4), (4, 2),
                                  (2, 2), (4, 4)])]
            la = str(rng.choice(layouts))
            lb = str(rng.choice(layouts))
            al = float(rng.choice([0.0, 200_000.0]))
            for s in ("bmm_right", "bmm_left", "cpmm", "rmm", "xla",
                      "summa", "spgemm"):
                want = _legacy_comm_cost(s, n, k, m, da, db, gx, gy,
                                         a_layout=la, b_layout=lb,
                                         alpha_bytes=al)
                got = planner.comm_cost(s, n, k, m, da, db, gx, gy,
                                        a_layout=la, b_layout=lb,
                                        alpha_bytes=al,
                                        weights=(1.0, 1.0))
                assert got == want, (s, n, k, m, la, lb, gx, gy, al)

    def test_axes_decomposition_sums_to_flat_bill(self):
        # per-axis bytes are a DECOMPOSITION of the flat bill, not a
        # second model: x + y must equal the alpha-free flat cost
        rng = np.random.default_rng(29)
        for _ in range(30):
            n, k, m = (int(rng.integers(1, 2000)) for _ in range(3))
            gx, gy = [int(v) for v in
                      rng.choice([(2, 4), (4, 2), (2, 2), (1, 8)])]
            la = str(rng.choice(("2d", "row", "col", "rep")))
            lb = str(rng.choice(("2d", "row", "col", "rep")))
            for s in ("bmm_right", "bmm_left", "cpmm", "rmm", "summa"):
                flat = planner.comm_cost(s, n, k, m, 1.0, 1.0, gx, gy,
                                         a_layout=la, b_layout=lb)
                bx, by = planner.comm_cost_axes(
                    s, n, k, m, 1.0, 1.0, gx, gy,
                    a_layout=la, b_layout=lb)
                assert bx + by == pytest.approx(flat, rel=1e-12), \
                    (s, la, lb, gx, gy)

    def test_weighted_cost_is_weighted_sum_of_axes(self):
        # with alpha 0 the weighted scalar is exactly wx*x + wy*y of
        # the recorded decomposition — the auditability contract
        wts = (3.0, 5.0)
        for s in ("bmm_right", "bmm_left", "cpmm", "rmm", "summa"):
            gx, gy = (2, 2) if s == "summa" else (2, 4)
            cw = planner.comm_cost(s, 512, 128, 256, 1.0, 1.0, gx, gy,
                                   weights=wts)
            bx, by = planner.comm_cost_axes(s, 512, 128, 256, 1.0, 1.0,
                                            gx, gy, weights=wts)
            assert cw == pytest.approx(wts[0] * bx + wts[1] * by,
                                       rel=1e-12), s

    def test_alpha_steps_weighted_per_axis(self):
        # rmm pays one y-gather step at wy and one x-gather step at wx
        al = 1e6
        base = planner.comm_cost("rmm", 512, 512, 512, 1.0, 1.0, 2, 4,
                                 weights=(3.0, 5.0))
        got = planner.comm_cost("rmm", 512, 512, 512, 1.0, 1.0, 2, 4,
                                alpha_bytes=al, weights=(3.0, 5.0))
        assert got == pytest.approx(base + al * (3.0 + 5.0))

    def test_strategy_flip_avoids_slow_axis(self, mesh8):
        # THE acceptance flip (VERDICT Next #4 "done when"): in the
        # 3a/8 < b < 3a/4 band on the (2,4) grid the beta-only argmin
        # is rmm, whose A all-gather rides y; pricing y 8x (the DCN
        # axis) provably routes to bmm_right, whose broadcast's
        # expensive stage stays on x
        node = matmul(_fab(mesh8, 8192, 2048), _fab(mesh8, 2048, 4096))
        flat, src0 = planner.choose_strategy_ex(node, mesh8,
                                                MatrelConfig())
        assert (flat, src0) == ("rmm", "model")
        cfg_w = MatrelConfig(axis_cost_weights=(1.0, 8.0))
        weighted, srcw = planner.choose_strategy_ex(node, mesh8, cfg_w)
        assert (weighted, srcw) == ("bmm_right", "model")
        # and the flip is the slow axis's doing: rmm really is y-heavy
        bx, by = planner.comm_cost_axes("rmm", 8192, 2048, 4096,
                                        1.0, 1.0, 2, 4)
        assert by > 5 * bx

    def test_weighted_join_scheme_avoids_slow_broadcast(self, mesh8):
        # join analogue: replicate schemes all-gather over the whole
        # mesh (their big stage rides one axis); weighting can flip a
        # broadcast win to align. Similar-sized operands on (2,4):
        # align already wins flat (stage-11 dryrun); shrink b so
        # "right" wins flat, then weight y to flip it back to align,
        # whose row-reshards ride only y at 1/p the volume
        from matrel_tpu.relational import ops as R
        e = R.join_on_rows(_fab(mesh8, 1024, 512),
                           _fab(mesh8, 1024, 96), "mul")
        flat = planner.choose_join_scheme(e, mesh8, MatrelConfig())
        w = planner.choose_join_scheme(
            e, mesh8, MatrelConfig(axis_cost_weights=(1.0, 64.0)))
        # the weighted pick never moves MORE weighted bytes than the
        # flat pick would under the weighted model
        def wcost(scheme):
            gx, gy = 2, 4
            wts = (1.0, 64.0)
            ab = planner._bytes((1024, 512), 1.0)
            bb = planner._bytes((1024, 96), 1.0)
            if scheme == "left":
                return planner._split_full_mesh(ab, gx, gy, *wts)[0]
            if scheme == "right":
                return planner._split_full_mesh(bb, gx, gy, *wts)[0]
            return (planner._reshard_to_axis(ab, "2d", "row", gx, gy,
                                             weights=wts)
                    + planner._reshard_to_axis(bb, "2d", "row", gx, gy,
                                               weights=wts))
        assert wcost(w) <= wcost(flat)

    def test_mesh_topology_resolution(self, mesh8):
        from matrel_tpu.core import mesh as mesh_lib
        topo = mesh_lib.mesh_topology(mesh8, MatrelConfig())
        assert topo.axis_weights == (1.0, 1.0)
        assert topo.source == "default" and topo.uniform
        topo_c = mesh_lib.mesh_topology(
            mesh8, MatrelConfig(axis_cost_weights=(1.0, 8.0)))
        assert topo_c.axis_weights == (1.0, 8.0)
        assert topo_c.source == "config" and not topo_c.uniform
        # CPU devices expose no slice_index: detection must stay flat
        assert mesh_lib.detect_slice_axes(mesh8) == (False, False)

    def test_slice_detection_on_fake_multislice(self):
        # detection only reads mesh.devices — drive it with fake
        # slice-indexed device objects (a 2-slice (2,4) mesh laid out
        # slice-per-row: the x axis crosses DCN, y stays in-slice)
        import types
        from matrel_tpu.core import mesh as mesh_lib

        def dev(s):
            return types.SimpleNamespace(slice_index=s)

        two_slice = types.SimpleNamespace(
            devices=[[dev(0)] * 4, [dev(1)] * 4])
        assert mesh_lib.detect_slice_axes(two_slice) == (True, False)
        topo = mesh_lib.mesh_topology(two_slice, MatrelConfig())
        assert topo.source == "detected"
        assert topo.axis_weights == (mesh_lib.DCN_AXIS_WEIGHT, 1.0)
        # explicit config stays the calibration override
        topo_c = mesh_lib.mesh_topology(
            two_slice, MatrelConfig(axis_cost_weights=(16.0, 1.0)))
        assert (topo_c.source, topo_c.axis_weights) == ("config",
                                                        (16.0, 1.0))
        # single-slice: homogeneous however the ids read
        one = types.SimpleNamespace(devices=[[dev(0)] * 4] * 2)
        assert mesh_lib.detect_slice_axes(one) == (False, False)

    def test_matmul_decisions_record_axis_bytes(self, mesh8):
        cfg = MatrelConfig(axis_cost_weights=(1.0, 8.0))
        ann = planner.annotate_strategies(
            matmul(_fab(mesh8, 512, 128), _fab(mesh8, 128, 256)),
            mesh8, cfg)
        (rec,) = planner.matmul_decisions(ann, mesh8, cfg)
        assert len(rec["est_axis_bytes"]) == 2
        assert all(v >= 0 for v in rec["est_axis_bytes"])
        assert rec["axis_weights"] == [1.0, 8.0]
        assert rec["topology_source"] == "config"
        # unit discipline (review r7): est_ici_bytes stays RAW bytes
        # (flat weights — the unit history sums as MiB, comparable
        # across sessions); the weighted ranking quantity is its own
        # field. With alpha excluded the axes sum to the raw bill.
        flat_beta = planner.comm_cost(rec["strategy"], 512, 128, 256,
                                      1.0, 1.0, 2, 4,
                                      a_layout=rec["layouts"][0],
                                      b_layout=rec["layouts"][1])
        assert sum(rec["est_axis_bytes"]) == pytest.approx(flat_beta,
                                                           rel=1e-12)
        assert rec["est_weighted_cost"] > rec["est_ici_bytes"]
        # uniform mesh: decomposition recorded, weight fields omitted
        (rec0,) = planner.matmul_decisions(ann, mesh8, MatrelConfig())
        assert "axis_weights" not in rec0
        assert "est_weighted_cost" not in rec0
        assert "est_axis_bytes" in rec0
        assert rec0["est_ici_bytes"] == rec["est_ici_bytes"]

    def test_weighted_plan_cache_key_never_collides(self, mesh8):
        from matrel_tpu.session import MatrelSession
        a = BlockMatrix.from_numpy(
            np.random.default_rng(0).standard_normal(
                (64, 64)).astype(np.float32), mesh=mesh8)
        e = a.expr().multiply(a.expr())
        s0 = MatrelSession(mesh=mesh8, config=MatrelConfig())
        sw = MatrelSession(mesh=mesh8, config=MatrelConfig(
            axis_cost_weights=(1.0, 8.0)))
        _, _, k0 = s0._compile_entry(e)
        _, _, kw = sw._compile_entry(e)
        assert k0 != kw and kw.startswith("axisw:1x8|")
