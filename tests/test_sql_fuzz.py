"""SQL conformance fuzz: random queries generated FROM the documented
grammar, rendered to SQL text, parsed by sql.py, executed, and checked
against a numpy oracle built alongside the text. Catches drift between
the docstring grammar, the parser, and the executor."""

import numpy as np
import pytest

from matrel_tpu.session import MatrelSession


N = 6


def _gen(rng, env, depth):
    """Returns (sql_text, numpy_value) for an N x N expression."""
    if depth <= 0 or rng.random() < 0.35:
        name = str(rng.choice(list(env)))
        return name, env[name]
    op = str(rng.choice(["mm", "em", "em_pct", "add", "sub", "div",
                         "smul", "sadd", "t", "sel", "selrows",
                         "power", "joinidx", "emin", "emax"]))
    a_s, a_v = _gen(rng, env, depth - 1)
    if op == "t":
        return f"transpose({a_s})", a_v.T
    if op == "smul":
        c = round(float(rng.uniform(-2, 2)), 3)
        return f"{c} * ({a_s})", np.float32(c) * a_v
    if op == "sadd":
        c = round(float(rng.uniform(-2, 2)), 3)
        return f"({a_s}) + {c}", a_v + np.float32(c)
    if op == "power":
        return f"power({a_s}, 2)", a_v.astype(np.float64) ** 2
    if op == "sel":
        t = round(float(rng.uniform(-0.5, 0.5)), 3)
        return (f"select({a_s}, 'v > {t}')",
                np.where(a_v > t, a_v, 0.0))
    if op == "selrows":
        m = int(rng.integers(2, 4))
        out = a_v.copy()
        out[np.arange(N) % m == 0, :] = 0
        return f"selectrows({a_s}, 'i % {m} != 0')", out
    b_s, b_v = _gen(rng, env, depth - 1)
    if op == "mm":
        return f"({a_s}) * ({b_s})", a_v @ b_v
    if op == "em":
        return f"elemmult({a_s}, {b_s})", a_v * b_v
    if op == "emin":
        return f"elemmin({a_s}, {b_s})", np.minimum(a_v, b_v)
    if op == "emax":
        return f"elemmax({a_s}, {b_s})", np.maximum(a_v, b_v)
    if op == "em_pct":
        return f"({a_s}) % ({b_s})", a_v * b_v
    if op == "add":
        return f"({a_s}) + ({b_s})", a_v + b_v
    if op == "sub":
        return f"({a_s}) - ({b_s})", a_v - b_v
    if op == "div":
        return (f"({a_s}) / (({b_s}) % ({b_s}) + 10)",
                a_v / (b_v * b_v + 10))
    if op == "joinidx":
        # round-4 grammar: structured merge keywords alongside
        # expression strings
        if rng.random() < 0.5:
            kw = str(rng.choice(["left", "right", "add", "mul"]))
            oracle = {"left": lambda x, y: x, "right": lambda x, y: y,
                      "add": np.add, "mul": np.multiply}[kw]
            return f"joinindex({a_s}, {b_s}, '{kw}')", oracle(a_v, b_v)
        return (f"joinindex({a_s}, {b_s}, 'x * y + x')",
                a_v * b_v + a_v)
    raise AssertionError(op)


def _nnz_avg(v):
    n = np.count_nonzero(v)
    return (v.sum() / max(n, 1)).reshape(1, 1)


_TERMINALS = {
    "rowsum({q})": lambda v: v.sum(1, keepdims=True),
    "colsum({q})": lambda v: v.sum(0, keepdims=True),
    "sum({q})": lambda v: v.sum().reshape(1, 1),
    "trace({q})": lambda v: np.trace(v).reshape(1, 1),
    "rowmax({q})": lambda v: v.max(1, keepdims=True),
    "colmin({q})": lambda v: v.min(0, keepdims=True),
    # round-3 grammar closure: global + diag aggregate spellings
    "max({q})": lambda v: v.max().reshape(1, 1),
    "min({q})": lambda v: v.min().reshape(1, 1),
    "count({q})": lambda v: np.float64(np.count_nonzero(v)).reshape(1, 1),
    "avg({q})": _nnz_avg,
    "diagsum({q})": lambda v: np.trace(v).reshape(1, 1),
    "diagmax({q})": lambda v: v.diagonal().max().reshape(1, 1),
    "diagmin({q})": lambda v: v.diagonal().min().reshape(1, 1),
    "diagavg({q})": lambda v: _nnz_avg(v.diagonal()),
    "{q}": lambda v: v,
}


@pytest.mark.parametrize("seed", range(200, 218))
def test_random_grammar_queries_match_oracle(seed, mesh8):
    rng = np.random.default_rng(seed)
    sess = MatrelSession(mesh=mesh8)
    env = {}
    for name in ("A", "B", "C"):
        v = rng.standard_normal((N, N)).astype(np.float32)
        env[name] = v
        sess.register(name, sess.from_numpy(v))
    q, want = _gen(rng, env, depth=int(rng.integers(1, 4)))
    tmpl = str(rng.choice(list(_TERMINALS)))
    q_full = "SELECT " + tmpl.format(q=q)
    want_full = _TERMINALS[tmpl](want.astype(np.float64))
    got = sess.compute(sess.sql(q_full)).to_numpy()
    np.testing.assert_allclose(got, want_full, rtol=2e-3, atol=2e-3,
                               err_msg=q_full)
