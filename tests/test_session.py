"""Session hygiene: plan-cache LRU eviction bounds and builder
config-conflict warnings (long-lived sessions must not grow HBM pins
without bound, and a second builder must not silently lose its
settings)."""

import logging

import numpy as np

from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.session import MatrelSession, reset_session


class TestPlanCacheEviction:
    def test_count_bound_evicts_lru(self, mesh8, rng):
        sess = MatrelSession(
            mesh=mesh8, config=MatrelConfig(plan_cache_max_plans=3))
        mats = [BlockMatrix.from_numpy(
            rng.standard_normal((8, 8)).astype(np.float32), mesh=mesh8)
            for _ in range(5)]
        for m in mats:
            sess.compute(m.expr().t())
        assert sess.plan_cache_info()["plans"] == 3
        keys_before = list(sess._plan_cache)
        # the OLDEST (mats[0]) was evicted: recomputing it recompiles,
        # inserting a fresh entry and evicting the current LRU
        sess.compute(mats[0].expr().t())
        keys_after = list(sess._plan_cache)
        assert keys_after[-1] not in keys_before   # new entry appended
        assert keys_before[0] not in keys_after    # LRU evicted
        assert sess.plan_cache_info()["plans"] == 3

    def test_lru_order_on_hit(self, mesh8, rng):
        sess = MatrelSession(
            mesh=mesh8, config=MatrelConfig(plan_cache_max_plans=2))
        a = BlockMatrix.from_numpy(
            rng.standard_normal((8, 8)).astype(np.float32), mesh=mesh8)
        b = BlockMatrix.from_numpy(
            rng.standard_normal((8, 8)).astype(np.float32), mesh=mesh8)
        c = BlockMatrix.from_numpy(
            rng.standard_normal((8, 8)).astype(np.float32), mesh=mesh8)
        pa = sess.compile(a.expr().t())
        sess.compile(b.expr().t())
        assert sess.compile(a.expr().t()) is pa    # hit refreshes a
        sess.compile(c.expr().t())                 # evicts b (LRU)
        assert sess.compile(a.expr().t()) is pa    # a survived
        assert sess.plan_cache_info()["plans"] == 2

    def test_byte_budget_evicts_hoisted_payloads(self, mesh8, rng):
        # COO plans hoist their table payloads into extra_args; a tiny
        # byte budget must evict old plans once exceeded
        from matrel_tpu.core.coo import COOMatrix
        sess = MatrelSession(
            mesh=mesh8, config=MatrelConfig(plan_cache_max_bytes=1,
                                            plan_cache_max_plans=64))
        x = BlockMatrix.from_numpy(
            rng.standard_normal((2000, 2)).astype(np.float32),
            mesh=mesh8)
        plans = []
        for seed in range(3):
            # ≥1 MB of plan tables so the payloads actually hoist
            m = 400_000
            r = rng.integers(0, 2000, m)
            c = rng.integers(0, 2000, m)
            v = rng.standard_normal(m).astype(np.float32)
            A = COOMatrix.from_edges(r, c, v, shape=(2000, 2000))
            plans.append(sess.compile(A.multiply(x.expr())))
        assert any(p.extra_args for p in plans), \
            "fixture too small: nothing hoisted"
        info = sess.plan_cache_info()
        # with a 1-byte budget only the newest plan may stay
        assert info["plans"] == 1
        # sole-plan exception: the just-inserted plan is never evicted
        assert list(sess._plan_cache.values())[0] is plans[-1]

    def test_sole_plan_never_evicted(self, mesh8, rng):
        from matrel_tpu.core.coo import COOMatrix
        sess = MatrelSession(
            mesh=mesh8, config=MatrelConfig(plan_cache_max_bytes=1))
        r = rng.integers(0, 500, 20_000)
        c = rng.integers(0, 500, 20_000)
        A = COOMatrix.from_edges(r, c, shape=(500, 500))
        x = BlockMatrix.from_numpy(
            rng.standard_normal((500, 2)).astype(np.float32), mesh=mesh8)
        p = sess.compile(A.multiply(x.expr()))
        assert sess.compile(A.multiply(x.expr())) is p


class TestBuilderConflicts:
    def test_explicit_config_conflict_warns(self, caplog):
        reset_session()
        s1 = MatrelSession.builder().config(use_pallas=True).get_or_create()
        with caplog.at_level(logging.WARNING, logger="matrel_tpu"):
            s2 = MatrelSession.builder().config(
                use_pallas=False).get_or_create()
        assert s2 is s1
        assert any("ignoring the requested config" in r.message
                   for r in caplog.records)

    def test_default_builder_does_not_warn(self, caplog):
        reset_session()
        MatrelSession.builder().config(block_size=256).get_or_create()
        with caplog.at_level(logging.WARNING, logger="matrel_tpu"):
            MatrelSession.builder().get_or_create()
        assert not [r for r in caplog.records
                    if "ignoring the requested" in r.message]

    def test_mesh_conflict_warns(self, mesh8, mesh4x2, caplog):
        reset_session()
        MatrelSession.builder().mesh(mesh8).get_or_create()
        with caplog.at_level(logging.WARNING, logger="matrel_tpu"):
            MatrelSession.builder().mesh(mesh4x2).get_or_create()
        assert any("ignoring the requested mesh" in r.message
                   for r in caplog.records)

    def test_same_mesh_no_warning(self, mesh8, caplog):
        reset_session()
        MatrelSession.builder().mesh(mesh8).get_or_create()
        with caplog.at_level(logging.WARNING, logger="matrel_tpu"):
            MatrelSession.builder().mesh(mesh8).get_or_create()
        assert not [r for r in caplog.records
                    if "ignoring the requested" in r.message]


def test_iterative_queries_under_aggressive_eviction(mesh8, rng):
    """An iterative workload whose per-step queries exceed the plan
    cache: evicted plans recompile transparently and results stay
    correct across many steps (long-lived-session shape)."""
    sess = MatrelSession(
        mesh=mesh8, config=MatrelConfig(plan_cache_max_plans=2))
    mats = [sess.from_numpy(
        rng.standard_normal((12, 12)).astype(np.float32))
        for _ in range(4)]
    oracles = [m.to_numpy() for m in mats]
    state = np.eye(12, dtype=np.float32)
    S = sess.from_numpy(state)
    for step in range(8):
        m = step % 4                      # cycles past the cache bound
        out = sess.compute(S.expr().multiply(mats[m].expr()))
        want = state @ oracles[m]
        np.testing.assert_allclose(out.to_numpy(), want, rtol=2e-3,
                                   atol=2e-3, err_msg=f"step {step}")
        state = want
        S = sess.from_numpy(state)
    assert sess.plan_cache_info()["plans"] <= 2
