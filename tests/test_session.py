"""Session hygiene: plan-cache LRU eviction bounds and builder
config-conflict warnings (long-lived sessions must not grow HBM pins
without bound, and a second builder must not silently lose its
settings)."""

import logging

import pytest

import numpy as np

from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.session import MatrelSession, reset_session


class TestPlanCacheEviction:
    def test_count_bound_evicts_lru(self, mesh8, rng):
        sess = MatrelSession(
            mesh=mesh8, config=MatrelConfig(plan_cache_max_plans=3))
        mats = [BlockMatrix.from_numpy(
            rng.standard_normal((8, 8)).astype(np.float32), mesh=mesh8)
            for _ in range(5)]
        for m in mats:
            sess.compute(m.expr().t())
        assert sess.plan_cache_info()["plans"] == 3
        keys_before = list(sess._plan_cache)
        # the OLDEST (mats[0]) was evicted: recomputing it recompiles,
        # inserting a fresh entry and evicting the current LRU
        sess.compute(mats[0].expr().t())
        keys_after = list(sess._plan_cache)
        assert keys_after[-1] not in keys_before   # new entry appended
        assert keys_before[0] not in keys_after    # LRU evicted
        assert sess.plan_cache_info()["plans"] == 3

    def test_lru_order_on_hit(self, mesh8, rng):
        sess = MatrelSession(
            mesh=mesh8, config=MatrelConfig(plan_cache_max_plans=2))
        a = BlockMatrix.from_numpy(
            rng.standard_normal((8, 8)).astype(np.float32), mesh=mesh8)
        b = BlockMatrix.from_numpy(
            rng.standard_normal((8, 8)).astype(np.float32), mesh=mesh8)
        c = BlockMatrix.from_numpy(
            rng.standard_normal((8, 8)).astype(np.float32), mesh=mesh8)
        pa = sess.compile(a.expr().t())
        sess.compile(b.expr().t())
        assert sess.compile(a.expr().t()) is pa    # hit refreshes a
        sess.compile(c.expr().t())                 # evicts b (LRU)
        assert sess.compile(a.expr().t()) is pa    # a survived
        assert sess.plan_cache_info()["plans"] == 2

    def test_byte_budget_evicts_hoisted_payloads(self, mesh8, rng):
        # COO plans hoist their table payloads into extra_args; a tiny
        # byte budget must evict old plans once exceeded
        from matrel_tpu.core.coo import COOMatrix
        sess = MatrelSession(
            mesh=mesh8, config=MatrelConfig(plan_cache_max_bytes=1,
                                            plan_cache_max_plans=64))
        x = BlockMatrix.from_numpy(
            rng.standard_normal((2000, 2)).astype(np.float32),
            mesh=mesh8)
        plans = []
        for seed in range(3):
            # ≥1 MB of plan tables so the payloads actually hoist
            m = 400_000
            r = rng.integers(0, 2000, m)
            c = rng.integers(0, 2000, m)
            v = rng.standard_normal(m).astype(np.float32)
            A = COOMatrix.from_edges(r, c, v, shape=(2000, 2000))
            plans.append(sess.compile(A.multiply(x.expr())))
        assert any(p.extra_args for p in plans), \
            "fixture too small: nothing hoisted"
        info = sess.plan_cache_info()
        # with a 1-byte budget only the newest plan may stay
        assert info["plans"] == 1
        # sole-plan exception: the just-inserted plan is never evicted
        assert list(sess._plan_cache.values())[0] is plans[-1]

    def test_sole_plan_never_evicted(self, mesh8, rng):
        from matrel_tpu.core.coo import COOMatrix
        sess = MatrelSession(
            mesh=mesh8, config=MatrelConfig(plan_cache_max_bytes=1))
        r = rng.integers(0, 500, 20_000)
        c = rng.integers(0, 500, 20_000)
        A = COOMatrix.from_edges(r, c, shape=(500, 500))
        x = BlockMatrix.from_numpy(
            rng.standard_normal((500, 2)).astype(np.float32), mesh=mesh8)
        p = sess.compile(A.multiply(x.expr()))
        assert sess.compile(A.multiply(x.expr())) is p


class TestBuilderConflicts:
    def test_explicit_config_conflict_warns(self, caplog):
        reset_session()
        s1 = MatrelSession.builder().config(use_pallas=True).get_or_create()
        with caplog.at_level(logging.WARNING, logger="matrel_tpu"):
            s2 = MatrelSession.builder().config(
                use_pallas=False).get_or_create()
        assert s2 is s1
        assert any("ignoring the requested config" in r.message
                   for r in caplog.records)

    def test_default_builder_does_not_warn(self, caplog):
        reset_session()
        MatrelSession.builder().config(block_size=256).get_or_create()
        with caplog.at_level(logging.WARNING, logger="matrel_tpu"):
            MatrelSession.builder().get_or_create()
        assert not [r for r in caplog.records
                    if "ignoring the requested" in r.message]

    def test_mesh_conflict_warns(self, mesh8, mesh4x2, caplog):
        reset_session()
        MatrelSession.builder().mesh(mesh8).get_or_create()
        with caplog.at_level(logging.WARNING, logger="matrel_tpu"):
            MatrelSession.builder().mesh(mesh4x2).get_or_create()
        assert any("ignoring the requested mesh" in r.message
                   for r in caplog.records)

    def test_same_mesh_no_warning(self, mesh8, caplog):
        reset_session()
        MatrelSession.builder().mesh(mesh8).get_or_create()
        with caplog.at_level(logging.WARNING, logger="matrel_tpu"):
            MatrelSession.builder().mesh(mesh8).get_or_create()
        assert not [r for r in caplog.records
                    if "ignoring the requested" in r.message]


def test_iterative_queries_under_aggressive_eviction(mesh8, rng):
    """An iterative workload whose per-step queries exceed the plan
    cache: evicted plans recompile transparently and results stay
    correct across many steps (long-lived-session shape)."""
    sess = MatrelSession(
        mesh=mesh8, config=MatrelConfig(plan_cache_max_plans=2))
    mats = [sess.from_numpy(
        rng.standard_normal((12, 12)).astype(np.float32))
        for _ in range(4)]
    oracles = [m.to_numpy() for m in mats]
    state = np.eye(12, dtype=np.float32)
    S = sess.from_numpy(state)
    for step in range(8):
        m = step % 4                      # cycles past the cache bound
        out = sess.compute(S.expr().multiply(mats[m].expr()))
        want = state @ oracles[m]
        np.testing.assert_allclose(out.to_numpy(), want, rtol=2e-3,
                                   atol=2e-3, err_msg=f"step {step}")
        state = want
        S = sess.from_numpy(state)
    assert sess.plan_cache_info()["plans"] <= 2


class TestPlanCacheCallableKeys:
    """The plan key must distinguish callable attrs (ADVICE r2 high):
    pre-fix, two queries differing only in a predicate/merge callable
    shared one cache entry and the second silently returned the first's
    results."""

    def test_where_predicates_key_separately(self, mesh8, rng):
        sess = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((8, 8)).astype(np.float32)
        sess.register("A", sess.from_numpy(a))
        pos = sess.compute(sess.sql("SELECT A WHERE v > 0")).to_numpy()
        neg = sess.compute(sess.sql("SELECT A WHERE v < 0")).to_numpy()
        np.testing.assert_allclose(pos, np.where(a > 0, a, 0), rtol=1e-5)
        np.testing.assert_allclose(neg, np.where(a < 0, a, 0), rtol=1e-5)

    def test_joinvalue_merge_exprs_key_separately(self, mesh8, rng):
        sess = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((4, 4)).astype(np.float32)
        sess.register("A", sess.from_numpy(a))
        add = sess.compute(
            sess.sql("rowsum(joinvalue(A, A, 'x + y'))")).to_numpy()
        sub = sess.compute(
            sess.sql("rowsum(joinvalue(A, A, 'x - y'))")).to_numpy()
        assert not np.allclose(add, sub)

    def test_raw_lambdas_key_separately(self, mesh8, rng):
        sess = MatrelSession(mesh=mesh8)
        m = sess.from_numpy(rng.standard_normal((8, 8)).astype(np.float32))
        a = m.to_numpy()
        hi = sess.compute(m.expr().select_value(lambda v: v > 0.5)).to_numpy()
        lo = sess.compute(m.expr().select_value(lambda v: v < -0.5)).to_numpy()
        np.testing.assert_allclose(hi, np.where(a > 0.5, a, 0), rtol=1e-5)
        np.testing.assert_allclose(lo, np.where(a < -0.5, a, 0), rtol=1e-5)

    def test_identical_sql_text_still_hits_cache(self, mesh8, rng):
        # correctness must not cost the cache: re-parsing the same query
        # makes a fresh callable, but the attached source key matches
        sess = MatrelSession(mesh=mesh8)
        sess.register("A", sess.from_numpy(
            rng.standard_normal((8, 8)).astype(np.float32)))
        p1 = sess.compile(sess.sql("SELECT A WHERE v > 0"))
        p2 = sess.compile(sess.sql("SELECT A WHERE v > 0"))
        assert p1 is p2
        assert sess.plan_cache_info()["plans"] == 1

    def test_selectblocks_predicates_key_separately(self, mesh8, rng):
        sess = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((8, 8)).astype(np.float32)
        sess.register("A", sess.from_numpy(a))
        diag = sess.compute(
            sess.sql("selectblocks(A, 'bi == bj', 4)")).to_numpy()
        off = sess.compute(
            sess.sql("selectblocks(A, 'bi != bj', 4)")).to_numpy()
        np.testing.assert_allclose(diag + off, a, rtol=1e-5)
        assert not np.allclose(diag, off)


class TestPlanKeyGlobalsAndPinning:
    """Code-review r3 findings: lambdas reading module globals must key
    by the global's VALUE, and id-keyed objects must stay pinned while
    their plan is cached (CPython address reuse)."""

    def test_global_value_change_keys_differently(self, mesh8, rng):
        sess = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((8, 8)).astype(np.float32)
        m = sess.from_numpy(a)
        g = {"thr": 0.5}
        f1 = eval("lambda v: v > thr", g)          # noqa: S307 — test fixture
        r1 = sess.compute(m.expr().select_value(f1)).to_numpy()
        g["thr"] = -0.5
        f2 = eval("lambda v: v > thr", g)          # noqa: S307
        r2 = sess.compute(m.expr().select_value(f2)).to_numpy()
        np.testing.assert_allclose(r1, np.where(a > 0.5, a, 0), rtol=1e-5)
        np.testing.assert_allclose(r2, np.where(a > -0.5, a, 0), rtol=1e-5)

    def test_cached_plan_pins_keyed_callable(self, mesh8, rng):
        import gc
        import weakref
        sess = MatrelSession(mesh=mesh8)
        m = sess.from_numpy(rng.standard_normal((8, 8)).astype(np.float32))

        def pred(v):
            return v > 0.25

        wr = weakref.ref(pred)
        sess.compile(m.expr().select_value(pred))
        del pred
        gc.collect()
        # while the plan is cached, the callable's id must stay valid
        assert wr() is not None
        sess._plan_cache.clear()
        gc.collect()
        assert wr() is None

    def test_rebound_array_global_keys_and_pins(self, mesh8, rng):
        # review r3: a non-scalar global (numpy array) keys by id and
        # its OLD value must stay pinned after rebinding — the recycled
        # address can otherwise falsely hit the stale plan
        sess = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((8, 8)).astype(np.float32)
        m = sess.from_numpy(a)
        g = {"thr": np.array(0.5, np.float32)}
        f1 = eval("lambda v: v > thr", g)          # noqa: S307
        r1 = sess.compute(m.expr().select_value(f1)).to_numpy()
        old_thr = g["thr"]
        g["thr"] = np.array(-0.5, np.float32)      # rebind the global
        f2 = eval("lambda v: v > thr", g)          # noqa: S307
        r2 = sess.compute(m.expr().select_value(f2)).to_numpy()
        np.testing.assert_allclose(r1, np.where(a > 0.5, a, 0), rtol=1e-5)
        np.testing.assert_allclose(r2, np.where(a > -0.5, a, 0), rtol=1e-5)
        # the old value object is pinned by the cached first plan
        pinned = [p for plan in sess._plan_cache.values()
                  for p in plan._cache_pin[1]]
        assert any(p is old_thr for p in pinned)

    def test_nested_lambda_global_keys_differently(self, mesh8, rng):
        # review r3 (confirmed repro): a global read only by a NESTED
        # code object must still enter the fingerprint
        sess = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((8, 8)).astype(np.float32)
        m = sess.from_numpy(a)
        g = {"thr": 0.5}
        make = eval("lambda: (lambda v: (lambda w: w > thr)(v))", g)  # noqa: S307
        r1 = sess.compute(m.expr().select_value(make())).to_numpy()
        g["thr"] = -3.0
        make2 = eval("lambda: (lambda v: (lambda w: w > thr)(v))", g)  # noqa: S307
        r2 = sess.compute(m.expr().select_value(make2())).to_numpy()
        np.testing.assert_allclose(r1, np.where(a > 0.5, a, 0), rtol=1e-5)
        np.testing.assert_allclose(r2, np.where(a > -3.0, a, 0), rtol=1e-5)

    def test_custom_repr_default_objects_key_differently(self, mesh8, rng):
        # review r3: default objects with state-independent __repr__
        # must key by identity, not repr
        sess = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((8, 8)).astype(np.float32)
        m = sess.from_numpy(a)

        class Thr:
            def __init__(self, t):
                self.t = t

            def __repr__(self):
                return "<Thr>"

        f1 = lambda v, thr=Thr(0.5): v > thr.t      # noqa: E731
        f2 = lambda v, thr=Thr(-0.5): v > thr.t     # noqa: E731
        r1 = sess.compute(m.expr().select_value(f1)).to_numpy()
        r2 = sess.compute(m.expr().select_value(f2)).to_numpy()
        np.testing.assert_allclose(r1, np.where(a > 0.5, a, 0), rtol=1e-5)
        np.testing.assert_allclose(r2, np.where(a > -0.5, a, 0), rtol=1e-5)


class TestPlanKeyBoundMethodsAndKwdefaults:
    """Advisor r3 medium: _fn_token omitted __kwdefaults__ and bound-method
    __self__ state, so behaviourally distinct callables collided in the
    plan cache (the second query silently returned the first's result)."""

    def test_bound_method_instance_state_keys_separately(self, mesh8, rng):
        sess = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((8, 8)).astype(np.float32)
        m = sess.from_numpy(a)

        class Thresh:
            def __init__(self, t):
                self.t = t

            def pred(self, v):
                return v > self.t

        r1 = sess.compute(
            m.expr().select_value(Thresh(16.5).pred)).to_numpy()
        r2 = sess.compute(
            m.expr().select_value(Thresh(0.0).pred)).to_numpy()
        np.testing.assert_allclose(r1, np.where(a > 16.5, a, 0), rtol=1e-5)
        np.testing.assert_allclose(r2, np.where(a > 0.0, a, 0), rtol=1e-5)

    def test_kwonly_defaults_key_separately(self, mesh8, rng):
        sess = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((8, 8)).astype(np.float32)
        m = sess.from_numpy(a)

        def make(t):
            def pred(v, *, thr=t):
                return v > thr
            return pred

        r1 = sess.compute(m.expr().select_value(make(0.5))).to_numpy()
        r2 = sess.compute(m.expr().select_value(make(-0.5))).to_numpy()
        np.testing.assert_allclose(r1, np.where(a > 0.5, a, 0), rtol=1e-5)
        np.testing.assert_allclose(r2, np.where(a > -0.5, a, 0), rtol=1e-5)

    def test_global_list_mutated_in_place_rekeys(self, mesh8, rng):
        # advisor r3 low: a mutable global mutated IN PLACE (same id)
        # must not falsely hit the cached plan — containers key by value
        sess = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((8, 8)).astype(np.float32)
        m = sess.from_numpy(a)
        g = {"thrs": [0.5]}
        f1 = eval("lambda v: v > thrs[0]", g)       # noqa: S307
        r1 = sess.compute(m.expr().select_value(f1)).to_numpy()
        g["thrs"][0] = -0.5                         # in-place, id unchanged
        f2 = eval("lambda v: v > thrs[0]", g)       # noqa: S307
        r2 = sess.compute(m.expr().select_value(f2)).to_numpy()
        np.testing.assert_allclose(r1, np.where(a > 0.5, a, 0), rtol=1e-5)
        np.testing.assert_allclose(r2, np.where(a > -0.5, a, 0), rtol=1e-5)

    def test_cyclic_global_container_terminates(self, mesh8, rng):
        # review r4: a self-referential container reachable from a
        # predicate's globals must key finitely (back-edge by pinned id)
        sess = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((8, 8)).astype(np.float32)
        m = sess.from_numpy(a)
        g = {"cfg": {"thr": 0.5}}
        g["cfg"]["self"] = g["cfg"]             # cycle
        f1 = eval("lambda v: v > cfg['thr']", g)   # noqa: S307
        r1 = sess.compute(m.expr().select_value(f1)).to_numpy()
        np.testing.assert_allclose(r1, np.where(a > 0.5, a, 0), rtol=1e-5)

    def test_large_dict_mutated_in_place_rekeys(self, mesh8, rng):
        # review r4: no silent size cap — a 65+-entry global dict
        # mutated in place must still re-key by value
        sess = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((8, 8)).astype(np.float32)
        m = sess.from_numpy(a)
        g = {"thrs": {i: 0.0 for i in range(70)}}
        g["thrs"][0] = 0.5
        f1 = eval("lambda v: v > thrs[0]", g)      # noqa: S307
        r1 = sess.compute(m.expr().select_value(f1)).to_numpy()
        g["thrs"][0] = -0.5                        # in-place, id unchanged
        f2 = eval("lambda v: v > thrs[0]", g)      # noqa: S307
        r2 = sess.compute(m.expr().select_value(f2)).to_numpy()
        np.testing.assert_allclose(r1, np.where(a > 0.5, a, 0), rtol=1e-5)
        np.testing.assert_allclose(r2, np.where(a > -0.5, a, 0), rtol=1e-5)

    def test_recursive_global_function_terminates(self, mesh8, rng):
        # the value-keyed globals walk must terminate when a predicate's
        # global namespace reaches the predicate itself
        sess = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((8, 8)).astype(np.float32)
        m = sess.from_numpy(a)
        g = {}
        g["pred"] = eval("lambda v: v > 0.5 if pred else v", g)  # noqa: S307
        r1 = sess.compute(m.expr().select_value(g["pred"])).to_numpy()
        np.testing.assert_allclose(r1, np.where(a > 0.5, a, 0), rtol=1e-5)


def test_session_explain_includes_physical_plan(mesh8, rng):
    """round-3: EXPLAIN shows the physical annotations (strategy,
    collectives) without the user reaching for compile().explain()."""
    sess = MatrelSession(mesh=mesh8)
    a = sess.from_numpy(rng.standard_normal((32, 32)).astype(np.float32))
    b = sess.from_numpy(rng.standard_normal((32, 32)).astype(np.float32))
    e = a.expr().multiply(b.expr())
    txt = sess.explain(e)
    assert "strategy=" in txt
    assert "== Logical plan ==" in txt and "== Optimized plan ==" in txt
    # logical-only mode skips compilation
    txt2 = sess.explain(e, physical=False)
    assert "strategy=" not in txt2
    # explain warmed the cache: compute() reuses the compiled plan
    assert sess.plan_cache_info()["plans"] >= 1


def test_explain_survives_compile_failure(mesh8, rng, monkeypatch):
    """review r3: when compilation (incl. the optimizer) raises, explain
    degrades to the logical plan + a note instead of crashing."""
    from matrel_tpu import executor as executor_lib
    sess = MatrelSession(mesh=mesh8)
    a = sess.from_numpy(rng.standard_normal((8, 8)).astype(np.float32))
    e = a.expr().t()

    def boom(*args, **kw):
        raise RuntimeError("optimizer exploded")

    monkeypatch.setattr(executor_lib, "compile_expr", boom)
    txt = sess.explain(e)
    assert "== Logical plan ==" in txt
    assert "Physical plan unavailable" in txt and "exploded" in txt


def test_catalog_save_and_load_roundtrip(mesh8, rng, tmp_path):
    """round-3: catalog persistence — registered tables survive a
    session restart with sharding and numerics intact."""
    sess = MatrelSession(mesh=mesh8)
    a = rng.standard_normal((16, 8)).astype(np.float32)
    b = rng.standard_normal((8, 16)).astype(np.float32)
    sess.register("A", sess.from_numpy(a))
    sess.register("B", sess.from_numpy(b))
    sess.save_catalog(str(tmp_path))

    fresh = MatrelSession(mesh=mesh8)
    names = fresh.load_catalog(str(tmp_path))
    assert names == ["A", "B"]
    np.testing.assert_allclose(fresh.table("A").to_numpy(), a, rtol=0)
    assert fresh.table("A").spec == sess.table("A").spec
    # the restored catalog answers SQL
    out = fresh.compute(fresh.sql("SELECT A * B FROM A, B")).to_numpy()
    np.testing.assert_allclose(out, a @ b, rtol=1e-4, atol=1e-4)


def test_load_catalog_empty_dir(mesh8, tmp_path):
    sess = MatrelSession(mesh=mesh8)
    assert sess.load_catalog(str(tmp_path)) == []


def test_save_catalog_steps_are_monotonic(mesh8, rng, tmp_path):
    # review r3: default step must not collide with keep-k GC — three
    # consecutive saves all restore the LATEST catalog
    import os
    sess = MatrelSession(mesh=mesh8)
    for i in range(3):
        sess.register("T", sess.from_numpy(
            np.full((4, 4), float(i), np.float32)))
        p = sess.save_catalog(str(tmp_path))
        assert os.path.isdir(p), p        # the fresh save survives GC
    fresh = MatrelSession(mesh=mesh8)
    fresh.load_catalog(str(tmp_path))
    np.testing.assert_allclose(fresh.table("T").to_numpy(),
                               np.full((4, 4), 2.0))


def test_save_catalog_rejects_path_escaping_names(mesh8, rng, tmp_path):
    sess = MatrelSession(mesh=mesh8)
    m = sess.from_numpy(rng.standard_normal((4, 4)).astype(np.float32))
    for bad in ("a/b", "..", "x\\y", ""):
        sess.catalog = {bad: m}
        with pytest.raises(ValueError):
            sess.save_catalog(str(tmp_path))


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_plan_cache_never_aliases_predicates(seed, mesh8):
    """Cache-aliasing fuzz (round 4): across every predicate
    construction form the keying supports — closures, globals (scalar
    and container, including in-place mutation), bound methods, kw-only
    factory defaults — repeated queries must always match the numpy
    oracle. Repeating an identical threshold is allowed to HIT the
    cache; a differing one must MISS. The silent-stale-result class
    (ADVICE r2 high, r3 medium) is exactly what this net catches."""
    prng = np.random.default_rng(7000 + seed)
    sess = MatrelSession(mesh=mesh8)
    a = prng.standard_normal((8, 8)).astype(np.float32)
    m = sess.from_numpy(a)
    g = {"thr": 0.0, "thrs": [0.0]}

    class Thresh:
        def __init__(self, t):
            self.t = t

        def pred(self, v):
            return v > self.t

    def factory(t):
        def pred(v, *, thr=t):
            return v > thr
        return pred

    # small pool so thresholds REPEAT across forms and iterations —
    # exercising both cache hits and misses
    pool = [-0.5, 0.0, 0.25, 0.8]
    for _ in range(12):
        t = float(prng.choice(pool))
        form = str(prng.choice(["closure", "global", "global_list",
                                "bound", "kwdefault"]))
        if form == "closure":
            pred = lambda v, t=t: v > t          # noqa: E731
        elif form == "global":
            g["thr"] = t
            pred = eval("lambda v: v > thr", g)  # noqa: S307
        elif form == "global_list":
            g["thrs"][0] = t                     # in-place mutation
            pred = eval("lambda v: v > thrs[0]", g)  # noqa: S307
        elif form == "bound":
            pred = Thresh(t).pred
        else:
            pred = factory(t)
        got = sess.compute(m.expr().select_value(pred)).to_numpy()
        np.testing.assert_allclose(
            got, np.where(a > t, a, 0), rtol=1e-5,
            err_msg=f"form={form} t={t}")
    # the fuzz must actually exercise cache HITS: with 12 queries over
    # <=20 (form, threshold) combinations and per-query-text keys,
    # always-miss keying (the conservative inverse regression) would
    # show up as 12 distinct plans
    assert sess.plan_cache_info()["plans"] < 12


class TestOversizedContainerCap:
    """advisor r4 low: containers above _VALUE_KEY_MAX_ELEMS key by
    pinned identity + length instead of by value, so a predicate
    referencing a big module-level list doesn't re-walk it on every
    plan-cache lookup. Growth/shrink still re-keys (length is in the
    token); same-length in-place mutation requires rebinding (documented
    caveat, same as id-keyed objects)."""

    def test_token_forms(self):
        from matrel_tpu import session as S
        big = list(range(S._VALUE_KEY_MAX_ELEMS + 1))
        pins = []
        t = S._attr_token(big, pins)
        assert t.startswith("bigcont:list:") and t.endswith(
            f"len{len(big)}")
        assert any(p is big for p in pins)
        # growth re-keys even at the same id
        big.append(-1)
        assert S._attr_token(big, []) != t
        # small containers still key by value (no pin, no id)
        small = [1, 2, 3]
        pins2 = []
        assert S._attr_token(small, pins2) == S._attr_token(
            [1, 2, 3], [])
        assert not pins2

    def test_distinct_oversized_globals_never_collide(self, mesh8, rng):
        sess = MatrelSession(mesh=mesh8)
        a = rng.standard_normal((8, 8)).astype(np.float32)
        m = sess.from_numpy(a)
        from matrel_tpu import session as S
        n = S._VALUE_KEY_MAX_ELEMS + 10
        g1 = {"thrs": [0.5] * n}
        g2 = {"thrs": [-0.5] * n}   # same length, different values/id
        f1 = eval("lambda v: v > thrs[0]", g1)      # noqa: S307
        f2 = eval("lambda v: v > thrs[0]", g2)      # noqa: S307
        r1 = sess.compute(m.expr().select_value(f1)).to_numpy()
        r2 = sess.compute(m.expr().select_value(f2)).to_numpy()
        np.testing.assert_allclose(r1, np.where(a > 0.5, a, 0), rtol=1e-5)
        np.testing.assert_allclose(r2, np.where(a > -0.5, a, 0),
                                   rtol=1e-5)
