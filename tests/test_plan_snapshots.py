"""Plan-snapshot regression suite — the Catalyst ``comparePlans`` idiom
at corpus scale (SURVEY.md §4): every representative expression's
OPTIMIZED plan signature (kinds, strategies + provenance, join schemes,
inferred layouts) must match the committed snapshot, so planner changes
show their plan-shape consequences explicitly in review.

On an INTENTIONAL planner change, regenerate with

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/plan_snapshot.py --update

and commit the JSON with the change that moved it.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool():
    spec = importlib.util.spec_from_file_location(
        "plan_snapshot", os.path.join(REPO, "tools", "plan_snapshot.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def snapshots():
    from matrel_tpu.serve import replan
    tool = _load_tool()
    with open(tool.SNAPSHOT_PATH) as f:
        want = json.load(f)
    before = replan._CONSTRUCTED["count"]
    got = tool.build_snapshots()
    constructed = replan._CONSTRUCTED["count"] - before
    return want, got, constructed


def test_snapshot_build_constructs_no_replan_state(snapshots):
    # poisoned-init proof at corpus scale: planning the whole default-
    # config corpus must never build a ReplanController — the cost-
    # model loop is structurally absent until coeff_replan_enable
    *_, constructed = snapshots
    assert constructed == 0


def test_snapshot_corpus_covered(snapshots):
    want, got, _ = snapshots
    assert set(want) == set(got), (
        "corpus and snapshot disagree on entry names — regenerate via "
        "tools/plan_snapshot.py --update")


def _snapshot_names():
    """Collection-time name list; a missing/corrupt snapshot file must
    fail THIS module's tests with a pointer to --update, not abort the
    whole pytest collection."""
    try:
        with open(os.path.join(REPO, "tests",
                               "plan_snapshots.json")) as f:
            return sorted(json.load(f))
    except (OSError, json.JSONDecodeError):
        return ["__snapshot_file_unreadable__"]


@pytest.mark.parametrize("name", _snapshot_names())
def test_plan_signature_stable(name, snapshots):
    assert name != "__snapshot_file_unreadable__", (
        "tests/plan_snapshots.json is missing or corrupt — regenerate "
        "via tools/plan_snapshot.py --update")
    want, got, _ = snapshots
    assert got[name] == want[name], (
        f"plan for {name!r} changed — if intentional, regenerate via "
        f"tools/plan_snapshot.py --update and commit the JSON\n"
        f"now:  {json.dumps(got[name], sort_keys=True)}\n"
        f"snap: {json.dumps(want[name], sort_keys=True)}")
