"""Examples smoke battery: every examples/*.py must run clean on the
CPU mesh — worked examples are documentation and rot silently without
this (each runs in its own subprocess so platform env is hermetic)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(
    f[:-3] for f in os.listdir(os.path.join(REPO, "examples"))
    if f.endswith(".py"))


@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs_clean(name):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    # prepend the repo but DROP any axon site dir from the inherited
    # tail: its sitecustomize registers the TPU plugin at interpreter
    # start, and while the relay is wedged that HANGS the subprocess
    # regardless of JAX_PLATFORMS (docs/INTERNALS.md operational note)
    prev = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO, *prev])
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", f"{name}.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert r.returncode == 0, (name, r.stdout[-800:], r.stderr[-800:])
    assert r.stdout.strip(), f"{name} printed nothing"
