"""Full BASELINE.md benchmark suite — one JSON line per config.

Rows (BASELINE.json configs):
  1. 4k×4k dense BlockMatrix multiply            → TFLOPS/chip
  2. chain A·B·C, 10k dims, skewed, DP reorder   → wall-clock + plan
  3. tall-skinny linreg 10M×1k (streaming Gram)  → wall-clock
  4. block-sparse × dense, 1% blocks, 100k×100k  → wall-clock + eff. TFLOPS
  4b. block-sparse × block-sparse SpGEMM, same S → wall-clock + crossover
  5. PageRank 1M nodes / 10M edges, 30 rounds    → wall-clock/round
  5b. PageRank 10M nodes / 100M edges (10×)      → wall-clock/round
  x1. conjugate gradient, implicit SPD 8k system → wall-clock + iters
  x2. power iteration, dense 8k, 50 rounds       → wall-clock
  x3. triangle count, dense 8k adjacency         → wall-clock + count
  6. north star 65k chain A·B·C                  → TFLOPS/chip
  (x-rows track the round-3 workload families — not BASELINE.json
  configs, but captured in the same batch so they get on-chip numbers)

Methodology notes: the axon relay acks dispatch before completion, so every
timing forces a scalar fetch; fast ops use marginal timing over two repeat
counts (see bench.py). Run on the real chip: `python bench_all.py`.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _timed(fn, warm: int = 1, reps: int = 3) -> float:
    """Median wall-clock of fn() (fn must block/fetch internally)."""
    for _ in range(warm):
        fn()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def bench_dense_4k(mesh, cfg):
    import bench
    payload = bench.measure_tpu()      # {"tflops": ..., "phases": ...}
    return {"metric": "dense_blockmatmul_tflops_per_chip",
            "value": round(payload["tflops"], 2),
            "unit": "TFLOPS", "config": "4096x4096 bf16, f32 accumulate"}


def bench_spgemm(mesh, cfg):
    """S×S tile-intersection SpGEMM (ops/spgemm.py) at BASELINE row-4
    scale + the executor-dispatch crossover comparison vs the densify
    fallback at a reduced scale (see bench.measure_spgemm)."""
    import bench
    payload = bench.measure_spgemm()
    return {"metric": "blocksparse_spgemm_100k_1pct", **payload}


def bench_sparse_kernels(mesh, cfg):
    """Structure-specialized SpGEMM kernel sweep (ops/kernel_registry):
    per structure class, every relevant registered kernel vs the fixed
    pre-registry Pallas baseline, plus the autotune persist/replay
    proof (see bench.measure_sparse_kernels)."""
    import bench
    payload = bench.measure_sparse_kernels()
    return {"metric": "sparse_kernel_sweep", **payload}


def bench_fusion(mesh, cfg):
    """Whole-plan fusion sweep: the PageRank-step and linreg-epilogue
    chains as one jitted program per fused region vs one per physical
    op, ms + dispatch counts both ways (see bench.measure_fusion)."""
    import bench
    payload = bench.measure_fusion()
    return {"metric": "fusion_region_sweep", **payload}


def bench_serve(mesh, cfg):
    """Repeated-traffic serving QPS (matrel_tpu/serve/): mixed query
    stream, {result cache off/on} x {sequential/micro-batched} — the
    cross-query amortization row (see bench.measure_serve)."""
    import bench
    payload = bench.measure_serve()
    return {"metric": "serve_repeated_traffic_qps", **payload}


def bench_cse(mesh, cfg):
    """Shared-interior batch + plan-template row (serve/mqo.py;
    docs/SERVING.md): k dashboard variants over one Gram-polynomial
    interior, cse_enable off vs on at first contact, plus the
    rebound-leaf template replay (see bench.measure_cse)."""
    import bench
    payload = bench.measure_cse()
    return {"metric": "cse_shared_interior_batch", **payload}


def bench_traffic(mesh, cfg):
    """Open-loop overload traffic harness (tools/traffic.py;
    docs/OVERLOAD.md): seeded Poisson arrivals at 2x measured
    closed-loop capacity over 3 weighted tenants — per-tenant
    percentiles, goodput ratio, typed-shed counts, Jain fairness,
    brownout enter/exit. Run as a subprocess: the harness forces the
    CPU backend (it drills the control plane, not the chip) and must
    not re-initialise this process's backend."""
    import subprocess
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "tools", "traffic.py")],
        capture_output=True, text=True, timeout=600)
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    if not lines:
        raise RuntimeError(
            f"traffic harness emitted no artifact (rc {proc.returncode}): "
            f"{proc.stderr[-400:]}")
    return json.loads(lines[-1])


def bench_fleet(mesh, cfg):
    """Multi-slice serving-fleet scale-out row (serve/fleet.py;
    docs/FLEET.md): aggregate QPS going 1 -> 2 virtual slices on the
    repeated-traffic stream whose working set only fits the fleet's
    AGGREGATE cache, plus the mid-stream slice-kill drill (see
    bench.measure_fleet)."""
    import bench
    payload = bench.measure_fleet()
    return {"metric": "fleet_scaleout_qps", **payload}


def bench_stream(mesh, cfg):
    """Streaming IVM row: the sliding-window graph dashboard's
    steady-state per-update latency, delta-patch vs full recompute
    (see bench.measure_stream; docs/IVM.md)."""
    import bench
    payload = bench.measure_stream()
    return {"metric": "stream_update_latency", **payload}


def bench_reshard(mesh, cfg):
    """Reshard-planner sweep: planned staged step sequences vs the
    naive one-shot constraint per src→dst layout move, {ms, bytes
    moved, peak bytes} each (see bench.measure_reshard)."""
    import bench
    payload = bench.measure_reshard()
    return {"metric": "reshard_sweep", **payload}


def bench_precision(mesh, cfg):
    """Precision-tier sweep: f32 vs bf16x1 vs bf16x3 vs int32 on the
    dense flagship multiply, TFLOPS + measured max-abs-error vs an f64
    oracle per tier (see bench.measure_precision)."""
    import bench
    payload = bench.measure_precision()
    return {"metric": "precision_tier_sweep", **payload}


def bench_chain(mesh, cfg):
    import jax.numpy as jnp
    import jax
    from matrel_tpu.workloads import chain_bench
    mats = chain_bench.skewed_abc(mesh, n=10_000, mid=100, dtype="bfloat16")
    plan, paren, est = chain_bench.compile_chain(mats)
    a_leaf = plan.leaf_order[0]
    fetch = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))

    def chained(reps):
        # result shape == A's shape: rebind so every rep depends on the last
        cur = plan.run()
        for _ in range(reps - 1):
            cur = plan.run(bindings={a_leaf.uid: cur})
        np.asarray(fetch(cur.data))

    chained(2)
    # latency-bound op on a shared chip: median of 3 marginal estimates
    # (the single-estimate round-1 methodology showed a 0.5-2.3 ms
    # run-to-run band; same treatment as bench_spmm)
    lo, hi = 3, 43
    ests = []
    for _ in range(3):
        t0 = time.perf_counter(); chained(lo); t_lo = time.perf_counter() - t0
        t0 = time.perf_counter(); chained(hi); t_hi = time.perf_counter() - t0
        ests.append(max((t_hi - t_lo) / (hi - lo), 1e-9))
    dt = sorted(ests)[1]
    # optimal order A·(B·C): 2*(100*10000*100) + 2*(10000*100*100) FLOPs
    fl = 2 * (100 * 10_000 * 100) + 2 * (10_000 * 100 * 100)
    return {"metric": "chain_abc_10k_skewed_wallclock", "value": round(dt * 1e3, 3),
            "unit": "ms", "plan": paren,
            "effective_tflops": round(fl / dt / 1e12, 3)}


def bench_linreg(mesh, cfg):
    import jax
    import jax.numpy as jnp
    from matrel_tpu.workloads.linreg import fit_streaming
    n, k, panel = 10_000_000, 1000, 250_000

    def panel_fn(p):
        # cheap deterministic on-device generator (integer-hash mixing):
        # the benchmark measures the Gram pipeline, not RNG throughput.
        # NOTE a sin(r*a + c*b) generator would be RANK 2 (sum formula)
        # and make the normal equations singular — the hash keeps X
        # full-rank and well-conditioned.
        r = jnp.arange(panel, dtype=jnp.int32)[:, None]
        c = jnp.arange(k, dtype=jnp.int32)[None, :]
        s = r * 1664525 + c * 1013904223 + p * 69069 + 12345
        s = s * 1664525 + 1013904223          # one more LCG round to mix
        xp = (s >> 8).astype(jnp.float32) * (2.0 ** -23)
        yp = xp @ jnp.ones((k, 1), jnp.float32)
        return xp, yp

    def run():
        theta = fit_streaming(n, k, panel_fn, panel_rows=panel, mesh=mesh,
                              precision="high")
        np.asarray(theta)

    dt = _timed(run, warm=1, reps=2)
    fl = 2.0 * n * k * k + 2.0 * n * k  # gram + rhs
    return {"metric": "linreg_normal_eq_10Mx1k_wallclock", "value": round(dt, 3),
            "unit": "s", "effective_tflops": round(fl / dt / 1e12, 2),
            "precision": "high (3-pass bf16 Gram)"}


def bench_spmm(mesh, cfg):
    import jax
    import jax.numpy as jnp
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.core.sparse import BlockSparseMatrix
    from matrel_tpu.ops import spmm as spmm_lib
    n = 100_352  # 196 blocks of 512
    bs = 512
    # bf16 payloads, f32 accumulation — same dtype policy as the dense
    # row-1 bench (f32 payloads: ~6.1 ms / 16.9 eff TFLOPS)
    S = BlockSparseMatrix.random((n, n), block_density=0.01, block_size=bs,
                                 mesh=mesh, seed=0, dtype="bfloat16")
    D = BlockMatrix.random((n, 512), mesh=mesh, seed=1, dtype="bfloat16")
    fetch = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))

    def chained(reps):
        cur = D  # C has D's shape (square S): feed the output back in
        for _ in range(reps):
            cur = spmm_lib.spmm(S, cur, cfg)
        np.asarray(fetch(cur.data))

    chained(2)
    # sub-ms op on a shared chip: median of several marginal estimates
    # over long chains, or the relay's dispatch jitter swamps the signal
    lo, hi = 5, 45
    ests = []
    for _ in range(3):
        t0 = time.perf_counter(); chained(lo); t_lo = time.perf_counter() - t0
        t0 = time.perf_counter(); chained(hi); t_hi = time.perf_counter() - t0
        ests.append(max((t_hi - t_lo) / (hi - lo), 1e-9))
    dt = sorted(ests)[1]
    fl = 2.0 * S.nnzb * bs * bs * 512
    return {"metric": "blocksparse_spmm_100k_1pct_wallclock",
            "value": round(dt * 1e3, 2), "unit": "ms", "nnzb": S.nnzb,
            "effective_tflops": round(fl / dt / 1e12, 3)}


def bench_pagerank(mesh, cfg):
    """Compact-table Pallas SpMV path (ops/pallas_spmv.py): plan built
    once per graph (host fill only — no table expansion; device tables
    are the 13 B/slot compact layout), 30 rounds in one fori_loop at
    f32 fidelity (passes=3; the expanded-table path at the same
    fidelity measured 32.4 ms/round)."""
    n, n_edges, rounds = 1_000_000, 10_000_000, 30
    from matrel_tpu.workloads.pagerank import (
        prepare_pagerank_onehot, run_pagerank_compact)
    rng = np.random.default_rng(0)
    src = rng.integers(0, n, n_edges, dtype=np.int32)
    dst = rng.integers(0, n, n_edges, dtype=np.int32)
    prepared = prepare_pagerank_onehot(src, dst, n)

    def run(r=rounds):
        out = run_pagerank_compact(prepared, rounds=r, passes=3)
        np.asarray(out[:1])

    run(1)          # table upload + compile of the small program
    run(rounds)     # warm the 30-round program
    dt = _timed(run, warm=0, reps=2)
    return {"metric": "pagerank_1M_30rounds_wallclock_per_round",
            "value": round(dt / rounds * 1e3, 2), "unit": "ms/round",
            "total_s": round(dt, 3), "impl": "compact-pallas-spmv"}


def bench_pagerank_10x(mesh, cfg):
    """10×-scale PageRank: 10M nodes / 100M edges, single chip. The
    compact 13 B/slot tables are what make this FIT at all — the
    expanded tables (~23.5 GB) exceed the chip's 16 GB HBM entirely —
    so this row tracks the HBM-capacity win as a re-runnable benchmark
    (round-2 VERDICT: it was prose in BASELINE.md row-5 notes). Fewer
    rounds than row 5: the per-round cost is what's tracked."""
    n, n_edges, rounds = 10_000_000, 100_000_000, 5
    from matrel_tpu.workloads.pagerank import (
        prepare_pagerank_onehot, run_pagerank_compact)
    rng = np.random.default_rng(0)
    src = rng.integers(0, n, n_edges, dtype=np.int32)
    dst = rng.integers(0, n, n_edges, dtype=np.int32)
    prepared = prepare_pagerank_onehot(src, dst, n)

    def run(r=rounds):
        out = run_pagerank_compact(prepared, rounds=r, passes=3)
        np.asarray(out[:1])

    run(1)
    run(rounds)
    dt = _timed(run, warm=0, reps=2)
    return {"metric": "pagerank_10M_100Medges_wallclock_per_round",
            "value": round(dt / rounds * 1e3, 1), "unit": "ms/round",
            "rounds_timed": rounds, "impl": "compact-pallas-spmv",
            "note": "expanded tables (~23.5 GB) cannot fit 16 GB HBM"}


def bench_cg(mesh, cfg):
    """Conjugate gradient on an implicit SPD 8k system: two MXU matmuls
    per iteration inside one jitted while_loop (tracked extra row —
    round-3 workload family, first on-chip number wanted round 4)."""
    import jax.numpy as jnp

    from matrel_tpu.workloads.cg import cg_runner
    n = 8192
    rng = np.random.default_rng(0)
    m = jnp.asarray(rng.standard_normal((n, n)).astype(np.float32)
                    / np.sqrt(n))
    b = jnp.asarray(rng.standard_normal(n).astype(np.float32))

    def matvec(p):
        # A = M·Mᵀ/1 + I — SPD, well-conditioned, never materialised
        return m @ (m.T @ p) + p

    run = cg_runner(matvec, tol=1e-5, maxiter=100)

    def go():
        x, it = run(b)
        float(x[0])            # forced fetch (relay acks early)
        return int(it)

    iters = go()               # compile + warm
    dt = _timed(go, warm=0)
    fl = 4.0 * n * n * iters   # 2 matmuls x 2nk flops per iteration
    return {"metric": "cg_8k_spd_wallclock", "value": round(dt, 3),
            "unit": "s", "iters": iters,
            "effective_tflops": round(fl / dt / 1e12, 2)}


def bench_eigen(mesh, cfg):
    """Power iteration, 50 rounds on a dense 8k matrix in one jitted
    fori_loop (tracked extra row — round-3 workload family)."""
    import jax.numpy as jnp

    from matrel_tpu.workloads.eigen import power_runner
    n, rounds = 8192, 50
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.standard_normal((n, n)).astype(np.float32)
                    / np.sqrt(n))
    run = power_runner(rounds, 0)

    def go():
        lam, v = run(a)
        return float(lam)

    lam = go()                 # compile + warm
    dt = _timed(go, warm=0)
    fl = 2.0 * n * n * (rounds + 1)   # rounds matvecs + the final A.v
    return {"metric": "power_iteration_8k_50rounds_wallclock",
            "value": round(dt, 3), "unit": "s",
            "dominant_eig": round(lam, 4),
            "effective_tflops": round(fl / dt / 1e12, 2)}


def bench_triangles(mesh, cfg):
    """Triangle counting on a dense 8k 0/1 adjacency through the FULL
    query stack: trace(A·A·A) — chain DP ties, R3 pushes the diagonal
    aggregate into the final multiply, so the compiled plan does one
    full matmul plus a diagonal-only contraction (tracked extra row)."""
    import jax
    import jax.numpy as jnp

    from matrel_tpu import executor as executor_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.workloads.triangles import triangle_count_expr
    n = 8192
    rng = np.random.default_rng(2)
    a = (rng.random((n, n)) < 0.01).astype(np.float32)
    a = np.triu(a, 1)
    a = a + a.T
    A = BlockMatrix.from_numpy(a, mesh=mesh)
    plan = executor_lib.compile_expr(triangle_count_expr(A), mesh, cfg)
    fetch = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))

    def go():
        out = plan.run()
        return float(np.asarray(fetch(out.data)))

    tri6 = go()                # compile + warm
    dt = _timed(go, warm=0)
    fl = 2.0 * n * n * n + 2.0 * n * n   # post-R3: one matmul + diag
    return {"metric": "triangles_8k_dense_wallclock",
            "value": round(dt, 3), "unit": "s",
            "triangles": int(round(tri6 / 6.0)),
            "effective_tflops": round(fl / dt / 1e12, 2)}


def bench_north_star(mesh, cfg):
    from matrel_tpu.workloads.big_chain import (
        streaming_chain_slab, cheap_gen, north_star_flops)
    n, tile, panel = 65_536, 8192, 16_384
    gens = tuple(cheap_gen(s, tile) for s in (1, 2, 3))
    def run():
        float(streaming_chain_slab(n, *gens, tile=tile, panel=panel))
    dt = _timed(run, warm=1, reps=2)
    return {"metric": "north_star_65k_chain_wallclock", "value": round(dt, 2),
            "unit": "s", "tflops_per_chip": round(north_star_flops(n) / dt / 1e12, 1),
            "note": "slab-scheduled, streamed on ONE v5e chip "
                    "(spec target: v5e-64)"}


def main():
    # probe the backend FIRST (subprocess + hard timeout, with
    # bench.py's retry/backoff schedule) — while the axon relay is
    # wedged, backend init HANGS rather than erroring, and this process
    # would block before printing anything (docs/INTERNALS.md "relay
    # can wedge"). NOTE this bounds the wedged-at-start case only: a
    # wedge striking MID-run still hangs the current benchmark — this
    # is an operator-attended tool; the driver's unattended capture
    # path (bench.py) isolates every TPU stage in its own timed
    # subprocess instead.
    import bench
    errors = []
    for attempt in range(1 + len(bench.BACKOFFS_S)):
        if attempt > 0:
            delay = bench.BACKOFFS_S[attempt - 1]
            print(f"# probe failed ({errors[-1]}); retrying in {delay}s",
                  file=sys.stderr, flush=True)
            time.sleep(delay)
        ok, payload = bench._run_child("probe", bench.PROBE_TIMEOUT_S)
        if ok:
            break
        errors.append(str(payload))
    else:
        print(json.dumps({"metric": "bench_all",
                          "error": "; ".join(errors)[-800:]}), flush=True)
        sys.exit(2)
    from matrel_tpu.config import MatrelConfig, set_default_config
    from matrel_tpu.core import mesh as mesh_lib
    cfg = MatrelConfig()
    set_default_config(cfg)
    mesh = mesh_lib.make_mesh()
    # MATREL_DRY (tools/tpu_batch.sh --dry): run the rows whose fixed
    # configs are CPU-feasible, emit an explicit parseable skip record
    # for each row whose hard-coded full scale is not (10M-row linreg,
    # 100k SpMM, the 65k north star, …) — the fire-drill proves the
    # step order, the JSON contract and the harness glue, not the
    # numbers.
    dry = bool(os.environ.get("MATREL_DRY"))
    dry_rows = (bench_dense_4k, bench_chain, bench_spgemm,
                bench_sparse_kernels, bench_fusion, bench_serve,
                bench_cse, bench_fleet, bench_stream, bench_precision,
                bench_reshard, bench_traffic)
    for fn in (bench_dense_4k, bench_chain, bench_linreg, bench_spmm,
               bench_spgemm, bench_sparse_kernels, bench_fusion,
               bench_serve, bench_cse, bench_fleet, bench_stream,
               bench_precision, bench_reshard, bench_traffic,
               bench_pagerank, bench_pagerank_10x, bench_cg,
               bench_eigen, bench_triangles, bench_north_star):
        if dry and fn not in dry_rows:
            print(json.dumps({"metric": fn.__name__, "skipped": "dry"}),
                  flush=True)
            continue
        try:
            print(json.dumps(fn(mesh, cfg)), flush=True)
        except Exception as e:  # keep the suite running
            print(json.dumps({"metric": fn.__name__, "error": repr(e)}),
                  flush=True)


if __name__ == "__main__":
    main()
