"""lockcheck — static lock-order analyzer for the serve/fleet
concurrency plane (the LK1xx rule family; matlint's sibling, one
abstraction level up: matlint pins per-line hazards, lockcheck derives
the INTERPROCEDURAL lock-nesting graph and proves order/hold-span
properties over it — docs/CONCURRENCY.md).

Every concurrency bug shipped so far (the PR 8 submit/close race,
PR 15's directory-invalidation ordering and wedged-slice drain) was
caught by hand in review. lockcheck inventories every lock the
ML017 seam (utils/lockdep.py) constructs, resolves ``with`` blocks to
those locks through the call graph, and flags:

  LK101  lock-order cycle: two locks observed nesting in both orders
         across any pair of code paths — a schedule exists that
         deadlocks (the static half of lockdep's inversion check)
  LK102  blocking call while holding a lock: ``block_until_ready``,
         ``Future.result``, ``Thread.join`` / queue joins,
         ``time.sleep``, ``.to_numpy`` host transfers — directly or
         through any transitive callee (the PR 8 drain-wedge class).
         Locks constructed with ``dispatch_ok=True`` (the fleet's
         dispatch-to-completion arbitration) are sanctioned and
         exempt.
  LK103  shared attribute written from two or more declared thread
         roots (serve worker, replication daemon, metrics exporter,
         future done-callbacks — the THREAD_ROOTS table) with no
         common lock guarding every write site
  LK104  double-acquisition of a non-reentrant Lock on any path
         (directly nested ``with``, or a call whose transitive
         acquisition set re-takes a plain Lock already held)

Usage:
    python tools/lockcheck.py                 # scan matrel_tpu/, rc 1 on findings
    python tools/lockcheck.py --list-rules
    python tools/lockcheck.py --graph         # dump the nesting graph

Suppression: append ``# lockcheck: disable=LK102 <why>`` to the line
the finding anchors on (comma-separated codes; justification prose
mandatory by convention). The repo-wide run (``make lint``,
tests/test_lockcheck.py) stays green only through deliberate,
reviewable suppressions — the matlint discipline.

Soundness notes (deliberate approximations, documented for the
reviewer): acquisition via bare ``.acquire()`` calls is not modeled
(the package idiom is ``with``); calls are resolved for ``self.m()``,
same-module ``f()`` and lexically-nested functions — foreign-object
calls (``pipe.readmit_entry()``) resolve only through the ALIASES
table, so the graph under-approximates across objects it cannot
type; a ``with obj.attr:`` whose attribute is not unique package-wide
and not aliased becomes an AMBIGUOUS hold — counted for LK102 hold
spans, excluded from LK101/LK104 edges (a wrong edge would fabricate
deadlocks).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import sys
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_PATHS = ("matrel_tpu",)

_SUPPRESS_RE = re.compile(r"#\s*lockcheck:\s*disable=([A-Za-z0-9_,\s]+)")

#: Declared thread entry points (the LK103 root table): qualnames per
#: root, or "*" for every function in the module. An attribute
#: written from >= 2 distinct roots with no common guard is a data
#: race candidate. Fixture tests pass their own table.
THREAD_ROOTS: Dict[str, Sequence[Tuple[str, str]]] = {
    "serve_worker": (("matrel_tpu/serve/pipeline.py",
                      "ServePipeline._run"),),
    "drain_sync": (("matrel_tpu/serve/pipeline.py", "_sync"),),
    "replication": (("matrel_tpu/serve/fleet.py",
                     "FleetController._maybe_replicate.<locals>._run"),
                    ("matrel_tpu/serve/fleet.py",
                     "FleetController._replicate_entry")),
    "finalizer": (("matrel_tpu/serve/fleet.py",
                   "FleetController._track_insert.<locals>._done"),),
    "exporter": (("matrel_tpu/obs/export.py", "*"),),
}

#: Foreign-receiver lock resolution: (module relpath, dotted source
#: text) -> declared lock name. The one place cross-object knowledge
#: is stated instead of inferred (the THREAD_ROOTS discipline).
ALIASES: Dict[Tuple[str, str], str] = {
    ("matrel_tpu/serve/fleet.py", "pipe._lock"): "serve.pipeline",
}

#: LK102 blocking vocabulary: dotted-tail -> label. ``.join`` is
#: special-cased in ``_is_blocking`` (str.join excluded by arg shape).
_BLOCKING_TAILS = {
    "block_until_ready": "device sync",
    "result": "Future.result",
    "sleep": "time.sleep",
    "to_numpy": "host transfer",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclasses.dataclass(frozen=True)
class Lock:
    """One inventoried lock. ``lid`` is the declared seam name
    (make_lock("fleet.directory")) or the derived ``Class.attr`` /
    ``module:var`` id for bare constructions (fixtures)."""
    lid: str
    reentrant: bool
    dispatch_ok: bool
    module: str
    line: int
    ambiguous: bool = False


_AMBIGUOUS = Lock("?", reentrant=True, dispatch_ok=False,
                  module="?", line=0, ambiguous=True)


def _dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return (base + "." if base else ".") + node.attr
    return ""


def _lock_ctor(call: ast.Call) -> Optional[Tuple[bool, Optional[str],
                                                 bool]]:
    """(reentrant, declared_name, dispatch_ok) when ``call`` builds a
    lock through the seam or bare threading — else None."""
    tail = _dotted(call.func).rsplit(".", 1)[-1]
    if tail in ("make_lock", "make_rlock"):
        name = None
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            name = call.args[0].value
        ok = any(k.arg == "dispatch_ok"
                 and isinstance(k.value, ast.Constant)
                 and bool(k.value.value) for k in call.keywords)
        return (tail == "make_rlock", name, ok)
    if _dotted(call.func) in ("threading.Lock", "threading.RLock",
                              "Lock", "RLock"):
        return (tail == "RLock", None, False)
    return None


def _is_blocking(call: ast.Call) -> Optional[str]:
    """LK102 vocabulary match (label) or None."""
    tail = _dotted(call.func).rsplit(".", 1)[-1]
    if tail in _BLOCKING_TAILS:
        # plain attribute access `fut.result` (no call) never gets
        # here; `.result()` with args is still Future.result(timeout)
        return _BLOCKING_TAILS[tail]
    if tail == "join":
        # exclude str.join: a str-literal receiver, or a single
        # positional argument that is an iterable display /
        # comprehension / string (the separator.join(parts) shape) —
        # Thread/queue joins take nothing, a numeric timeout, or
        # timeout=
        func = call.func
        if isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Constant):
            return None
        if _dotted(func).endswith("path.join") or len(call.args) > 1:
            return None     # os.path.join — not a thread/queue join
        if len(call.args) == 1 and not call.keywords:
            a = call.args[0]
            if isinstance(a, (ast.List, ast.Tuple, ast.GeneratorExp,
                              ast.ListComp, ast.SetComp)):
                return None
            if isinstance(a, ast.Constant) \
                    and not isinstance(a.value, (int, float)):
                return None
        return "join"
    return None


@dataclasses.dataclass
class FuncInfo:
    module: str
    qual: str
    cls: Optional[str]
    node: ast.AST
    # populated by the scan:
    acquires: List[Tuple[Lock, int]] = dataclasses.field(
        default_factory=list)
    calls: List[Tuple[str, Tuple[str, ...], int]] = dataclasses.field(
        default_factory=list)       # (callee_key_or_"", held lids, line)
    blocking: List[Tuple[str, Tuple[str, ...], int]] = \
        dataclasses.field(default_factory=list)  # (label, held, line)
    writes: List[Tuple[str, Tuple[str, ...], int]] = \
        dataclasses.field(default_factory=list)  # (attr, held, line)
    edges: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list)       # (held lid, acquired lid, line)
    double: List[Tuple[str, int]] = dataclasses.field(
        default_factory=list)       # (lid, line) direct re-acquire


class Analyzer:
    """Whole-package pass: inventory -> per-function scan -> call-
    graph fixpoint -> LK101..LK104 findings."""

    def __init__(self, files: Dict[str, ast.Module],
                 thread_roots=None, aliases=None):
        self.files = files
        self.thread_roots = (THREAD_ROOTS if thread_roots is None
                             else thread_roots)
        self.aliases = ALIASES if aliases is None else aliases
        self.locks: Dict[str, Lock] = {}            # lid -> Lock
        self.by_class_attr: Dict[Tuple[str, str], str] = {}
        self.by_attr: Dict[str, Set[str]] = {}
        self.by_module_var: Dict[Tuple[str, str], str] = {}
        # conditions: (class, attr) / attr -> underlying lock lid
        self.cond_by_class_attr: Dict[Tuple[str, str], str] = {}
        self.cond_by_attr: Dict[str, Set[str]] = {}
        self.funcs: Dict[Tuple[str, str], FuncInfo] = {}
        self.findings: List[Finding] = []

    # -- pass 1: lock + function inventory -----------------------------------

    def _inventory(self) -> None:
        for mod, tree in self.files.items():
            for cls, fn, node in _iter_funcs(tree):
                self.funcs[(mod, fn)] = FuncInfo(mod, fn, cls, node)
            for cls_name, target, call, line in _iter_lock_decls(tree):
                ctor = _lock_ctor(call)
                if ctor is not None:
                    reentrant, name, ok = ctor
                    lid = name or (f"{cls_name}.{target}" if cls_name
                                   else f"{mod}:{target}")
                    lk = Lock(lid, reentrant, ok, mod, line)
                    self.locks.setdefault(lid, lk)
                    if cls_name:
                        self.by_class_attr[(cls_name, target)] = lid
                        self.by_attr.setdefault(target, set()).add(lid)
                    else:
                        self.by_module_var[(mod, target)] = lid
                        self.by_attr.setdefault(target, set()).add(lid)
                    continue
                if _dotted(call.func).rsplit(".", 1)[-1] == "Condition" \
                        and call.args:
                    under = self._resolve_expr(call.args[0], mod,
                                               cls_name)
                    if under is not None and not under.ambiguous:
                        if cls_name:
                            self.cond_by_class_attr[
                                (cls_name, target)] = under.lid
                        self.cond_by_attr.setdefault(
                            target, set()).add(under.lid)

    # -- lock-expression resolution ------------------------------------------

    def _resolve_expr(self, expr: ast.AST, mod: str,
                      cls: Optional[str]) -> Optional[Lock]:
        """``with EXPR:`` -> Lock, _AMBIGUOUS, or None (not a lock)."""
        dotted = _dotted(expr)
        if not dotted:
            return None
        alias = self.aliases.get((mod, dotted))
        if alias is not None:
            return self.locks.get(alias, _AMBIGUOUS)
        if isinstance(expr, ast.Name):
            lid = self.by_module_var.get((mod, expr.id))
            return self.locks[lid] if lid else None
        if not isinstance(expr, ast.Attribute):
            return None
        attr = expr.attr
        if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and cls is not None:
            lid = self.by_class_attr.get((cls, attr))
            if lid:
                return self.locks[lid]
            cid = self.cond_by_class_attr.get((cls, attr))
            if cid:
                return self.locks[cid]
        # foreign receiver (or self in an unindexed class): unique-
        # attribute resolution across the package, else ambiguous —
        # but ONLY for lock-looking attributes; `with self._q.
        # all_tasks_done:` resolves through the condition index
        cands = self.by_attr.get(attr, set())
        if len(cands) == 1:
            return self.locks[next(iter(cands))]
        ccands = self.cond_by_attr.get(attr, set())
        if len(ccands) == 1:
            return self.locks[next(iter(ccands))]
        if cands or ccands or attr.endswith("lock") \
                or attr.startswith("_lock"):
            return _AMBIGUOUS
        return None

    # -- pass 2: per-function scan -------------------------------------------

    def _scan_all(self) -> None:
        for info in self.funcs.values():
            held: List[Lock] = []
            for st in info.node.body:
                self._scan(st, held, info)

    def _scan(self, node: ast.AST, held: List[Lock],
              info: FuncInfo) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return      # deferred execution: scanned as its own node
        if isinstance(node, ast.With):
            pushed = 0
            for item in node.items:
                self._scan(item.context_expr, held, info)
                lk = self._resolve_expr(item.context_expr, info.module,
                                        info.cls)
                if lk is None:
                    continue
                line = item.context_expr.lineno
                if not lk.ambiguous:
                    info.acquires.append((lk, line))
                    for h in held:
                        if h.ambiguous or h.lid == lk.lid:
                            continue
                        info.edges.append((h.lid, lk.lid, line))
                    if not lk.reentrant and any(
                            h.lid == lk.lid for h in held):
                        info.double.append((lk.lid, line))
                held.append(lk)
                pushed += 1
            for st in node.body:
                self._scan(st, held, info)
            del held[len(held) - pushed:]
            return
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Subscript):
                    t = t.value
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" \
                        and info.cls is not None \
                        and not info.qual.endswith("__init__"):
                    info.writes.append(
                        (t.attr, tuple(h.lid for h in held),
                         node.lineno))
        if isinstance(node, ast.Call):
            callee = self._resolve_call(node, info)
            info.calls.append((callee,
                               tuple(h.lid for h in held),
                               node.lineno))
            label = _is_blocking(node)
            if label is not None:
                info.blocking.append(
                    (label, tuple(h.lid for h in held), node.lineno))
        for child in ast.iter_child_nodes(node):
            self._scan(child, held, info)

    def _resolve_call(self, call: ast.Call, info: FuncInfo) -> str:
        """Callee key "module|qual" or "" when unresolvable."""
        func = call.func
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name) \
                and func.value.id == "self" and info.cls is not None:
            key = (info.module, f"{info.cls}.{func.attr}")
            if key in self.funcs:
                return f"{key[0]}|{key[1]}"
        elif isinstance(func, ast.Name):
            # lexically nested first (the closure-call idiom), then
            # module level
            parts = info.qual.split(".<locals>.")
            for depth in range(len(parts), 0, -1):
                nested = ".<locals>.".join(
                    parts[:depth] + [func.id])
                if (info.module, nested) in self.funcs:
                    return f"{info.module}|{nested}"
            if (info.module, func.id) in self.funcs:
                return f"{info.module}|{func.id}"
        return ""

    # -- pass 3: fixpoints ----------------------------------------------------

    def _fixpoints(self):
        acq: Dict[Tuple[str, str], Set[str]] = {}
        blk: Dict[Tuple[str, str], Optional[Tuple[str, int]]] = {}
        for key, info in self.funcs.items():
            acq[key] = {lk.lid for lk, _ in info.acquires}
            blk[key] = (info.blocking[0][:1] + (info.blocking[0][2],)
                        if info.blocking else None)
        changed = True
        while changed:
            changed = False
            for key, info in self.funcs.items():
                for callee, _, _ in info.calls:
                    if not callee:
                        continue
                    ck = tuple(callee.split("|", 1))
                    extra = acq.get(ck, set()) - acq[key]
                    if extra:
                        acq[key] |= extra
                        changed = True
                    if blk[key] is None and blk.get(ck) is not None:
                        blk[key] = blk[ck]
                        changed = True
        return acq, blk

    # -- pass 4: findings -----------------------------------------------------

    def run(self) -> List[Finding]:
        self._inventory()
        self._scan_all()
        acq_star, blk_star = self._fixpoints()

        # assemble the full edge set: direct nesting + held-across-call
        edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for key, info in self.funcs.items():
            for a, b, line in info.edges:
                edges.setdefault((a, b), (info.module, line))
            for callee, held, line in info.calls:
                if not callee or not held:
                    continue
                ck = tuple(callee.split("|", 1))
                for b in sorted(acq_star.get(ck, ())):
                    for a in held:
                        if a != b and not a.startswith("?"):
                            edges.setdefault((a, b),
                                             (info.module, line))
        self.edge_index = edges

        # LK101: cycles
        for cycle in _cycles({e for e in edges}):
            sites = sorted((edges[(a, b)], (a, b))
                           for a, b in zip(cycle, cycle[1:] + cycle[:1])
                           if (a, b) in edges)
            (mod, line), _ = sites[0]
            path = " -> ".join(cycle + [cycle[0]])
            self.findings.append(Finding(
                mod, line, "LK101",
                f"lock-order cycle {path}: these locks nest in both "
                f"orders across the code paths meeting here — a "
                f"thread interleaving exists that deadlocks; pick ONE "
                f"global order (docs/CONCURRENCY.md) or break the "
                f"nesting"))

        # LK102: blocking while holding (direct + via calls)
        for key, info in self.funcs.items():
            for label, held, line in info.blocking:
                eff = self._unsanctioned(held)
                if eff:
                    self.findings.append(Finding(
                        info.module, line, "LK102",
                        f"blocking call ({label}) while holding "
                        f"{_fmt(eff)} — the drain-wedge class: any "
                        f"thread needing the lock stalls behind "
                        f"device/host waits; move the wait outside "
                        f"the hold span"))
            for callee, held, line in info.calls:
                eff = self._unsanctioned(held)
                if not callee or not eff:
                    continue
                ck = tuple(callee.split("|", 1))
                b = blk_star.get(ck)
                if b is not None:
                    self.findings.append(Finding(
                        info.module, line, "LK102",
                        f"call into {ck[1]}() while holding "
                        f"{_fmt(eff)} — it blocks ({b[0]}, "
                        f"{ck[0]}:{b[1]}) with the lock still held; "
                        f"move the blocking work outside the hold "
                        f"span"))

        # LK104: double acquisition of a non-reentrant lock
        for key, info in self.funcs.items():
            for lid, line in info.double:
                self.findings.append(Finding(
                    info.module, line, "LK104",
                    f"non-reentrant lock {lid!r} re-acquired while "
                    f"already held — self-deadlock; make it an RLock "
                    f"(make_rlock) or hoist the outer hold"))
            for callee, held, line in info.calls:
                if not callee:
                    continue
                ck = tuple(callee.split("|", 1))
                for lid in held:
                    lk = self.locks.get(lid)
                    if (lk is not None and not lk.reentrant
                            and lid in acq_star.get(ck, ())):
                        self.findings.append(Finding(
                            info.module, line, "LK104",
                            f"call into {ck[1]}() re-acquires the "
                            f"non-reentrant lock {lid!r} already "
                            f"held here — self-deadlock on this "
                            f"path"))

        # LK103: shared writes from >= 2 thread roots, no common guard
        self._lk103()
        return sorted(self.findings,
                      key=lambda f: (f.path, f.line, f.rule))

    def _unsanctioned(self, held: Tuple[str, ...]) -> List[str]:
        out = []
        for lid in held:
            lk = self.locks.get(lid)
            if lk is not None and lk.dispatch_ok:
                continue
            out.append(lid)
        return out

    def _lk103(self) -> None:
        reach: Dict[Tuple[str, str], Set[str]] = {}
        for root, seeds in self.thread_roots.items():
            frontier = []
            for mod, qual in seeds:
                if qual == "*":
                    frontier.extend(k for k in self.funcs
                                    if k[0] == mod)
                elif (mod, qual) in self.funcs:
                    frontier.append((mod, qual))
            seen = set(frontier)
            while frontier:
                key = frontier.pop()
                reach.setdefault(key, set()).add(root)
                for callee, _, _ in self.funcs[key].calls:
                    if callee:
                        ck = tuple(callee.split("|", 1))
                        if ck in self.funcs and ck not in seen:
                            seen.add(ck)
                            frontier.append(ck)
        # (class, attr) -> [(roots, guards, module, line)]
        sites: Dict[Tuple[str, str], list] = {}
        for key, info in self.funcs.items():
            roots = reach.get(key)
            if not roots or info.cls is None:
                continue
            for attr, held, line in info.writes:
                sites.setdefault((info.cls, attr), []).append(
                    (roots, set(held), info.module, line))
        for (cls, attr), ws in sorted(sites.items()):
            roots = set().union(*(w[0] for w in ws))
            if len(roots) < 2:
                continue
            common = set.intersection(*(w[1] for w in ws))
            if common:
                continue
            mod, line = ws[0][2], ws[0][3]
            self.findings.append(Finding(
                mod, line, "LK103",
                f"{cls}.{attr} written from {len(roots)} thread "
                f"roots ({', '.join(sorted(roots))}) with no common "
                f"guard across the write sites — a lost-update race; "
                f"guard every write with one lock (or confine the "
                f"attribute to one thread)"))


def _fmt(lids: Sequence[str]) -> str:
    return ", ".join(repr(x) for x in lids)


def _iter_funcs(tree: ast.Module) -> Iterator[
        Tuple[Optional[str], str, ast.AST]]:
    """(class, qualname, node) for every function incl. nested ones.
    Nested functions inherit the enclosing class for ``self``
    resolution (closures over methods — the replication daemon)."""

    def walk(node, cls, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, child.name, child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                qual = (f"{prefix}.{child.name}" if prefix
                        else child.name)
                yield cls, qual, child
                yield from walk(child, cls, f"{qual}.<locals>")
            else:
                yield from walk(child, cls, prefix)

    yield from walk(tree, None, "")


def _iter_lock_decls(tree: ast.Module) -> Iterator[
        Tuple[Optional[str], str, ast.Call, int]]:
    """(class_or_None, attr_or_var, ctor_call, line) for every
    ``self.X = <ctor>`` / module-level ``V = <ctor>`` assignment."""
    for cls, qual, fnode in _iter_funcs(tree):
        for node in ast.walk(fnode):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.value, ast.Call):
                t = node.targets[0]
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" and cls is not None:
                    yield cls, t.attr, node.value, node.lineno
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            yield (None, node.targets[0].id, node.value, node.lineno)


def _cycles(edges: Set[Tuple[str, str]]) -> List[List[str]]:
    """Elementary cycles, canonicalized + deduplicated (rotation-
    invariant), smallest first — Tarjan SCCs then one simple cycle
    per strongly-connected component pair."""
    adj: Dict[str, Set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    out = []
    seen = set()
    for a, b in sorted(edges):
        # a cycle through edge (a, b) exists iff b reaches a
        stack, visited, parent = [b], {b}, {}
        found = False
        while stack and not found:
            n = stack.pop()
            for m in sorted(adj.get(n, ())):
                if m == a:
                    parent[m] = n
                    found = True
                    break
                if m not in visited:
                    visited.add(m)
                    parent[m] = n
                    stack.append(m)
        if not found:
            continue
        # edge a->b, then b ~> a along the parent chain (recorded
        # child -> parent while searching forward from b)
        rev = []
        n = parent.get(a)
        while n is not None and n != b:
            rev.append(n)
            n = parent.get(n)
        cyc = [a, b] + rev[::-1]
        # canonical rotation for dedup
        i = cyc.index(min(cyc))
        canon = tuple(cyc[i:] + cyc[:i])
        if canon not in seen:
            seen.add(canon)
            out.append(list(canon))
    return sorted(out, key=lambda c: (len(c), c))


# -- file plumbing (the matlint skeleton) ------------------------------------

def _rel(path: str, root: str) -> str:
    try:
        return os.path.relpath(path, root)
    except ValueError:
        return path


def iter_python_files(paths: Sequence[str],
                      root: str = REPO) -> Iterator[str]:
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            yield full
        elif os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def _suppressed_codes(line: str) -> set:
    m = _SUPPRESS_RE.search(line)
    if not m:
        return set()
    return {tok for tok in re.split(r"[\s,]+", m.group(1))
            if re.fullmatch(r"LK\d+", tok)}


def analyze_paths(paths: Sequence[str] = DEFAULT_PATHS,
                  root: str = REPO, thread_roots=None, aliases=None,
                  ) -> List[Finding]:
    """Analyze a file set and return unsuppressed findings. The
    fixture-test entry point: tests point ``root`` at a tmp mini-
    package with their own roots/aliases tables."""
    files: Dict[str, ast.Module] = {}
    sources: Dict[str, List[str]] = {}
    findings: List[Finding] = []
    for f in iter_python_files(paths, root):
        rel = _rel(f, root)
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        try:
            files[rel] = ast.parse(src, filename=f)
        except SyntaxError as e:
            findings.append(Finding(rel, e.lineno or 0, "LK100",
                                    f"file does not parse: {e.msg}"))
            continue
        sources[rel] = src.splitlines()
    ana = Analyzer(files, thread_roots=thread_roots, aliases=aliases)
    for f in ana.run():
        lines = sources.get(f.path, ())
        line = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        if f.rule in _suppressed_codes(line):
            continue
        findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def analyzer_for(paths: Sequence[str] = DEFAULT_PATHS,
                 root: str = REPO, thread_roots=None,
                 aliases=None) -> Analyzer:
    """The raw analyzer (post-run) — graph/inventory introspection
    for --graph and the lockcheck tests."""
    files = {}
    for f in iter_python_files(paths, root):
        with open(f, encoding="utf-8") as fh:
            try:
                files[_rel(f, root)] = ast.parse(fh.read(),
                                                 filename=f)
            except SyntaxError:
                continue
    ana = Analyzer(files, thread_roots=thread_roots, aliases=aliases)
    ana.run()
    return ana


_RULES = (
    ("LK101", "lock-order cycle in the interprocedural nesting graph"),
    ("LK102", "blocking call (device sync / join / sleep / host "
              "transfer) while holding a lock"),
    ("LK103", "shared attribute written from >= 2 thread roots with "
              "no common guard"),
    ("LK104", "double-acquisition of a non-reentrant Lock"),
)


def main(argv: Sequence[str]) -> int:
    if "--list-rules" in argv:
        for rid, desc in _RULES:
            print(f"{rid}  {desc}")
        return 0
    paths = [a for a in argv if not a.startswith("-")] or list(
        DEFAULT_PATHS)
    if "--graph" in argv:
        ana = analyzer_for(paths)
        print(f"locks ({len(ana.locks)}):")
        for lid, lk in sorted(ana.locks.items()):
            print(f"  {lid}  {'RLock' if lk.reentrant else 'Lock'}"
                  f"{'  dispatch_ok' if lk.dispatch_ok else ''}"
                  f"  {lk.module}:{lk.line}")
        print(f"nesting edges ({len(ana.edge_index)}):")
        for (a, b), (mod, line) in sorted(ana.edge_index.items()):
            print(f"  {a} -> {b}  ({mod}:{line})")
        return 0
    findings = analyze_paths(paths)
    for f in findings:
        print(f.render())
    print(f"lockcheck: {len(findings)} finding(s) in scan set "
          f"{tuple(paths)}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
