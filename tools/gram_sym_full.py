"""Full 10Mx1k fit_streaming with the round-3 symmetric 2-pass Gram.

MATREL_GRAMFULL_{N,K,PANEL} scale it down for the dry-batch
fire-drill (tools/tpu_batch.sh --dry) — same streaming path."""
import os
import sys
import time, json

# run as a script from anywhere (the round-6 dry fire-drill caught this
# staged tool crashing on import — tools/ is the script dir, not the
# repo root, so the package was never importable)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np
from matrel_tpu.workloads.linreg import fit_streaming
from matrel_tpu.core import mesh as mesh_lib

n = int(os.environ.get("MATREL_GRAMFULL_N", 10_000_000))
k = int(os.environ.get("MATREL_GRAMFULL_K", 1000))
panel = int(os.environ.get("MATREL_GRAMFULL_PANEL", 250_000))

def panel_fn(p):
    r = jnp.arange(panel, dtype=jnp.int32)[:, None]
    c = jnp.arange(k, dtype=jnp.int32)[None, :]
    s = r * 1664525 + c * 1013904223 + p * 69069 + 12345
    s = s * 1664525 + 1013904223
    xp = (s >> 8).astype(jnp.float32) * (2.0 ** -23)
    yp = xp @ jnp.ones((k, 1), jnp.float32)
    return xp, yp

mesh = mesh_lib.make_mesh()
def run():
    theta = fit_streaming(n, k, panel_fn, panel_rows=panel, mesh=mesh,
                          precision="high")
    return np.asarray(theta)

th = run()   # compile + warm; also correctness
ts = []
for _ in range(3):
    t0 = time.perf_counter(); run(); ts.append(time.perf_counter() - t0)
dt = sorted(ts)[1]
fl = 2.0 * n * k * k + 2.0 * n * k
print(json.dumps({"metric": "linreg_sym2pass_10Mx1k_s",
                  "value": round(dt, 3),
                  "effective_tflops": round(fl / dt / 1e12, 2),
                  "theta_head": [round(float(v), 5) for v in th[:3, 0]]}))
