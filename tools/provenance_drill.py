"""Obs tier-4 smoke drill: answer provenance ledger + audit replay.

Drives real sessions through every provenance-bearing serve path and
then proves the ledgers by full audit replay (the tpu_batch.sh
fire-drill discipline — a staged tool that crashes on import is found
HERE, not in a relay window):

  1. a 3-query serve batch (``run_many``) twice — fresh ``execute``
     records, then whole ``rc_hit`` records — plus a superexpression
     (``rc_interior``) on a ledger-enabled session;
  2. a catalog REBIND (plain ``register``) followed by a COO delta
     (``register_delta``) — the re-served query's record is
     ``ivm_patched`` with the patch chain attached;
  3. a 2-slice fleet: repeat submits cross the directory
     (``fleet_directory``), trip hot-entry replication, and the next
     ask serves from the replica (``fleet_replica``);
  4. an injected-fault session that climbs the full degradation
     ladder — the completing attempt's record is ``degraded`` at
     rung 4;
  5. FULL audit replay over every ledger (cache bypassed, MV113
     comparison: bit-equal when the composed bound is 0, within the
     stamped err_bound otherwise) + the MV115 dynamic ledger check.

Emits one parseable JSON line (tools/tpu_batch.sh step; asserted by
tests/test_batch_dry.py). CPU-only by construction — this drills the
lineage plumbing, not the chip, so it forces the CPU backend even
inside a TPU batch (wedge-safe: never touches the relay). Artifact
paths follow the config env knobs (MATREL_OBS_EVENT_LOG), so the dry
batch redirects the event log outside the repo.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _paths(sess):
    led = sess._prov
    return sorted({r.path for r in led.records()}) if led else []


def main() -> int:
    from matrel_tpu.analysis import provenance_pass
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.obs import provenance as provenance_lib
    from matrel_tpu.session import MatrelSession

    # env (MATREL_*) overrides flow over the drill's base configs, so
    # the dry batch's redirects land every artifact outside the repo
    base = dict(obs_level="on", obs_provenance=256,
                result_cache_max_bytes=1 << 28)
    mesh = mesh_lib.make_mesh((2, 4))
    rng = np.random.default_rng(0)

    # 1. + 2. serve batch / hits / interior / rebind + delta patch
    sess = MatrelSession(
        mesh=mesh, config=MatrelConfig.from_env(MatrelConfig(**base)))
    adj = (rng.random((48, 48)) < 0.2).astype(np.float32)
    sess.register("A", sess.from_numpy(adj, integral=True))
    sess.register("B", sess.from_numpy(
        rng.standard_normal((48, 32)).astype(np.float32)))

    def q_counts():
        return sess.table("A").expr().multiply(sess.table("A").expr())

    def q_ab():
        return sess.table("A").expr().multiply(sess.table("B").expr())

    batch = [q_ab(), q_ab().multiply_scalar(2.0), q_counts()]
    sess.run_many(batch)
    sess.run_many(batch)                      # whole hits
    sess.run(q_ab().multiply_scalar(3.0))     # interior substitution
    # rebind B (invalidation, a fresh execute on the re-serve) ...
    sess.register("B", sess.from_numpy(
        rng.standard_normal((48, 32)).astype(np.float32)))
    sess.run(q_ab())
    # ... then a sparse delta on A: the patched entry's next serve is
    # the ivm_patched path, exact (integer path counts)
    k = 6
    sess.register_delta(
        "A", (rng.integers(0, 48, k), rng.integers(0, 48, k),
              np.ones(k, np.float32)), kind="coo")
    sess.run(q_counts())
    serve_paths = _paths(sess)

    # 3. fleet: directory hit, replication, replica-local serve
    fsess = MatrelSession(mesh=mesh, config=MatrelConfig.from_env(
        MatrelConfig(fleet_slices=2, fleet_replicate_hits=1, **base)))
    fsess.register("A", fsess.from_numpy(
        rng.standard_normal((64, 64)).astype(np.float32)))
    fsess.register("B", fsess.from_numpy(
        rng.standard_normal((64, 64)).astype(np.float32)))
    fq = fsess.table("A").expr().multiply(fsess.table("B").expr())
    fsess.submit(fq).result(timeout=120)      # placed execute
    fsess.serve_drain()
    fsess.submit(fq).result(timeout=120)      # directory hit (remote)
    fleet = fsess._ensure_fleet()
    fleet.quiesce_replication(timeout=60)
    for _ in range(4):
        # placement load-balances the preferred slice across repeats;
        # the ask that prefers the replica's slice serves from it
        fsess.submit(fq).result(timeout=120)
        fsess.serve_drain()
        if "fleet_replica" in _paths(fsess):
            break
    fleet_paths = _paths(fsess)
    fsess.serve_close()

    # 4. the full ladder: every attempt's execute faults until the
    #    cap, the completing attempt runs degraded at rung 4
    dsess = MatrelSession(mesh=mesh, config=MatrelConfig.from_env(
        MatrelConfig(fault_inject="execute:transient:p=1.0:max=4",
                     retry_max_attempts=4, retry_backoff_ms=0.5,
                     **base)))
    A = dsess.from_numpy(rng.standard_normal((32, 48)).astype(np.float32))
    B = dsess.from_numpy(rng.standard_normal((48, 16)).astype(np.float32))
    dsess.run(A.expr().multiply(B.expr()))
    degrade_paths = _paths(dsess)
    degrade_rungs = sorted({r.rung for r in dsess._prov.records()})

    # 5. full audit replay over every ledger + MV115 dynamic check
    audits = {name: provenance_lib.audit(s, sample=0)
              for name, s in (("serve", sess), ("fleet", fsess),
                              ("degrade", dsess))}
    mv115 = sum(len(provenance_pass.verify_ledger(s))
                for s in (sess, fsess, dsess))

    covered = set(serve_paths) | set(fleet_paths) | set(degrade_paths)
    need = {"execute", "rc_hit", "rc_interior", "ivm_patched",
            "fleet_directory", "fleet_replica", "degraded"}
    record = {
        "metric": "provenance_drill",
        "serve_paths": serve_paths,
        "fleet_paths": fleet_paths,
        "degrade_paths": degrade_paths,
        "degrade_rungs": degrade_rungs,
        "missing_paths": sorted(need - covered),
        "mv115_findings": mv115,
        "audit": {name: {k: v[k] for k in
                         ("sampled", "replayable", "failed", "ok")}
                  for name, v in audits.items()},
    }
    record["ok"] = bool(
        not record["missing_paths"]
        and 4 in degrade_rungs
        and mv115 == 0
        and all(v["ok"] for v in audits.values()))
    print(json.dumps(record))
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
