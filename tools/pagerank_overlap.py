"""Gather/scatter pipelining experiment for the PageRank SpMV
(VERDICT r3 #6): can chunking the block axis — so chunk i+1's gather can
interleave with chunk i's MXU scatter — close any of the ~6 ms/round gap
between the measured 27.1 ms round and the ~21 ms gather-engine floor
(BASELINE.md row 5)?

STOP RULE (encoded): if the best chunked variant improves the baseline
matvec by <10%, print the negative result; BASELINE.md row 5 then
records that the schedule family is exhausted and the gather engine
floor stands.

Run on chip (relay alive): ``python tools/pagerank_overlap.py``.
"""
import json
import sys
import time

import jax.numpy as jnp
import numpy as np


def measure(apply_fn, x0, reps=(2, 8)):
    """Marginal seconds per matvec: chained y->x dependencies + scalar
    fetch (bench.py methodology — the axon relay acks dispatch early)."""
    import jax
    f = jax.jit(apply_fn)
    fetch = jax.jit(lambda v: jnp.sum(v))

    def chained(k):
        cur = x0
        for _ in range(k):
            cur = f(cur)
        float(fetch(cur))

    chained(2)
    ts = []
    for _ in range(3):
        lo, hi = reps
        t0 = time.perf_counter()
        chained(lo)
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        chained(hi)
        t_hi = time.perf_counter() - t0
        ts.append((t_hi - t_lo) / (hi - lo))
    ts.sort()
    return ts[1]


def main(n=1_000_000, n_edges=10_000_000):
    from matrel_tpu.ops import pallas_spmv as pc
    from matrel_tpu.ops import spmv as spmv_lib

    rng = np.random.default_rng(0)
    src = rng.integers(0, n, n_edges, dtype=np.int32)
    dst = rng.integers(0, n, n_edges, dtype=np.int32)
    plan = spmv_lib.build_spmv_plan(dst, src, None, n_rows=n, n_cols=n)
    if plan is None:
        print(json.dumps({"error": "planner refused graph"}))
        return
    static = (plan.n_rows, plan.n_cols, plan.block, spmv_lib.LO)
    tables = pc.compact_tables(plan)
    ov = plan.overflow
    x0 = jnp.ones((n,), jnp.float32) / n

    base = measure(lambda v: pc.compact_apply(static, tables, ov, v))
    res = {"baseline_ms": round(base * 1e3, 3), "chunked_ms": {}}
    best = None
    for k in (2, 4, 8):
        t = measure(lambda v, k=k: pc.compact_apply_chunked(
            static, tables, ov, v, chunks=k))
        res["chunked_ms"][k] = round(t * 1e3, 3)
        if best is None or t < best[1]:
            best = (k, t)
    gain = 1.0 - best[1] / base
    res["best_chunks"] = best[0]
    res["gain_pct"] = round(gain * 100, 1)
    res["verdict"] = ("IMPROVED — adopt chunked schedule" if gain >= 0.10
                      else "NEGATIVE — <10% gain; gather-engine floor "
                           "stands, schedule family exhausted")
    print(json.dumps({"metric": "pagerank_overlap_experiment", **res}))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
