"""matlint — AST-based custom linter for this codebase's own hazard
classes (the static-analysis layer's source-level half; the plan-level
half is matrel_tpu/analysis/).

Generic linters cannot know that a ``block_until_ready`` inside the
executor's lowering is a query-hot-path sync regression, that a
``to_dense`` inside a sparse dispatch module silently voids the SpGEMM
no-densify guarantee, or that a ``shard_map`` without explicit
``out_specs`` leaves the collective contract implicit. Each of those
has bitten (or nearly bitten) a past round; matlint pins them.

Usage:
    python tools/matlint.py                # default scan set, rc 1 on findings
    python tools/matlint.py path1 path2    # explicit files/dirs
    python tools/matlint.py --list-rules   # rule catalogue

Suppression: append ``# matlint: disable=ML001`` (comma-separated for
several codes) to the line where the flagged call STARTS, with a
justification in the same comment. Suppressions are deliberate,
reviewable exceptions — the repo-wide run (``make lint``,
tests/test_matlint.py) stays green only through them.

Rule catalogue (each rule's class docstring is the authority):
  ML001  host-sync call in lowering-path modules
  ML002  to_dense/todense inside a sparse dispatch module
  ML003  shard_map call without explicit out_specs
  ML004  direct MatrelConfig() construction inside the package
  ML005  cache dict keyed by sharding-spec-ish values
  ML006  raw wall-clock timing in library code outside obs/
  ML007  bare/broad except that silently swallows and continues
  ML008  layout-changing jax.device_put in lowering modules
  ML009  Pallas kernel defined outside ops/kernel_registry.py in
         executor-reachable ops modules (the "one seam" rule)
  ML010  jax.jit call site outside the executor's region-emission
         seam (executor.py) and utils/ — jitted-program emission is
         one compilation seam (the ML009 idiom for programs)
  ML011  unbounded-queue growth idiom: deque()/queue.Queue() without
         a bound in matrel_tpu/serve/, or threading.Thread without
         an explicit daemon= anywhere in the package
  ML012  ResultCache entry payloads mutated outside the sanctioned
         patch/apply seam in serve/result_cache.py (the ML009/ML010
         one-seam idiom applied to cached state)
  ML013  ad-hoc timing accumulation (append/extend onto latency-named
         lists) in matrel_tpu/ outside obs/ — timing metrics flow
         through the registry's sketch/histogram API so live and
         offline quantiles share one definition
  ML014  cross-slice result-cache mutation outside the fleet API
         (serve/fleet.py) — another slice's cache mutates only
         through the directory/replication seam
  ML015  provenance stamp written outside the answer ledger's
         sanctioned writers (obs/provenance.py) — lineage stores are
         one seam so MV115 can trust what it cross-checks
  ML016  template/CSE cache keyed by identity or spec values
         (id()/.uid/.spec/.sharding) instead of the canonical
         structural key — the ML005 hazard extended to the
         multi-query-optimization plane (serve/mqo.py)
  ML017  bare threading.Lock()/RLock() construction outside the
         utils/lockdep.py seam — locks are named, inventoried and
         lockdep-swappable only when built through make_lock/
         make_rlock (the ML009/ML010 one-seam idiom applied to
         locks; docs/CONCURRENCY.md)
  ML018  raw drift-table read (drift.load_table) in planner/serve
         code outside the parallel/coeffs.py seam — coefficient
         consults flow through one memoized, epoch-stamped reader so
         every consumer ranks by the SAME table state and plan keys
         shatter exactly when decisions could change
         (docs/COST_MODEL.md)
  ML019  raw file IO (open/np.save/np.load/json.dump/os.replace) in
         matrel_tpu/serve/ outside the spill/checkpoint seam
         (serve/spill.py) — durable serving state goes through ONE
         writer so every artifact is sha1-stamped, atomically
         renamed, and readable by the robust restore path; an ad-hoc
         write is invisible to save_state and unverifiable on thaw
         (docs/DURABILITY.md)
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import sys
from typing import Iterator, List, Optional, Sequence

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Default scan set for ``make lint`` / the repo-clean test. tests/ is
#: excluded by design: tests legitimately poke every hazard (poisoned
#: to_dense spies, sync-forcing fixtures) and carry their own review.
DEFAULT_PATHS = ("matrel_tpu", "tools", "examples", "bench.py",
                 "bench_all.py")

_SUPPRESS_RE = re.compile(r"#\s*matlint:\s*disable=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


def _rel(path: str) -> str:
    try:
        return os.path.relpath(path, REPO)
    except ValueError:
        return path


def _call_name(func: ast.AST) -> str:
    """Dotted tail of a call target: ``jax.block_until_ready`` ->
    "jax.block_until_ready", ``x.to_dense`` -> ".to_dense"."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        base = _call_name(func.value)
        return (base + "." if base else ".") + func.attr
    return ""


class Rule:
    """One hazard class. ``applies_to`` scopes the MODULE set (the
    hazard is contextual — the same call is fine elsewhere); ``check``
    yields findings for one parsed file."""

    id: str = "ML000"

    def applies_to(self, relpath: str) -> bool:
        return True

    def check(self, tree: ast.Module, relpath: str) -> Iterator[Finding]:
        raise NotImplementedError


#: Modules whose code runs on (or traces into) the query hot path —
#: the executor's lowering, the strategy kernels, the ops kernels, the
#: IR/relational lowerings. A host sync here stalls every query.
_LOWERING_MODULES = re.compile(
    r"^matrel_tpu/(executor\.py|ops/|parallel/strategies\.py|"
    r"relational/|ir/)")


class HostSyncRule(Rule):
    """ML001: host-synchronising calls in lowering-path modules.

    ``block_until_ready``/``jax.device_get`` force a device round-trip;
    on the query hot path that serialises the pipeline the whole
    one-compiled-program design exists to avoid (the obs_level="off"
    contract: zero extra syncs — tests/test_obs.py enforces it
    dynamically for the executor, this rule pins it statically for
    every lowering module). ``np.asarray`` inside a Lowerer method is
    the same hazard wearing numpy clothes — on a traced value it
    either syncs or raises — unless it sits under
    ``jax.ensure_compile_time_eval()`` (host-side metadata work, the
    sanctioned idiom). The ONE legitimate sync — the analyze-mode
    op_hook in executor.py, guarded by ``self.op_hook is not None`` —
    carries the inline suppression this rule's docstring mandates."""

    id = "ML001"
    _SYNC_TAILS = ("block_until_ready", "device_get")

    def applies_to(self, relpath: str) -> bool:
        return bool(_LOWERING_MODULES.match(relpath))

    def check(self, tree, relpath):
        # (node, inside_lowerer_class, under_compile_time_eval)
        stack: List[tuple] = [(tree, False, False)]
        while stack:
            node, in_lowerer, under_cte = stack.pop()
            if isinstance(node, ast.ClassDef):
                in_lowerer = in_lowerer or node.name.endswith("Lowerer")
            if isinstance(node, ast.With):
                for item in node.items:
                    name = _call_name(item.context_expr.func) if \
                        isinstance(item.context_expr, ast.Call) else ""
                    if name.endswith("ensure_compile_time_eval"):
                        under_cte = True
            if isinstance(node, ast.Call):
                name = _call_name(node.func)
                tail = name.rsplit(".", 1)[-1]
                if tail in self._SYNC_TAILS:
                    yield Finding(relpath, node.lineno, self.id,
                                  f"host sync `{name}` on a "
                                  "lowering path — stalls every query "
                                  "(obs_level='off' contract)")
                elif (tail == "asarray" and in_lowerer
                        and not under_cte
                        and name.split(".", 1)[0] in ("np", "numpy")):
                    yield Finding(
                        relpath, node.lineno, self.id,
                        "np.asarray inside a Lowerer method outside "
                        "jax.ensure_compile_time_eval() — syncs or "
                        "raises on traced values")
            for child in ast.iter_child_nodes(node):
                stack.append((child, in_lowerer, under_cte))


class NoDensifyRule(Rule):
    """ML002: ``to_dense``/``todense`` inside a sparse dispatch module.

    matrel_tpu/ops/ holds the kernels whose whole reason to exist is
    NOT materialising dense forms (SpGEMM's no-densify guarantee is
    asserted dynamically by test_spgemm's poisoned-to_dense spy; the
    verifier's MV104 pins the dispatch side). A densify call added to
    one of these modules is either a bug or a fallback that belongs in
    the executor's dispatch, where the planner can see and price it."""

    id = "ML002"
    _TAILS = ("to_dense", "todense")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("matrel_tpu/ops/")

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                tail = _call_name(node.func).rsplit(".", 1)[-1]
                if tail in self._TAILS:
                    yield Finding(
                        relpath, node.lineno, self.id,
                        f"`{tail}` inside a sparse dispatch module — "
                        "densify fallbacks belong in the executor "
                        "dispatch where the planner prices them")


class ShardMapOutSpecsRule(Rule):
    """ML003: ``shard_map`` without explicit ``out_specs``.

    The out_spec IS the collective contract: it decides whether the
    runtime all-gathers, leaves shards in place, or replicates — and an
    implicit/defaulted one makes the comm cost invisible to review and
    to the planner's byte model. Every call must say what it emits
    (the compat shim that forwards kwargs is exempt)."""

    id = "ML003"

    def applies_to(self, relpath: str) -> bool:
        return relpath != "matrel_tpu/utils/compat.py"

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node.func).rsplit(".", 1)[-1] != "shard_map":
                continue
            has_kw = any(k.arg == "out_specs" for k in node.keywords)
            # positional form: shard_map(f, mesh, in_specs, out_specs)
            if not has_kw and len(node.args) < 4:
                yield Finding(
                    relpath, node.lineno, self.id,
                    "shard_map without explicit out_specs — the "
                    "collective contract must be stated at the call "
                    "site")


class ConfigFlowRule(Rule):
    """ML004: direct ``MatrelConfig(...)`` construction inside the
    package.

    Library code must consume the config that FLOWS to it (a ``config``
    parameter defaulting through ``default_config()``) — a fresh
    ``MatrelConfig()`` silently discards every session/env override the
    caller set (the round-2 class of bug where a module ran with
    default thresholds while the session was configured otherwise).
    Construction is for entry points: config.py itself, tests, and the
    bench/tool harnesses outside the package."""

    id = "ML004"

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith("matrel_tpu/")
                and relpath != "matrel_tpu/config.py")

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                tail = _call_name(node.func).rsplit(".", 1)[-1]
                if tail == "MatrelConfig":
                    yield Finding(
                        relpath, node.lineno, self.id,
                        "direct MatrelConfig() construction in library "
                        "code — accept a config parameter and default "
                        "through default_config() so session/env "
                        "overrides flow")


class SpecKeyedCacheRule(Rule):
    """ML005: cache/memo dicts keyed by sharding-spec-ish values.

    ``PartitionSpec``/``NamedSharding``/``Mesh`` objects (and ``.spec``
    attributes) make treacherous dict keys: some are unhashable, others
    hash by identity across semantically-equal instances, and a jax
    upgrade can flip either property — turning a cache into a
    permanent miss (rebuild storm) or, worse, an identity-aliased hit.
    Key caches by the STABLE tuple you derive from the spec (axis
    names, grid shape, padded dims), the way the autotune table and the
    plan cache do."""

    id = "ML005"
    _NAME_RE = re.compile(r"(cache|memo)", re.IGNORECASE)
    _SPEC_CTORS = ("PartitionSpec", "NamedSharding", "Mesh")
    _SPEC_ATTRS = ("spec", "sharding")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("matrel_tpu/")

    def _cacheish(self, target: ast.AST) -> bool:
        if isinstance(target, ast.Name):
            return bool(self._NAME_RE.search(target.id))
        if isinstance(target, ast.Attribute):
            return bool(self._NAME_RE.search(target.attr))
        return False

    def _specish(self, key: ast.AST) -> bool:
        for node in ast.walk(key):
            if (isinstance(node, ast.Attribute)
                    and node.attr in self._SPEC_ATTRS):
                return True
            if isinstance(node, ast.Call):
                tail = _call_name(node.func).rsplit(".", 1)[-1]
                if tail in self._SPEC_CTORS:
                    return True
        return False

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            key = None
            target = None
            if isinstance(node, ast.Subscript):
                target, key = node.value, node.slice
            elif isinstance(node, ast.Call):
                tail = _call_name(node.func).rsplit(".", 1)[-1]
                if tail in ("get", "setdefault") and node.args and \
                        isinstance(node.func, ast.Attribute):
                    target, key = node.func.value, node.args[0]
            if key is None or not self._cacheish(target):
                continue
            if self._specish(key):
                yield Finding(
                    relpath, node.lineno, self.id,
                    "cache keyed by a sharding spec / mesh object — "
                    "hashability is jax-version-dependent; key by the "
                    "derived stable tuple instead")


class RawTimingRule(Rule):
    """ML006: raw ``time.perf_counter()``/``time.time()``/
    ``time.monotonic()`` calls in library modules outside
    ``matrel_tpu/obs/`` and ``utils/profiling.py``.

    Timing that matters belongs in the observability layer: a span
    (``obs.trace.span``/``phase``) or a ``StepTimer`` step, so the
    measurement lands in the event log where ``history``, the chrome
    exporter and the drift auditor can read it — a bare perf_counter
    pair produces a number that dies in a local variable (or worse, a
    print). The round-9 conversion moved every hot-path timing onto
    spans; this rule keeps new code from regressing to private
    stopwatches. ``parallel/autotune.py`` is scoped out wholesale —
    it is the measurement subsystem, its wall-clocks ARE its output
    and persist to the autotune table (the ML001 precedent: scope
    encodes where the hazard is contextual). The two remaining
    legitimate exceptions (the analyze-mode op_hook, the serve
    queue-wait timestamps — both of which land their numbers in the
    event log) carry inline suppressions with their justification."""

    id = "ML006"
    _DOTTED = ("time.perf_counter", "time.time", "time.monotonic")
    _BARE = ("perf_counter", "monotonic")

    def applies_to(self, relpath: str) -> bool:
        # resilience/retry.py is scoped out like autotune: deadline /
        # backoff arithmetic IS that module's function (every other
        # resilience module stays in scope), and its outcomes land in
        # the event log as retry/degrade records
        return (relpath.startswith("matrel_tpu/")
                and not relpath.startswith("matrel_tpu/obs/")
                and relpath not in ("matrel_tpu/utils/profiling.py",
                                    "matrel_tpu/parallel/autotune.py",
                                    "matrel_tpu/resilience/retry.py"))

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            if name in self._DOTTED or name in self._BARE:
                yield Finding(
                    relpath, node.lineno, self.id,
                    f"raw `{name}()` timing in library code — route "
                    "through obs.trace.span()/phase() or StepTimer so "
                    "the measurement lands in the event log")


class BroadSwallowRule(Rule):
    """ML007: bare/broad ``except`` that silently swallows and
    continues in library modules.

    ``except Exception: pass`` (or a bare ``except:``/``continue``
    body) erases the failure AND the information needed to classify it
    — exactly the anti-pattern the resilience layer's typed taxonomy
    (matrel_tpu/resilience/errors.py) exists to replace: a swallowed
    transient is a lost retry, a swallowed deterministic error is a
    silent wrong answer waiting to recur. Library code must either
    raise a TYPED error, classify-and-handle, or at minimum log the
    failure it chose to survive. The handful of legitimate
    swallow-and-continue sites (never-fail observability sinks, the
    autotune loop dropping strategies that fail to compile, fallback
    encoders) carry inline suppressions with their justification —
    deliberate, reviewable exceptions, not defaults. Narrow excepts
    (``except OSError:``) are out of scope: naming the exception IS
    the classification."""

    id = "ML007"
    _BROAD_NAMES = ("Exception", "BaseException")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("matrel_tpu/")

    def _broad(self, etype) -> bool:
        if etype is None:                       # bare except:
            return True
        if isinstance(etype, ast.Name):
            return etype.id in self._BROAD_NAMES
        if isinstance(etype, ast.Attribute):    # e.g. builtins.Exception
            return etype.attr in self._BROAD_NAMES
        return False

    @staticmethod
    def _swallows(body) -> bool:
        """True when the handler body ONLY discards: pass/continue
        statements (an ``...`` Ellipsis expression counts as pass)."""
        for stmt in body:
            if isinstance(stmt, (ast.Pass, ast.Continue)):
                continue
            if (isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Constant)
                    and stmt.value.value is Ellipsis):
                continue
            return False
        return True

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if self._broad(node.type) and self._swallows(node.body):
                yield Finding(
                    relpath, node.lineno, self.id,
                    "broad except swallows the failure and continues "
                    "— raise a typed error (resilience/errors.py), "
                    "classify-and-handle, or log what you chose to "
                    "survive")


class DevicePutRule(Rule):
    """ML008: ``jax.device_put`` in lowering modules — a layout change
    the planner cannot see or price.

    The reshard planner (matrel_tpu/parallel/reshard.py, round 10)
    exists so that every layout change lowers through a COSTED,
    peak-bounded step sequence: a raw ``device_put`` in a lowering
    module re-lays an array with whatever one-shot collective XLA
    picks, invisible to the byte model, to MV109's peak proof and to
    the obs decision records. Route layout changes through the planner
    (sharding constraints the reshard plan stages) instead. Out of
    scope by design: ``core/`` (construction-time initial placement is
    where arrays are BORN), the reshard module itself (it IS the
    sanctioned lowering), and ``utils/``/``obs/`` (checkpoint IO,
    host-side tooling). Two in-scope idioms are exempt: placements
    under ``jax.ensure_compile_time_eval()`` (host-built static
    metadata, the ML001-sanctioned pattern) and placements onto a
    fully-REPLICATED sharding (a ``rep``/``repl`` destination or
    ``replicated(...)`` call — metadata broadcast, not a re-lay).
    The remaining legit sites (host-built kernel tables placed onto
    their sharded layout at plan-build time) carry justified inline
    suppressions."""

    id = "ML008"
    _SCOPE = re.compile(
        r"^matrel_tpu/(executor\.py|session\.py|ops/|relational\.?/|"
        r"serve/|workloads/|ir/|parallel/)")
    _EXEMPT = ("matrel_tpu/parallel/reshard.py",)

    def applies_to(self, relpath: str) -> bool:
        return bool(self._SCOPE.match(relpath)) \
            and relpath not in self._EXEMPT

    @staticmethod
    def _replicated_dest(node: ast.Call) -> bool:
        dest = None
        if len(node.args) >= 2:
            dest = node.args[1]
        for kw in node.keywords:
            if kw.arg == "device":
                dest = kw.value
        if dest is None:
            return False
        if isinstance(dest, ast.Name) and re.match(r"^repl?\b", dest.id):
            return True
        if isinstance(dest, ast.Call):
            tail = _call_name(dest.func).rsplit(".", 1)[-1]
            if tail == "replicated":
                return True
        return False

    def check(self, tree, relpath):
        # (node, under ensure_compile_time_eval) — the ML001 walker
        stack: List[tuple] = [(tree, False)]
        while stack:
            node, under_cte = stack.pop()
            if isinstance(node, ast.With):
                for item in node.items:
                    name = _call_name(item.context_expr.func) if \
                        isinstance(item.context_expr, ast.Call) else ""
                    if name.endswith("ensure_compile_time_eval"):
                        under_cte = True
            if isinstance(node, ast.Call):
                tail = _call_name(node.func).rsplit(".", 1)[-1]
                if (tail == "device_put" and not under_cte
                        and not self._replicated_dest(node)):
                    yield Finding(
                        relpath, node.lineno, self.id,
                        "jax.device_put in a lowering module — a "
                        "layout change the planner cannot price; "
                        "route it through the reshard planner "
                        "(parallel/reshard.py) or a costed sharding "
                        "constraint")
            for child in ast.iter_child_nodes(node):
                stack.append((child, under_cte))


class KernelSeamRule(Rule):
    """ML009: Pallas kernel construction outside the kernel registry,
    in modules reachable from executor dispatch — the "one seam" rule.

    The sparse kernel registry (matrel_tpu/ops/kernel_registry.py)
    exists so that every kernel the executor's sparse-matmul dispatch
    can reach is REGISTERED: declared structure classes for the
    planner's stamp, admissibility MV110 can verify, a row the
    autotuner can measure, a forcing knob the degradation ladder can
    escape. A ``pallas_call`` authored elsewhere in ``ops/`` is a
    kernel the registry cannot select, verify, measure or escape —
    exactly the hardcoded branch the registry replaced (and the seam
    where future GPU/multi-backend kernels must land, ROADMAP north
    star). Scope: ``matrel_tpu/ops/`` (the executor's kernel modules);
    the registry module itself is the sanctioned home. The legacy
    SpMV/SpMM paths (ops/pallas_spmv.py, ops/pallas_spmm.py,
    ops/spmv_routed.py) predate the registry and stay unported this
    round — they carry justified inline suppressions, which double as
    the porting worklist."""

    id = "ML009"
    _SCOPE = re.compile(r"^matrel_tpu/ops/")
    _EXEMPT = ("matrel_tpu/ops/kernel_registry.py",)

    def applies_to(self, relpath: str) -> bool:
        return bool(self._SCOPE.match(relpath)) \
            and relpath not in self._EXEMPT

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            tail = _call_name(node.func).rsplit(".", 1)[-1]
            if tail == "pallas_call":
                yield Finding(
                    relpath, node.lineno, self.id,
                    "pallas_call outside the kernel registry — a "
                    "kernel the registry cannot select/verify/"
                    "measure/escape; define it in "
                    "ops/kernel_registry.py (the one seam) and "
                    "register it")


class JitSeamRule(Rule):
    """ML010: ``jax.jit`` call sites in ``matrel_tpu/`` outside the
    executor's region-emission seam (``executor.py``) and ``utils/``.

    The whole-plan fusion work (ir/fusion.py, docs/FUSION.md) made
    program emission a PLANNER decision: the executor compiles whole
    plans, fused regions and per-op staged units through ONE seam,
    where the boundary is stamped, measured (the autotune ``fuse|``
    family), verified (MV111) and escapable (degradation rung 3). A
    ``jax.jit`` authored elsewhere in the package is a compiled
    program the planner cannot see, the dispatch-count accounting
    cannot count, and the fused-vs-staged measurement cannot sweep —
    the ML009 "one seam" argument applied to programs instead of
    kernels. Scope: the package minus executor.py (the seam) and
    utils/ (host-side tooling/profiling helpers); harness scripts
    (bench/tools/tests) are out of scope — they ARE measurement.
    The pre-existing legitimate sites (workload runner caches, ops
    table builders, autotune probes, core constructors) carry
    justified inline suppressions, which double as the worklist for
    porting them onto the seam."""

    id = "ML010"
    _EXEMPT = ("matrel_tpu/executor.py",)

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith("matrel_tpu/")
                and relpath not in self._EXEMPT
                and not relpath.startswith("matrel_tpu/utils/"))

    @staticmethod
    def _is_jit(node: ast.AST) -> bool:
        # Name/Attribute targets only: an ast.Call target (the
        # `jax.jit(f)(x)` outer call's func) must NOT match, or an
        # immediately-invoked jit site reports twice at one line
        if isinstance(node, ast.Call):
            return False
        return _call_name(node).rsplit(".", 1)[-1] == "jit"

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and self._is_jit(node.func):
                yield Finding(
                    relpath, node.lineno, self.id,
                    "jax.jit outside the executor's region-emission "
                    "seam — a compiled program the planner cannot "
                    "see/measure/escape; emit it through "
                    "matrel_tpu/executor.py (or justify with an "
                    "inline suppression)")
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                # bare `@jax.jit` only — call-form decorators
                # (`@jax.jit` with args, `@partial(jax.jit, ...)`)
                # are ast.Calls the branch above already walks
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call) \
                            and self._is_jit(dec):
                        yield Finding(
                            relpath, dec.lineno, self.id,
                            "@jax.jit outside the executor's "
                            "region-emission seam — a compiled "
                            "program the planner cannot see/measure/"
                            "escape; emit it through "
                            "matrel_tpu/executor.py (or justify with "
                            "an inline suppression)")


class UnboundedQueueRule(Rule):
    """ML011: unbounded-queue growth idioms in the serve plane.

    The overload control plane (docs/OVERLOAD.md) exists because an
    unbounded queue turns overload into memory exhaustion plus
    unbounded latency — the exact failure the typed AdmissionShed
    contract replaces with refusal. Two idioms are pinned:

    - ``deque()`` / ``queue.Queue()`` (or LifoQueue/PriorityQueue)
      constructed WITHOUT a bound (no maxlen/maxsize argument) inside
      ``matrel_tpu/serve/`` — the modules whose queues sit on the
      admission path. A queue that is bounded by surrounding shed
      logic rather than by its constructor carries a justified inline
      suppression (the AdmissionQueue's per-tenant deques: a maxlen
      deque DROPS silently, and refusal must be typed).
    - ``threading.Thread(...)`` without an explicit ``daemon=``
      anywhere in ``matrel_tpu/``: a non-daemon worker left running
      wedges interpreter shutdown — every sanctioned worker/helper
      thread in the package states its daemon-ness at the call site.
    """

    id = "ML011"
    _QUEUE_TAILS = ("Queue", "LifoQueue", "PriorityQueue")
    _BOUND_KW = ("maxlen", "maxsize")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("matrel_tpu/")

    @staticmethod
    def _has_bound(node: ast.Call, kw_names, bound_pos: int) -> bool:
        """An explicit bound: the named keyword, or enough positional
        args to reach the bound's slot — ``deque(iterable)`` is NOT
        bounded (the first positional is the iterable; maxlen is the
        second), while ``queue.Queue(n)``'s first positional IS
        maxsize."""
        if any(k.arg in kw_names for k in node.keywords):
            return True
        return len(node.args) >= bound_pos

    def check(self, tree, relpath):
        in_serve = relpath.startswith("matrel_tpu/serve/")
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            tail = _call_name(node.func).rsplit(".", 1)[-1]
            if in_serve and tail == "deque" \
                    and not self._has_bound(node, ("maxlen",), 2):
                yield Finding(
                    relpath, node.lineno, self.id,
                    "unbounded deque() on the serve path — bound it "
                    "(maxlen=) or shed typed past an explicit bound "
                    "(AdmissionShed), with a justified suppression "
                    "when the bound lives in surrounding logic")
            elif in_serve and tail in self._QUEUE_TAILS \
                    and not self._has_bound(node, ("maxsize",), 1):
                yield Finding(
                    relpath, node.lineno, self.id,
                    f"unbounded queue.{tail}() on the serve path — "
                    "pass maxsize (or shed typed past an explicit "
                    "bound)")
            elif tail == "Thread" and not any(
                    k.arg == "daemon" for k in node.keywords):
                yield Finding(
                    relpath, node.lineno, self.id,
                    "threading.Thread without an explicit daemon= — "
                    "a non-daemon worker wedges interpreter "
                    "shutdown; state the thread's lifecycle at the "
                    "call site")


@dataclasses.dataclass(frozen=True)
class ResultCacheSeamRule(Rule):
    """ML012: ResultCache entry payloads mutate ONLY through the
    sanctioned patch/apply seam in serve/result_cache.py.

    The IVM plane (serve/ivm.py; docs/IVM.md) made cached entries
    LONG-LIVED MUTABLE STATE: a patched entry's result/deps/bound
    must change together, under the cache lock, with the byte
    accounting and the provenance stamp kept coherent — so every
    mutation goes through ResultCache.apply_patch / rekey / drop /
    put (the ML009 one-kernel-seam and ML010 one-jit-seam idiom,
    applied to cached state). A module that pokes an entry's fields
    or the cache's internal stores directly produces answers whose
    provenance nobody can verify (MV113 would assert a bound the
    mutation silently voided) and byte accounting that drifts from
    the entries it claims to bound. Pinned, in matrel_tpu/ outside
    serve/result_cache.py:

    - attribute ASSIGNMENT (plain, augmented, or del) to a CacheEntry
      payload field — result, dep_ids, pins, nbytes, key_hash,
      err_bound, delta_gen, delta_rule, prec, ivm_id — on any object
      (``dataclasses.replace`` builds a NEW entry and is fine; the
      seam inserts it);
    - any use of an attribute named ``_entries`` / ``_stale`` (the
      cache's internal stores): subscript stores/deletes, mutating
      method calls (pop/popitem/clear/update/setdefault/move_to_end),
      or reads — outside the owning module even a read races the
      serve worker without the cache lock.
    """

    id = "ML012"
    _ENTRY_FIELDS = ("result", "dep_ids", "pins", "nbytes", "key_hash",
                     "err_bound", "delta_gen", "delta_rule", "prec",
                     "ivm_id")
    _STORES = ("_entries", "_stale")

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith("matrel_tpu/")
                and relpath != "matrel_tpu/serve/result_cache.py")

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Delete):
                targets = node.targets
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and t.attr in self._ENTRY_FIELDS:
                    yield Finding(
                        relpath, node.lineno, self.id,
                        f"direct store to a cache-entry payload field "
                        f".{t.attr} — mutate entries only through the "
                        f"ResultCache patch/apply seam "
                        f"(apply_patch/rekey/drop/put in "
                        f"serve/result_cache.py)")
            if isinstance(node, ast.Attribute) \
                    and node.attr in self._STORES:
                yield Finding(
                    relpath, node.lineno, self.id,
                    f"direct access to the result cache's internal "
                    f".{node.attr} store — the entries mutate only "
                    f"under the cache lock through the sanctioned "
                    f"seam (serve/result_cache.py)")


class TimingAccumulationRule(Rule):
    """ML013: ad-hoc latency accumulation outside the metrics
    registry — ``.append()``/``.extend()`` onto a latency-named list
    in ``matrel_tpu/`` outside ``matrel_tpu/obs/``.

    The live telemetry plane (obs/metrics.py round 15) made quantiles
    a SHARED definition: every timing metric flows through the
    registry's sketch/histogram API (or ``obs.metrics.percentile``),
    so the live endpoint, ``history``'s replay and ``top`` can never
    disagree beyond the sketch's documented relative error — and
    memory stays bounded by construction. A private
    ``latencies.append(ms)`` list is the pre-sketch anti-pattern
    wearing new clothes: unbounded on a long-lived server, invisible
    to the endpoint, and quantiled by whatever ad-hoc rank math its
    author re-derives (the exact drift the history-vs-live fix
    removed). ML006 pins the CLOCK CALLS; this rule pins the
    ACCUMULATION — both ends of a private stopwatch.

    Scope: the package minus ``obs/`` (the registry and its readers
    ARE the sanctioned accumulation) ; harness scripts (bench/tools/
    tests) are out of scope — measurement is their output (the ML006
    autotune precedent). The two legitimate in-scope sites — the
    brownout controller's bounded sliding window (measurement IS that
    subsystem, and its p95 reads through the shared definition) and
    the serve worker's per-cycle overload-event assembly (the values
    land in the event log) — carry justified inline suppressions.

    Matched names: the append target's variable/attribute name (or a
    string subscript key) containing a latency-ish token — ``lat``/
    ``latency``/``latencies``, ``wait``/``waits``, ``duration(s)``,
    ``elapsed``, ``timing(s)`` — or ending in ``_ms``.
    """

    id = "ML013"
    _TIMING_RE = re.compile(
        r"(?i)(?:^|_)(lat|lats|latency|latencies|wait|waits|"
        r"dur|durs|duration|durations|elapsed|timing|timings)(?:$|_)"
        r"|_ms$")

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith("matrel_tpu/")
                and not relpath.startswith("matrel_tpu/obs/"))

    @classmethod
    def _target_name(cls, node: ast.AST) -> str:
        """The accumulation target's human name: ``waits`` for
        ``waits.append``, ``_waits`` for ``self._waits.append``,
        ``latencies`` for ``row["latencies"].append``."""
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        if isinstance(node, ast.Subscript):
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value,
                                                           str):
                return sl.value
        return ""

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in ("append", "extend"):
                continue
            name = self._target_name(node.func.value)
            if name and self._TIMING_RE.search(name):
                yield Finding(
                    relpath, node.lineno, self.id,
                    f"ad-hoc timing accumulation `{name}."
                    f"{node.func.attr}(...)` — record through the "
                    "metrics registry's sketch/histogram API "
                    "(obs/metrics.py) so live and offline quantiles "
                    "share one bounded-memory definition")


class FleetSeamRule(Rule):
    """ML014: cross-slice state mutation pinned onto the fleet API
    (serve/fleet.py; docs/FLEET.md).

    The fleet made OTHER sessions' result caches reachable: every
    slice owns one, and the directory/replication protocol depends on
    exactly one module mutating them — a serve/ module that writes
    another slice's cache directly produces entries the directory
    never recorded (unreachable by the hit-anywhere protocol, wrong
    ownership on failover) and bypasses the replication pricing that
    keeps migrations under the HBM budget. Pinned, in
    ``matrel_tpu/serve/`` outside ``fleet.py`` and the cache's own
    module: a call to a MUTATING ResultCache method (put / drop /
    apply_patch / rekey / invalidate_deps / clear / rebuild_stale)
    whose receiver chain reaches ``._result_cache`` through anything
    other than plain ``self`` / ``self.session`` — e.g.
    ``fleet.slices[i].session._result_cache.put(...)``. A session
    mutating ITS OWN cache (the IVM plane, the rebind path) is the
    sanctioned single-slice seam and stays clean."""

    id = "ML014"
    _MUT = ("put", "drop", "apply_patch", "rekey", "invalidate_deps",
            "clear", "rebuild_stale")

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith("matrel_tpu/serve/")
                and relpath not in ("matrel_tpu/serve/fleet.py",
                                    "matrel_tpu/serve/result_cache.py"))

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute) \
                    or f.attr not in self._MUT:
                continue
            chain = []
            cur = f.value
            through_subscript = False
            while True:
                if isinstance(cur, ast.Attribute):
                    chain.append(cur.attr)
                    cur = cur.value
                elif isinstance(cur, ast.Subscript):
                    through_subscript = True
                    cur = cur.value
                elif isinstance(cur, ast.Call):
                    cur = cur.func
                else:
                    break
            if "_result_cache" not in chain:
                continue
            # sanctioned receivers: a session mutating its OWN cache
            # — self._result_cache / self.session._result_cache / the
            # conventional sess/session local alias. Anything reached
            # through a subscript (slices[i]) or a foreign object is
            # another slice's state.
            own_root = (isinstance(cur, ast.Name)
                        and cur.id in ("self", "sess", "session"))
            sanctioned = (own_root and not through_subscript
                          and set(chain) <= {"_result_cache",
                                             "session"})
            if not sanctioned:
                yield Finding(
                    relpath, node.lineno, self.id,
                    f"cross-slice result-cache mutation "
                    f"`...{'.'.join(reversed(chain))}.{f.attr}(...)`"
                    f" outside the fleet API — another slice's cache "
                    f"mutates only through serve/fleet.py (the "
                    f"directory/replication seam, docs/FLEET.md)")


class ProvenanceSeamRule(Rule):
    """ML015: answer-lineage stamps are written ONLY by the ledger's
    sanctioned writers in obs/provenance.py (the ML012/ML014 one-seam
    idiom applied to lineage).

    The answer provenance ledger (docs/OBSERVABILITY.md tier 4) makes
    ``CacheEntry.provenance`` and the substitution leaf's
    ``attrs["provenance"]`` the account of where a served answer came
    from — and MV115 cross-checks that account against the mechanism
    stamps, while ``why --audit`` replays answers against the bounds
    it records. Both are only sound if the stamps have exactly one
    producer: a module hand-writing a provenance dict produces
    lineage the ledger never witnessed (un-audited, un-renderable,
    schema-drifting) — precisely the unverifiable-answer class ML012
    pins for cache payloads. Serve/session modules CALL
    ``stamp_entry`` / ``stamp_patched`` / ``stamp_leaf``; they never
    build the stamp themselves. Pinned, in ``matrel_tpu/`` outside
    ``matrel_tpu/obs/provenance.py``:

    - attribute assignment (plain, augmented, annotated, or del) to a
      ``.provenance`` field on any object;
    - a subscript store ``X["provenance"] = ...`` (the attrs-dict
      route around the attribute check);
    - a ``provenance=`` keyword in a ``with_attrs(...)`` call (the
      immutable-expr route).

    Reads are fine everywhere — the ledger exists to be read.
    """

    id = "ML015"

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith("matrel_tpu/")
                and relpath != "matrel_tpu/obs/provenance.py")

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Delete):
                targets = node.targets
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and t.attr == "provenance":
                    yield Finding(
                        relpath, node.lineno, self.id,
                        "direct store to a .provenance stamp — "
                        "lineage is written only by the ledger's "
                        "stamp_entry/stamp_patched/stamp_leaf "
                        "(obs/provenance.py), so MV115 and the "
                        "audit replay can trust it")
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.slice, ast.Constant) \
                        and t.slice.value == "provenance":
                    yield Finding(
                        relpath, node.lineno, self.id,
                        "subscript store to a ['provenance'] stamp — "
                        "lineage is written only by the ledger's "
                        "stamp writers (obs/provenance.py)")
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "with_attrs":
                for kw in node.keywords:
                    if kw.arg == "provenance":
                        yield Finding(
                            relpath, node.lineno, self.id,
                            "with_attrs(provenance=...) outside the "
                            "ledger — thread lineage onto leaves via "
                            "stamp_leaf (obs/provenance.py)")


class TemplateKeyRule(Rule):
    """ML016: plan-template / CSE caches keyed by identity or spec
    values instead of the canonical structural key (ML005 extended to
    the multi-query-optimization plane, serve/mqo.py).

    A template entry outlives the queries that built it — that is the
    point — so its key must mean the same thing at probe time as it
    did at insert time. ``id()`` is recycled the moment the original
    object dies (a false hit rebinds a STRANGER's matrices into a
    compiled plan); node ``.uid`` values are per-tree counters that
    collide across independently-built expressions; spec/sharding
    objects hash by identity or not at all (the ML005 hazard). The
    only sound key is the leaf-abstracted STRUCTURAL key
    (``mqo.template_key`` / ``session._plan_key``) — derived strings
    whose equality IS plan equivalence. Pinned: subscript stores and
    ``get``/``setdefault`` consults on template-/hoist-named dicts
    whose key expression reaches an ``id(...)`` call or a
    ``.uid``/``.spec``/``.sharding`` attribute. Local first-occurrence
    maps (``classes.setdefault(id(m), ...)`` inside one
    ``template_key`` walk) are fine — they die with the walk, which
    is why the rule scopes by cache NAME, not by module."""

    id = "ML016"
    _NAME_RE = re.compile(r"(template|tpl|hoist)", re.IGNORECASE)
    _UNSTABLE_ATTRS = ("uid", "spec", "sharding")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("matrel_tpu/")

    def _cacheish(self, target: ast.AST) -> bool:
        if isinstance(target, ast.Name):
            return bool(self._NAME_RE.search(target.id))
        if isinstance(target, ast.Attribute):
            return bool(self._NAME_RE.search(target.attr))
        return False

    def _unstable(self, key: ast.AST) -> Optional[str]:
        for node in ast.walk(key):
            if isinstance(node, ast.Call) \
                    and _call_name(node.func).rsplit(".", 1)[-1] == "id":
                return "id()"
            if isinstance(node, ast.Attribute) \
                    and node.attr in self._UNSTABLE_ATTRS:
                return f".{node.attr}"
        return None

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            key = None
            target = None
            if isinstance(node, ast.Subscript):
                target, key = node.value, node.slice
            elif isinstance(node, ast.Call):
                tail = _call_name(node.func).rsplit(".", 1)[-1]
                if tail in ("get", "setdefault") and node.args and \
                        isinstance(node.func, ast.Attribute):
                    target, key = node.func.value, node.args[0]
            if key is None or not self._cacheish(target):
                continue
            bad = self._unstable(key)
            if bad is not None:
                yield Finding(
                    relpath, node.lineno, self.id,
                    f"template/CSE cache keyed by {bad} — identity "
                    f"and spec values do not survive the entry (a "
                    f"recycled id() falsely rebinds, uids collide "
                    f"across trees); key by the canonical structural "
                    f"key (mqo.template_key / session._plan_key)")


class LockSeamRule(Rule):
    """ML017: bare ``threading.Lock()``/``RLock()`` construction in
    ``matrel_tpu/`` outside the ``utils/lockdep.py`` seam.

    The concurrency sanitizer (docs/CONCURRENCY.md) hangs off ONE
    construction seam: ``lockdep.make_lock(name)`` /
    ``make_rlock(name)`` return raw threading primitives by default
    (zero objects — the structural-zero contract) and instrumented
    wrappers under ``config.lockdep_enable``. A lock built bare is
    invisible to all three layers the seam feeds: it has no inventory
    name (docs/CONCURRENCY.md's lock table and lockcheck's LK1xx
    findings key on them), the runtime order graph never sees its
    acquisitions, and the race drill cannot prove schedules over it —
    the ML009/ML010 one-seam argument applied to locks.
    ``Condition``/``Event``/``Semaphore`` stay legal: they are
    signalling primitives, not mutual-exclusion state, and the
    Conditions in the serve plane deliberately WRAP a seam-built lock.
    The sanitizer's own internal guard in utils/lockdep.py is the one
    necessarily-raw lock (it cannot instrument itself)."""

    id = "ML017"
    _SEAM = ("matrel_tpu/utils/lockdep.py",)

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith("matrel_tpu/")
                and relpath not in self._SEAM)

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            if name in ("threading.Lock", "threading.RLock",
                        "Lock", "RLock"):
                kind = name.rsplit(".", 1)[-1]
                yield Finding(
                    relpath, node.lineno, self.id,
                    f"bare threading.{kind}() outside the lockdep "
                    f"seam — construct it via lockdep.make_"
                    f"{'r' if kind == 'RLock' else ''}lock"
                    f"(\"<inventory.name>\") (utils/lockdep.py) so "
                    f"it is named, order-tracked and drill-able")


class CoeffSeamRule(Rule):
    """ML018: raw ``drift.load_table`` consult in planner/serve code
    outside the ``parallel/coeffs.py`` seam.

    The cost-model loop (docs/COST_MODEL.md) hangs off ONE coefficient
    reader: ``parallel/coeffs.py`` parses the drift table once per
    file state (stat-signature memoized), drops non-finite rows, and
    stamps the coefficient EPOCH the session embeds in every plan key
    (``coeffv:``). A planner or serve module that calls
    ``drift.load_table`` directly re-reads and re-parses the raw JSON
    on its own schedule: it can rank by a table state no other
    consumer saw, its decisions carry no epoch (so a re-plan round
    cannot invalidate the plans it influenced), and the NaN/zero-ms
    hardening lives only in the seam — the ML009/ML010 one-seam
    argument applied to learned coefficients. ``obs/`` is out of
    scope (the auditor/controller own the table and its writers);
    the seam itself is exempt."""

    id = "ML018"
    _EXEMPT = ("matrel_tpu/parallel/coeffs.py",)

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith("matrel_tpu/")
                and not relpath.startswith("matrel_tpu/obs/")
                and relpath not in self._EXEMPT)

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if (node.module or "").endswith("obs.drift") and any(
                        a.name == "load_table" for a in node.names):
                    yield Finding(
                        relpath, node.lineno, self.id,
                        "load_table imported from obs.drift outside "
                        "the coefficient seam — consult "
                        "parallel/coeffs.py (strategy_row/"
                        "class_coefficients/epoch) so the read is "
                        "memoized, hardened and epoch-stamped")
            elif isinstance(node, ast.Call):
                # drift-qualified calls only (drift.load_table,
                # drift_lib.load_table): the autotune table has its
                # own same-named reader in parallel/autotune.py and
                # is a different store with its own seam
                name = _call_name(node.func)
                if (name.rsplit(".", 1)[-1] == "load_table"
                        and "drift" in name.rsplit(".", 1)[0]):
                    yield Finding(
                        relpath, node.lineno, self.id,
                        "raw drift.load_table consult outside the "
                        "coefficient seam — consult "
                        "parallel/coeffs.py (strategy_row/"
                        "class_coefficients/epoch) so the read is "
                        "memoized, hardened and epoch-stamped")


class DurableIoSeamRule(Rule):
    """ML019: raw file IO in ``matrel_tpu/serve/`` outside the
    spill/checkpoint seam.

    The durability plane (docs/DURABILITY.md) hangs off ONE writer:
    ``serve/spill.py`` stages every artifact through the checkpoint
    format's atomic tmp+rename with a streamed sha1, and its restore
    path treats any mismatch as a typed miss (SnapshotCorruption —
    recompute, never a wrong answer). A serve module that opens files
    on its own creates durable state save_state() does not know to
    freeze and restore() cannot verify — a restart either loses it
    silently or thaws bytes nothing checksummed. The ML009/ML010
    one-seam idiom applied to durable serving state; the seam itself
    is exempt, and modules outside serve/ (obs exporters, the
    checkpoint manager, tools) keep their own IO discipline."""

    id = "ML019"
    _EXEMPT = ("matrel_tpu/serve/spill.py",)
    #: call tokens whose tail identifies a raw durable-IO primitive
    _IO_TAILS = {"save": ("np", "numpy"), "load": ("np", "numpy"),
                 "dump": ("json",), "dumps": (),
                 "replace": ("os",), "remove": ("os",),
                 "unlink": ("os",)}

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith("matrel_tpu/serve/")
                and relpath not in self._EXEMPT)

    def check(self, tree, relpath):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node.func)
            head, _, tail = name.rpartition(".")
            if name == "open":
                yield Finding(
                    relpath, node.lineno, self.id,
                    "raw open() in serve code — durable serving "
                    "state goes through the spill/checkpoint seam "
                    "(serve/spill.py) so artifacts are sha1-stamped, "
                    "atomically renamed and restore-verifiable")
            elif tail in ("save", "load", "dump", "replace",
                          "remove", "unlink"):
                owners = self._IO_TAILS.get(tail, ())
                if head in owners:
                    yield Finding(
                        relpath, node.lineno, self.id,
                        f"raw {name}() in serve code — durable "
                        "serving state goes through the spill/"
                        "checkpoint seam (serve/spill.py) so "
                        "artifacts are sha1-stamped, atomically "
                        "renamed and restore-verifiable")


RULES: Sequence[Rule] = (HostSyncRule(), NoDensifyRule(),
                        ShardMapOutSpecsRule(), ConfigFlowRule(),
                        SpecKeyedCacheRule(), RawTimingRule(),
                        BroadSwallowRule(), DevicePutRule(),
                        KernelSeamRule(), JitSeamRule(),
                        UnboundedQueueRule(), ResultCacheSeamRule(),
                        TimingAccumulationRule(), FleetSeamRule(),
                        ProvenanceSeamRule(), TemplateKeyRule(),
                        LockSeamRule(), CoeffSeamRule(),
                        DurableIoSeamRule())


def _suppressed_codes(line: str) -> set:
    """Codes disabled on this line. Tokens after the code list are
    justification prose (mandatory by convention, ignored by the
    parser): ``# matlint: disable=ML001 analyze-mode op_hook``."""
    m = _SUPPRESS_RE.search(line)
    if not m:
        return set()
    return {tok for tok in re.split(r"[\s,]+", m.group(1))
            if re.fullmatch(r"ML\d+", tok)}


def lint_file(path: str, rules: Sequence[Rule] = RULES,
              relpath: Optional[str] = None) -> List[Finding]:
    """All unsuppressed findings for one file. ``relpath`` overrides
    the repo-relative path used for rule scoping (fixture tests lint
    temp files AS IF they lived at a package path)."""
    rel = relpath if relpath is not None else _rel(path)
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(rel, e.lineno or 0, "ML000",
                        f"file does not parse: {e.msg}")]
    lines = src.splitlines()
    out: List[Finding] = []
    for rule in rules:
        if not rule.applies_to(rel):
            continue
        for f in rule.check(tree, rel):
            line = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
            if f.rule in _suppressed_codes(line):
                continue
            out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(REPO, p)
        if os.path.isfile(full):
            yield full
        elif os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def lint_paths(paths: Sequence[str] = DEFAULT_PATHS) -> List[Finding]:
    out: List[Finding] = []
    for f in iter_python_files(paths):
        out.extend(lint_file(f))
    return out


def main(argv: Sequence[str]) -> int:
    if "--list-rules" in argv:
        for r in RULES:
            doc = (r.__doc__ or "").strip().splitlines()[0]
            print(f"{r.id}  {doc}")
        return 0
    paths = [a for a in argv if not a.startswith("-")] or list(
        DEFAULT_PATHS)
    findings = lint_paths(paths)
    for f in findings:
        print(f.render())
    n = len(findings)
    print(f"matlint: {n} finding(s) in scan set {tuple(paths)}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
