"""Randomized soak harness — many more cases than the pytest suite runs.

Three batteries, all oracle-checked against numpy/scipy:
  fuzz   random mixed-leaf expression trees (dense/block-sparse/COO)
         through optimizer + executor            (tests/test_fuzz.py gen)
  spmv   random graphs (uniform/hub/banded/degenerate) through the
         one-hot SpMV/SpMM plans
  all    both

Run on the CPU mesh (default) or the real chip:
  python tools/soak.py all --seeds 150
  JAX_PLATFORMS= python tools/soak.py fuzz --seeds 25 --tpu

Exit code = number of failing cases (0 = clean).
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _setup(tpu: bool):
    if not tpu:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        # the axon sitecustomize pins the platform at interpreter start;
        # env vars alone do NOT override it (see tests/conftest.py)
        import jax
        jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)
    sys.path.insert(0, os.path.join(REPO, "tests"))


def soak_fuzz(n_seeds: int, base: int, tol: float):
    import importlib.util
    import numpy as np
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.executor import compile_expr

    spec = importlib.util.spec_from_file_location(
        "fuzzmod", os.path.join(REPO, "tests", "test_fuzz.py"))
    fuzz = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fuzz)
    mesh = mesh_lib.make_mesh()
    fails = []
    for seed in range(base, base + n_seeds):
        rng = np.random.default_rng(seed)
        env = {}
        try:
            e = fuzz.gen_expr(rng, env, mesh,
                              depth=int(rng.integers(2, 5)),
                              leaf_kinds=("dense", "dense", "sparse",
                                          "coo"),
                              rand_specs=(seed % 2 == 1))
            oracle = fuzz.np_eval(e, env)
            # half the seeds force the Pallas paths (interpret mode
            # off TPU): the compact COO executor dispatch and Pallas
            # SpMM get soaked alongside the XLA lowerings. The OTHER
            # half randomise leaf PartitionSpecs (round-5 layout net:
            # the planner's per-layout credits must never move
            # numerics). A third sweep runs
            # matmul_precision="high" — the generator's gram nodes then
            # take the symmetric 2-pass split (round-3) and every f32
            # matmul runs bf16x3-class, so tolerance widens with it
            prec = "high" if seed % 3 == 0 else "highest"
            cfg = MatrelConfig(pallas_interpret=(seed % 2 == 0),
                               matmul_precision=prec)
            t = 10 * tol if prec == "high" else tol
            got = compile_expr(e, mesh, cfg).run().to_numpy()
            np.testing.assert_allclose(got, oracle, rtol=t, atol=t)
        except Exception as ex:  # noqa: BLE001 — soak collects everything
            fails.append((seed, type(ex).__name__, str(ex)[:200]))
        done = seed - base + 1
        if done % 30 == 0:
            print(f"  fuzz {done}/{n_seeds}, {len(fails)} failures",
                  flush=True)
    return fails


def soak_deep(n_seeds: int, base: int, tol: float):
    """Deep expression trees (depth 5-7): heavier rewrite/CSE/planner
    pressure than the default battery's depth 2-4."""
    import importlib.util
    import numpy as np
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.executor import compile_expr

    spec = importlib.util.spec_from_file_location(
        "fuzzmod", os.path.join(REPO, "tests", "test_fuzz.py"))
    fuzz = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fuzz)
    mesh = mesh_lib.make_mesh()
    fails = []
    for seed in range(base, base + n_seeds):
        rng = np.random.default_rng(seed)
        env = {}
        try:
            e = fuzz.gen_expr(rng, env, mesh,
                              depth=int(rng.integers(5, 8)),
                              leaf_kinds=("dense", "dense", "sparse",
                                          "coo"),
                              rand_specs=(seed % 2 == 1))
            oracle = fuzz.np_eval(e, env)
            cfg = MatrelConfig(pallas_interpret=(seed % 2 == 0))
            got = compile_expr(e, mesh, cfg).run().to_numpy()
            np.testing.assert_allclose(got, oracle, rtol=tol, atol=tol)
        except Exception as ex:  # noqa: BLE001
            fails.append(("deep", seed, type(ex).__name__, str(ex)[:200]))
    return fails


def soak_spmv(n_trials: int, base: int, tol: float):
    import numpy as np
    import scipy.sparse as sp
    import jax.numpy as jnp
    from matrel_tpu.ops import spmv as spmv_lib

    fails = []
    for trial in range(n_trials):
        rng = np.random.default_rng(base + trial)
        n_r = int(rng.integers(1, 5000))
        n_c = int(rng.integers(1, 5000))
        m = int(rng.integers(0, 30_000))
        style = rng.choice(["uniform", "hub", "banded", "single-col"])
        if style == "uniform" or n_r < 4 or n_c < 4:
            rows = rng.integers(0, n_r, m)
            cols = rng.integers(0, n_c, m)
        elif style == "hub":
            rows = np.where(rng.random(m) < 0.5,
                            rng.integers(0, max(n_r // 100, 1)),
                            rng.integers(0, n_r, m))
            cols = rng.integers(0, n_c, m)
        elif style == "banded":
            rows = rng.integers(0, n_r, m)
            cols = np.clip(rows * n_c // n_r + rng.integers(-3, 4, m),
                           0, n_c - 1)
        else:
            rows = rng.integers(0, n_r, m)
            cols = np.zeros(m, np.int64)
        vals = rng.standard_normal(m).astype(np.float32)
        try:
            S = sp.coo_matrix((vals, (rows, cols)),
                              shape=(n_r, n_c)).tocsr()
            plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                            n_rows=n_r, n_cols=n_c)
            if plan is None:
                continue
            x = rng.standard_normal(n_c).astype(np.float32)
            want = S @ x
            scale = max(float(np.abs(want).max()), 1.0)
            got = np.asarray(spmv_lib.spmv(plan, jnp.asarray(x)))
            np.testing.assert_allclose(got / scale, want / scale,
                                       rtol=tol, atol=tol)
            k = int(rng.integers(1, 9))
            X = rng.standard_normal((n_c, k)).astype(np.float32)
            got2 = np.asarray(spmv_lib.spmm(plan, jnp.asarray(X)))
            np.testing.assert_allclose(got2 / scale, (S @ X) / scale,
                                       rtol=tol, atol=tol)
            # compact-table Pallas scatter (interpret off-TPU)
            from matrel_tpu.ops import pallas_spmv as pc
            import jax as _jax
            interp = _jax.default_backend() not in ("tpu", "axon")
            got3 = np.asarray(pc.spmv_compact(plan, jnp.asarray(x),
                                              interpret=interp))
            np.testing.assert_allclose(got3 / scale, want / scale,
                                       rtol=tol, atol=tol)
        except Exception as ex:  # noqa: BLE001
            fails.append((trial, style, n_r, n_c, m,
                          type(ex).__name__, str(ex)[:150]))
    return fails


def soak_sharded(n_trials: int, base: int, tol: float):
    """Mesh-sharded sparse paths vs scipy oracles: tile-stack SpMM
    (spmm_sharded) and one-hot sharded SpMV (spmv_sharded). The routed
    formulation has its own battery (soak_routed)."""
    import numpy as np
    import scipy.sparse as sp
    import jax.numpy as jnp
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.core.sparse import BlockSparseMatrix
    from matrel_tpu.ops import spmv as spmv_lib

    mesh = mesh_lib.make_mesh()
    fails = []
    for trial in range(n_trials):
        rng = np.random.default_rng(base + trial)
        try:
            # tile-stack SpMM over the mesh
            bs = int(rng.choice([4, 8, 16]))
            gr = int(rng.integers(1, 12))
            gc = int(rng.integers(1, 12))
            n, k = gr * bs, gc * bs
            dens = float(rng.uniform(0.05, 0.9))
            a = np.zeros((n, k), np.float32)
            for f in range(gr * gc):
                if rng.random() < dens:
                    bi, bj = f // gc, f % gc
                    a[bi*bs:(bi+1)*bs, bj*bs:(bj+1)*bs] = \
                        rng.standard_normal((bs, bs))
            w = int(rng.integers(1, 33))
            d = rng.standard_normal((k, w)).astype(np.float32)
            S = BlockSparseMatrix.from_numpy(a, block_size=bs, mesh=mesh)
            if S.nnzb:
                got = S.shard().multiply(
                    BlockMatrix.from_numpy(d, mesh=mesh)).to_numpy()
                np.testing.assert_allclose(got, a @ d, rtol=tol, atol=tol)

            # tile-intersection SpGEMM (plain + sharded) vs oracle
            from matrel_tpu.ops import spgemm as spgemm_lib
            gm = int(rng.integers(1, 12))
            b = np.zeros((k, gm * bs), np.float32)
            for f in range(gc * gm):
                if rng.random() < dens:
                    bi, bj = f // gm, f % gm
                    b[bi*bs:(bi+1)*bs, bj*bs:(bj+1)*bs] = \
                        rng.standard_normal((bs, bs))
            B2 = BlockSparseMatrix.from_numpy(b, block_size=bs,
                                              mesh=mesh)
            want = a @ b
            got = spgemm_lib.spgemm(S, B2).to_numpy()
            np.testing.assert_allclose(got, want, rtol=tol, atol=tol)
            got = spgemm_lib.spgemm_sharded(S, B2).to_numpy()
            np.testing.assert_allclose(got, want, rtol=tol, atol=tol)

            # sharded one-hot SpMV
            n_r = int(rng.integers(64, 4000))
            n_c = int(rng.integers(64, 4000))
            m = int(rng.integers(1, 20_000))
            rows = rng.integers(0, n_r, m)
            cols = rng.integers(0, n_c, m)
            vals = rng.standard_normal(m).astype(np.float32)
            plan = spmv_lib.build_spmv_plan(rows, cols, vals,
                                            n_rows=n_r, n_cols=n_c)
            if plan is not None:
                plan_s = spmv_lib.shard_plan(plan, mesh)
                x = rng.standard_normal(n_c).astype(np.float32)
                want = sp.coo_matrix((vals, (rows, cols)),
                                     shape=(n_r, n_c)) @ x
                scale = max(float(np.abs(want).max()), 1.0)
                got = np.asarray(spmv_lib.spmv_sharded(plan_s, x, mesh))
                np.testing.assert_allclose(got / scale, want / scale,
                                           rtol=tol, atol=tol)

            # topology-weighted planning (round 7): random per-axis
            # weights re-route strategy choices — whatever the weighted
            # pick, execution must stay oracle-exact, and the verifier
            # (incl. MV106's slow-axis pass) must find nothing to flag
            # on the planner's own output
            from matrel_tpu import analysis
            from matrel_tpu.config import MatrelConfig
            from matrel_tpu.executor import execute
            from matrel_tpu.parallel import planner as pl
            wcfg = MatrelConfig(
                axis_cost_weights=(float(rng.choice([1.0, 2.0, 16.0])),
                                   float(rng.choice([1.0, 8.0, 32.0]))),
                comm_alpha_bytes=float(rng.choice([0.0, 200_000.0])))
            wn = int(rng.integers(2, 9)) * 8
            wk = int(rng.integers(2, 9)) * 8
            wm = int(rng.integers(2, 9)) * 8
            wa = rng.standard_normal((wn, wk)).astype(np.float32)
            wb = rng.standard_normal((wk, wm)).astype(np.float32)
            wc = rng.standard_normal((wm, wn)).astype(np.float32)
            wexpr = (BlockMatrix.from_numpy(wa, mesh=mesh).expr()
                     .multiply(BlockMatrix.from_numpy(wb, mesh=mesh)
                               .expr())
                     .multiply(BlockMatrix.from_numpy(wc, mesh=mesh)
                               .expr()))
            wann = pl.annotate_strategies(wexpr, mesh, wcfg)
            diags = analysis.verify_plan(wann, mesh, wcfg)
            assert not [d for d in diags if d.code == "MV106"], diags
            got_w = execute(wann, mesh, wcfg).to_numpy()
            np.testing.assert_allclose(got_w, wa @ wb @ wc,
                                       rtol=5e-3, atol=5e-3)
        except Exception as ex:  # noqa: BLE001
            fails.append(("sharded", trial, type(ex).__name__,
                          str(ex)[:150]))
    return fails


def soak_routed(n_trials: int, base: int, tol: float,
                interpret: bool = True):
    """Routed (gather-free) SpMV plans vs scipy. ``interpret=True`` is
    the CPU battery; ``interpret=False`` under --tpu runs the kernels
    through REAL Mosaic once per round (VERDICT r3 #7: a kept kernel
    that only ever ran interpret mode is latent rot — real-chip soak
    has caught Mosaic bugs CI missed, e.g. seed 50114)."""
    import numpy as np
    import scipy.sparse as sp
    import jax.numpy as jnp
    from matrel_tpu.ops import spmv_routed as rt

    fails = []
    for trial in range(n_trials):
        rng = np.random.default_rng(base + trial)
        try:
            if interpret:
                n_r = int(rng.integers(1000, 50_000))
                n_c = int(rng.integers(1000, 50_000))
                m = int(rng.integers(100, 40_000))
            else:
                # on-chip: small shapes — this battery validates Mosaic
                # lowering, not throughput (the routed path measured 52
                # ms vs 29 at row-5 scale and is kept as a reference
                # formulation)
                n_r = int(rng.integers(1000, 8_000))
                n_c = int(rng.integers(1000, 8_000))
                m = int(rng.integers(100, 10_000))
            rows = rng.integers(0, n_r, m)
            cols = rng.integers(0, n_c, m)
            vals = rng.standard_normal(m).astype(np.float32)
            plan = rt.build_routed_plan(rows, cols, vals, n_r, n_c,
                                        max_padding=50.0)
            if plan is None:
                continue
            x = rng.standard_normal(n_c).astype(np.float32)
            want = sp.coo_matrix((vals, (rows, cols)),
                                 shape=(n_r, n_c)) @ x
            scale = max(float(np.abs(want).max()), 1.0)
            got = np.asarray(rt.routed_spmv(plan, jnp.asarray(x),
                                            interpret=interpret))
            np.testing.assert_allclose(got / scale, want / scale,
                                       rtol=tol, atol=tol)
        except Exception as ex:  # noqa: BLE001
            fails.append(("routed", trial, type(ex).__name__,
                          str(ex)[:150]))
    return fails


def soak_sparse_kernels(n_trials: int, base: int, tol: float):
    """Sparse kernel-registry battery (round 11): random matrices
    drawn PER structure class × EVERY registered kernel forced via the
    config override, each checked against the numpy oracle; one
    rotating kernel per trial additionally runs the full
    executor/planner path — annotated plan verified clean (MV104 +
    MV110) and the structural no-densify guarantee re-asserted with a
    poisoned ``to_dense`` (the test_spgemm acceptance idiom, per
    variant)."""
    import numpy as np
    from matrel_tpu import analysis, executor as executor_lib
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.sparse import BlockSparseMatrix
    from matrel_tpu.ops import kernel_registry as kr
    from matrel_tpu.ops import spgemm as spgemm_lib
    from matrel_tpu.parallel import planner

    mesh = mesh_lib.make_mesh()
    fails = []
    structures = ("row_band", "clustered_tile", "powerlaw_coo",
                  "generic")
    kids = kr.kernel_ids()
    for trial in range(base, base + n_trials):
        rng = np.random.default_rng(trial)
        try:
            structure = structures[trial % len(structures)]
            bs = int(rng.choice([8, 16]))
            n = bs * int(rng.integers(48, 72))
            A = kr.synthesize_structure(structure, n, bs, mesh,
                                        seed=trial)
            B = kr.synthesize_structure(structure, n, bs, mesh,
                                        seed=trial + 17)
            ref = A.to_numpy() @ B.to_numpy()
            scale = max(float(np.abs(ref).max()), 1.0)
            for kid in kids:
                cfg = MatrelConfig(pallas_interpret=True, block_size=bs,
                                   spgemm_kernel_override=kid)
                got = spgemm_lib.spgemm(A, B, cfg).to_numpy()
                np.testing.assert_allclose(got / scale, ref / scale,
                                           rtol=tol, atol=tol)
            # full executor path for one rotating kernel per trial
            # (compiles are the expensive part of this battery)
            kid = kids[trial % len(kids)]
            cfg = MatrelConfig(pallas_interpret=True, block_size=bs,
                               spgemm_kernel_override=kid)
            e = A.multiply(B)
            if not executor_lib._spgemm_dispatch(e, cfg):
                continue
            ann = planner.annotate_strategies(e, mesh, cfg)
            assert ann.attrs.get("spgemm_kernel") == kid, \
                (kid, ann.attrs.get("spgemm_kernel"))
            bad = [d for d in analysis.verify_plan(ann, mesh, cfg)
                   if d.code in ("MV104", "MV110")]
            assert not bad, bad
            orig = BlockSparseMatrix.to_dense

            def _boom(self, *a, **k):
                raise AssertionError(
                    "SpGEMM kernel variant densified an operand")

            BlockSparseMatrix.to_dense = _boom
            try:
                out = executor_lib.execute(ann, mesh, cfg)
            finally:
                BlockSparseMatrix.to_dense = orig
            np.testing.assert_allclose(
                out.to_numpy()[:n, :n] / scale, ref / scale,
                rtol=tol, atol=tol)
        except Exception as ex:  # noqa: BLE001 — soak collects all
            fails.append(("spk", trial, type(ex).__name__,
                          str(ex)[:200]))
    return fails


def soak_fusion(n_trials: int, base: int, tol: float):
    """Whole-plan fusion battery (round 12): random elementwise/
    reduction chains over DENSE, S×S (block-sparse) and COO producers
    executed with fusion FORCED ON against numpy oracles, per
    precision tier on the dense trials — and, every trial, the fused
    run compared tightly against the staged (fusion-off) run of the
    SAME expression, which must agree to float noise (identical member
    lowerings, one program boundary apart). A rotating
    fusion-boundary pass additionally compiles one trial per round
    under ``verify_plans="error"`` so a boundary MV111 would reject
    can never reach execution."""
    import numpy as np
    from matrel_tpu import analysis, executor as executor_lib
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.core.coo import COOMatrix
    from matrel_tpu.ops import kernel_registry as kr
    from matrel_tpu.parallel import planner

    mesh = mesh_lib.make_mesh()
    fails = []
    producers = ("dense", "sxs", "coo")
    tiers = ("default", "float32", "high", "fast")
    for trial in range(n_trials):
        rng = np.random.default_rng(base + trial)
        try:
            producer = producers[trial % len(producers)]
            sla = tiers[trial % len(tiers)] if producer == "dense" \
                else "default"
            n = int(rng.choice([24, 32, 48]))
            if producer == "dense":
                a = rng.standard_normal((n, n)).astype(np.float32)
                b = rng.standard_normal((n, n)).astype(np.float32)
                A = BlockMatrix.from_numpy(a, mesh=mesh)
                B = BlockMatrix.from_numpy(b, mesh=mesh)
                e = A.expr().multiply(B.expr())
                ref = a.astype(np.float64) @ b.astype(np.float64)
            elif producer == "sxs":
                bs = int(rng.choice([8, 16]))
                n = bs * int(rng.integers(16, 32))
                SA = kr.synthesize_structure("row_band", n, bs, mesh,
                                             seed=base + trial)
                SB = kr.synthesize_structure("row_band", n, bs, mesh,
                                             seed=base + trial + 9)
                e = SA.multiply(SB)
                ref = (SA.to_numpy().astype(np.float64)
                       @ SB.to_numpy().astype(np.float64))
            else:
                nnz = max(8, 3 * n)
                flat = rng.choice(n * n, size=min(nnz, n * n),
                                  replace=False)
                rows, cols = flat // n, flat % n
                vals = rng.standard_normal(rows.size).astype(
                    np.float32)
                C = COOMatrix.from_edges(rows, cols, vals, (n, n))
                d = rng.standard_normal((n, 4)).astype(np.float32)
                D = BlockMatrix.from_numpy(d, mesh=mesh)
                e = C.expr().multiply(D.expr())
                cd = np.zeros((n, n), np.float64)
                cd[rows, cols] = vals.astype(np.float64)
                ref = cd @ d.astype(np.float64)
            # random fusable chain over the producer (the oracle
            # follows along in float64)
            for _ in range(int(rng.integers(2, 6))):
                op = int(rng.integers(0, 5))
                if op == 0:
                    s = float(rng.uniform(-2, 2))
                    e, ref = e.multiply_scalar(s), ref * s
                elif op == 1:
                    s = float(rng.uniform(-1, 1))
                    e, ref = e.add_scalar(s), ref + s
                elif op == 2:
                    w = rng.standard_normal(ref.shape).astype(
                        np.float32)
                    W = BlockMatrix.from_numpy(w, mesh=mesh)
                    e = e.add(W.expr())
                    ref = ref + w.astype(np.float64)
                elif op == 3:
                    w = rng.standard_normal(ref.shape).astype(
                        np.float32)
                    W = BlockMatrix.from_numpy(w, mesh=mesh)
                    e = e.elem_multiply(W.expr())
                    ref = ref * w.astype(np.float64)
                else:
                    if ref.shape[0] > 1:
                        e, ref = e.row_sum(), ref.sum(
                            axis=1, keepdims=True)
            cfg_on = MatrelConfig(fusion_enable=True,
                                  precision_sla=sla)
            cfg_off = cfg_on.replace(fusion_enable=False)
            out_on = executor_lib.execute(e, mesh, cfg_on).to_numpy()
            out_off = executor_lib.execute(e, mesh,
                                           cfg_off).to_numpy()
            lr, lc = ref.shape
            scale = max(float(np.abs(ref).max()), 1.0)
            # bf16 tiers carry their documented looser bound; the
            # fused-vs-staged comparison below stays TIGHT per tier
            tier_tol = {"high": 2 * tol, "fast": 2e-2}.get(sla, tol)
            np.testing.assert_allclose(
                out_on[:lr, :lc] / scale, ref / scale,
                rtol=tier_tol, atol=tier_tol)
            np.testing.assert_allclose(
                out_on / scale, out_off / scale,
                rtol=1e-5, atol=1e-5)
            if trial % 3 == 0:
                # rotating fusion-boundary pass: the annotated fused
                # plan verifies clean and compiles under the error
                # gate (nothing MV111 rejects may execute)
                opt = planner.annotate_strategies(
                    __import__("matrel_tpu.ir.rules",
                               fromlist=["optimize"]).optimize(
                        e, cfg_on), mesh, cfg_on)
                from matrel_tpu.ir import fusion as fusion_lib
                opt = fusion_lib.annotate_fusion(opt, mesh, cfg_on)
                bad = [d for d in analysis.verify_plan(opt, mesh,
                                                       cfg_on)
                       if d.code == "MV111"
                       and d.severity == "error"]
                assert not bad, bad
                executor_lib.compile_expr(
                    e, mesh, cfg_on.replace(verify_plans="error"))
        except Exception as ex:  # noqa: BLE001 — soak collects all
            fails.append(("fusion", trial, type(ex).__name__,
                          str(ex)[:200]))
    return fails


def soak_serve(n_trials: int, base: int, tol: float):
    """Serving-layer battery: a random query stream (with heavy
    repetition, so the result cache and the MultiPlan plan cache both
    get real traffic) served through session.run_many / session.run
    with the cross-query result cache ON must match the numpy oracle
    QUERY-FOR-QUERY — reuse may never change an answer. Mid-stream a
    catalog rebind exercises invalidation under load."""
    import numpy as np
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.session import MatrelSession

    mesh = mesh_lib.make_mesh()
    fails = []
    for trial in range(n_trials):
        rng = np.random.default_rng(base + trial)
        try:
            n = int(rng.choice([16, 24, 32]))
            mats_np = [rng.standard_normal((n, n)).astype(np.float32)
                       for _ in range(3)]
            mats = [BlockMatrix.from_numpy(a, mesh=mesh)
                    for a in mats_np]

            def rand_query(depth=0):
                """(expr, numpy oracle) pairs over the shared mats."""
                kind = int(rng.integers(0, 6 if depth < 2 else 3))
                if kind in (0, 1, 2) or depth >= 2:
                    i = int(rng.integers(0, len(mats)))
                    return mats[i].expr(), mats_np[i]
                a, na = rand_query(depth + 1)
                b, nb = rand_query(depth + 1)
                if kind == 3:
                    return a.multiply(b), na @ nb
                if kind == 4:
                    return a.add(b), na + nb
                s = float(rng.uniform(-2, 2))
                return a.multiply_scalar(s).t(), (na * s).T

            pool = [rand_query() for _ in range(int(rng.integers(3, 7)))]
            stream = [pool[int(rng.integers(0, len(pool)))]
                      for _ in range(3 * len(pool))]
            sess = MatrelSession(mesh=mesh, config=MatrelConfig(
                result_cache_max_bytes=32 << 20))
            sess.register("t0", mats[0])
            i = 0
            rebound = False
            while i < len(stream):
                if rng.random() < 0.5:
                    bs = int(rng.integers(1, 5))
                    chunk = stream[i:i + bs]
                    outs = sess.run_many([e for e, _ in chunk])
                else:
                    chunk = stream[i:i + 1]
                    outs = [sess.run(chunk[0][0])]
                for (e, want), out in zip(chunk, outs):
                    scale = max(float(np.abs(want).max()), 1.0)
                    np.testing.assert_allclose(
                        out.to_numpy() / scale, want / scale,
                        rtol=tol, atol=tol)
                i += len(chunk)
                if not rebound and i >= len(stream) // 2:
                    # rebind under load: dependent entries must drop.
                    # Crossed-midpoint flag, not equality — variable
                    # chunk sizes jump over any exact index, and an
                    # equality check would silently skip the very
                    # behaviour this battery claims to soak
                    sess.register("t0", mats[1])
                    rebound = True
        except Exception as ex:  # noqa: BLE001
            fails.append(("serve", trial, type(ex).__name__,
                          str(ex)[:150]))
    return fails


def soak_cse(n_trials: int, base: int, tol: float):
    """Multi-query-optimization battery (serve/mqo.py;
    docs/SERVING.md): every trial builds batches with SEEDED shared
    interiors — a dense Gram polynomial, an S×S block-sparse product,
    a COO SpMV — under a random precision tier, runs them through a
    ``cse_enable`` session, and checks every answer against the numpy
    oracle query-for-query (sharing may never change an answer).
    Also per trial: at least one interior actually HOISTS (a battery
    that never shares proves nothing); MV116's dynamic pass proves
    every remembered substitution against unshared execution; a
    catalog rebind mid-trial invalidates the hoisted node's cached
    result and the same structural batch over the NEW binding must
    answer from fresh data (a stale hoist is a wrong answer the
    oracle catches); and a fleet-routed repeat (fleet_slices=2) runs
    a shared-interior batch through placement."""
    import numpy as np
    from matrel_tpu.analysis import cse_pass
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.core.coo import COOMatrix
    from matrel_tpu.core.sparse import BlockSparseMatrix
    from matrel_tpu.session import MatrelSession

    mesh = mesh_lib.make_mesh()
    fails = []
    for trial in range(n_trials):
        rng = np.random.default_rng(base + trial)
        try:
            n = int(rng.choice([16, 24, 32]))
            k = int(rng.integers(3, 6))
            sla = str(rng.choice(["default", "high", "exact"]))
            x_np = rng.standard_normal((n, n)).astype(np.float32)
            y_np = rng.standard_normal((n, n)).astype(np.float32)
            X = BlockMatrix.from_numpy(x_np, mesh=mesh)
            Y = BlockMatrix.from_numpy(y_np, mesh=mesh)
            sess = MatrelSession(mesh=mesh, config=MatrelConfig(
                cse_enable=True, precision_sla=sla,
                result_cache_max_bytes=16 << 20))
            sess.register("src", X)

            def check(outs, oracles):
                for out, want in zip(outs, oracles):
                    scale = max(float(np.abs(want).max()), 1.0)
                    np.testing.assert_allclose(
                        out.to_numpy().astype(np.float64) / scale,
                        want / scale, rtol=tol, atol=tol)

            def gram_batch(M, m_np):
                g = M.expr().t().multiply(M.expr())
                go = m_np.astype(np.float64).T @ m_np.astype(
                    np.float64)
                ss = [float(rng.uniform(0.5, 2.0)) for _ in range(k)]
                return ([g.multiply_scalar(s) for s in ss],
                        [go * s for s in ss])

            # dense Gram interior, shared across k scalar variants
            qs, oracles = gram_batch(X, x_np)
            check(sess.run_many(qs), oracles)

            # S×S block-sparse product interior (SpGEMM output feeds
            # every variant)
            sp = __import__("scipy.sparse", fromlist=["random"])
            s_sp = sp.random(n, n, density=0.3, random_state=int(
                rng.integers(1 << 30)), dtype=np.float32)
            S = BlockSparseMatrix.from_scipy(s_sp, block_size=8,
                                             mesh=mesh)
            s_np = s_sp.toarray().astype(np.float64)
            gs = S.expr().multiply(S.expr())
            so = s_np @ s_np
            sqs = [gs.multiply_scalar(1.0 + i) for i in range(k)]
            check(sess.run_many(sqs), [so * (1.0 + i)
                                       for i in range(k)])

            # COO SpMV interior: A_coo · X dense, shared by variants
            c_sp = sp.random(n, n, density=0.05, random_state=int(
                rng.integers(1 << 30)), dtype=np.float32)
            C = COOMatrix.from_scipy(c_sp.tocoo()).shard(mesh)
            c_np = c_sp.toarray().astype(np.float64)
            gc = C.expr().multiply(X.expr())
            co = c_np @ x_np.astype(np.float64)
            cqs = [gc.multiply_scalar(2.0 + i) for i in range(k)]
            check(sess.run_many(cqs), [co * (2.0 + i)
                                       for i in range(k)])

            info = sess.mqo_info()
            assert info["cse_hoisted"] >= 1, info
            diags = cse_pass.verify_cse_executions(sess)
            assert diags == [], [d.render() for d in diags]

            # rebind invalidation: the hoisted Gram's source rebinds;
            # the same STRUCTURE over the new binding must answer
            # from fresh data, never the stale hoisted result
            sess.register("src", Y)
            qs2, oracles2 = gram_batch(Y, y_np)
            check(sess.run_many(qs2), oracles2)

            # fleet-routed repeat: the shared-interior batch through
            # placement over 2 slices, same oracle contract
            fsess = MatrelSession(mesh=mesh, config=MatrelConfig(
                cse_enable=True, precision_sla=sla, fleet_slices=2,
                result_cache_max_bytes=16 << 20))
            fq, fo = gram_batch(X, x_np)
            check(fsess.run_many(fq), fo)
        except Exception as ex:  # noqa: BLE001
            fails.append(("cse", trial, type(ex).__name__,
                          str(ex)[:150]))
    return fails


def soak_stream(n_trials: int, base: int, tol: float):
    """Streaming-graph IVM battery (docs/IVM.md): a sliding-window
    edge stream (workloads/streaming.py) drives register_delta ticks
    over the dashboard query set, and EVERY tick's every answer is
    checked against the numpy oracle — the integer queries (degrees,
    label counts, common neighbors, trace(A³)) BIT-EXACTLY, so a
    wrong patch can never hide in a tolerance. Also covered per
    trial: an INELIGIBLE query (select_value — no delta rule) rides
    the stream and must fall back to kill-and-recompute correctly;
    MV113's dynamic check proves every surviving patched entry
    against fresh execution; the PageRank warm restart lands on the
    cold-start fixed point; and at least one entry actually PATCHED
    (a battery that silently recomputed everything proves nothing)."""
    import numpy as np
    from matrel_tpu.analysis import delta_pass
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.ir.delta import pagerank_warm_restart
    from matrel_tpu.session import MatrelSession
    from matrel_tpu.workloads.streaming import StreamingGraph

    mesh = mesh_lib.make_mesh()
    fails = []
    for trial in range(n_trials):
        rng = np.random.default_rng(base + trial)
        try:
            n = int(rng.choice([96, 128, 160]))
            batch = int(rng.choice([2, 3, 4]))
            sess = MatrelSession(mesh=mesh, config=MatrelConfig(
                result_cache_max_bytes=256 << 20))
            g = StreamingGraph(sess, n=n, batch_edges=batch,
                               window=int(rng.integers(3, 7)),
                               feature_k=16, seed=base + trial)
            thresh = float(rng.uniform(0.5, 1.5))
            def ineligible():
                # select_value has no delta rule — this entry MUST
                # fall back to the transitive kill and recompute
                return sess.table(g.name).expr().select_value(
                    lambda v: v > thresh).sum()
            g.run_all()
            sess.run(ineligible())
            g.pagerank()        # seed the cached vector: the check
            total_patched = 0   # after the ticks must be a WARM call
            for _tick in range(int(rng.integers(3, 6))):
                s = g.step_delta()
                total_patched += s["patched"]
                got = g.run_all()
                want = g.oracle()
                for k in got:
                    w = np.asarray(want[k], np.float32).reshape(
                        got[k].shape)
                    err = float(np.abs(got[k] - w).max())
                    exact = k != "feature_product"
                    if (err != 0.0) if exact else (err > tol):
                        raise AssertionError(
                            f"tick answer wrong: {k} err={err}")
                ineo = sess.run(ineligible()).to_numpy()
                wo = (g.adj * (g.adj > thresh)).sum()
                if abs(float(ineo[0, 0]) - float(wo)) > tol * max(
                        abs(wo), 1.0):
                    raise AssertionError(
                        "ineligible-query fallback answered wrong")
                diags = delta_pass.verify_patched_entries(sess)
                if diags:
                    raise AssertionError(
                        f"MV113: {diags[0].render()[:140]}")
            if total_patched == 0:
                raise AssertionError(
                    "stream never patched a single entry — the "
                    "battery exercised nothing")
            assert g._pr is not None   # seeded above — this IS warm
            pr = g.pagerank(rounds=80)
            cold = pagerank_warm_restart(
                g.adj.astype(np.float64),
                np.full(g.n, 1.0 / g.n), rounds=300)
            if float(np.abs(pr - cold).sum()) > 1e-5:
                raise AssertionError("pagerank warm restart drifted "
                                     "off the cold fixed point")
        except Exception as ex:  # noqa: BLE001
            fails.append(("stream", trial, type(ex).__name__,
                          str(ex)[:150]))
    return fails


def soak_fleet(n_trials: int, base: int, tol: float):
    """Multi-slice fleet battery (docs/FLEET.md): a randomized
    catalog + query stream served through a 2-/3-slice fleet with a
    random slice KILLED mid-stream. Every resolved answer is checked
    against its numpy oracle (ZERO wrong answers — a failover that
    rebinds onto the wrong replica would show up here, not as a
    crash), every failure must be TYPED (ResilienceError family), the
    directory must have answered repeats (hits > 0), and the stream
    must COMPLETE: at least one post-kill answer resolves on a
    survivor. Randomized per trial: slice count, replication
    threshold, stream composition, kill point and victim."""
    import numpy as np
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.resilience.errors import ResilienceError
    from matrel_tpu.session import MatrelSession

    mesh = mesh_lib.make_mesh()
    fails = []
    for trial in range(n_trials):
        rng = np.random.default_rng(base + trial)
        sess = None
        try:
            n = int(rng.choice([48, 64, 96]))
            n_slices = int(rng.choice([2, 3]))
            cfg = MatrelConfig(
                fleet_slices=n_slices,
                result_cache_max_bytes=128 << 20,
                serve_max_batch=1,
                fleet_replicate_hits=int(rng.choice([0, 1, 3])))
            sess = MatrelSession(mesh=mesh, config=cfg)
            mats = {}
            for nm in ("A", "B", "C"):
                arr = rng.standard_normal((n, n)).astype(np.float32)
                mats[nm] = arr
                sess.register(nm, sess.from_numpy(arr))
            A = sess.table("A").expr()
            B = sess.table("B").expr()
            C = sess.table("C").expr()
            oAB = mats["A"] @ mats["B"]
            templates = [
                (A.multiply(B), oAB),
                (A.multiply(B).multiply_scalar(2.0), 2.0 * oAB),
                (A.multiply(B.multiply(C)),
                 mats["A"] @ (mats["B"] @ mats["C"])),
                (A.add(B).multiply(C),
                 (mats["A"] + mats["B"]) @ mats["C"]),
                (A.t().multiply(B).add_scalar(1.0),
                 mats["A"].T @ mats["B"] + 1.0),
            ]
            stream_len = int(rng.integers(20, 36))
            picks = rng.integers(0, len(templates), size=stream_len)
            kill_at = int(rng.integers(stream_len // 4,
                                       3 * stream_len // 4))
            victim = int(rng.integers(0, n_slices))
            futs = []
            for i, p in enumerate(picks):
                futs.append((int(p), sess.submit(templates[p][0])))
                if i % 6 == 5:
                    # paced bursts: every sixth submission waits, so
                    # directory inserts land mid-stream and later
                    # repeats exercise the hit-anywhere protocol
                    # (a fully-async stream would outrun every
                    # insert and prove nothing about the directory)
                    try:
                        futs[-1][1].result(timeout=120)
                    except ResilienceError:
                        pass
                if i == kill_at:
                    sess._fleet.kill_slice(victim)
            sess.serve_drain(timeout=120)
            wrong = untyped = 0
            post_kill_ok = 0
            for j, (p, fut) in enumerate(futs):
                try:
                    out = fut.result(timeout=120)
                    got = np.asarray(out.to_numpy())
                    want = templates[p][1]
                    err = float(np.abs(got - want).max())
                    if err > tol * max(float(np.abs(want).max()),
                                       1.0):
                        wrong += 1
                    elif j > kill_at:
                        post_kill_ok += 1
                except ResilienceError:
                    pass                  # typed — the contract
                except Exception:
                    untyped += 1
            info = sess.fleet_info()
            if wrong:
                raise AssertionError(f"{wrong} wrong answers")
            if untyped:
                raise AssertionError(f"{untyped} untyped failures")
            if post_kill_ok == 0:
                raise AssertionError(
                    "stream did not complete past the kill")
            if info["failovers"] != 1:
                raise AssertionError(
                    f"failovers={info['failovers']} (expected 1)")
            if info["directory"]["hits"] == 0:
                raise AssertionError("directory never answered")
            alive = [sl for sl in info["slices"] if sl["alive"]]
            if len(alive) != n_slices - 1:
                raise AssertionError("wrong surviving-slice census")
            sess.serve_close(timeout=60)
            print(f"  fleet trial {trial + 1}/{n_trials} ok")
        except Exception as e:  # noqa: BLE001 — tally and continue
            fails.append(f"fleet trial {trial}: {type(e).__name__} {e}")
            print(f"  FAIL {fails[-1]}")
        finally:
            # a FAILED trial must still tear its fleet down — leaked
            # slice sessions (worker threads + replicated catalogs)
            # would distort every later trial on the shared host
            if sess is not None:
                try:
                    sess.serve_close(timeout=60)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
    return fails


def soak_race(n_trials: int, base: int, tol: float):
    """Concurrency battery (docs/CONCURRENCY.md): the race_drill
    schedules — submit/close/drain, kill-during-replication,
    rebind-vs-template-probes, delta-under-load — each run n_trials
    seeds with runtime lockdep armed. A trial fails on a wrong
    answer, an untyped failure, a recorded lock-order inversion, or a
    cyclic order graph; failures reproduce by (schedule, seed)."""
    from matrel_tpu.utils import lockdep
    from tools import race_drill

    fails = []
    for name, fn in race_drill.SCHEDULES.items():
        for trial in range(n_trials):
            seed = base + trial
            lockdep.reset()
            try:
                res = fn(seed, 10)
                diags = lockdep.diagnostics()
                bad = []
                if res["wrong"]:
                    bad.append(f"{res['wrong']} wrong")
                if res["untyped"]:
                    bad.append(f"{res['untyped']} untyped")
                inv = sum(1 for d in diags
                          if d["diag"] in ("inversion",
                                           "self_deadlock"))
                if inv:
                    bad.append(f"{inv} lockdep inversion(s)")
                if not lockdep.is_acyclic():
                    bad.append("cyclic lock-order graph")
                if bad:
                    raise AssertionError("; ".join(bad))
                print(f"  race {name} trial {trial + 1}/{n_trials} ok")
            except Exception as e:  # noqa: BLE001 — tally and continue
                fails.append(f"race {name} seed {seed}: "
                             f"{type(e).__name__} {e}")
                print(f"  FAIL {fails[-1]}")
    lockdep.reset()
    lockdep.disable()
    return fails


def soak_precision(n_trials: int, base: int, tol: float):
    """Precision-SLA battery: random matmul-shaped queries executed at
    every SLA tier against an f64 numpy oracle, asserting the
    DOCUMENTED per-tier error bound (planner.tier_error_bound — the
    docs/PRECISION.md table: bf16x3 within ~f32 tolerance, bf16x1
    within the single-pass bf16 bound, int paths EXACT), including
    under the sharded 8-device mesh and with result-cache tier
    isolation live (a "fast" entry must never answer an "exact"
    probe — checked by running the same stream at two SLAs through one
    cache-on session and oracle-checking both)."""
    import numpy as np
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.executor import compile_expr
    from matrel_tpu.parallel import planner
    from matrel_tpu.session import MatrelSession

    mesh = mesh_lib.make_mesh()
    fails = []
    for trial in range(n_trials):
        rng = np.random.default_rng(base + trial)
        try:
            n = int(rng.integers(2, 12)) * 8
            k = int(rng.integers(2, 12)) * 8
            m = int(rng.integers(2, 12)) * 8
            a = rng.uniform(-1.0, 1.0, (n, k)).astype(np.float32)
            b = rng.uniform(-1.0, 1.0, (k, m)).astype(np.float32)
            c = rng.uniform(-1.0, 1.0, (m, n)).astype(np.float32)
            A = BlockMatrix.from_numpy(a, mesh=mesh)
            B = BlockMatrix.from_numpy(b, mesh=mesh)
            C = BlockMatrix.from_numpy(c, mesh=mesh)
            # two chained contractions: error bounds must hold through
            # the composition, not just one product
            want = (a.astype(np.float64) @ b.astype(np.float64)
                    @ c.astype(np.float64))
            for sla, tiers in (("exact", ("f32",)),
                               ("high", ("bf16x3", "f32")),
                               ("fast", ("bf16x1",)),
                               ("bfloat16", ("bf16x1",)),
                               ("bf16x3", ("bf16x3",))):
                cfg = MatrelConfig(precision_sla=sla)
                expr = A.expr().multiply(B.expr()).multiply(C.expr())
                plan = compile_expr(expr, mesh, cfg)
                got = plan.run().to_numpy().astype(np.float64)
                # documented bound, composed over both contractions:
                # bound(A·B) propagates through the second multiply
                # (× m·max|C|) and the second contraction adds its own
                worst = max(planner.TIER_EPS[t] for t in tiers)
                bound = (worst * k * 1.0 * 1.0) * m * 1.0 \
                    + worst * m * (k * 1.0) * 1.0
                err = float(np.abs(got - want).max())
                assert err <= max(bound, 64 * tol), \
                    (sla, err, bound)
            # integer-exact path, sharded: "exact" on integral inputs
            # must be EXACT, not merely close
            ai = rng.integers(-3, 4, (n, k))
            bi = rng.integers(-3, 4, (k, m))
            Ai = BlockMatrix.from_numpy(ai, mesh=mesh)
            Bi = BlockMatrix.from_numpy(bi, mesh=mesh)
            cfg = MatrelConfig(precision_sla="exact")
            plan = compile_expr(Ai.expr().multiply(Bi.expr()), mesh,
                                cfg)
            got_i = plan.run().to_numpy()
            assert got_i.dtype == np.int32, got_i.dtype
            assert np.array_equal(got_i, ai @ bi)
            # result-cache tier isolation under load: one cache-on
            # session serves the same query at "fast" then "exact" —
            # the exact answer must be exact (a cross-tier hit would
            # hand back the bf16 result)
            sess = MatrelSession(mesh=mesh, config=MatrelConfig(
                result_cache_max_bytes=16 << 20))
            qi = Ai.expr().multiply(Bi.expr())
            fast = sess.run(qi, precision="fast")
            assert fast.dtype == np.float32       # bf16x1 path ran
            exact = sess.run(qi, precision="exact")
            # dtype is the non-vacuous discriminator: small-int bf16
            # products are VALUE-exact, so a cross-tier hit would
            # still match the oracle — but it could never be int32
            assert exact.dtype == np.int32, "cross-tier rc hit"
            assert np.array_equal(exact.to_numpy(), ai @ bi)
        except Exception as ex:  # noqa: BLE001
            fails.append(("precision", trial, type(ex).__name__,
                          str(ex)[:150]))
    return fails


def soak_chaos(n_trials: int, base: int, tol: float):
    """Randomized chaos: each trial builds a session with a RANDOM
    seeded fault schedule (random sites, kinds, probabilities) and
    runs a small mixed query stream against numpy oracles. The
    resilience contract under soak: every query either converges to
    the correct answer (retries + degradation ladder) or fails with a
    TYPED error attributable to a deterministic fault — never a wrong
    answer, never an unclassified crash, never a hang."""
    import numpy as np
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.resilience import errors as rerrors, faults
    from matrel_tpu.session import MatrelSession

    mesh = mesh_lib.make_mesh()
    fails = []
    for trial in range(base, base + n_trials):
        rng = np.random.default_rng(trial)
        # total transient fire budget (sum of max=) stays STRICTLY
        # below retry_max_attempts: the stream must be able to absorb
        # every transient even if one query eats the whole budget —
        # otherwise "transient escaped the retry loop" would be a
        # legitimate outcome and the battery seed-flaky, not a check
        sites = list(rng.choice(faults.SITES,
                                size=int(rng.integers(1, 4)),
                                replace=False))
        has_fatal = bool(rng.random() < 0.3)
        rules = [f"{s}:transient:p={float(rng.uniform(0.05, 0.3)):.3f}"
                 f":max=1" for s in sites]
        if has_fatal:
            # one deterministic one-shot fault somewhere in the stream
            rules.append(
                f"{str(rng.choice(faults.SITES))}:fatal"
                f":n={int(rng.integers(1, 20))}")
        try:
            faults.reset()
            cfg = MatrelConfig(
                fault_inject=";".join(rules),
                fault_inject_seed=trial,
                retry_max_attempts=6, retry_backoff_ms=1.0,
                result_cache_max_bytes=(1 << 24
                                        if trial % 2 else 0))
            sess = MatrelSession(mesh=mesh, config=cfg)
            n = int(rng.choice([16, 32, 48]))
            an = rng.standard_normal((n, n)).astype(np.float32)
            bn = rng.standard_normal((n, n)).astype(np.float32)
            A, B = sess.from_numpy(an), sess.from_numpy(bn)
            for q in range(6):
                e = (A.expr().multiply(B.expr())
                     .multiply_scalar(float(q + 1)))
                want = an @ bn * (q + 1)
                try:
                    got = sess.run(e).to_numpy()
                except rerrors.InjectedFault as ex:
                    # only a DETERMINISTIC injected fault may surface
                    if ex.transient:
                        raise AssertionError(
                            f"transient fault escaped the retry "
                            f"loop: {ex}") from ex
                    continue
                np.testing.assert_allclose(got, want, rtol=tol,
                                           atol=tol)
            # batch surface too, same contract
            try:
                outs = sess.run_many(
                    [A.expr().multiply(B.expr()),
                     B.expr().multiply(A.expr())])
                np.testing.assert_allclose(outs[0].to_numpy(), an @ bn,
                                           rtol=tol, atol=tol)
                np.testing.assert_allclose(outs[1].to_numpy(), bn @ an,
                                           rtol=tol, atol=tol)
            except rerrors.InjectedFault as ex:
                if ex.transient:
                    raise AssertionError(
                        f"transient fault escaped run_many: "
                        f"{ex}") from ex
        except Exception as ex:  # noqa: BLE001 — soak collects all
            fails.append(("chaos", trial, type(ex).__name__,
                          str(ex)[:200]))
    faults.reset()
    return fails


def soak_overload(n_trials: int, base: int, tol: float):
    """Randomized overload-control soak (docs/OVERLOAD.md): each trial
    drives seeded open-loop-ish bursts of tenant-tagged submissions
    through a session with weighted-fair admission, tight quotas, an
    aggressive brownout controller, circuit breakers AND a PR 8 fault
    schedule (capped transient fires + fatal execute fires). The
    contract under soak: every admitted query either matches its
    numpy oracle or fails TYPED (shed / deadline / circuit / injected
    — never a wrong answer, never an unclassified crash), and after
    the fault window every breaker closes again (a probe success must
    re-admit the class)."""
    import numpy as np
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.resilience import errors as rerrors, faults
    from matrel_tpu.session import MatrelSession

    mesh = mesh_lib.make_mesh()
    fails = []
    typed_kinds = (rerrors.ResilienceError,)
    for trial in range(base, base + n_trials):
        rng = np.random.default_rng(trial)
        try:
            faults.reset()
            # fatal fires are CAPPED so the fault window provably
            # ends; transient budget stays strictly below the retry
            # budget (the soak_chaos discipline)
            rules = ["execute:fatal:p=0.25:max=3",
                     "serve_admit:transient:p=0.1:max=2"]
            cfg = MatrelConfig(
                serve_tenant_weights="a:3,b:1",
                serve_tenant_queue_max=4,
                serve_queue_max=10,
                serve_max_batch=int(rng.integers(1, 4)),
                brownout_enable=True,
                brownout_window=8, brownout_dwell=2,
                brownout_wait_high_ms=5.0, brownout_wait_low_ms=1.0,
                brownout_depth_high=6, brownout_depth_low=1,
                breaker_threshold=2, breaker_cooldown_ms=30.0,
                retry_max_attempts=4, retry_backoff_ms=1.0,
                fault_inject=";".join(rules),
                fault_inject_seed=trial,
                result_cache_max_bytes=(1 << 24 if trial % 2 else 0))
            sess = MatrelSession(mesh=mesh, config=cfg)
            n = int(rng.choice([16, 32]))
            an = rng.standard_normal((n, n)).astype(np.float32)
            bn = rng.standard_normal((n, n)).astype(np.float32)
            A, B = sess.from_numpy(an), sess.from_numpy(bn)
            pool = [(A.expr().multiply(B.expr())
                     .multiply_scalar(float(s + 1)),
                     an @ bn * (s + 1)) for s in range(3)]
            futs = []
            # seeded bursts: submit without waiting (open loop), gaps
            # from an exponential draw — admission pressure is the
            # point, so most trials overrun the tiny quotas
            for q in range(28):
                e, want = pool[q % len(pool)]
                tenant = "a" if rng.random() < 0.5 else "b"
                try:
                    futs.append(
                        (sess.submit(e, tenant=tenant,
                                     deadline_ms=5_000.0), want))
                except rerrors.AdmissionShed:
                    continue       # typed refusal IS the contract
                if rng.random() < 0.3:
                    __import__("time").sleep(
                        float(rng.exponential(0.004)))
            sess.serve_drain(timeout=120)
            for fut, want in futs:
                ex = fut.exception(timeout=60)
                if ex is None:
                    got = fut.result().to_numpy()
                    # brownout rung 1 legitimately runs default-SLA
                    # queries at the bf16 fast tier: the oracle bound
                    # is the FAST tier's documented max-norm error,
                    # not f32's (docs/PRECISION.md / OVERLOAD.md)
                    scale = max(1.0, float(np.max(np.abs(want))))
                    np.testing.assert_allclose(got, want, rtol=0,
                                               atol=2e-2 * scale)
                elif not isinstance(ex, typed_kinds):
                    raise AssertionError(
                        f"untyped failure escaped: "
                        f"{type(ex).__name__}: {ex}") from ex
            # the fault window is over (max= caps reached): the
            # breaker must close again — settle with single queries,
            # waiting out cooldowns on typed CircuitOpen refusals
            e, want = pool[0]
            for _ in range(12):
                try:
                    got = sess.run(e)
                    scale = max(1.0, float(np.max(np.abs(want))))
                    np.testing.assert_allclose(got.to_numpy(), want,
                                               rtol=0,
                                               atol=2e-2 * scale)
                    break
                except rerrors.CircuitOpen:
                    __import__("time").sleep(0.04)
                except rerrors.InjectedFault as ex:
                    if ex.transient:
                        raise AssertionError(
                            "transient escaped the retry loop") from ex
                    __import__("time").sleep(0.01)
            else:
                raise AssertionError(
                    "breaker never re-admitted the class after the "
                    "fault window")
            snap = sess._breakers.snapshot()
            assert not snap["open"], (
                f"breaker still open after settle: {snap}")
        except Exception as ex:  # noqa: BLE001 — soak collects all
            fails.append(("overload", trial, type(ex).__name__,
                          str(ex)[:200]))
    faults.reset()
    return fails


def soak_coeffs(n_trials: int, base: int, tol: float):
    """Cost-model closed-loop battery (parallel/coeffs.py,
    serve/replan.py; docs/COST_MODEL.md): seeded-miscalibration
    convergence. Per trial, the drift table is POISONED >=4x off — the
    shape class's cheapest-by-bytes strategy (the one the analytic
    byte model loves) claims coefficients far below reality while its
    TRUE cost is 4x the worst candidate — so the coefficient-ranked
    planner provably mispicks it on first contact. Replay traffic then
    flows a ReplanController wired to a live session: per round, the
    planner's current pick plus (round 1 only) a canary sweep of every
    candidate, each sample's execute_ms drawn from a deterministic
    per-strategy ground-truth model with seeded noise. The checks:

      * the poison takes (initial pick == the decoy),
      * a DRIFT rank flag fires and the controller re-calibrates,
        converging the pick to the TRUE winner within <=3 re-plan
        rounds (count-weighted blend: poisoned priors wash out),
      * ZERO wrong answers: a real query runs on the session every
        round — including the rounds where the coefficient epoch flips
        under it — and matches the numpy oracle,
      * ZERO oscillation: over a 3-round exploit-only tail the pick
        never leaves the winner and no further re-plan actions (the
        cooldown + dropped-window + reversal-dwell hysteresis),
      * the epoch bump is visible end-to-end (replan record old !=
        new epoch; the session's plan re-warm census counted it).
    """
    import json as _json
    import shutil
    import tempfile

    import numpy as np
    import jax
    from matrel_tpu import executor as executor_lib
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.obs import drift
    from matrel_tpu.parallel import coeffs as coeffs_lib, planner
    from matrel_tpu.serve import replan as replan_lib
    from matrel_tpu.session import MatrelSession

    mesh = mesh_lib.make_mesh()
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    backend = jax.default_backend()
    fails = []
    for trial in range(n_trials):
        seed = base + trial
        rng = np.random.default_rng(seed)
        tmp = tempfile.mkdtemp(prefix="matrel_soak_coeffs_")
        table = os.path.join(tmp, "drift.json")
        try:
            n = int(rng.choice([96, 112, 128]))
            cls = drift.shape_class((n, n, n))
            gf = 2.0 * n ** 3 / 1e9
            cands = [s for s in ("bmm_right", "bmm_left", "cpmm",
                                 "rmm", "summa", "xla")
                     if not (s == "summa" and gx != gy)]
            est = {s: max(float(planner.comm_cost(s, n, n, n, 1.0,
                                                  1.0, gx, gy)),
                          1024.0)
                   for s in cands}
            # ground truth: a well-separated ms ladder shuffled over
            # the candidates (gaps >= 45%, far above the 3% sample
            # noise, so the calibrated ranking can never flap on a
            # near-tie); the DECOY is the byte model's favourite (min
            # est bytes, deterministic name tiebreak) with its true
            # cost forced to 4x the worst other — the drift scenario
            # in its purest form
            ladder = [0.4, 0.6, 0.9, 1.35, 2.0, 3.0][:len(cands)]
            rng.shuffle(ladder)
            ms_tab = dict(zip(cands, ladder))
            decoy = min(cands, key=lambda s: (est[s], s))
            ms_tab[decoy] = 4.0 * max(ms_tab[s] for s in cands
                                      if s != decoy)

            def ms_true(s):
                return ms_tab[s]

            def write_table(poisoned: bool) -> None:
                # rows shaped exactly as drift.calibrate derives them
                # from live samples (both ratios from the SAME total
                # ms), so a re-calibration from truthful traffic
                # reproduces the truthful rows and the blend is a
                # fixed point
                entries = {}
                for s in cands:
                    ms = ms_tab[s]
                    r = {"strategy": s, "class": cls,
                         "backend": backend, "count": 10,
                         "ms_median": round(ms, 5),
                         "ms_per_gflop": round(ms / gf, 5),
                         "ms_per_est_mib": round(
                             ms / (est[s] / 2 ** 20), 5)}
                    if poisoned and s == decoy:
                        r["ms_per_gflop"] = 0.01
                        r["ms_per_est_mib"] = 0.0001
                    entries[f"{s}|{cls}|{backend}"] = r
                with open(table, "w") as f:
                    _json.dump({"schema": 1, "entries": entries}, f)
                coeffs_lib.reset_coefficient_cache()

            cfg = MatrelConfig(obs_level="off",
                               drift_table_path=table,
                               coeff_planner_enable=True,
                               coeff_min_samples=2)
            cfg_ctl = cfg.replace(coeff_replan_enable=True,
                                  coeff_replan_interval=10 ** 6,
                                  coeff_replan_cooldown=1)
            A = BlockMatrix.random((n, n), mesh=mesh, seed=seed)
            B = BlockMatrix.random((n, n), mesh=mesh, seed=seed + 1)
            oracle = (A.to_numpy().astype(np.float64)
                      @ B.to_numpy().astype(np.float64))

            def pick():
                plan = executor_lib.compile_expr(
                    A.expr().multiply(B.expr()), mesh, cfg)
                decs = executor_lib.plan_matmul_decisions(plan)
                return decs[0].get("strategy"), \
                    decs[0].get("cost", "analytic")

            # the MEASURED WINNER is the system's own choice under a
            # truth-calibrated table — the pick the loop must converge
            # back to once the poison washes out
            write_table(poisoned=False)
            winner, wcost = pick()
            if wcost != "measured" or winner == decoy:
                fails.append(("coeffs", seed, "BadTruthPick",
                              f"{winner}/{wcost}, decoy {decoy}"))
                continue
            write_table(poisoned=True)

            sess = MatrelSession(mesh=mesh, config=cfg)
            ctl = replan_lib.ReplanController(cfg_ctl, session=sess)

            def feed(s, k=6):
                for _ in range(k):
                    noise = float(rng.uniform(0.97, 1.03))
                    ctl.observe({
                        "kind": "query", "backend": backend,
                        "cache": "miss",
                        "execute_ms": max(ms_true(s) * noise, 1e-4),
                        "matmuls": [{"strategy": s,
                                     "dims": [n, n, n],
                                     "flops": 2.0 * n ** 3,
                                     "est_ici_bytes": est[s]}]})

            first, first_cost = pick()
            if first_cost != "measured" or first != decoy:
                fails.append(("coeffs", seed, "PoisonDidNotTake",
                              f"first pick {first}/{first_cost}, "
                              f"decoy {decoy}"))
                continue
            # prime the session's plan cache under the POISONED epoch:
            # this is the live plan the re-plan round must find, match
            # and re-warm (and the answer must already be right)
            out = sess.run(A.expr().multiply(B.expr()))
            np.testing.assert_allclose(
                out.to_numpy().astype(np.float64), oracle,
                rtol=tol, atol=tol)
            converged_at = None
            tail_replans = 0
            rounds = 6
            for rnd in range(1, rounds + 1):
                cur, _ = pick()
                feed(cur)
                if rnd == 1:
                    # canary sweep: one exploration burst, the
                    # heterogeneous-traffic stand-in that gives
                    # rank_flags its cross-strategy evidence
                    for s in cands:
                        if s != cur:
                            feed(s)
                before = ctl.replans
                ctl.check()
                if converged_at is not None:
                    tail_replans += ctl.replans - before
                # zero wrong answers, epoch flips and all: a REAL
                # query through the session every round
                out = sess.run(A.expr().multiply(B.expr()))
                np.testing.assert_allclose(
                    out.to_numpy().astype(np.float64), oracle,
                    rtol=tol, atol=tol)
                cur, _ = pick()
                if converged_at is None and cur == winner:
                    converged_at = ctl.replans
                elif converged_at is not None and cur != winner:
                    fails.append(("coeffs", seed, "Oscillation",
                                  f"pick left winner {winner} -> "
                                  f"{cur} round {rnd}"))
                    break
            ctl.drain()
            if converged_at is None:
                fails.append(("coeffs", seed, "NoConvergence",
                              f"decoy {decoy} winner {winner} "
                              f"pick {pick()[0]} "
                              f"replans {ctl.replans}"))
                continue
            if converged_at > 3:
                fails.append(("coeffs", seed, "SlowConvergence",
                              f"{converged_at} re-plan rounds"))
            if tail_replans:
                fails.append(("coeffs", seed, "ReplanChurn",
                              f"{tail_replans} re-plan(s) after "
                              f"convergence"))
            if not ctl.events:
                fails.append(("coeffs", seed, "NoReplanRecord", ""))
            else:
                ev = ctl.events[0]
                if ev["old_epoch"] == ev["epoch"]:
                    fails.append(("coeffs", seed, "EpochDidNotBump",
                                  str(ev)))
                if ev.get("replanned") is None \
                        or ev.get("matched", 0) < 1:
                    fails.append(("coeffs", seed, "WarmMissedPlan",
                                  str(ev)))
        except Exception as ex:  # noqa: BLE001 — soak collects everything
            fails.append(("coeffs", seed, type(ex).__name__,
                          str(ex)[:200]))
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        print(f"  coeffs {trial + 1}/{n_trials}, "
              f"{len(fails)} failures", flush=True)
    return fails


def soak_checkpoint(n_trials: int, base: int, tol: float):
    """Randomized checkpoint/restore: matrices with random specs, sparse
    tile stacks, loop state — restored values AND shardings must match;
    keep-k GC must hold."""
    import shutil
    import tempfile
    import numpy as np
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.core.sparse import BlockSparseMatrix
    from matrel_tpu.utils.checkpoint import CheckpointManager
    from jax.sharding import PartitionSpec as P

    mesh = mesh_lib.make_mesh()
    x, y = mesh.axis_names
    specs = [P(x, y), P((x, y), None), P(None, (x, y)), P(None, None)]
    fails = []
    for trial in range(base, base + n_trials):
        rng = np.random.default_rng(trial)
        d = tempfile.mkdtemp(prefix="matrel_soak_ckpt_")
        try:
            mgr = CheckpointManager(d, keep=2)
            n = int(rng.choice([8, 16, 24, 32]))
            mats = {}
            vals = {}
            for i in range(int(rng.integers(1, 4))):
                v = rng.standard_normal((n, n)).astype(np.float32)
                spec = specs[int(rng.integers(0, len(specs)))]
                mats[f"m{i}"] = BlockMatrix.from_numpy(v, mesh=mesh,
                                                       spec=spec)
                vals[f"m{i}"] = v
            sp_np = rng.standard_normal((n, n)).astype(np.float32)
            sp_np[rng.random((n, n)) < 0.6] = 0.0
            sp = BlockSparseMatrix.from_numpy(sp_np, block_size=8,
                                              mesh=mesh)
            state = {"iter": int(rng.integers(0, 100))}
            for step in range(int(rng.integers(1, 4))):
                mgr.save(step, matrices=mats, sparse={"s": sp},
                         state=state)
            got = mgr.restore(mesh)
            assert got is not None
            _, rmats, _, rstate = got
            assert rstate == state, (rstate, state)
            for name, v in vals.items():
                np.testing.assert_allclose(rmats[name].to_numpy(), v,
                                           rtol=tol, atol=tol)
                assert rmats[name].spec == mats[name].spec
            rsp = mgr.restore_sparse(mesh)["s"]
            np.testing.assert_allclose(rsp.to_numpy(), sp_np,
                                       rtol=tol, atol=tol)
            assert len(mgr._steps()) <= 2       # keep-k GC held
        except Exception as ex:  # noqa: BLE001
            fails.append(("ckpt", trial, type(ex).__name__,
                          str(ex)[:200]))
        finally:
            shutil.rmtree(d, ignore_errors=True)
    return fails


#: The restore half of soak_durable, run as a NEW PROCESS (the
#: kill-and-restore contract — an in-process "restore" would share
#: interpreter state with the session that saved). Args: state root,
#: matrix side, catalog names, integer-valued names, float tolerance.
_DURABLE_CHILD = '''\
import json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_f = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _f:
    os.environ["XLA_FLAGS"] = (
        _f + " --xla_force_host_platform_device_count=8").strip()
import numpy as np
root, n = sys.argv[1], int(sys.argv[2])
names = sys.argv[3].split(",")
int_names = set(filter(None, sys.argv[4].split(",")))
tol = float(sys.argv[5])
from matrel_tpu.config import MatrelConfig
from matrel_tpu.core import mesh as mesh_lib
from matrel_tpu.session import MatrelSession
entry = n * n * 4
cfg = MatrelConfig(obs_level="off", spill_enable=True,
                   result_cache_max_bytes=int(1.5 * entry),
                   result_cache_max_entries=16,
                   spill_host_max_bytes=2 * entry,
                   spill_disk_hits=0, state_dir=root)
sess = MatrelSession(mesh=mesh_lib.make_mesh(), config=cfg)
out = sess.restore()
assert out.get("restored"), out
wrong = int_mismatch = 0
for name in names:
    m = sess.catalog[name]
    got = np.asarray(sess.run(m.expr().t().multiply(m.expr())).data)
    oracle = np.load(os.path.join(root, "oracle_%s.npy" % name))
    if name in int_names and not np.array_equal(got, oracle):
        int_mismatch += 1
    elif not np.allclose(got, oracle, rtol=tol, atol=tol):
        wrong += 1
info = sess.result_cache_info().get("spill") or {}
print(json.dumps({"wrong": wrong, "int_mismatch": int_mismatch,
                  "thawed": info.get("thawed_restored", 0)}))
'''


def soak_durable(n_trials: int, base: int, tol: float):
    """Kill-and-restore battery (docs/DURABILITY.md): random named
    working sets LARGER than the HBM budget serve traffic through the
    spill tiers, the session snapshots (``save_state``) MID-TRAFFIC
    (queries keep flowing after the save), and a NEW PROCESS restores
    the snapshot and repeats the whole query mix — zero wrong
    answers, integer-valued working sets bit-exact (``array_equal``,
    the precision plane's int discipline), and at least one answer
    must come from a thawed snapshot entry (a battery that silently
    recomputed everything proves nothing)."""
    import json as json_lib
    import shutil
    import subprocess
    import tempfile
    import numpy as np
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.session import MatrelSession

    mesh = mesh_lib.make_mesh()
    fails = []
    for trial in range(base, base + n_trials):
        rng = np.random.default_rng(trial)
        root = tempfile.mkdtemp(prefix="matrel_soak_durable_")
        try:
            n = int(rng.choice([32, 48, 64]))
            m_count = int(rng.integers(3, 6))
            entry = n * n * 4
            cfg = MatrelConfig(
                obs_level="off", spill_enable=True,
                result_cache_max_bytes=int(1.5 * entry),
                result_cache_max_entries=16,
                spill_host_max_bytes=2 * entry,
                spill_disk_hits=0, state_dir=root)
            sess = MatrelSession(mesh=mesh, config=cfg)
            names, int_names = [], set()
            for i in range(m_count):
                name = f"d{i}"
                if rng.random() < 0.4:
                    v = rng.integers(-4, 5, (n, n)).astype(np.float32)
                    int_names.add(name)
                else:
                    v = rng.standard_normal((n, n)).astype(np.float32)
                sess.register(name,
                              BlockMatrix.from_numpy(v, mesh=mesh))
                names.append(name)

            def gram(s, name):
                mm = s.catalog[name]
                return s.run(mm.expr().t().multiply(mm.expr()))

            oracle = {nm: np.asarray(gram(sess, nm).data)
                      for nm in names}
            # mid-traffic snapshot: repeats flow before AND after
            for nm in names[: max(m_count // 2, 1)]:
                gram(sess, nm)
            sess.save_state()
            for nm in names[m_count // 2:]:
                gram(sess, nm)
            for nm in names:
                np.save(os.path.join(root, f"oracle_{nm}.npy"),
                        oracle[nm])
            child = os.path.join(root, "child.py")
            with open(child, "w") as f:
                f.write(_DURABLE_CHILD)
            env = dict(os.environ)
            env["PYTHONPATH"] = (
                REPO + os.pathsep + env.get("PYTHONPATH", ""))
            out = subprocess.run(
                [sys.executable, child, root, str(n),
                 ",".join(names), ",".join(sorted(int_names)),
                 str(tol)],
                capture_output=True, text=True, timeout=600, env=env)
            assert out.returncode == 0, out.stderr[-400:]
            rep = json_lib.loads(
                out.stdout.strip().splitlines()[-1])
            assert rep["wrong"] == 0, rep
            assert rep["int_mismatch"] == 0, rep
            assert rep["thawed"] > 0, (
                "restore served nothing from the snapshot", rep)
        except Exception as ex:  # noqa: BLE001
            fails.append(("durable", trial, type(ex).__name__,
                          str(ex)[:200]))
        finally:
            shutil.rmtree(root, ignore_errors=True)
    return fails


def main():
    p = argparse.ArgumentParser()
    p.add_argument("battery",
                   choices=["fuzz", "deep", "spmv", "sharded", "routed",
                            "ckpt", "serve", "precision", "chaos",
                            "sparse_kernels", "fusion", "overload",
                            "stream", "fleet", "cse", "race",
                            "coeffs", "durable", "all"])
    p.add_argument("--seeds", type=int, default=100)
    p.add_argument("--base", type=int, default=10_000)
    p.add_argument("--tpu", action="store_true",
                   help="run on the real chip (looser tolerance)")
    args = p.parse_args()
    _setup(args.tpu)
    t_start = __import__("time").time()
    tol = 5e-3 if args.tpu else 3e-3
    fails = []
    if args.battery in ("fuzz", "all"):
        fails += soak_fuzz(args.seeds, args.base, tol)
    if args.battery in ("deep", "all"):
        # deeper trees accumulate more bf16 matmul error; widen slightly
        fails += soak_deep(max(args.seeds // 4, 5), args.base, 2 * tol)
    if args.battery in ("spmv", "all"):
        fails += soak_spmv(args.seeds, args.base,
                           1e-3 if args.tpu else 2e-4)
    if args.battery in ("ckpt", "all"):
        fails += soak_checkpoint(max(args.seeds // 5, 5), args.base,
                                 1e-6)
    if args.battery in ("serve", "all"):
        fails += soak_serve(max(args.seeds // 2, 5), args.base, tol)
    if args.battery in ("cse", "all"):
        fails += soak_cse(max(args.seeds // 5, 4), args.base, tol)
    if args.battery in ("chaos", "all"):
        fails += soak_chaos(max(args.seeds // 4, 5), args.base, tol)
    if args.battery in ("overload", "all"):
        fails += soak_overload(max(args.seeds // 5, 5), args.base, tol)
    if args.battery in ("stream", "all"):
        fails += soak_stream(max(args.seeds // 5, 4), args.base, tol)
    if args.battery in ("fleet", "all"):
        fails += soak_fleet(max(args.seeds // 5, 4), args.base, tol)
    if args.battery in ("coeffs", "all"):
        fails += soak_coeffs(max(args.seeds // 10, 8), args.base, tol)
    if args.battery in ("durable", "all"):
        fails += soak_durable(max(args.seeds // 20, 3), args.base, tol)
    if args.battery in ("race", "all"):
        fails += soak_race(max(args.seeds // 10, 3), args.base, tol)
    if args.battery in ("precision", "all"):
        fails += soak_precision(max(args.seeds // 2, 5), args.base, tol)
    if args.battery in ("sharded", "all"):
        fails += soak_sharded(max(args.seeds // 2, 5), args.base, tol)
    if args.battery in ("sparse_kernels", "all"):
        fails += soak_sparse_kernels(max(args.seeds // 5, 4),
                                     args.base, tol)
    if args.battery in ("fusion", "all"):
        fails += soak_fusion(max(args.seeds // 4, 6), args.base, tol)
    if args.battery in ("routed", "all"):
        if args.tpu:
            # REAL-Mosaic routed battery: few trials, small shapes —
            # enough to prove the kernels lower and agree with scipy on
            # the chip (VERDICT r3 #7)
            fails += soak_routed(max(args.seeds // 4, 3), args.base,
                                 5e-4, interpret=False)
        else:
            fails += soak_routed(max(args.seeds // 2, 5), args.base,
                                 5e-4)
    print(f"SOAK COMPLETE: {len(fails)} failures")
    for f in fails[:20]:
        print(" ", f)
    _log_tally(args, len(fails), fails[:20], t_start)
    sys.exit(min(len(fails), 125))


def _log_tally(args, n_fails, fail_heads, t_start):
    """Append a machine-checkable tally line to SOAKLOG.jsonl — the
    committed evidence trail for soak runs (round-2 VERDICT: tallies
    lived only as prose in docs). Every run, CPU or TPU, logs here;
    soak_guard additionally logs its wrapper event to PROGRESS.jsonl."""
    import json
    import time
    try:
        import jax
        backend = jax.default_backend()
    except Exception:
        backend = os.environ.get("JAX_PLATFORMS", "(default)")
    rec = {"ts": round(time.time(), 1),
           "when": time.strftime("%Y-%m-%dT%H:%M:%S"),
           "event": "soak", "battery": args.battery,
           "seeds": args.seeds, "base": args.base,
           "tpu": bool(args.tpu),
           "backend": backend,
           "failures": n_fails,
           "fail_heads": [str(f) for f in fail_heads],
           "wall_s": round(time.time() - t_start, 1)}
    # $MATREL_SOAKLOG_PATH: the dry-batch fire-drill redirects the
    # tally (toy CPU drills must not write into the committed soak
    # evidence trail) — same contract as MATREL_PROGRESS_PATH
    path = os.environ.get("MATREL_SOAKLOG_PATH",
                          os.path.join(REPO, "SOAKLOG.jsonl"))
    try:
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError as e:
        print(f"# could not append {path}: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
