#!/bin/sh
# One-command TPU capture batch — run (or auto-triggered by a relay
# watch) the moment the axon relay is alive. Every step is wedge-safe
# (probe-first, hard timeouts), so a relay that dies mid-batch cannot
# hang this script. Results: stdout JSON lines per tool + structured
# entries in PROGRESS.jsonl (soak_guard, north_star_sweep).
set -u
cd "$(dirname "$0")/.."
log() { echo "$(date '+%H:%M:%S') $*"; }
log "TPU batch start"
log "--- bench.py (headline, BENCH row 1)"
python bench.py
log "--- soak_guard (on-chip oracle soak)"
python tools/soak_guard.py --seeds 8
log "--- bench.py --spgemm (S x S tile-intersection SpGEMM row, staged this round)"
python bench.py --spgemm
log "--- bench_all.py (all BASELINE rows)"
python bench_all.py
log "--- north_star_sweep (VERDICT #10 residual)"
python tools/north_star_sweep.py
log "--- gram_manual3 (symmetric-Gram microbench, BASELINE row 3 support)"
python tools/gram_manual3.py
log "--- gram_sym_full (10Mx1k linreg, symmetric 2-pass Gram, BASELINE row 3)"
python tools/gram_sym_full.py
log "--- autotune_capture (re-capture table under round-4 tie rules)"
python tools/autotune_capture.py
log "TPU batch done"
