#!/bin/sh
# One-command TPU capture batch — run (or auto-triggered by a relay
# watch) the moment the axon relay is alive. Every step is wedge-safe
# (probe-first, hard timeouts), so a relay that dies mid-batch cannot
# hang this script. Results: stdout JSON lines per tool + structured
# entries in PROGRESS.jsonl (soak_guard, north_star_sweep).
#
# --dry (or MATREL_BATCH_DRY=1): the fire-drill (VERDICT r5 Next #2).
# Runs the SAME step sequence end-to-end on the CPU backend at toy
# sizes, with every artifact redirected under MATREL_BATCH_DRY_DIR
# (default /tmp/matrel_batch_dry) so a drill can never pollute the
# real capture history (PROGRESS.jsonl, cpu_baseline.json,
# bench_last_good.json, the on-chip autotune table, the obs event
# log). `make tpu-batch-dry` runs it; tests/test_batch_dry.py asserts
# each step's parseable artifact — the first real relay window is
# spent measuring, not debugging the harness.
set -u
cd "$(dirname "$0")/.."
log() { echo "$(date '+%H:%M:%S') $*"; }

DRY=0
[ "${1:-}" = "--dry" ] && DRY=1
[ "${MATREL_BATCH_DRY:-0}" = "1" ] && DRY=1
SEEDS=8
AUTOTUNE_TABLE=autotune_v5e_1chip.json
if [ "$DRY" = 1 ]; then
    DRY_DIR="${MATREL_BATCH_DRY_DIR:-/tmp/matrel_batch_dry}"
    mkdir -p "$DRY_DIR"
    export JAX_PLATFORMS=cpu
    export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
    # artifact redirects — nothing a drill writes lands in the repo
    export MATREL_PROGRESS_PATH="$DRY_DIR/progress.jsonl"
    export MATREL_SOAKLOG_PATH="$DRY_DIR/soaklog.jsonl"
    export MATREL_OBS_EVENT_LOG="$DRY_DIR/events.jsonl"
    export MATREL_OBS_FLIGHT_RECORDER_PATH="$DRY_DIR/flight.json"
    export MATREL_DRIFT_TABLE_PATH="$DRY_DIR/drift.json"
    export MATREL_BENCH_CPU_CACHE="$DRY_DIR/cpu_baseline.json"
    export MATREL_BENCH_LAST_GOOD="$DRY_DIR/bench_last_good.json"
    AUTOTUNE_TABLE="$DRY_DIR/autotune_dry.json"
    # toy sizes: same code paths, CPU-feasible scales
    export MATREL_DRY=1
    export MATREL_BENCH_N=512 MATREL_BENCH_REPEATS=3
    export MATREL_BENCH_BACKOFFS="" MATREL_BENCH_DEADLINE=360
    export MATREL_SPGEMM_N=8192 MATREL_SPGEMM_CMP_N=4096
    export MATREL_SPK_N=1024 MATREL_SPK_BS=64 MATREL_SPK_REPEATS=3 \
           MATREL_SPK_AUTOTUNE_SIDE=1024 \
           MATREL_SPK_TABLE="$DRY_DIR/spk_autotune.json"
    export MATREL_FUSION_N=256 MATREL_FUSION_K=64 \
           MATREL_FUSION_REPEATS=5 MATREL_FUSION_INNER=4
    export MATREL_SERVE_N=256 MATREL_SERVE_K=64 \
           MATREL_SERVE_QUERIES=18 MATREL_SERVE_MEAS=3
    export MATREL_CSE_N=512 MATREL_CSE_COLS=128 \
           MATREL_CSE_VARIANTS=8 MATREL_CSE_MEAS=3
    export MATREL_FLEET_N=192 MATREL_FLEET_QUERIES=7 \
           MATREL_FLEET_REPLAYS=2
    export MATREL_TRAFFIC_SLICES=2
    export MATREL_STREAM_N=256 MATREL_STREAM_EDGES=8 \
           MATREL_STREAM_UPDATES=3 MATREL_STREAM_K=16
    export MATREL_TRAFFIC_SECONDS=5 MATREL_TRAFFIC_TAIL_SECONDS=2.5 \
           MATREL_TRAFFIC_CAL=300 MATREL_TRAFFIC_N=48
    export MATREL_PRECISION_N=256 MATREL_PRECISION_REPEATS=3
    export MATREL_COEFFS_N=128 MATREL_COEFFS_K=64 \
           MATREL_COEFFS_MEAS=3 MATREL_COEFFS_INNER=4
    export MATREL_RESHARD_N=256 MATREL_RESHARD_REPEATS=3
    export MATREL_SPILL_N=128 MATREL_SPILL_MATS=4 \
           MATREL_SPILL_REPEATS=2
    export MATREL_NS_N=2048
    export MATREL_GRAM3_K=64 MATREL_GRAM3_PANEL=4096 MATREL_GRAM3_NPANELS=2
    export MATREL_GRAMFULL_N=200000 MATREL_GRAMFULL_K=64 \
           MATREL_GRAMFULL_PANEL=25000
    export MATREL_AUTOTUNE_SIDES=256 MATREL_AUTOTUNE_DTYPES=float32
    export MATREL_AUTOTUNE_SPMV=2000,20000
    export MATREL_RACE_SEEDS=2 MATREL_RACE_QUERIES=6
    SEEDS=2
    log "TPU batch DRY fire-drill (CPU backend; artifacts in $DRY_DIR)"
fi

log "TPU batch start"
log "--- bench.py (headline, BENCH row 1)"
python bench.py
log "--- soak_guard (on-chip oracle soak)"
python tools/soak_guard.py --seeds $SEEDS
log "--- bench.py --spgemm (S x S tile-intersection SpGEMM row, staged this round)"
python bench.py --spgemm
log "--- bench.py --sparse-kernels (structure-specialized kernel sweep + autotune replay, staged this round)"
python bench.py --sparse-kernels
log "--- bench.py --fusion (fused-vs-staged region sweep, staged this round)"
python bench.py --fusion
log "--- bench.py --serve (repeated-traffic serving QPS row, staged this round)"
python bench.py --serve
log "--- bench.py --cse (shared-interior CSE batch + plan-template row, staged this round)"
python bench.py --cse
log "--- bench.py --fleet (multi-slice fleet scale-out QPS + kill drill, staged this round)"
python bench.py --fleet
log "--- bench.py --stream (streaming IVM delta-patch vs recompute row, staged this round)"
python bench.py --stream
log "--- bench.py --precision (bf16/int precision-tier sweep + error bounds, staged this round)"
python bench.py --precision
log "--- bench.py --reshard (staged-vs-naive reshard sweep, staged this round)"
python bench.py --reshard
log "--- bench.py --coeffs (calibrated-vs-analytic planner row, staged this round)"
python bench.py --coeffs
log "--- bench.py --spill (spill-tier sweep + cold-vs-thawed restart row, staged this round)"
python bench.py --spill
log "--- bench_all.py (all BASELINE rows)"
python bench_all.py
log "--- topology_flip (ICI/DCN-weighted planner flip proof, staged this round)"
python tools/topology_flip.py
log "--- flight_drill (obs tier 2: flight recorder + chrome trace + drift smoke, staged this round)"
python tools/flight_drill.py
log "--- chaos_drill (resilience: seeded fault schedule over a mixed serve stream, staged this round)"
python tools/chaos_drill.py
log "--- provenance_drill (obs tier 4: answer lineage on every serve path + full audit replay, staged this round)"
python tools/provenance_drill.py
log "--- traffic (open-loop overload harness: weighted tenants, brownout, typed shed, staged this round)"
python tools/traffic.py
log "--- traffic --slo (SLO burn-rate alert fire/clear proof + live metrics endpoint, staged this round)"
python tools/traffic.py --slo
log "--- traffic --slices (open-loop fleet drill: placement spread, directory hits, mid-stream slice kill, staged this round)"
python tools/traffic.py --slices
log "--- race_drill (concurrency sanitizer: seeded serve/fleet interleavings under runtime lockdep, staged this round)"
python tools/race_drill.py
log "--- north_star_sweep (VERDICT #10 residual)"
python tools/north_star_sweep.py
log "--- gram_manual3 (symmetric-Gram microbench, BASELINE row 3 support)"
python tools/gram_manual3.py
log "--- gram_sym_full (10Mx1k linreg, symmetric 2-pass Gram, BASELINE row 3)"
python tools/gram_sym_full.py
log "--- autotune_capture (re-capture table under round-4 tie rules)"
python tools/autotune_capture.py "$AUTOTUNE_TABLE"
log "TPU batch done"
