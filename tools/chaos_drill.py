"""Chaos drill: a mixed serve stream under a seeded fault schedule.

The resilience layer's acceptance harness (docs/RESILIENCE.md; the
tpu_batch.sh fire-drill discipline): drive >= 50 queries — direct
``run``, micro-batched ``run_many``, async ``submit`` — through a
session whose EVERY instrumented choke point (compile, lower,
strategy, execute, rc_probe, serve_admit, checkpoint) injects
transient faults on a deterministic seeded schedule, plus deliberate
poison queries and an impossible deadline, and assert
converge-to-correct-or-typed-failure:

  - every healthy query's result matches its numpy oracle
    (0 wrong answers — retries + the degradation ladder absorb every
    transient);
  - ONLY the deterministic-fault queries fail, each with a TYPED
    error (the mixed-mesh poisons raise ValueError and fail exactly
    their own futures — batch-bisection isolation; the impossible
    deadline raises DeadlineExceeded);
  - zero hangs: the whole stream drains under an explicit timeout
    (``serve_drain(timeout=...)`` — a wedge raises the typed
    DrainTimeout instead of wedging this script);
  - every instrumented site actually CHECKED and actually FIRED under
    the schedule (the injector's own stats — a silently-unwired site
    would pass vacuously);
  - a checkpoint save/restore cycle survives its injected IO faults
    and round-trips the catalog exactly.

Emits one parseable JSON line (tools/tpu_batch.sh step; asserted by
tests/test_batch_dry.py). CPU-only by construction — this drills the
recovery plumbing, not the chip, so it forces the CPU backend even
inside a TPU batch (wedge-safe: never touches the relay).
MATREL_CHAOS_SEED varies the schedule; any fixed seed is bit-for-bit
reproducible.
"""

import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

#: Transient faults at EVERY instrumented site: one guaranteed nth-call
#: fire per site (coverage cannot depend on luck) plus capped random
#: fires (max= bounds total fires, so the stream provably converges —
#: retries outnumber the worst-case fire budget).
FAULT_SPEC = (
    "compile:transient:n=3;compile:transient:p=0.05:max=2;"
    "lower:transient:n=40;lower:transient:p=0.002:max=2;"
    "strategy:transient:n=5;strategy:transient:p=0.02:max=2;"
    "execute:transient:n=4;execute:transient:p=0.05:max=2;"
    "rc_probe:transient:n=6;rc_probe:transient:p=0.03:max=2;"
    "serve_admit:transient:n=2;serve_admit:transient:p=0.1:max=2;"
    "checkpoint:transient:n=1"
)


def main() -> int:
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.obs.events import read_events, resolve_path
    from matrel_tpu.obs.history import summarize
    from matrel_tpu.resilience import errors as rerrors, faults
    from matrel_tpu.session import MatrelSession
    from matrel_tpu.utils.checkpoint import CheckpointManager

    seed = int(os.environ.get("MATREL_CHAOS_SEED", "0"))
    faults.reset()
    # env (MATREL_*) overrides flow over the drill's base config, so
    # the dry batch's redirects land every artifact outside the repo
    cfg = MatrelConfig.from_env(MatrelConfig(
        fault_inject=FAULT_SPEC,
        fault_inject_seed=seed,
        retry_max_attempts=6,
        retry_backoff_ms=1.0,
        retry_jitter=0.5,
        obs_level="on",
        result_cache_max_bytes=1 << 26,
        serve_max_batch=5,
    ))
    mesh = mesh_lib.make_mesh((2, 4))
    sess = MatrelSession(mesh=mesh, config=cfg)
    rng = np.random.default_rng(seed)
    an, bn = (rng.standard_normal((48, 64)).astype(np.float32),
              rng.standard_normal((64, 24)).astype(np.float32))
    A, B = sess.from_numpy(an), sess.from_numpy(bn)
    other = mesh_lib.make_mesh((1, 1), devices=jax.devices()[:1])
    M_other = BlockMatrix.from_numpy(bn, mesh=other)

    wrong = 0
    typed_failures = []
    untyped_failures = []
    n_queries = 0

    def check(got, want, tag):
        nonlocal wrong
        if not np.allclose(got, want, rtol=3e-4, atol=3e-4):
            wrong += 1
            print(f"# WRONG ANSWER: {tag}", file=sys.stderr)

    def expr_oracle(i):
        s = float(i % 7 + 1)
        if i % 3 == 0:
            return (A.expr().t().multiply(A.expr())
                    .multiply_scalar(s), (an.T @ an) * s)
        if i % 3 == 1:
            return (A.expr().multiply(B.expr())
                    .multiply_scalar(s), (an @ bn) * s)
        return (A.expr().multiply(B.expr()).add(
            A.expr().multiply(B.expr())), 2 * (an @ bn))

    # -- 1. direct session.run stream (20 queries) ------------------------
    for i in range(20):
        e, want = expr_oracle(i)
        n_queries += 1
        try:
            check(sess.run(e).to_numpy(), want, f"run[{i}]")
        except Exception as ex:  # noqa: BLE001 — tallied below
            (typed_failures if isinstance(ex, rerrors.ResilienceError)
             else untyped_failures).append(
                 (f"run[{i}]", type(ex).__name__))

    # -- 2. micro-batched run_many (4 batches x 4 = 16 queries) -----------
    for b in range(4):
        batch, wants = zip(*(expr_oracle(b * 4 + j) for j in range(4)))
        n_queries += len(batch)
        try:
            outs = sess.run_many(list(batch))
            for j, (o, w) in enumerate(zip(outs, wants)):
                check(o.to_numpy(), w, f"run_many[{b}][{j}]")
        except Exception as ex:  # noqa: BLE001 — tallied below
            (typed_failures if isinstance(ex, rerrors.ResilienceError)
             else untyped_failures).append(
                 (f"run_many[{b}]", type(ex).__name__))

    # -- 3. async submit stream incl. ONE poison in a 5-query batch -------
    # (batch bisection: exactly the poison's future may fail, typed)
    futs, wants = [], []
    for i in range(4):
        e, want = expr_oracle(10 + i)
        futs.append(sess.submit(e))
        wants.append(want)
    poison_fut = sess.submit(A.expr().multiply(M_other.expr()))
    n_queries += 5
    for i in range(9):          # a second wave keeps the worker busy
        e, want = expr_oracle(20 + i)
        futs.append(sess.submit(e))
        wants.append(want)
        n_queries += 1
    try:
        sess.serve_drain(timeout=300.0)
    except rerrors.DrainTimeout as ex:
        print(f"# DRAIN TIMEOUT: {ex}", file=sys.stderr)
        untyped_failures.append(("serve_drain", "DrainTimeout"))
    sibling_failures = 0
    for i, (f, w) in enumerate(zip(futs, wants)):
        ex = f.exception(timeout=60)
        if ex is not None:
            sibling_failures += 1
            untyped_failures.append((f"submit[{i}]",
                                     type(ex).__name__))
        else:
            check(f.result().to_numpy(), w, f"submit[{i}]")
    poison_ex = poison_fut.exception(timeout=60)
    poison_isolated = (isinstance(poison_ex, ValueError)
                      and sibling_failures == 0)
    if poison_ex is not None:
        typed_failures.append(("poison", type(poison_ex).__name__))

    # -- 4. an impossible deadline fails TYPED ----------------------------
    n_queries += 1
    deadline_typed = False
    try:
        sess.run(expr_oracle(0)[0], deadline_ms=1e-6)
    except rerrors.DeadlineExceeded:
        deadline_typed = True
        typed_failures.append(("deadline", "DeadlineExceeded"))
    except Exception as ex:  # noqa: BLE001 — wrong type = drill failure
        untyped_failures.append(("deadline", type(ex).__name__))

    # -- 5. checkpoint round-trip under injected IO faults ----------------
    ckpt_ok = False
    d = tempfile.mkdtemp(prefix="matrel_chaos_ckpt_")
    try:
        sess.register("A", A)
        mgr = CheckpointManager(d, config=cfg)
        for attempt in range(6):
            try:
                mgr.save(attempt, matrices={"A": A})
                got = mgr.restore(mesh)
                ckpt_ok = (got is not None and np.allclose(
                    got[1]["A"].to_numpy(), an, rtol=1e-6, atol=1e-6))
                break
            except rerrors.InjectedFault:
                continue        # the drill's own driver-level retry
    finally:
        shutil.rmtree(d, ignore_errors=True)

    # -- verdict ----------------------------------------------------------
    stats = faults.injector_for(cfg).stats()
    sites_checked = sorted(s for s, v in stats.items()
                           if v["calls"] > 0)
    sites_fired = sorted(s for s, v in stats.items() if v["fires"] > 0)
    log_path = resolve_path(cfg.obs_event_log
                            or os.environ.get("MATREL_OBS_EVENT_LOG"))
    rollup = summarize(read_events(log_path)).get("resilience", {})
    record = {
        "metric": "chaos_drill",
        "seed": seed,
        "queries": n_queries,
        "wrong_answers": wrong,
        "typed_failures": len(typed_failures),
        "untyped_failures": len(untyped_failures),
        "failure_heads": (typed_failures + untyped_failures)[:8],
        "poison_isolated": poison_isolated,
        "deadline_typed": deadline_typed,
        "checkpoint_ok": ckpt_ok,
        "sites_checked": sites_checked,
        "sites_fired": sites_fired,
        "fault_stats": stats,
        "retries": rollup.get("retries", 0),
        "degrades": rollup.get("degrades", 0),
        "log": log_path,
    }
    record["ok"] = bool(
        n_queries >= 50
        and wrong == 0
        and not untyped_failures
        and poison_isolated
        and deadline_typed
        and ckpt_ok
        and set(sites_checked) == set(faults.SITES)
        and set(sites_fired) == set(faults.SITES)
        and record["retries"] > 0)
    print(json.dumps(record))
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
