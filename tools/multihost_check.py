"""Multi-HOST validation: the framework's collectives over a real
process boundary.

The CPU-mesh tests and ``dryrun_multichip`` exercise multi-device
sharding inside ONE process. This tool goes one step further and runs
the same code over MULTIPLE PROCESSES — jax.distributed + a Gloo/TCP
coordinator, each process owning 4 virtual CPU devices — which is the
same control/data plane shape as hosts in a TPU pod connected over DCN
(SURVEY.md §5 "Distributed comm backend"). It validates:

  1. mesh bring-up across processes (`core.mesh.init_distributed` — the
     executor-registration analogue),
  2. a CPMM (reduce-scatter) matmul whose collective crosses the
     process boundary,
  3. an RMM (all-gather) matmul likewise,
  4. global-array construction from per-host numpy + result agreement
     on every process via process_allgather,
  5. the sharded one-hot SpMV (plan tables row-decomposed over the
     global mesh),
  6. the sharded COMPACT-table SpMV (the TPU-default executor path,
     pallas interpret per device),
  7. the sharded tile-stack SpMM (BlockSparseMatrix.shard()),
  8. the streaming value-join aggregate with its query side sharded
     across processes (round-3: both the sorted and the callable
     chunked path),
  9. the symmetric 2-pass Gram lowering (round-3) through the full
     executor under precision="high",
 10. the v3 "align" join scheme (round-4): both operands re-laid 1D
     along the join axis on the global mesh, shard-local pairwise merge.

Run:  python tools/multihost_check.py [--nproc 2]
Exit code 0 on success; worker logs live in a fresh temp dir (path
printed on failure). The coordinator port is ephemeral by default.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os, sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, %(repo)r)
from matrel_tpu.core import mesh as mesh_lib
mesh_lib.init_distributed(f"127.0.0.1:{port}", nproc, pid)

import numpy as np
import jax.numpy as jnp
from jax.experimental import multihost_utils
from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.parallel import strategies

n_dev = len(jax.devices())
assert n_dev == 4 * nproc, (n_dev, nproc)
mesh = mesh_lib.make_mesh()
print(f"[p{pid}] mesh {dict(mesh.shape)} over {n_dev} devices "
      f"({len(jax.local_devices())} local)", flush=True)

rng = np.random.default_rng(0)          # same data on every process
a = rng.standard_normal((32, 32)).astype(np.float32)
b = rng.standard_normal((32, 32)).astype(np.float32)
A = BlockMatrix.from_numpy(a, mesh=mesh)
B = BlockMatrix.from_numpy(b, mesh=mesh)
cfg = MatrelConfig()
oracle = a @ b

for strat in ("cpmm", "rmm", "xla"):
    f = jax.jit(lambda x, y, s=strat: strategies.run_matmul(
        s, x, y, mesh, cfg))
    out = f(A.data, B.data)
    # every process receives the full value; collectives crossed the
    # process boundary to produce it
    full = np.asarray(multihost_utils.process_allgather(
        out, tiled=True))[:32, :32]
    np.testing.assert_allclose(full, oracle, rtol=1e-3, atol=1e-3)
    print(f"[p{pid}] {strat} matches oracle", flush=True)

# sharded one-hot SpMV: plan tables row-decomposed over the global mesh
from matrel_tpu.ops import spmv as spmv_lib
n_r, n_c, m = 4096, 2048, 40_000
rows = rng.integers(0, n_r, m); cols = rng.integers(0, n_c, m)
vals = rng.standard_normal(m).astype(np.float32)
plan = spmv_lib.build_spmv_plan(rows, cols, vals, n_rows=n_r, n_cols=n_c)
plan_s = spmv_lib.shard_plan(plan, mesh)
x = rng.standard_normal(n_c).astype(np.float32)
y = spmv_lib.spmv_sharded(plan_s, jnp.asarray(x), mesh)
got = np.asarray(multihost_utils.process_allgather(
    y, tiled=True)).reshape(-1)[:n_r]
want = np.zeros(n_r); np.add.at(want, rows, vals * x[cols])
np.testing.assert_allclose(got, want, rtol=1e-4,
                           atol=1e-4 * max(abs(want).max(), 1.0))
print(f"[p{pid}] sharded one-hot SpMV matches oracle", flush=True)

# sharded COMPACT-table SpMV (the TPU-default executor path): tables
# row-decomposed over the GLOBAL mesh, pallas interpret per device,
# tiled all_gather crossing the process boundary
from matrel_tpu.ops import pallas_spmv as pc
y_c = pc.spmv_compact_sharded(plan, x, mesh, interpret=True)
got_c = np.asarray(multihost_utils.process_allgather(
    y_c, tiled=True)).reshape(-1)[:n_r]
np.testing.assert_allclose(got_c, want, rtol=1e-4,
                           atol=1e-4 * max(abs(want).max(), 1.0))
print(f"[p{pid}] sharded compact-table SpMV matches oracle", flush=True)

# sharded tile-stack SpMM
from matrel_tpu.core.sparse import BlockSparseMatrix
sp = np.zeros((64, 64), np.float32)
sp[(rng.random((64, 64)) < 0.5)] = 1.5
d = rng.standard_normal((64, 8)).astype(np.float32)
S = BlockSparseMatrix.from_numpy(sp, block_size=8, mesh=mesh)
prod = S.shard().multiply(BlockMatrix.from_numpy(d, mesh=mesh))
full = np.asarray(multihost_utils.process_allgather(
    prod.data, tiled=True))[:64, :8]
np.testing.assert_allclose(full, sp @ d, rtol=1e-3, atol=1e-3)
print(f"[p{pid}] sharded tile-stack SpMM matches oracle", flush=True)

# streaming value-join aggregate, query side sharded across processes
from matrel_tpu.executor import execute as mat_execute
from matrel_tpu.relational import ops as R
vj_a = rng.standard_normal((40, 32)).astype(np.float32)
vj_b = rng.standard_normal((8, 8)).astype(np.float32)
va_o = vj_a.T.reshape(-1); vb_o = vj_b.T.reshape(-1)
# sorted (structured) path
n_pairs_a = vj_a.size
jv = R.join_on_values(BlockMatrix.from_numpy(vj_a, mesh=mesh),
                      BlockMatrix.from_numpy(vj_b, mesh=mesh),
                      merge="mul", predicate="lt")
got_vj = np.asarray(multihost_utils.process_allgather(
    mat_execute(R.aggregate(jv, "sum", "row"), mesh, cfg).data,
    tiled=True))[:n_pairs_a, 0]
want_p = np.where(va_o[:, None] < vb_o[None, :],
                  va_o[:, None] * vb_o[None, :], 0.0)
np.testing.assert_allclose(got_vj, want_p.sum(1), rtol=1e-4, atol=1e-4)
# chunked (callable) path
jc = R.join_on_values(BlockMatrix.from_numpy(vj_a, mesh=mesh),
                      BlockMatrix.from_numpy(vj_b, mesh=mesh),
                      merge=lambda x, y: x * y + x,
                      predicate=lambda x, y: x < y)
got_jc = np.asarray(multihost_utils.process_allgather(
    mat_execute(R.aggregate(jc, "sum", "row"), mesh, cfg).data,
    tiled=True))[:n_pairs_a, 0]
want_c = np.where(va_o[:, None] < vb_o[None, :],
                  va_o[:, None] * vb_o[None, :] + va_o[:, None], 0.0)
np.testing.assert_allclose(got_jc, want_c.sum(1), rtol=1e-4, atol=1e-4)
print(f"[p{pid}] streaming value-joins (sorted + chunked) match oracle",
      flush=True)

# symmetric 2-pass Gram through the executor across processes
gx = rng.standard_normal((48, 24)).astype(np.float32)
GX = BlockMatrix.from_numpy(gx, mesh=mesh)
got_g = np.asarray(multihost_utils.process_allgather(
    mat_execute(GX.expr().t().multiply(GX.expr()), mesh,
                MatrelConfig(matmul_precision="high")).data,
    tiled=True))[:24, :24]
np.testing.assert_allclose(got_g, gx.T @ gx, rtol=5e-3, atol=5e-3)
print(f"[p{pid}] symmetric gram matches oracle", flush=True)

# round-4: the v3 "align" join scheme across process boundaries — both
# operands re-laid 1D along the join axis on the GLOBAL mesh, the
# pairwise merge computes shard-locally on every process
from matrel_tpu.parallel import planner as pl_mod
j_a = rng.standard_normal((8 * nproc, 10)).astype(np.float32)
j_b = rng.standard_normal((8 * nproc, 6)).astype(np.float32)
je = R.join_on_rows(BlockMatrix.from_numpy(j_a, mesh=mesh),
                    BlockMatrix.from_numpy(j_b, mesh=mesh), "mul")
je_ann = pl_mod.annotate_strategies(je, mesh, cfg)
assert je_ann.attrs["replicate"] == "align", je_ann.attrs
got_j = np.asarray(multihost_utils.process_allgather(
    mat_execute(je_ann, mesh, cfg).data, tiled=True))[:8 * nproc, :60]
want_j = (j_a[:, :, None] * j_b[:, None, :]).reshape(8 * nproc, 60)
np.testing.assert_allclose(got_j, want_j, rtol=1e-4, atol=1e-4)
print(f"[p{pid}] align row-join matches oracle", flush=True)

multihost_utils.sync_global_devices("matrel-mh-done")
print(f"[p{pid}] DONE", flush=True)
"""


def _free_port() -> str:
    """Ask the kernel for an ephemeral port (fixed ports collide with
    concurrent runs or orphans from earlier failures)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return str(s.getsockname()[1])


def main() -> int:
    import tempfile
    import time

    ap = argparse.ArgumentParser()
    ap.add_argument("--nproc", type=int, default=2)
    ap.add_argument("--port", default=None,
                    help="coordinator port (default: ephemeral)")
    ap.add_argument("--timeout", type=float, default=240.0)
    args = ap.parse_args()
    port = args.port or _free_port()

    tmpdir = tempfile.mkdtemp(prefix="matrel_mh_")
    worker_path = os.path.join(tmpdir, "worker.py")
    with open(worker_path, "w") as f:
        f.write(_WORKER % {"repo": REPO})

    procs, logs = [], []
    log_paths = []
    env = dict(os.environ)
    rcs = [None] * args.nproc
    try:
        for pid in range(args.nproc):
            lp = os.path.join(tmpdir, f"p{pid}.log")
            log_paths.append(lp)
            log = open(lp, "w")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, worker_path, str(pid), str(args.nproc),
                 port],
                stdout=log, stderr=subprocess.STDOUT, env=env,
                start_new_session=True))
        deadline = time.monotonic() + args.timeout
        for i, p in enumerate(procs):
            rcs[i] = p.wait(timeout=max(1.0, deadline - time.monotonic()))
    except subprocess.TimeoutExpired:
        pass
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for log in logs:
            log.close()
    ok = all(rc == 0 for rc in rcs)
    for pid, lp in enumerate(log_paths):
        with open(lp) as f:
            for ln in f.read().splitlines():
                if ln.startswith(f"[p{pid}]"):
                    print(ln)
    print("MULTIHOST CHECK:", "OK" if ok else f"FAILED (rcs={rcs})",
          f"(logs under {tmpdir})" if not ok else "")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
