#!/bin/sh
# Poll the axon relay; on recovery run the staged on-chip capture batch.
#
# Round-4 version of the round-3 /tmp watcher (VERDICT r3 "what's weak" #4:
# the staged experiment scripts must live in the repo, not /tmp, so the
# driver or a fresh session can re-run every BASELINE.md number from a
# clean checkout). Detach with:
#   nohup sh tools/relay_watch.sh >/dev/null 2>&1 &
# State files (repo root):
#   relay_watch_r4.log          — timestamped probe + experiment output
#   .relay_experiments_done_r4  — touched once the batch completes
set -u
cd "$(dirname "$0")/.."
# tools/ scripts import matrel_tpu; keep the axon site dir too.
PYTHONPATH="$(pwd):${PYTHONPATH:-}"
export PYTHONPATH
LOG=relay_watch_r4.log
log() { echo "$(date '+%H:%M:%S') $*" >> "$LOG"; }
log "watch start (round 4)"
while true; do
  timeout 120 python bench.py --_probe > /tmp/probe_out_r4 2>&1
  rc=$?
  if [ "$rc" = "0" ] && grep -q '"probe": "ok"' /tmp/probe_out_r4; then
    log "relay ALIVE - running staged experiments"
    log "--- gram_manual3 (hi/lo 3-pass vs XLA HIGH microbench)"
    timeout 600 python tools/gram_manual3.py >> "$LOG" 2>&1
    log "--- gram_sym_full (10Mx1k fit_streaming, symmetric 2-pass Gram)"
    timeout 600 python tools/gram_sym_full.py >> "$LOG" 2>&1
    log "--- pagerank 10x row"
    timeout 900 python -c "
import bench_all, json
from matrel_tpu.config import MatrelConfig, set_default_config
from matrel_tpu.core import mesh as mesh_lib
cfg = MatrelConfig(); set_default_config(cfg)
mesh = mesh_lib.make_mesh()
print(json.dumps(bench_all.bench_pagerank_10x(mesh, cfg)))
" >> "$LOG" 2>&1
    log "--- pagerank gather/scatter overlap experiment (VERDICT r3 #6)"
    timeout 900 python tools/pagerank_overlap.py >> "$LOG" 2>&1
    log "--- full tpu batch (bench, soak, bench_all, north-star sweep)"
    timeout 3600 sh tools/tpu_batch.sh >> "$LOG" 2>&1
    log "experiments DONE"
    touch .relay_experiments_done_r4
    exit 0
  fi
  log "relay down (rc=$rc); sleeping 600"
  sleep 600
done
