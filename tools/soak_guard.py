"""Relay-wedge-safe on-chip soak runner — `make soak-tpu`.

The real-chip soak (tools/soak.py --tpu) has caught Mosaic bugs that
interpret-mode CI structurally cannot (bf16 rounding is elided in
interpret mode — docs/INTERNALS.md), but the axon relay can wedge and
hang any TPU process for 30+ minutes. This wrapper makes the soak safe
to run on a cadence:

1. probe the backend first (tiny matmul in a subprocess under a hard
   timeout — bench.py --_probe),
2. run the soak batteries in their own session/process group under a
   hard timeout (killpg on expiry, so a hung relay helper can't orphan),
3. append a structured result line to PROGRESS.jsonl either way.

Exit codes: 0 = clean soak; 2 = backend unavailable (probe failed —
not a code failure); 3 = soak timed out; 4 = soak failed (failure
count / signal details are in the PROGRESS.jsonl line).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# $MATREL_PROGRESS_PATH redirects the append target — the dry batch
# fire-drill (tools/tpu_batch.sh --dry) must not write toy-scale CPU
# records into the repo's real capture history
PROGRESS = os.environ.get("MATREL_PROGRESS_PATH",
                          os.path.join(REPO, "PROGRESS.jsonl"))


def _log(event: dict) -> None:
    event = {"ts": time.time(), "event": "soak_tpu", **event}
    try:
        with open(PROGRESS, "a") as f:
            f.write(json.dumps(event) + "\n")
    except OSError as e:
        print(f"# could not append to PROGRESS.jsonl: {e}",
              file=sys.stderr)
    # mirror into the obs/ event log ("soak" kind) so `python -m
    # matrel_tpu history --summary` sees soak outcomes next to query
    # and bench records. obs/events.py loaded by FILE PATH: importing
    # the matrel_tpu package would pull jax into this watchdog, which
    # must stay backend-free (relay-wedge safety). Never fails the soak.
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "_matrel_obs_events",
            os.path.join(REPO, "matrel_tpu", "obs", "events.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.emit_tool_event("soak",
                            {k: v for k, v in event.items() if k != "ts"},
                            anchor_dir=REPO)
    except Exception as e:
        print(f"# soak event not logged: {e}", file=sys.stderr)
    print(json.dumps(event))


def _run_pg(cmd, timeout_s: int):
    """Run cmd in its own session; killpg on timeout. Returns
    (rc, tail) with rc None on timeout."""
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            cwd=REPO, start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
        return proc.returncode, "\n".join(out.strip().splitlines()[-8:])
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        out, _ = proc.communicate()
        return None, "\n".join((out or "").strip().splitlines()[-8:])


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--seeds", type=int, default=10,
                   help="seeds per battery (keep small: deep compiles "
                        "take minutes each through the relay)")
    p.add_argument("--battery", default="all")
    p.add_argument("--probe-timeout", type=int, default=180)
    p.add_argument("--soak-timeout", type=int, default=3600)
    args = p.parse_args()

    rc, tail = _run_pg([sys.executable,
                        os.path.join(REPO, "bench.py"), "--_probe"],
                       args.probe_timeout)
    if rc != 0:
        _log({"ok": False, "stage": "probe",
              "detail": "backend probe "
              + ("timed out (relay wedge?)" if rc is None
                 else f"failed rc={rc}"),
              "tail": tail[-300:]})
        return 2

    t0 = time.time()
    # the dry fire-drill (tools/tpu_batch.sh --dry) soaks the CPU
    # backend, where --tpu's non-interpret Pallas batteries cannot run
    # ("Only interpret mode is supported on CPU backend") — drop the
    # flag there; the harness (probe, process groups, logging) is what
    # the drill proves
    soak_cmd = [sys.executable, os.path.join(REPO, "tools", "soak.py"),
                args.battery, "--seeds", str(args.seeds)]
    if not os.environ.get("MATREL_DRY"):
        soak_cmd.append("--tpu")
    rc, tail = _run_pg(soak_cmd, args.soak_timeout)
    ok = rc == 0
    _log({"ok": ok, "stage": "soak", "battery": args.battery,
          "seeds": args.seeds, "rc": rc,
          "wall_s": round(time.time() - t0, 1),
          "tail": tail[-500:]})
    if ok:
        return 0
    return 3 if rc is None else 4


if __name__ == "__main__":
    sys.exit(main())
