"""Verifier self-check over the plan-snapshot corpus — `make lint`'s
second half.

tools/plan_snapshot.py pins WHAT the planner chooses for a fixed
representative corpus; this tool pins that every one of those choices
is INTERNALLY CONSISTENT: replans the same corpus on the standard
(2, 4) test grid and runs the full static verifier
(matrel_tpu/analysis/) over each annotated plan, requiring zero
diagnostics. A planner change that starts stamping inadmissible
strategies, claiming unpinned layouts, or breaking the SpGEMM stamp
contract fails `make lint` even if no behavioural test happens to cover
the shape — the same corpus-scale discipline, applied to invariants
instead of plan shapes.

Exit codes: 0 = every corpus plan verifies clean; 1 = diagnostics
fired (each printed); 2 = the corpus itself failed to plan.
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import plan_snapshot  # noqa: E402 (needs REPO on sys.path)


def main() -> int:
    plan_snapshot._setup()
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.ir import rules
    from matrel_tpu.parallel import planner
    from matrel_tpu import analysis

    mesh = mesh_lib.make_mesh((2, 4))
    grid = mesh_lib.mesh_grid_shape(mesh)
    total = 0
    try:
        corpus = plan_snapshot.corpus(mesh)
    except Exception as ex:
        print(f"corpus construction failed: {ex!r}")
        return 2
    for name, e in corpus:
        try:
            opt = planner.annotate_strategies(
                rules.optimize(e, grid=grid, mesh=mesh), mesh)
        except Exception as ex:
            print(f"PLAN FAILED: {name}: {ex!r}")
            return 2
        diags = analysis.verify_plan(opt, mesh)
        for d in diags:
            print(f"DIAGNOSTIC: {name}: {d.render()}")
        total += len(diags)
    n = len(corpus)
    print(f"verified {n} corpus plans: {total} diagnostic(s)")
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
