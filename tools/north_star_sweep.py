"""Bounded north-star residual sweep (round-1 VERDICT #10) — one
command on a live chip: `python tools/north_star_sweep.py`.

Round-1 context (BASELINE.md row 6, docs/INTERNALS.md): the slab
schedule reaches 178.8 TFLOPS of a measured ~189 pure-matmul ceiling;
tile/panel sweeps all tied at ~6.34 s, locating the residual in
generator cost + slab glue. This sweep re-times the baseline plus the
most promising remaining variants, marginal-time methodology, and
appends the outcome to PROGRESS.jsonl. Per the VERDICT's stop rule: if
the top two schedules tie (<1% apart), the written negative result
stands and the sweep should not be re-run.

Wedge-safe: probes the backend first via bench.py's harness.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _append_progress(event: dict) -> None:
    # $MATREL_PROGRESS_PATH: see tools/soak_guard.py (dry-batch redirect)
    path = os.environ.get("MATREL_PROGRESS_PATH",
                          os.path.join(REPO, "PROGRESS.jsonl"))
    try:
        with open(path, "a") as f:
            f.write(json.dumps({"ts": time.time(),
                                "event": "north_star_sweep", **event})
                    + "\n")
    except OSError:
        pass


def measure(fn, reps: int = 2) -> float:
    """Median wall-clock; fn blocks internally (scalar fetch)."""
    fn()                      # warm/compile
    ts = []
    for _ in range(reps + 1):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def main() -> int:
    import bench
    ok, payload = bench._run_child("probe", bench.PROBE_TIMEOUT_S)
    if not ok:
        print(json.dumps({"error": str(payload)}))
        _append_progress({"ok": False, "detail": str(payload)[:300]})
        return 2

    from matrel_tpu.workloads.big_chain import (
        cheap_gen, north_star_flops, streaming_chain_slab)

    # $MATREL_NS_N scales the sweep down for the dry-batch fire-drill
    # (tools/tpu_batch.sh --dry): same code path, same artifact shape,
    # toy dims on the CPU backend
    n = int(os.environ.get("MATREL_NS_N", 65_536))
    flops = north_star_flops(n)
    results = []
    # variants: the round-1 winner, its neighbours one step out in each
    # direction, and f32 reduce (isolates the reduce-glue term)
    variants = [
        ("tile8192_panel16384", dict(tile=8192, panel=16384)),
        ("tile8192_panel32768", dict(tile=8192, panel=32768)),
        ("tile16384_panel16384", dict(tile=16384, panel=16384)),
        ("tile4096_panel16384", dict(tile=4096, panel=16384)),
    ]
    if n < 65_536:
        t = max(n // 4, 128)
        variants = [(f"dry_tile{t}_panel{t}", dict(tile=t, panel=t))]
    for name, kw in variants:
        gens = tuple(cheap_gen(s, kw["tile"]) for s in (1, 2, 3))

        def run(kw=kw, gens=gens):
            float(streaming_chain_slab(n, *gens, **kw))

        try:
            dt = measure(run)
            tf = flops / dt / 1e12
            results.append({"variant": name, "s": round(dt, 3),
                            "tflops": round(tf, 1)})
        except Exception as e:  # keep sweeping
            results.append({"variant": name, "error": repr(e)[:200]})
        print(json.dumps(results[-1]), flush=True)

    timed = sorted((r for r in results if "tflops" in r),
                   key=lambda r: -r["tflops"])
    verdict = {"ok": bool(timed), "results": results}
    if len(timed) >= 2:
        tie = timed[0]["tflops"] - timed[1]["tflops"] < 0.01 * timed[0]["tflops"]
        verdict["top_tie"] = tie
        verdict["conclusion"] = (
            "schedules tie — negative result stands (stop rule)"
            if tie and timed[0]["tflops"] < 182 else
            f"best {timed[0]['variant']} at {timed[0]['tflops']} TFLOPS")
    print(json.dumps(verdict))
    _append_progress(verdict)
    return 0


if __name__ == "__main__":
    sys.exit(main())
