"""Weighted-mesh strategy-flip check (VERDICT Next #4 "done when").

Builds a 2-level VIRTUAL mesh — 8 CPU host devices as a (2, 4) grid
with the y axis priced 8× (the DCN axis of a two-slice v5e fabric) —
and proves, through the real planner entry points, that:

  1. the β-only ranking picks the slow-axis collective (rmm's A
     all-gather rides y) and the topology-weighted ranking provably
     flips to the ICI-friendly bmm_right;
  2. MV106 flags a hand-stamped slow-axis plan under the weighted
     config, and stays quiet on the planner's own output;
  3. a weighted config executes a real multiply to oracle numerics
     (weights re-route choices, never change results).

Emits one parseable JSON line (tools/tpu_batch.sh step; asserted by
tests/test_batch_dry.py). CPU-only by construction — this is a
planning check, so it forces the CPU backend even inside a TPU batch.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

#: The flip shape: on the (2, 4) grid with 3a/8 < b_bytes < 3a/4, the
#: flat model's argmin (rmm) carries ~6× more y-axis bytes than the
#: broadcast alternative, so weighting y flips the pick (docs/TOPOLOGY.md
#: derives the band).
N, K, M = 8192, 2048, 4096
AXIS_WEIGHTS = (1.0, 8.0)


def main() -> int:
    import dataclasses
    from matrel_tpu import analysis
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.executor import execute
    from matrel_tpu.ir.expr import leaf, matmul
    from matrel_tpu.parallel import planner
    from jax.sharding import PartitionSpec as P

    mesh = mesh_lib.make_mesh((2, 4))
    base = BlockMatrix.from_numpy(np.zeros((8, 8), np.float32),
                                  mesh=mesh)

    def fab(n, m, spec=None):
        src = base if spec is None else BlockMatrix.from_numpy(
            np.zeros((8, 8), np.float32), mesh=mesh, spec=spec)
        return leaf(dataclasses.replace(src, shape=(n, m)))

    cfg_flat = MatrelConfig()
    cfg_w = MatrelConfig(axis_cost_weights=AXIS_WEIGHTS)
    node = matmul(fab(N, K), fab(K, M))
    flat_pick, _ = planner.choose_strategy_ex(node, mesh, cfg_flat)
    w_pick, _ = planner.choose_strategy_ex(node, mesh, cfg_w)
    flat_axes = planner.comm_cost_axes(flat_pick, N, K, M, 1.0, 1.0,
                                       2, 4, weights=AXIS_WEIGHTS)
    flipped = (flat_pick == "rmm" and w_pick == "bmm_right"
               and flat_axes[1] > flat_axes[0])

    # MV106: hand-stamp the slow-axis pick (replicated B makes the
    # broadcast free — the grossest version of the smell) on a
    # NON-root-exposed node; the planner's own annotation stays clean
    stamped = matmul(
        matmul(fab(N, K), fab(K, M, spec=P(None, None)))
        .with_attrs(strategy="rmm", strategy_source="override"),
        fab(M, 64))
    diags = analysis.verify_plan(
        planner.annotate_strategies(stamped, mesh, cfg_w), mesh, cfg_w)
    mv106 = [d for d in diags if d.code == "MV106"]
    clean = analysis.verify_plan(
        planner.annotate_strategies(matmul(fab(N, K), fab(K, M)), mesh,
                                    cfg_w), mesh, cfg_w)

    # weighted config executes to oracle numerics (tiny real multiply)
    rng = np.random.default_rng(0)
    xa = rng.standard_normal((64, 32)).astype(np.float32)
    xb = rng.standard_normal((32, 48)).astype(np.float32)
    got = execute(
        BlockMatrix.from_numpy(xa, mesh=mesh).expr().multiply(
            BlockMatrix.from_numpy(xb, mesh=mesh).expr()),
        mesh, cfg_w).to_numpy()
    numerics_ok = bool(np.allclose(got, xa @ xb, rtol=1e-4, atol=1e-4))

    ok = bool(flipped and mv106 and not clean and numerics_ok)
    print(json.dumps({
        "metric": "topology_strategy_flip",
        "grid": [2, 4],
        "axis_weights": list(AXIS_WEIGHTS),
        "dims": [N, K, M],
        "unweighted": flat_pick,
        "weighted": w_pick,
        "slow_axis_bytes": flat_axes[1],
        "fast_axis_bytes": flat_axes[0],
        "mv106_flagged": bool(mv106),
        "clean_plan_quiet": not clean,
        "numerics_ok": numerics_ok,
        "ok": ok,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
