"""Plan-snapshot corpus — the Catalyst ``comparePlans`` idiom at corpus
scale (SURVEY.md §4 "Optimizer tests: plan-level assertions").

A fixed corpus of representative expressions is planned on the standard
(2, 4) test grid and each OPTIMIZED plan's signature — node kinds,
chosen strategies with provenance, join schemes, inferred layouts — is
recorded in ``tests/plan_snapshots.json``. The paired test
(tests/test_plan_snapshots.py) replans the corpus and diffs against the
snapshot, so any future planner/optimizer change shows its plan-shape
consequences EXPLICITLY in review instead of silently reshaping
downstream collectives (the plan-stability discipline of database
query optimizers, which the reference inherits from Catalyst).

Regenerate after an INTENTIONAL planner change:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/plan_snapshot.py --update

and commit the JSON alongside the change that moved it.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SNAPSHOT_PATH = os.path.join(REPO, "tests", "plan_snapshots.json")


def _setup():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)


def corpus(mesh):
    """(name, optimized-ready MatExpr) pairs. Deterministic: fixed
    seeds, fixed shapes; planning has no randomness."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.core.coo import COOMatrix
    from matrel_tpu.core.sparse import BlockSparseMatrix
    from matrel_tpu.relational import ops as R

    rng = np.random.default_rng(1234)
    axes = tuple(mesh.axis_names)

    def bm(n, m, spec=None):
        return BlockMatrix.from_numpy(
            rng.standard_normal((n, m)).astype(np.float32), mesh=mesh,
            spec=spec)

    X = bm(4096, 256)
    y = bm(4096, 1)
    entries = []
    # 1. normal-equations linreg: the reference's headline pipeline
    entries.append(("linreg_normal_equations",
                    X.expr().t().multiply(X.expr()).solve(
                        X.expr().t().multiply(y.expr()))))
    # 2. skewed chain: the flagship chain-DP reorder
    A = bm(2048, 64)
    B = bm(64, 2048)
    C = bm(2048, 64)
    entries.append(("chain_skewed", A.expr().multiply(B.expr())
                    .multiply(C.expr())))
    # 3. FLOP-tied chain with a col-sharded middle operand: the
    #    round-5 layout-aware association flip
    entries.append(("chain_layout_flip",
                    bm(16, 512).expr()
                    .multiply(bm(512, 512, spec=P(None, axes)).expr())
                    .multiply(bm(512, 16).expr())))
    # 4. row-sharded leaf through a chain: interior bmm credit
    entries.append(("chain_interior_credit",
                    bm(1600, 512, spec=P(axes, None)).expr()
                    .multiply(bm(512, 512).expr())
                    .multiply(bm(512, 512).expr())))
    # 5. join feeding a matmul: align + consumer tiebreak
    entries.append(("join_under_matmul",
                    R.join_on_rows(bm(64, 4, spec=P(None, None)),
                                   bm(64, 3, spec=P(None, None)),
                                   "mul")
                    .multiply(bm(12, 8).expr())))
    # 6. replicated big operand: the symmetric rmm credit
    entries.append(("replicated_operand_matmul",
                    bm(512, 512, spec=P(None, None)).expr()
                    .multiply(bm(512, 128).expr())))
    # 7. COO SpMV dispatch (pagerank-shaped matvec chain step)
    adj = COOMatrix.from_edges(rng.integers(0, 2048, 8192),
                               rng.integers(0, 2048, 8192),
                               shape=(2048, 2048))
    entries.append(("coo_spmv_matvec", adj.multiply(bm(2048, 1).expr())))
    # 8. block-sparse x dense
    dense_for_tiles = rng.standard_normal((256, 256)).astype(np.float32)
    dense_for_tiles *= rng.random((256, 256)) < 0.3
    S = BlockSparseMatrix.from_numpy(dense_for_tiles, block_size=64,
                                     mesh=mesh)
    entries.append(("block_sparse_matmul",
                    S.multiply(bm(256, 128))))
    # 9. gram through transpose sharing (symmetric lowering candidate)
    G = bm(1024, 256)
    entries.append(("gram_AtA", G.expr().t().multiply(G.expr())))
    # 10. rank-1 update pushed through a multiply (R8)
    entries.append(("rank1_pushdown",
                    G.expr().rank_one_update(bm(1024, 1).expr(),
                                             bm(256, 1).expr())
                    .multiply(bm(256, 64).expr())))
    return entries


def signature(e, mesh, _lmemo=None):
    """Deterministic nested plan signature: kinds, strategy choices
    with provenance, join schemes, inferred layouts."""
    from matrel_tpu.parallel import planner

    if _lmemo is None:
        _lmemo = {}
    sig = {"kind": e.kind, "shape": list(e.shape)}
    if "strategy" in e.attrs:
        sig["strategy"] = e.attrs["strategy"]
        sig["source"] = e.attrs.get("strategy_source")
    if "replicate" in e.attrs:
        sig["scheme"] = e.attrs["replicate"]
    lay = planner.infer_layout(e, mesh, _lmemo)
    if lay != "2d":
        sig["layout"] = lay
    if e.children:
        sig["children"] = [signature(c, mesh, _lmemo)
                           for c in e.children]
    return sig


def build_snapshots():
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.ir import rules
    from matrel_tpu.parallel import planner

    mesh = mesh_lib.make_mesh((2, 4))
    grid = mesh_lib.mesh_grid_shape(mesh)
    snaps = {}
    for name, e in corpus(mesh):
        opt = planner.annotate_strategies(
            rules.optimize(e, grid=grid, mesh=mesh), mesh)
        snaps[name] = signature(opt, mesh)
    return snaps


def main():
    _setup()
    snaps = build_snapshots()
    if "--update" in sys.argv:
        with open(SNAPSHOT_PATH, "w") as f:
            json.dump(snaps, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {len(snaps)} plan snapshots to {SNAPSHOT_PATH}")
        return 0
    try:
        with open(SNAPSHOT_PATH) as f:
            want = json.load(f)
    except (OSError, json.JSONDecodeError) as ex:
        print(f"snapshot unreadable ({ex!r}); run with --update first")
        return 1
    bad = sorted(set(n for n in snaps if snaps[n] != want.get(n))
                 | set(n for n in want if n not in snaps))
    for n in bad:
        print(f"PLAN CHANGED: {n}")
        print("  now:  ", json.dumps(snaps.get(n), sort_keys=True))
        print("  snap: ", json.dumps(want.get(n), sort_keys=True))
    matches = sum(1 for n in snaps if snaps[n] == want.get(n))
    print(f"{matches}/{len(snaps)} plans match")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
