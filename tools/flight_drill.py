"""Obs tier-2 smoke drill: flight recorder + trace export + drift.

Drives a real session through the round-9 observability surfaces and
asserts each artifact end to end (the tpu_batch.sh fire-drill
discipline — a staged tool that crashes on import is found HERE, not
in a relay window):

  1. a 3-query micro-batched serve admission (``run_many``) plus one
     async ``submit`` — the admission/compile/execute span trail;
  2. a COMPILE FAILURE (mixed-mesh expression) — the flight recorder's
     automatic dump must leave a parseable post-mortem artifact;
  3. ``explain(analyze=True)`` — one ``analyze`` event, the drift
     auditor's measured-vs-estimated feed;
  4. chrome export over the session's event log (span count + at least
     one parent link — the Perfetto-loadable acceptance);
  5. a drift report with the calibration table persisted.

Emits one parseable JSON line (tools/tpu_batch.sh step; asserted by
tests/test_batch_dry.py). CPU-only by construction — this drills the
observability plumbing, not the chip, so it forces the CPU backend
even inside a TPU batch (wedge-safe: never touches the relay).

Artifact paths follow the config env knobs, so the dry batch redirects
everything: MATREL_OBS_EVENT_LOG (span/event log),
MATREL_OBS_FLIGHT_RECORDER_PATH (dump artifact),
MATREL_DRIFT_TABLE_PATH (calibration table).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> int:
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.obs import drift, trace as trace_lib
    from matrel_tpu.obs.events import read_events, resolve_path
    from matrel_tpu.session import MatrelSession

    # env (MATREL_*) overrides flow over the drill's base config, so
    # the dry batch's redirects land every artifact outside the repo
    cfg = MatrelConfig.from_env(MatrelConfig(
        obs_level="on", obs_flight_recorder=256,
        result_cache_max_bytes=1 << 26))
    mesh = mesh_lib.make_mesh((2, 4))
    sess = MatrelSession(mesh=mesh, config=cfg)
    rng = np.random.default_rng(0)
    A = sess.from_numpy(rng.standard_normal((64, 96)).astype(np.float32))
    B = sess.from_numpy(rng.standard_normal((96, 32)).astype(np.float32))

    # 1. the 3-query serve batch (the chrome-acceptance window) + one
    #    async submit so the admission-worker span trail exists too
    batch = [A.expr().multiply(B.expr()).multiply_scalar(s)
             for s in (1.0, 2.0, 3.0)]
    outs = sess.run_many(batch)
    ok_batch = len(outs) == 3 and outs[0].shape == (64, 32)
    sess.submit(A.expr().multiply(B.expr())).result()
    sess.serve_drain()

    # 2. compile failure → automatic flight-recorder dump. A mixed-mesh
    #    expression fails _check_one_mesh inside compile_expr — a real
    #    compile-path error, not a monkeypatched one.
    from matrel_tpu.core.blockmatrix import BlockMatrix
    other = mesh_lib.make_mesh((1, 1), devices=jax.devices()[:1])
    M_other = BlockMatrix.from_numpy(
        rng.standard_normal((96, 32)).astype(np.float32), mesh=other)
    compile_failed = False
    try:
        sess.run(A.expr().multiply(M_other.expr()))
    except ValueError:
        compile_failed = True
    flight_path = (cfg.obs_flight_recorder_path
                   or trace_lib.DEFAULT_FLIGHT_PATH)
    flight = None
    if os.path.exists(flight_path):
        with open(flight_path) as f:
            flight = json.load(f)

    # 3. one analyze event (the drift feed)
    sess.explain(A.expr().multiply(B.expr()), analyze=True)

    # 4. chrome export over the whole log
    log_path = resolve_path(cfg.obs_event_log
                            or os.environ.get("MATREL_OBS_EVENT_LOG"))
    events = read_events(log_path)
    doc = trace_lib.chrome_trace(events)
    names = {ev["name"] for ev in doc["traceEvents"]}
    ids = {ev["args"].get("span_id") for ev in doc["traceEvents"]}
    parent_linked = sum(
        1 for ev in doc["traceEvents"]
        if ev["args"].get("parent_id") in ids
        and ev["args"].get("parent_id") is not None)

    # 5. drift report + persisted table
    table_path = drift.table_path(cfg)
    drift_report = drift.report(events, table_path_str=table_path)
    drift_rows = len(drift.calibrate(list(drift.iter_samples(events))))

    record = {
        "metric": "flight_recorder_drill",
        "batch_ok": ok_batch,
        "compile_failure_dumped": bool(
            compile_failed and flight
            and flight.get("reason") == "compile_failure"
            and flight.get("records")),
        "flight_path": flight_path,
        "flight_records": len((flight or {}).get("records") or ()),
        "chrome_events": len(doc["traceEvents"]),
        "parent_linked": parent_linked,
        "span_names": sorted(names),
        "drift_rows": drift_rows,
        "drift_table": table_path,
        "log": log_path,
    }
    record["ok"] = bool(
        record["batch_ok"] and record["compile_failure_dumped"]
        and record["chrome_events"] > 0 and record["parent_linked"] > 0
        and {"serve.admit", "serve.batch", "plan.optimize",
             "serve.execute"} <= names
        and drift_rows >= 1
        and os.path.exists(table_path)
        and "drift audit" in drift_report)
    print(json.dumps(record))
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
