"""Re-capture the on-chip autotune table under the round-4 measurement
rules (median-of-3 marginals, bounded in-flight chains, tie → null).

Overwrites autotune_v5e_1chip.json for the shapes the round-3 capture
covered. VERDICT r3 #4: the round-3 single-marginal capture persisted
1e-9 noise sentinels as winners; this tool is the re-capture it asked
for, run from tpu_batch.sh whenever the relay is alive.
"""
import json
import os
import sys

# run as a script from anywhere (the round-6 dry fire-drill caught this
# staged tool crashing on import — tools/ is the script dir, not the
# repo root, so the package was never importable)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from matrel_tpu.config import MatrelConfig, set_default_config
from matrel_tpu.core import mesh as mesh_lib
from matrel_tpu.parallel import autotune

# MATREL_AUTOTUNE_{SIDES,DTYPES,SPMV} scale the capture down for the
# dry-batch fire-drill (tools/tpu_batch.sh --dry), which also points
# the positional table-path arg away from the real on-chip table
SIDES = tuple(int(s) for s in os.environ.get(
    "MATREL_AUTOTUNE_SIDES", "1024,2048,4096").split(","))
DTYPES = tuple(os.environ.get(
    "MATREL_AUTOTUNE_DTYPES", "float32,bfloat16").split(","))


def main(path: str = "autotune_v5e_1chip.json") -> None:
    cfg = MatrelConfig(autotune=True, autotune_table_path=path)
    set_default_config(cfg)
    mesh = mesh_lib.make_mesh()
    for side in SIDES:
        for dtype in DTYPES:
            best, times = autotune.autotune_matmul(
                side, side, side, mesh=mesh, dtype=dtype, config=cfg)
            print(json.dumps({"side": side, "dtype": dtype, "best": best,
                              "times": {k: round(v, 6)
                                        for k, v in times.items()}}))
            sys.stdout.flush()
    # SpMV executor choice (VERDICT r3 #8) at a scale whose expanded
    # tables still fit the measurement budget (~235 MB; the row-5 graph
    # itself is compact-only by the 2 GB gate)
    import numpy as np
    from matrel_tpu.core.coo import COOMatrix
    n, m = (int(v) for v in os.environ.get(
        "MATREL_AUTOTUNE_SPMV", "100000,1000000").split(","))
    rng = np.random.default_rng(0)
    A = COOMatrix.from_edges(rng.integers(0, n, m, dtype=np.int32),
                             rng.integers(0, n, m, dtype=np.int32),
                             shape=(n, n))
    plan = A._get_plan()
    if plan is not None:
        autotune._SPMV_CACHE.clear()
        best = autotune.lookup_or_measure_spmv(plan, mesh, cfg)
        gx, gy = mesh_lib.mesh_grid_shape(mesh)
        key = autotune._spmv_key(plan, gx, gy)
        entry = autotune.load_table(path).get(key, {})
        print(json.dumps({"spmv_key": key, "best": best,
                          "times": {k: round(v, 6) for k, v in
                                    entry.get("times", {}).items()}}))
        sys.stdout.flush()


if __name__ == "__main__":
    main(*sys.argv[1:])
