"""Re-capture the on-chip autotune table under the round-4 measurement
rules (median-of-3 marginals, bounded in-flight chains, tie → null).

Overwrites autotune_v5e_1chip.json for the shapes the round-3 capture
covered. VERDICT r3 #4: the round-3 single-marginal capture persisted
1e-9 noise sentinels as winners; this tool is the re-capture it asked
for, run from tpu_batch.sh whenever the relay is alive.
"""
import json
import sys

from matrel_tpu.config import MatrelConfig, set_default_config
from matrel_tpu.core import mesh as mesh_lib
from matrel_tpu.parallel import autotune

SIDES = (1024, 2048, 4096)
DTYPES = ("float32", "bfloat16")


def main(path: str = "autotune_v5e_1chip.json") -> None:
    cfg = MatrelConfig(autotune=True, autotune_table_path=path)
    set_default_config(cfg)
    mesh = mesh_lib.make_mesh()
    for side in SIDES:
        for dtype in DTYPES:
            best, times = autotune.autotune_matmul(
                side, side, side, mesh=mesh, dtype=dtype, config=cfg)
            print(json.dumps({"side": side, "dtype": dtype, "best": best,
                              "times": {k: round(v, 6)
                                        for k, v in times.items()}}))
            sys.stdout.flush()


if __name__ == "__main__":
    main(*sys.argv[1:])
