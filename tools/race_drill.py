"""race_drill — deterministic-seeded thread-interleaving drill for
the serve/fleet concurrency plane, run with runtime lockdep armed
(utils/lockdep.py; the dynamic half of tools/lockcheck.py — see
docs/CONCURRENCY.md).

Four known-hairy schedules, each the scene of a past (or statically
predicted) race, each trial seeded so a failure reproduces by seed:

  submit_close_drain   concurrent submit / close / drain against one
                       pipeline (the PR 8 submit-vs-close window and
                       drain-wedge class)
  kill_replication     kill_slice racing a rebind's re-replication
                       and in-flight directory inserts (PR 15's
                       invalidation-ordering plane)
  rebind_probes        register() rebinds racing identical template
                       queries (plan-template reuse + cross-query CSE
                       probes, the PR 17 sharing planes)
  delta_serve          register_delta IVM maintenance under live
                       serve load (the PR 13 patch-vs-lookup window)

Rebinds and deltas are VALUE-PRESERVING (same numbers, new objects),
so every resolved answer has one oracle regardless of interleaving:
any mismatch is a real race, not an ordering ambiguity.

Contract (the artifact line, asserted by tests/test_batch_dry.py and
staged in tools/tpu_batch.sh):
  - 0 wrong answers
  - 0 untyped failures (every refusal is ResilienceError-family)
  - lockdep order graph acyclic, 0 inversions recorded, across all
    seeds x schedules

Knobs (env, dry-run friendly):
  MATREL_RACE_SEEDS       trials per schedule     (default 8)
  MATREL_RACE_QUERIES     queries per trial       (default 10)
  MATREL_RACE_SCHEDULES   comma list to run       (default all)

Usage:
  python tools/race_drill.py            # CPU-forced, prints one JSON line
  MATREL_RACE_SEEDS=2 python tools/race_drill.py   # the batch dry stage
"""

from __future__ import annotations

import json
import os
import sys
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# CPU-forced BEFORE jax import (the drill-tool idiom: the axon
# sitecustomize pins the platform at interpreter start)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from matrel_tpu.config import MatrelConfig  # noqa: E402
from matrel_tpu.resilience.errors import ResilienceError  # noqa: E402
from matrel_tpu.session import MatrelSession  # noqa: E402
from matrel_tpu.utils import lockdep  # noqa: E402

N = 48                  # table side — small: interleaving, not FLOPs
TIMEOUT = 60            # every wait in the drill is bounded (wedge-safe)


def _base_cfg(**kw) -> MatrelConfig:
    """Drill base config; MATREL_* env still flows over it (the
    provenance_drill idiom) so the batch script can tighten knobs."""
    base = dict(lockdep_enable=True, lockdep_raise=False,
                serve_max_batch=1,
                result_cache_max_bytes=64 << 20)
    base.update(kw)
    return MatrelConfig.from_env(MatrelConfig(**base))


def _mats(sess, rng, names=("A", "B")):
    mats = {}
    for nm in names:
        arr = rng.standard_normal((N, N)).astype(np.float32)
        mats[nm] = arr
        sess.register(nm, sess.from_numpy(arr))
    return mats


def _score(futs, oracle, tol=3e-3):
    """(wrong, untyped, resolved) over a list of futures sharing one
    oracle. Typed refusals are the contract, not failures."""
    wrong = untyped = resolved = 0
    for fut in futs:
        try:
            got = np.asarray(fut.result(timeout=TIMEOUT).to_numpy())
            err = float(np.abs(got - oracle).max())
            if err > tol * max(float(np.abs(oracle).max()), 1.0):
                wrong += 1
            else:
                resolved += 1
        except ResilienceError:
            pass
        except Exception:  # noqa: BLE001 — untyped IS the finding
            untyped += 1
    return wrong, untyped, resolved


def _close(sess):
    try:
        sess.serve_close(timeout=TIMEOUT)
    except Exception:  # noqa: BLE001 — teardown best-effort
        pass


# -- schedules ---------------------------------------------------------------

def sched_submit_close_drain(seed: int, queries: int) -> dict:
    """Submitter races a drainer and a closer on one pipeline. Late
    submits must refuse TYPED (PipelineClosed/AdmissionShed), resolved
    answers must be right, and nothing may wedge."""
    rng = np.random.default_rng(seed)
    sess = MatrelSession(config=_base_cfg())
    try:
        mats = _mats(sess, rng)
        expr = sess.table("A").expr().multiply(sess.table("B").expr())
        oracle = mats["A"] @ mats["B"]
        close_after = int(rng.integers(1, max(queries - 1, 2)))
        submitted = threading.Semaphore(0)
        futs, errs = [], []

        def _drain():
            submitted.acquire(timeout=TIMEOUT)
            try:
                sess.serve_drain(timeout=TIMEOUT)
            except ResilienceError:
                pass
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        def _closer():
            for _ in range(close_after):
                submitted.acquire(timeout=TIMEOUT)
            try:
                sess.serve_close(timeout=TIMEOUT)
            except ResilienceError:
                pass
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=_drain, daemon=True),
              threading.Thread(target=_closer, daemon=True)]
        for t in ts:
            t.start()
        typed_refusals = 0
        for _ in range(queries):
            try:
                futs.append(sess.submit(expr))
            except ResilienceError:
                typed_refusals += 1    # closed/shed mid-race: typed
            submitted.release()
        for _ in range(queries, close_after + 1):
            submitted.release()        # closer never starves
        for t in ts:
            t.join(timeout=TIMEOUT)
        wedged = any(t.is_alive() for t in ts)
        wrong, untyped, resolved = _score(futs, oracle)
        untyped += len(errs) + (1 if wedged else 0)
        return {"wrong": wrong, "untyped": untyped,
                "resolved": resolved, "refused": typed_refusals}
    finally:
        _close(sess)


def sched_kill_replication(seed: int, queries: int) -> dict:
    """kill_slice concurrent with a value-preserving rebind (which
    re-replicates under the registration lock) and a live stream."""
    rng = np.random.default_rng(seed)
    sess = MatrelSession(config=_base_cfg(
        fleet_slices=2, fleet_replicate_hits=0))
    try:
        mats = _mats(sess, rng)
        expr = sess.table("A").expr().multiply(sess.table("B").expr())
        oracle = mats["A"] @ mats["B"]
        victim = int(rng.integers(0, 2))
        kill_at = int(rng.integers(1, max(queries - 1, 2)))
        errs = []

        def _rebind():
            try:
                # same values, new device objects: forces the full
                # on_register surgery + re-replication path
                sess.register("A", sess.from_numpy(mats["A"]))
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        futs = []
        rb = threading.Thread(target=_rebind, daemon=True)
        for i in range(queries):
            futs.append(sess.submit(expr))
            if i == kill_at:
                rb.start()
                sess._fleet.kill_slice(victim)
        rb.join(timeout=TIMEOUT)
        try:
            sess.serve_drain(timeout=TIMEOUT)
        except ResilienceError:
            pass
        wrong, untyped, resolved = _score(futs, oracle)
        untyped += len(errs) + (1 if rb.is_alive() else 0)
        return {"wrong": wrong, "untyped": untyped,
                "resolved": resolved, "refused": 0}
    finally:
        _close(sess)


def sched_rebind_probes(seed: int, queries: int) -> dict:
    """register() rebind storm racing identical template queries —
    the plan-template + cross-query-CSE sharing planes must never
    serve a torn binding."""
    rng = np.random.default_rng(seed)
    sess = MatrelSession(config=_base_cfg())
    try:
        mats = _mats(sess, rng)
        expr = (sess.table("A").expr()
                .multiply(sess.table("B").expr()).add_scalar(1.0))
        oracle = mats["A"] @ mats["B"] + 1.0
        stop = threading.Event()
        errs = []

        def _rebinder():
            try:
                while not stop.is_set():
                    sess.register("A", sess.from_numpy(mats["A"]))
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        rb = threading.Thread(target=_rebinder, daemon=True)
        rb.start()
        futs = [sess.submit(expr) for _ in range(queries)]
        try:
            sess.serve_drain(timeout=TIMEOUT)
        except ResilienceError:
            pass
        stop.set()
        rb.join(timeout=TIMEOUT)
        wrong, untyped, resolved = _score(futs, oracle)
        untyped += len(errs) + (1 if rb.is_alive() else 0)
        return {"wrong": wrong, "untyped": untyped,
                "resolved": resolved, "refused": 0}
    finally:
        _close(sess)


def sched_delta_serve(seed: int, queries: int) -> dict:
    """register_delta (zero-valued COO delta: IVM machinery runs,
    values stand still) under live serve load."""
    rng = np.random.default_rng(seed)
    sess = MatrelSession(config=_base_cfg())
    try:
        mats = _mats(sess, rng)
        expr = sess.table("A").expr().multiply(sess.table("B").expr())
        oracle = mats["A"] @ mats["B"]
        errs = []
        k = 8
        rows = rng.integers(0, N, size=k)
        cols = rng.integers(0, N, size=k)
        vals = np.zeros(k, dtype=np.float32)

        futs = []
        for i in range(queries):
            futs.append(sess.submit(expr))
            if i % 3 == 1:
                try:
                    sess.register_delta("A", (rows, cols, vals),
                                        kind="coo")
                except ResilienceError:
                    pass
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
        try:
            sess.serve_drain(timeout=TIMEOUT)
        except ResilienceError:
            pass
        wrong, untyped, resolved = _score(futs, oracle)
        untyped += len(errs)
        return {"wrong": wrong, "untyped": untyped,
                "resolved": resolved, "refused": 0}
    finally:
        _close(sess)


SCHEDULES = {
    "submit_close_drain": sched_submit_close_drain,
    "kill_replication": sched_kill_replication,
    "rebind_probes": sched_rebind_probes,
    "delta_serve": sched_delta_serve,
}


def main() -> int:
    seeds = int(os.environ.get("MATREL_RACE_SEEDS", "8"))
    queries = int(os.environ.get("MATREL_RACE_QUERIES", "10"))
    picked = os.environ.get("MATREL_RACE_SCHEDULES", "")
    names = ([s for s in picked.split(",") if s in SCHEDULES]
             if picked else list(SCHEDULES))

    totals = {"wrong": 0, "untyped": 0, "resolved": 0, "refused": 0}
    per_sched = {}
    inversions = 0
    dispatch_holds = 0
    acyclic = True
    trials = 0
    for name in names:
        fn = SCHEDULES[name]
        agg = {k: 0 for k in totals}
        for seed in range(seeds):
            # fresh order graph per trial: a cycle reproduces by
            # (schedule, seed), not by whatever ran before it
            lockdep.reset()
            res = fn(1000 * (list(SCHEDULES).index(name) + 1) + seed,
                     queries)
            trials += 1
            for key in totals:
                agg[key] += res[key]
                totals[key] += res[key]
            diags = lockdep.diagnostics()
            inversions += sum(1 for d in diags
                              if d["diag"] in ("inversion",
                                               "self_deadlock"))
            dispatch_holds += sum(
                1 for d in diags
                if d["diag"] == "held_across_dispatch")
            if not lockdep.is_acyclic():
                acyclic = False
            print(f"  {name} seed {seed}: {res}", file=sys.stderr,
                  flush=True)
        per_sched[name] = agg
    lockdep.reset()
    lockdep.disable()

    ok = (totals["wrong"] == 0 and totals["untyped"] == 0
          and inversions == 0 and acyclic
          and totals["resolved"] > 0)
    artifact = {
        "metric": "race_drill",
        "seeds": seeds,
        "queries": queries,
        "trials": trials,
        "schedules": per_sched,
        "wrong": totals["wrong"],
        "untyped": totals["untyped"],
        "resolved": totals["resolved"],
        "refused": totals["refused"],
        "inversions": inversions,
        "held_across_dispatch": dispatch_holds,
        "acyclic": acyclic,
        "ok": ok,
    }
    print(json.dumps(artifact), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
