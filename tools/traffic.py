"""Open-loop traffic harness — the overload control plane's proving
ground (docs/OVERLOAD.md; ROADMAP item 5's harness half).

Every serve number before round 13 was a CLOSED-loop replay: the next
query waited for the last one, so the engine was never driven at its
design point — sustained overload, mixed tenants, bursty arrivals.
This harness drives ``session.submit`` OPEN-loop: a seeded
Poisson (or bursty, Markov-modulated) arrival process over a
declarative tenant x workload mix submits on schedule whether or not
the engine kept up, which is the only way queue growth, typed
shedding, weighted fairness and brownout actually happen.

Three phases, one parseable JSON artifact (tpu_batch.sh step in BOTH
modes; asserted by tests/test_batch_dry.py::test_traffic_row_artifact):

  1. closed-loop calibration: sequential ``run`` over the workload
     pool measures capacity C (the goodput denominator);
  2. overload: ``MATREL_TRAFFIC_RATE_X`` x C arrivals (default 2x)
     for ``MATREL_TRAFFIC_SECONDS`` across 3 weighted tenants
     (gold:4 / silver:2 / bronze:1, equal arrival shares) with
     per-query deadlines — the brownout controller must ENTER;
  3. cool-down tail at a fraction of C — the controller must EXIT
     (the hysteresis proof), then a bounded drain.

Acceptance (the record's ``ok``), CPU backend acceptable while the
relay is wedged (this drills the control plane, not the chip):

  - goodput >= ``MATREL_TRAFFIC_GOODPUT_MIN`` (default 0.8) of the
    measured closed-loop capacity at ~2x sustained overload;
  - every rejected query fails TYPED (zero untyped errors) and zero
    wrong answers (every completed result checked against its numpy
    oracle, at the fast-tier tolerance — brownout rung 1 may
    legitimately downshift default-SLA queries);
  - admitted-and-met p99 latency stays bounded by the declared
    deadline;
  - the highest-weight tenant's miss rate (sheds + deadline misses
    over arrivals) is STRICTLY lower than the lowest-weight
    tenant's — weighted fairness under saturation;
  - brownout provably enters AND exits;
  - the Jain fairness index over weight-normalised per-tenant goodput
    is reported (1.0 = perfectly weight-proportional service).

``--slo`` (round 15, docs/OBSERVABILITY.md tier 3) runs the SAME
three phases under declared per-tenant objectives with the live
metrics endpoint on, and its acceptance is the alerting loop instead
of goodput: the violated (lowest-weight) tenant's fast-window
burn-rate alert must FIRE during saturation and every alert must
CLEAR after the load drops, with the Prometheus endpoint strict-
parsing clean on every poll throughout and still zero wrong answers.
One parseable ``traffic_slo_harness`` JSON artifact (tpu_batch.sh
stages both modes; test_batch_dry asserts both).

Latency is measured to future RESOLUTION (dispatch-complete — the
serve plane's own SLA semantics since PR 5). The workload mix reuses
``workloads/`` (triangle counting) and the kernel registry's
``synthesize_structure`` (an S x S SpGEMM pair) next to a dense
scaled-matmul class, all small enough that the CPU mesh saturates on
scheduling, not on FLOPs — exactly the admission-plane regime the
harness exists to measure. MATREL_TRAFFIC_SEED varies the arrival
schedule; any fixed seed is reproducible.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

#: The declarative tenant mix: weight drives admission fairness,
#: share drives the arrival split (equal — fairness must come from
#: the queue, not the generator).
TENANTS = ({"name": "gold", "weight": 4.0, "share": 1 / 3},
           {"name": "silver", "weight": 2.0, "share": 1 / 3},
           {"name": "bronze", "weight": 1.0, "share": 1 / 3})

#: Oracle tolerance: brownout rung 1 may run default-SLA queries at
#: the bf16 fast tier, so "wrong answer" means wrong beyond the fast
#: tier's documented bound on these small contractions — checked in
#: MAX norm (elementwise allclose punishes the near-zero entries of a
#: random gaussian contraction for bf16 input rounding that is tiny
#: relative to the result's scale).
TOL = 2e-2


def oracle_ok(got, oracle) -> bool:
    got = np.asarray(got, dtype=np.float64)
    oracle = np.asarray(oracle, dtype=np.float64)
    if got.shape != oracle.shape:
        return False
    scale = max(float(np.max(np.abs(oracle))), 1.0)
    return float(np.max(np.abs(got - oracle))) <= TOL * scale


def _env_f(name, default):
    return float(os.environ.get(name, default))


def build_pool(sess, rng, register=False):
    """The workload pool: (name, expr, numpy oracle) triples. Small by
    design — a bounded pool keeps the MultiPlan composition space
    finite so steady state is plan-cache-hitting (the serve plane's
    own operating point) and the harness measures ADMISSION, not
    compilation.

    ``register=True`` (--slices mode) binds the dense tables into the
    session catalog so the fleet can replicate them per slice and key
    the queries into its directory; the sparse/structured operands
    stay unregistered — those queries PIN to the full-mesh span path,
    so the fleet drill exercises both routings."""
    from matrel_tpu.ops import kernel_registry as kr
    from matrel_tpu.workloads.triangles import triangle_count_expr
    n = int(_env_f("MATREL_TRAFFIC_N", 48))
    an = rng.standard_normal((n, n + 16)).astype(np.float32)
    bn = rng.standard_normal((n + 16, n // 2)).astype(np.float32)
    A, B = sess.from_numpy(an), sess.from_numpy(bn)
    if register:
        sess.register("traffic_A", A)
        sess.register("traffic_B", B)
    # dense scaled-matmul class (two variants: distinct plans)
    pool = [
        ("matmul_s2", A.expr().multiply(B.expr()).multiply_scalar(2.0),
         (an @ bn) * 2.0),
        ("matmul_s3", A.expr().multiply(B.expr()).multiply_scalar(3.0),
         (an @ bn) * 3.0),
    ]
    # triangle counting (workloads/triangles.py): the full relational
    # stack — trace(A^3) with the diagonal aggregate pushed down
    adj = (rng.random((32, 32)) < 0.3).astype(np.float32)
    adj = np.triu(adj, 1)
    adj = adj + adj.T
    Adj = sess.from_numpy(adj)
    tri = np.array([[np.trace(adj @ adj @ adj)]], dtype=np.float64)
    pool.append(("triangles", triangle_count_expr(Adj), tri))
    # S x S SpGEMM over a synthesized structure class (the kernel
    # registry's shared generator — the sparse serving class)
    S1 = kr.synthesize_structure("row_band", 256, 64, sess.mesh,
                                 seed=0)
    S2 = kr.synthesize_structure("row_band", 256, 64, sess.mesh,
                                 seed=1)
    pool.append(("spgemm_band", S1.expr().multiply(S2.expr()),
                 S1.to_numpy() @ S2.to_numpy()))
    # dashboard-session class (round 17, serve/mqo.py): a burst of
    # structurally-identical-modulo-leaves queries — the same scaled
    # Gram shape over DISTINCT small tables. With cse_enable on, the
    # first compiles and inserts a plan template; every sibling
    # rebinds into it (template_hits), so dashboard traffic's compile
    # count plateaus at one — the artifact's mqo assertion.
    dn = 24
    for i in range(6):
        d = rng.standard_normal((dn, dn)).astype(np.float32)
        D = sess.from_numpy(d)
        if register:
            sess.register(f"traffic_dash{i}", D)
        pool.append((f"dash_{i}",
                     D.expr().t().multiply(D.expr())
                     .multiply_scalar(0.5),
                     (d.astype(np.float64).T @ d.astype(np.float64))
                     * 0.5))
    return pool


def arrival_schedule(rng, rate_qps, seconds, process):
    """Seeded arrival offsets (seconds from phase start). "poisson" =
    exponential inter-arrivals; "bursty" = Markov-modulated on/off
    (0.5 s phases at 3x / 0.2x the mean rate — same mean load,
    burstier queue dynamics)."""
    out = []
    t = 0.0
    if process == "bursty":
        phase_len, hot = 0.5, True
        phase_end = phase_len
        while t < seconds:
            r = rate_qps * (3.0 if hot else 0.2)
            t += float(rng.exponential(1.0 / max(r, 1e-9)))
            while t > phase_end:
                hot = not hot
                phase_end += phase_len
            if t < seconds:
                out.append(t)
    else:
        while t < seconds:
            t += float(rng.exponential(1.0 / max(rate_qps, 1e-9)))
            if t < seconds:
                out.append(t)
    return out


def _pctile(sorted_vals, q):
    if not sorted_vals:
        return None
    return sorted_vals[min(int(q * len(sorted_vals)),
                           len(sorted_vals) - 1)]


#: Open-loop submit-tick granularity (seconds): arrivals due inside a
#: tick submit back-to-back. A per-arrival sleep at thousands of
#: arrivals/s burns the client's share of the GIL on scheduler churn —
#: time the SERVER needs (client and server share one process here).
TICK_S = 0.005


def drive_phase(sess, pool, schedule, tenants, rng, deadline_ms,
                outcomes, rung_samples):
    """Submit one phase's arrivals on schedule (open loop: no waiting
    on completions). Tenant/workload assignments are PRE-DRAWN so the
    hot loop is submit-only; the brownout rung is sampled once per
    tick. Outcomes append into ``outcomes`` as dicts."""
    from matrel_tpu.resilience import errors as rerrors
    names = [t["name"] for t in tenants]
    shares = np.array([t["share"] for t in tenants])
    n = len(schedule)
    tenant_ix = rng.choice(len(names), size=max(n, 1),
                           p=shares / shares.sum())
    pool_ix = rng.integers(0, len(pool), size=max(n, 1))
    ctl = sess._brownout
    t0 = time.perf_counter()
    i = 0
    while i < n:
        now = time.perf_counter() - t0
        if schedule[i] > now:
            time.sleep(min(schedule[i] - now, TICK_S))
            now = time.perf_counter() - t0
        if ctl is not None:
            rung_samples.append(ctl.rung())
        while i < n and schedule[i] <= now:
            tenant = names[int(tenant_ix[i])]
            name, expr, oracle = pool[int(pool_ix[i])]
            rec = {"tenant": tenant, "workload": name,
                   "t": schedule[i], "status": None,
                   "latency_ms": None, "oracle": oracle}
            i += 1
            t_sub = time.perf_counter()
            try:
                fut = sess.submit(expr, tenant=tenant,
                                  deadline_ms=deadline_ms)
            except rerrors.AdmissionShed:
                rec["status"] = "shed"
                outcomes.append(rec)
                continue
            except rerrors.CircuitOpen:
                rec["status"] = "circuit"
                outcomes.append(rec)
                continue

            def _done(f, rec=rec, t_sub=t_sub):
                rec["latency_ms"] = (time.perf_counter() - t_sub) * 1e3
                ex = f.exception()
                if ex is None:
                    rec["status"] = "ok"
                    rec["result"] = f.result()
                elif isinstance(ex, rerrors.DeadlineExceeded):
                    rec["status"] = "deadline"
                elif isinstance(ex, rerrors.AdmissionShed):
                    rec["status"] = "shed"
                elif isinstance(ex, rerrors.CircuitOpen):
                    rec["status"] = "circuit"
                elif isinstance(ex, rerrors.ResilienceError):
                    rec["status"] = "typed"
                else:
                    rec["status"] = "untyped:" + type(ex).__name__
                outcomes.append(rec)

            fut.add_done_callback(_done)
    return time.perf_counter() - t0


def measure_capacity(sess, pool, tenants, cal_n,
                     windows: int = 3) -> float:
    """Closed-loop capacity: one submit-wait client PER TENANT running
    concurrently (the faithful closed-loop definition for a 3-tenant
    plane — each tenant always has exactly one query in the system),
    through the SAME serve path the open-loop phase drives. Returns
    the MINIMUM of ``windows`` runs: window-to-window spread on a
    small shared host is scheduling noise, and the goodput criterion
    is a congestion-collapse detector — it compares against the
    slowest capacity the host actually demonstrated, not against one
    lucky alignment of the three clients. (--slo mode passes
    windows=1: its acceptance is alert behaviour, not goodput, and
    capacity only sets the offered rate.)"""

    def window() -> float:
        per = max(cal_n // len(tenants), 8)
        done = []

        def client(tname, base):
            for i in range(per):
                sess.submit(pool[(base + i) % len(pool)][1],
                            tenant=tname).result(timeout=120)
            done.append(per)

        threads = [threading.Thread(target=client,
                                    args=(t["name"], j), daemon=True)
                   for j, t in enumerate(tenants)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=90)
        sess.serve_drain(timeout=60)
        return sum(done) / max(time.perf_counter() - t0, 1e-9)

    return min(window() for _ in range(windows))


# ---------------------------------------------------------------------------
# --slo mode support: endpoint polling + strict Prometheus parsing
# ---------------------------------------------------------------------------

#: Strict text-exposition line grammar (version 0.0.4): metric name,
#: optional {labels}, one float (NaN/inf included). Anything else —
#: including a malformed # comment — fails the poll.
import re  # noqa: E402

_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})?\s"
    r"[-+]?([0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?|NaN|[Ii]nf)$")


def prometheus_parse_ok(text: str) -> bool:
    saw_sample = False
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            if not re.match(r"^# (TYPE|HELP) [a-zA-Z_:]", line):
                return False
            continue
        if not _PROM_SAMPLE.match(line):
            return False
        saw_sample = True
    return saw_sample


class PrometheusPoller:
    """Background scraper for --slo mode: GETs /metrics on an
    interval, strict-parses every response, and keeps the violated
    tenant's burn gauge trail — the 'endpoint parses clean
    THROUGHOUT' half of the acceptance."""

    def __init__(self, port, interval_s=0.4):
        self.url = f"http://127.0.0.1:{port}/metrics"
        self.interval_s = interval_s
        self.polls = 0
        self.parse_failures = 0
        self.errors = 0
        self.last_error = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="traffic-prom-poll",
                                        daemon=True)

    def _run(self):
        import urllib.request
        while not self._stop.is_set():
            try:
                with urllib.request.urlopen(self.url,
                                            timeout=5) as resp:
                    text = resp.read().decode()
                self.polls += 1
                if not prometheus_parse_ok(text):
                    self.parse_failures += 1
                    self.last_error = "parse failure: " + text[:200]
            except Exception as ex:  # noqa: BLE001 — tallied, the
                # record's ok goes false on any scrape error
                self.errors += 1
                self.last_error = repr(ex)[:200]
            self._stop.wait(self.interval_s)

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)


def main(slo: bool = False) -> int:
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.resilience import faults
    from matrel_tpu.session import MatrelSession

    seed = int(os.environ.get("MATREL_TRAFFIC_SEED", "0"))
    seconds = _env_f("MATREL_TRAFFIC_SECONDS", 8.0)
    tail_s = _env_f("MATREL_TRAFFIC_TAIL_SECONDS", 4.0)
    rate_x = _env_f("MATREL_TRAFFIC_RATE_X", 2.0)
    cal_n = int(_env_f("MATREL_TRAFFIC_CAL", 300))
    goodput_min = _env_f("MATREL_TRAFFIC_GOODPUT_MIN", 0.8)
    deadline_ms = _env_f("MATREL_TRAFFIC_DEADLINE_MS", 500.0)
    process = os.environ.get("MATREL_TRAFFIC_PROCESS", "poisson")
    faults.reset()
    weights = ",".join(f"{t['name']}:{t['weight']:g}" for t in TENANTS)
    # --slo mode (round 15, docs/OBSERVABILITY.md tier 3): declare
    # per-tenant availability objectives sized so ~2x overload BURNS
    # them (budget 10%, fire at 3x sustainable consumption), shrink
    # the burn windows to fit the phases, turn the live metrics
    # endpoint + obs event log on, and prove: the violated (lowest-
    # weight) tenant's fast-window alert FIRES during saturation,
    # every alert CLEARS after the load drops, and the Prometheus
    # endpoint parses clean on every poll throughout.
    slo_fast_s = _env_f("MATREL_TRAFFIC_SLO_FAST_S", 1.5)
    slo_kw: dict = {}
    if slo:
        import socket
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        slo_port = s.getsockname()[1]
        s.close()
        slo_kw = dict(
            obs_level="on",
            obs_metrics_port=slo_port,
            slo_targets=(f"gold:avail=0.9,p95_ms={deadline_ms:g};"
                         f"silver:avail=0.9;bronze:avail=0.9"),
            slo_fast_window_s=slo_fast_s,
            slo_slow_window_s=max(4 * slo_fast_s, seconds + tail_s),
            slo_burn_threshold=3.0,
            slo_burn_exit=1.0,
        )
    # env (MATREL_*) overrides flow over the base config so the dry
    # batch's redirects land every artifact outside the repo
    cfg = MatrelConfig.from_env(MatrelConfig(
        **slo_kw,
        serve_tenant_weights=weights,
        serve_tenant_queue_max=16,
        serve_queue_max=48,
        # single-query admission on the CPU harness host: a MIXED
        # MultiPlan is a per-query LOSS without an MXU (profiled:
        # ~0.8 ms/query in a 4-root mixed program vs ~0.45 ms as
        # singles — no dense compute to amortize, collectives grow
        # with the program), and the harness proves the ADMISSION
        # plane — weighted-fair ORDER, quota sheds, brownout,
        # breakers — not batching throughput (bench.py --serve owns
        # that; fair batch COMPOSITION is unit-test-pinned in
        # tests/test_overload.py). MATREL_SERVE_MAX_BATCH widens it
        # on a real TPU, where the MXU turns coalescing into a win.
        serve_max_batch=1,
        plan_cache_max_plans=256,
        # round 17 (serve/mqo.py): plan-template reuse on — the
        # dashboard-session pool class (structurally identical modulo
        # leaves) must plateau its compile count: first variant pays
        # optimize/trace, every sibling rebinds into the cached
        # template (mqo.template_hits in the record)
        cse_enable=True,
        brownout_enable=True,
        brownout_window=16,
        brownout_dwell=4,
        brownout_wait_high_ms=max(deadline_ms / 8.0, 20.0),
        brownout_wait_low_ms=max(deadline_ms / 40.0, 4.0),
        brownout_depth_high=24,
        brownout_depth_low=4,
        brownout_miss_high=0.25,
        brownout_miss_low=0.02,
        breaker_threshold=3,
        breaker_cooldown_ms=250.0,
        # CPU has no MXU: the bf16 "fast" tier is EMULATED there
        # (measured ~1.45x slower than f32 + a collective-pileup
        # hazard on this jax), so the rung-1 downshift would be a
        # rate LOSS on the harness host. Gate it off: "fast" degrades
        # to f32 (the precision layer's documented semantics), every
        # control-plane mechanism (stamping, SLA key isolation,
        # MV112) still exercises. On a real TPU run
        # MATREL_PRECISION_ENABLE_BF16=1 — there the downshift is the
        # 2x-rate trade it exists for.
        precision_enable_bf16=(jax.default_backend()
                               in ("tpu", "axon")),
    ))
    mesh = mesh_lib.make_mesh((2, 4))
    t_session_start = time.time()
    sess = MatrelSession(mesh=mesh, config=cfg)
    rng = np.random.default_rng(seed)
    pool = build_pool(sess, rng)
    poller = None
    if slo:
        poller = PrometheusPoller(sess._exporter.port)
        poller.start()

    # -- phase 0: prewarm the MultiPlan composition space ------------------
    # the worker coalesces up to serve_max_batch queries into one
    # MultiPlan; over a bounded pool that is a bounded set of sorted-
    # root-key compositions (both tiers: brownout downshifts default
    # queries onto stamped "fast" variants). Compiling them HERE keeps
    # the measured window measuring admission, not one-time jit cost —
    # exactly what a steady-state serving host looks like.
    from itertools import combinations
    from matrel_tpu.resilience.brownout import downshift_stamp
    t_warm = time.perf_counter()
    exprs = [e for _n, e, _o in pool]
    fast = [e.with_attrs(brownout=downshift_stamp()) for e in exprs]
    for k in range(1, int(cfg.serve_max_batch) + 1):
        for combo in combinations(range(len(pool)), k):
            sess.run_many([exprs[i] for i in combo])
            sess.run_many([fast[i] for i in combo], precision="fast")
    warmup_s = time.perf_counter() - t_warm

    # -- phase 1: closed-loop capacity calibration ------------------------
    # one closed-loop client per tenant, through the SAME serve path
    # the open-loop phase drives: the goodput denominator prices queue
    # hops, batch formation and worker scheduling, not just warm plan
    # dispatch
    for _name, expr, _o in pool:
        sess.submit(expr).result(timeout=60)
    capacity_pre = measure_capacity(sess, pool, TENANTS, cal_n,
                                    windows=(1 if slo else 3))

    # -- phase 2: open-loop overload --------------------------------------
    outcomes: list = []
    rung_samples: list = []
    rate = rate_x * capacity_pre
    sched = arrival_schedule(rng, rate, seconds, process)
    t_overload_wall = time.time()
    wall = drive_phase(sess, pool, sched, TENANTS, rng, deadline_ms,
                       outcomes, rung_samples)
    t_overload_end_wall = time.time()
    overload_n = len(outcomes) + 0   # marker index: overload arrivals
    overload_sched = len(sched)
    max_rung_mid = (sess._brownout.snapshot()["max_rung_seen"]
                    if sess._brownout else 0)

    # -- phase 3: cool-down tail (the brownout EXIT proof) ----------------
    tail_outcomes: list = []
    tail_sched = arrival_schedule(rng, 0.15 * capacity_pre, tail_s,
                                  "poisson")
    drive_phase(sess, pool, tail_sched, TENANTS, rng, deadline_ms * 4,
                tail_outcomes, rung_samples)
    try:
        sess.serve_drain(timeout=60.0)
    except Exception as ex:  # noqa: BLE001 — tallied as a failure
        print(f"# DRAIN FAILED: {ex!r}", file=sys.stderr)
    time.sleep(0.2)          # let the last done-callbacks land
    if slo:
        # let the fast burn window slide past the last bad event so
        # the CLEAR transition provably happens (the worker's idle
        # tick evaluates the monitors while the queue is empty);
        # goodput is not this mode's acceptance, so the post capacity
        # window is skipped and the denominator is the pre number
        time.sleep(slo_fast_s + 1.0)
        capacity_post = capacity_pre
    else:
        # post-phase capacity window: the goodput denominator is the
        # MIN of the bracketing measurements — on a small shared host
        # the closed-loop number drifts with scheduling noise, and a
        # pre-only denominator would let host slowdown masquerade as
        # congestion collapse (or mask a real one)
        capacity_post = measure_capacity(sess, pool, TENANTS, cal_n)
    capacity_qps = min(capacity_pre, capacity_post)
    snap = sess._brownout.snapshot() if sess._brownout else {}
    brownout_entered = snap.get("max_rung_seen", 0) >= 1
    brownout_exited = brownout_entered and snap.get("rung", 0) == 0

    # -- tally ------------------------------------------------------------
    wrong = untyped = 0
    per_tenant: dict = {t["name"]: {
        "weight": t["weight"], "arrivals": 0, "ok": 0, "sheds": 0,
        "deadline_misses": 0, "circuit": 0, "latencies": []}
        for t in TENANTS}
    for rec in outcomes:
        row = per_tenant[rec["tenant"]]
        row["arrivals"] += 1
        st = rec["status"]
        if st == "ok":
            row["ok"] += 1
            if rec["latency_ms"] is not None:
                row["latencies"].append(rec["latency_ms"])
            if not oracle_ok(rec.pop("result").to_numpy(),
                             rec["oracle"]):
                wrong += 1
        elif st == "shed":
            row["sheds"] += 1
        elif st == "deadline":
            row["deadline_misses"] += 1
        elif st == "circuit":
            row["circuit"] += 1
        elif st is None or st.startswith("untyped"):
            untyped += 1
    for rec in tail_outcomes:         # tail: correctness checked only
        if rec["status"] == "ok":
            if not oracle_ok(rec.pop("result").to_numpy(),
                             rec["oracle"]):
                wrong += 1
        elif (rec["status"] is None
              or str(rec["status"]).startswith("untyped")):
            untyped += 1

    tenant_rows: dict = {}
    p99_within_deadline = True
    for name, row in per_tenant.items():
        lat = sorted(row["latencies"])
        arr = row["arrivals"]
        missed = row["sheds"] + row["deadline_misses"] + row["circuit"]
        p99 = _pctile(lat, 0.99)
        if p99 is not None and p99 > deadline_ms * 1.05:
            p99_within_deadline = False
        tenant_rows[name] = {
            "weight": row["weight"],
            "arrivals": arr,
            "ok": row["ok"],
            "sheds": row["sheds"],
            "deadline_misses": row["deadline_misses"],
            "circuit_open": row["circuit"],
            "miss_rate": round(missed / arr, 4) if arr else None,
            "goodput_qps": round(row["ok"] / max(wall, 1e-9), 2),
            "p50_ms": _pctile(lat, 0.50),
            "p95_ms": _pctile(lat, 0.95),
            "p99_ms": p99,
        }
    total_ok = sum(r["ok"] for r in tenant_rows.values())
    goodput_qps = total_ok / max(wall, 1e-9)
    goodput_ratio = goodput_qps / max(capacity_qps, 1e-9)
    # Jain fairness over weight-normalised goodput: J = (Σx)²/(n·Σx²)
    xs = [r["goodput_qps"] / r["weight"] for r in tenant_rows.values()]
    jain = (sum(xs) ** 2 / (len(xs) * sum(x * x for x in xs))
            if any(xs) else 0.0)
    rung_census: dict = {}
    for r in rung_samples:
        rung_census[str(r)] = rung_census.get(str(r), 0) + 1
    miss_hi = tenant_rows["gold"]["miss_rate"] or 0.0
    miss_lo = tenant_rows["bronze"]["miss_rate"] or 0.0
    # compile-count plateau over the dashboard class: 6 dash_* pool
    # entries (+ their brownout-stamped "fast" twins) share one
    # structure each way, so at most 2 of the 12 first contacts pay
    # optimize/trace — every other lands as a template rebind. >= 5
    # hits proves the plateau held under the open-loop stream.
    mqo = sess.mqo_info()
    mqo_plateau = int(mqo.get("template_hits", 0)) >= 5

    if slo:
        # -- slo-mode verdict: alert fired during saturation, cleared
        # after, endpoint clean throughout, zero wrong answers -------------
        poller.stop()
        from matrel_tpu.obs.events import read_events, resolve_path
        plane = sess._slo.snapshot()
        al = [e for e in read_events(resolve_path(cfg.obs_event_log),
                                     kinds=("alert",))
              if (e.get("ts") or 0) >= t_session_start]
        fired = [e for e in al if e.get("state") == "firing"]
        # the violated tenant: bronze is weight-lowest — quota sheds,
        # rung-3 brownout sheds and deadline misses all land on it
        # first; its alert must fire DURING the overload phase (one
        # fast window of detection latency allowed)
        bronze_fired_in_window = any(
            e.get("tenant") == "bronze"
            and e.get("objective") == "avail"
            and (t_overload_wall - 1.0 <= (e.get("ts") or 0)
                 <= t_overload_end_wall + slo_fast_s + 1.0)
            for e in fired)
        last_state: dict = {}
        for e in al:
            last_state[(str(e.get("tenant")),
                        str(e.get("objective")))] = e.get("state")
        uncleared = sorted(f"{t}:{o}"
                           for (t, o), st in last_state.items()
                           if st == "firing")
        prom_ok = (poller.polls > 0 and poller.parse_failures == 0
                   and poller.errors == 0)
        record = {
            "metric": "traffic_slo_harness",
            "seed": seed,
            "process": process,
            "backend": jax.default_backend(),
            "slo_targets": cfg.slo_targets,
            "windows_s": [cfg.slo_fast_window_s,
                          cfg.slo_slow_window_s],
            "burn_threshold": cfg.slo_burn_threshold,
            "burn_exit": cfg.slo_burn_exit,
            "capacity_qps_closed_loop": round(capacity_qps, 2),
            "offered_qps": round(rate, 2),
            "arrivals": overload_sched,
            "alert_events": len(al),
            "alerts_fired": len(fired),
            "alerts_cleared": sum(1 for e in al
                                  if e.get("state") == "clear"),
            "fired_objectives": sorted(
                {f"{e.get('tenant')}:{e.get('objective')}"
                 for e in fired}),
            "violated_tenant_fired_in_window":
                bronze_fired_in_window,
            "uncleared": uncleared,
            "alerts_active_final": plane["alerts_active"],
            "tenants": {t: {"miss_rate": r["miss_rate"],
                            "arrivals": r["arrivals"],
                            "sheds": r["sheds"]}
                        for t, r in tenant_rows.items()},
            "prometheus": {"polls": poller.polls,
                           "parse_failures": poller.parse_failures,
                           "errors": poller.errors,
                           "last_error": poller.last_error,
                           "ok": prom_ok},
            "brownout": {"entered": brownout_entered,
                         "exited": brownout_exited,
                         "max_rung": snap.get("max_rung_seen", 0)},
            "wrong_answers": wrong,
            "untyped_errors": untyped,
        }
        record["ok"] = bool(
            bronze_fired_in_window
            and fired
            and not uncleared
            and plane["alerts_active"] == 0
            and prom_ok
            and wrong == 0
            and untyped == 0)
        print(json.dumps(record))
        return 0 if record["ok"] else 1

    record = {
        "metric": "traffic_overload_harness",
        "seed": seed,
        "process": process,
        "backend": jax.default_backend(),
        "warmup_s": round(warmup_s, 2),
        "capacity_qps_closed_loop": round(capacity_qps, 2),
        "capacity_qps_pre": round(capacity_pre, 2),
        "capacity_qps_post": round(capacity_post, 2),
        "offered_rate_x": rate_x,
        "offered_qps": round(rate, 2),
        "overload_seconds": round(wall, 2),
        "arrivals": overload_sched,
        "submitted": overload_n,
        "tenants": tenant_rows,
        "goodput_qps": round(goodput_qps, 2),
        "goodput_ratio": round(goodput_ratio, 3),
        "fairness_jain": round(jain, 4),
        "wrong_answers": wrong,
        "untyped_errors": untyped,
        "deadline_ms": deadline_ms,
        "p99_within_deadline": p99_within_deadline,
        "brownout": {"entered": brownout_entered,
                     "exited": brownout_exited,
                     "max_rung": snap.get("max_rung_seen", 0),
                     "max_rung_overload": max_rung_mid,
                     "final_rung": snap.get("rung"),
                     "rung_census": rung_census},
        "breakers": (sess._breakers.snapshot()
                     if sess._breakers else None),
        "queue": sess._serve._q.counters() if sess._serve else {},
        "mqo": {"templates": mqo.get("templates", 0),
                "template_hits": mqo.get("template_hits", 0),
                "template_inserts": mqo.get("template_inserts", 0),
                "plateau": mqo_plateau},
    }
    record["ok"] = bool(
        wrong == 0
        and untyped == 0
        and goodput_ratio >= goodput_min
        and p99_within_deadline
        and miss_hi < miss_lo
        and brownout_entered
        and brownout_exited
        and mqo_plateau
        and 0.0 < jain <= 1.0)
    print(json.dumps(record))
    return 0 if record["ok"] else 1


def main_slices() -> int:
    """--slices mode (docs/FLEET.md): the SAME open-loop machinery
    driven through a MULTI-SLICE fleet, with a mid-stream slice kill.
    The acceptance is the fleet plane's, not the overload plane's:

      - both slices serve traffic before the kill (placement spreads
        the stream) and the directory answers repeats from wherever
        placement lands them (>= 1 directory hit);
      - pool entries over unregistered operands PIN to the span path
        — both routings exercise under open-loop fire;
      - slice 0 is killed at the phase midpoint: the stream completes
        with ZERO wrong answers (every completed result checked
        against its numpy oracle) and only TYPED failures, queued
        entries re-admitted with deadlines/tenants intact.

    One parseable ``traffic_fleet_harness`` JSON artifact (staged in
    tpu_batch.sh; asserted by test_batch_dry)."""
    from matrel_tpu.config import MatrelConfig
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.resilience import faults
    from matrel_tpu.session import MatrelSession

    seed = int(os.environ.get("MATREL_TRAFFIC_SEED", "0"))
    seconds = _env_f("MATREL_TRAFFIC_SECONDS", 8.0)
    rate_x = _env_f("MATREL_TRAFFIC_RATE_X", 2.0)
    cal_n = int(_env_f("MATREL_TRAFFIC_CAL", 300))
    deadline_ms = _env_f("MATREL_TRAFFIC_DEADLINE_MS", 500.0)
    n_slices = int(_env_f("MATREL_TRAFFIC_SLICES", 2))
    process = os.environ.get("MATREL_TRAFFIC_PROCESS", "poisson")
    faults.reset()
    cfg = MatrelConfig.from_env(MatrelConfig(
        fleet_slices=n_slices,
        result_cache_max_bytes=1 << 28,
        serve_max_batch=1,       # the CPU-host admission discipline
        serve_queue_max=96,      # (see main()'s rationale)
        plan_cache_max_plans=256,
    ))
    mesh = mesh_lib.make_mesh((2, 4))
    sess = MatrelSession(mesh=mesh, config=cfg)
    rng = np.random.default_rng(seed)
    pool = build_pool(sess, rng, register=True)
    # prewarm: builds the fleet (replicating the registered tables),
    # compiles each pool entry once per routing
    for _name, expr, _o in pool:
        sess.submit(expr).result(timeout=120)
    sess.serve_drain(timeout=60)
    capacity = measure_capacity(sess, pool, TENANTS, cal_n,
                                windows=1)
    rate = rate_x * capacity
    outcomes: list = []
    rungs: list = []
    half = max(seconds / 2.0, 0.5)
    wall = drive_phase(sess, pool,
                       arrival_schedule(rng, rate, half, process),
                       TENANTS, rng, deadline_ms, outcomes, rungs)
    placed_before = {sl["id"]: sl["submitted"]
                     for sl in sess.fleet_info()["slices"]}
    requeued = sess._fleet.kill_slice(0, reason="traffic_drill")
    wall += drive_phase(sess, pool,
                        arrival_schedule(rng, rate, half, process),
                        TENANTS, rng, deadline_ms, outcomes, rungs)
    try:
        sess.serve_drain(timeout=60.0)
    except Exception as ex:  # noqa: BLE001 — tallied below, typed
        print(f"# DRAIN FAILED: {ex!r}", file=sys.stderr)
    time.sleep(0.2)          # let the last done-callbacks land
    ok_n = wrong = untyped = sheds = deadlines = typed = 0
    for rec in outcomes:
        st = rec["status"]
        if st == "ok":
            if oracle_ok(rec.pop("result").to_numpy(),
                         rec["oracle"]):
                ok_n += 1
            else:
                wrong += 1
        elif st == "shed":
            sheds += 1
        elif st == "deadline":
            deadlines += 1
        elif st in ("circuit", "typed"):
            typed += 1
        elif st is None or str(st).startswith("untyped"):
            untyped += 1
    info = sess.fleet_info()
    record = {
        "metric": "traffic_fleet_harness",
        "seed": seed,
        "process": process,
        "backend": jax.default_backend(),
        "slices": n_slices,
        "capacity_qps_closed_loop": round(capacity, 2),
        "offered_qps": round(rate, 2),
        "overload_seconds": round(wall, 2),
        "submitted": len(outcomes),
        "ok": None,           # verdict filled below
        "completed": ok_n,
        "wrong_answers": wrong,
        "untyped_errors": untyped,
        "sheds": sheds,
        "deadline_misses": deadlines,
        "other_typed": typed,
        "goodput_qps": round(ok_n / max(wall, 1e-9), 2),
        "placed": info["placed"],
        "pinned": info["pinned"],
        "directory": info["directory"],
        "failovers": info["failovers"],
        "requeued_on_kill": requeued,
        "slices_served_before_kill": sorted(
            sid for sid, n in placed_before.items() if n > 0),
        "slice_state": [{"id": sl["id"], "alive": sl["alive"],
                         "submitted": sl["submitted"]}
                        for sl in info["slices"]],
    }
    record["ok"] = bool(
        wrong == 0
        and untyped == 0
        and ok_n > 0
        and info["failovers"] == 1
        and len(record["slices_served_before_kill"]) >= 2
        and info["directory"]["hits"] >= 1
        and info["placed"]["slice"] > 0
        and info["placed"]["span"] > 0)
    print(json.dumps(record))
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    if "--slices" in sys.argv[1:]:
        sys.exit(main_slices())
    sys.exit(main(slo="--slo" in sys.argv[1:]))
