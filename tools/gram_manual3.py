"""Manual hi/lo bf16 3-pass Gram vs XLA precision=HIGH, on chip.

MATREL_GRAM3_{K,PANEL,NPANELS} scale it down for the dry-batch
fire-drill (tools/tpu_batch.sh --dry) — same jits, same artifact."""
import os
import time, json
import jax, jax.numpy as jnp
import numpy as np

k = int(os.environ.get("MATREL_GRAM3_K", 1000))
panel = int(os.environ.get("MATREL_GRAM3_PANEL", 250_000))
n_panels = int(os.environ.get("MATREL_GRAM3_NPANELS", 40))

def timed(f, *a):
    float(f(*a))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter(); float(f(*a)); ts.append(time.perf_counter()-t0)
    return sorted(ts)[1]

rng = np.random.default_rng(0)
x = jnp.asarray(rng.random((panel, k)), jnp.float32)

@jax.jit
def xla_high(x):
    def body(p, g):
        xp = x + g[0, 0] * 0
        return g + jnp.einsum("nk,nj->kj", xp, xp,
                              precision=jax.lax.Precision.HIGH,
                              preferred_element_type=jnp.float32)
    return jnp.sum(jax.lax.fori_loop(0, n_panels, body,
                                     jnp.zeros((k, k), jnp.float32)))

@jax.jit
def manual3(x):
    def body(p, g):
        xp = x + g[0, 0] * 0
        hi = xp.astype(jnp.bfloat16)
        lo = (xp - hi.astype(jnp.float32)).astype(jnp.bfloat16)
        d = lambda a, b: jnp.einsum("nk,nj->kj", a, b,
                                    preferred_element_type=jnp.float32)
        hihi = d(hi, hi)
        hilo = d(hi, lo)
        return g + (hihi + (hilo + hilo.T))
    return jnp.sum(jax.lax.fori_loop(0, n_panels, body,
                                     jnp.zeros((k, k), jnp.float32)))

@jax.jit
def single_bf16(x):
    def body(p, g):
        xp = (x + g[0, 0] * 0).astype(jnp.bfloat16)
        return g + jnp.einsum("nk,nj->kj", xp, xp,
                              preferred_element_type=jnp.float32)
    return jnp.sum(jax.lax.fori_loop(0, n_panels, body,
                                     jnp.zeros((k, k), jnp.float32)))

res = {
    "xla_high_s": round(timed(xla_high, x), 4),
    "manual3_sym_s": round(timed(manual3, x), 4),
    "single_bf16_s": round(timed(single_bf16, x), 4),
}
# numeric sanity: manual symmetric 3-pass must match XLA HIGH closely
g1 = float(xla_high(x)); g2 = float(manual3(x))
res["rel_diff_vs_high"] = abs(g1 - g2) / abs(g1)
print(json.dumps(res))
