"""Routed SpMV: sparse matvec as pure MXU matmuls — no gather engine.

The one-hot SpMV plan (ops/spmv.py) is scatter-free but still pays the
TPU gather engine ~2 ns per edge slot for the x-row fetch; at BASELINE
row-5 scale that gather is ~21 ms of the ~30 ms round (measured
2026-07-30: gather+select 26.9 ms, one-hot scatter 3.0 ms). Locality and
dtype do not move it — the engine is rate-limited per index. This module
removes the gather entirely by reshaping SpMV into the two dense
contractions the MXU executes well, the same way the reference reshapes
its matvec into shuffle + per-block kernels (SURVEY.md §3.5).

**Measured outcome (v5e, 1M nodes / 10M edges): 52 ms vs 29 ms for the
gather-based plan — the routed path does NOT win on this hardware.** The
kernels are matmul-light but must GENERATE four ~(slots, 128) one-hot/
mask tensors per matvec on the VPU (~2.7 ns/slot at ~5 vector ops per
lane), which costs as much as the gather engine it replaces; `passes`=2
vs 3 timing is identical, confirming mask generation, not MXU work, is
the bound. Lane padding makes narrower masks free-of-charge impossible
(<128-wide vectors occupy full lanes). The module is kept as a correct,
tested reference formulation: it is the shape a multi-chip all_to_all
SpMV takes (phase 2's layout transpose IS the shuffle), and the
trade-off flips wherever index-gather is slower relative to VPU/MXU
than on v5e. Algorithm:

* Edges are bucketed by (source group, destination group), both groups
  ``span = 128·128`` wide, with a fixed per-cell capacity (large cells →
  tiny padding: Poisson concentration gives ~1.1× at 10M edges).

* **Phase 1 — gather as matmul.** For cell (gs, gd), each edge's source
  offset inside its group factors as ``a·128 + b``. With x's group
  reshaped to a (128, 128) tile X2, ``x[src] = Σ_a oh_a · X2[a, b]``:
  one (cap, 128) one-hot GENERATED IN VMEM (never stored to HBM)
  contracts against X2 on the MXU, and a cheap VPU one-hot select reads
  lane b. f32 accuracy from bf16 passes: X2 ships as [hi | lo] bf16
  halves (hi = bf16(x), lo = bf16(x − hi)) in one 256-wide matmul —
  exact because one-hot rows have a single 1.

* **Phase 2 — the shuffle is a BlockSpec.** Phase 1 writes per-edge
  products W in (gs, gd, cap) source-major layout; phase 3 simply reads
  block (gs, gd) via its index map while iterating destination-major.
  The layout transpose (Spark's shuffle; all_to_all on a mesh) costs one
  11 KB DMA per cell — there is no transpose pass at all.

* **Phase 3 — scatter as matmul.** Destination offsets factor as
  ``c·128 + d``; the cell's contribution to its destination group's
  (128, 128) accumulator tile is ``oh_cᵀ @ (oh_d ⊙ w)`` — one MXU
  contraction over the cell's slots, accumulated in VMEM scratch across
  all source groups, flushed once per destination group. w rides as
  [hi | lo] bf16 halves for f32 accuracy.

Everything static-shaped per plan; overflow edges beyond cell capacity
go to a small COO handled by segment_sum (same contract as
ops/spmv.py). Build returns None when padding would blow past
``max_padding`` so callers can fall back.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax

from matrel_tpu.utils import compat
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

SPAN = 128 * 128          # source/destination group width (a, b each 128)
LANE = 128


@dataclasses.dataclass
class RoutedSpMVPlan:
    """Compiled routed layout for ``y[i] = Σ_{e: rows[e]=i} vals[e]·x[cols[e]]``.

    Tables are (G_s, G_d, cap//128, 128) in source-major order (the
    trailing two dims are the cell's slots in TPU tile layout);
    ``loc_src``/``loc_dst`` hold offsets inside the edge's source/
    destination group (< SPAN, packed a·128+b), ``val`` is 0 in padded
    slots so they contribute nothing in either phase.
    """
    n_rows: int
    n_cols: int
    g_src: int
    g_dst: int
    cap: int
    loc_src: "np.ndarray | jax.Array"   # (G_s, G_d, cap/128, 128) int32
    loc_dst: "np.ndarray | jax.Array"   # (G_s, G_d, cap/128, 128) int32
    val: "np.ndarray | jax.Array"       # (G_s, G_d, cap/128, 128) f32
    ov_rows: Optional[jax.Array]        # overflow COO (dst-sorted)
    ov_cols: Optional[jax.Array]
    ov_vals: Optional[jax.Array]
    padding_ratio: float
    _dev: Optional[tuple] = dataclasses.field(default=None, repr=False)

    @property
    def slots(self) -> int:
        return self.g_src * self.g_dst * self.cap

    def arrays(self):
        """Device-array tuple for jit boundaries (placed on first use).
        The tables are host numpy from the build, so jnp.asarray yields
        concrete constants even inside an outer trace — safe to cache."""
        ov = () if self.ov_rows is None else (self.ov_rows, self.ov_cols,
                                              self.ov_vals)
        if self._dev is None:
            self._dev = (jnp.asarray(self.loc_src),
                         jnp.asarray(self.loc_dst), jnp.asarray(self.val))
            self.loc_src = self.loc_dst = self.val = None
        return self._dev + ov


def build_routed_plan(rows, cols, vals=None, n_rows: int = None,
                      n_cols: int = None, *,
                      capacity_quantile: float = 0.997,
                      max_padding: float = 3.0,
                      max_slots: Optional[int] = None,
                      max_cap: int = 4096
                      ) -> Optional[RoutedSpMVPlan]:
    """Host-side plan build (numpy, once per graph).

    Cell capacity is the ``capacity_quantile`` of per-cell edge counts
    rounded up to a multiple of 128 (the matmul row dim); edges past it
    go to the overflow COO. Returns None when the padded slot count
    exceeds ``max_padding``× the edge count (sparse cells — small or
    very skewed graphs are better served by ops/spmv.py), ``max_slots``,
    or when capacity exceeds ``max_cap`` — the kernels keep ~(cap, 128)
    one-hot and (cap, 128·passes) contraction buffers in VMEM (~16 MB),
    so edge-dense cells must fall back rather than fail at Mosaic
    compile time.
    """
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    m = rows.shape[0]
    if n_rows is None:
        n_rows = int(rows.max()) + 1 if m else 1
    if n_cols is None:
        n_cols = int(cols.max()) + 1 if m else 1
    if m and (rows.min() < 0 or rows.max() >= n_rows
              or cols.min() < 0 or cols.max() >= n_cols):
        raise ValueError("edge indices out of bounds for "
                         f"({n_rows}, {n_cols})")
    if vals is None:
        vals = np.ones((m,), np.float32)
    else:
        vals = np.asarray(vals, dtype=np.float32)

    g_s = max(1, -(-n_cols // SPAN))
    g_d = max(1, -(-n_rows // SPAN))
    n_cells = g_s * g_d
    cell = (cols // SPAN) * g_d + rows // SPAN
    cnt = np.bincount(cell, minlength=n_cells)
    if m == 0:
        cap = LANE
    else:
        pos = cnt[cnt > 0]
        cap_q = int(np.quantile(pos, capacity_quantile)) if pos.size else 0
        cap = max(LANE, -(-cap_q // LANE) * LANE)
    if cap > max_cap:
        return None
    if m and n_cells * cap > max_padding * m:
        return None
    if max_slots is not None and n_cells * cap > max_slots:
        return None

    order = np.argsort(cell, kind="stable")
    cell_s = cell[order]
    starts = np.zeros(n_cells + 1, np.int64)
    np.cumsum(cnt, out=starts[1:])
    slot = np.arange(m, dtype=np.int64) - starts[cell_s]
    in_main = slot < cap

    loc_src = np.zeros((n_cells, cap), np.int32)
    loc_dst = np.zeros((n_cells, cap), np.int32)
    val_t = np.zeros((n_cells, cap), np.float32)
    cm, sm = cell_s[in_main], slot[in_main]
    rows_s, cols_s, vals_s = rows[order], cols[order], vals[order]
    loc_src[cm, sm] = (cols_s % SPAN)[in_main]
    loc_dst[cm, sm] = (rows_s % SPAN)[in_main]
    val_t[cm, sm] = vals_s[in_main]

    n_ov = int(np.count_nonzero(~in_main))
    if n_ov:
        ov_r, ov_c, ov_v = (rows_s[~in_main], cols_s[~in_main],
                            vals_s[~in_main])
        o = np.argsort(ov_r, kind="stable")
        ov = (jnp.asarray(ov_r[o], jnp.int32),
              jnp.asarray(ov_c[o], jnp.int32),
              jnp.asarray(ov_v[o], jnp.float32))
    else:
        ov = (None, None, None)

    shp = (g_s, g_d, cap // LANE, LANE)   # TPU tile layout (see kernels)
    return RoutedSpMVPlan(
        n_rows=n_rows, n_cols=n_cols, g_src=g_s, g_dst=g_d, cap=cap,
        loc_src=loc_src.reshape(shp), loc_dst=loc_dst.reshape(shp),
        val=val_t.reshape(shp),
        ov_rows=ov[0], ov_cols=ov[1], ov_vals=ov[2],
        padding_ratio=(n_cells * cap + n_ov) / max(m, 1))


# -- kernels -----------------------------------------------------------------


def _bf16_split(v, passes: int):
    """Residual bf16 decomposition: Σ parts ≈ v with error ~2^(-8·passes).
    The one-hot factor of each routed matmul is exact in bf16, so the
    split of the VALUE side is the only precision knob.

    Parts are carved by MASKING the low mantissa bits (truncation toward
    zero), not by dtype casts, and returned as f32 arrays whose values
    sit exactly on the bf16 grid (a later astype(bf16) is lossless).
    Two reasons: pallas interpret mode ELIDES bf16 rounding on casts
    (measured 2026-07-30: astype(bf16).astype(f32) round-trips unrounded
    inside a kernel), which silently collapsed a cast-based split to its
    first term; and Mosaic only supports minor-dim-inserting broadcasts
    for 32-bit types, so downstream masking must happen in f32 anyway."""
    parts = []
    rem = v
    for _ in range(passes):
        bits = jax.lax.bitcast_convert_type(rem, jnp.uint32)
        hi = jax.lax.bitcast_convert_type(
            bits & jnp.uint32(0xFFFF0000), jnp.float32)
        parts.append(hi)                        # f32, on the bf16 grid
        rem = rem - hi
    return parts


def _make_gather_kernel(passes: int):
    def _gather_kernel(loc_ref, val_ref, x_ref, w_ref):
        """Phase 1, one cell: w = x[src] · val via one-hot matmul.

        Slot tables arrive as (cap_r, 128) tiles (TPU block layout: the
        last two dims must tile (8, 128) or equal the array's); the
        one-hot is built 3D and contracted with a single dot, no
        in-kernel reshapes. x_ref block is this source group's
        (128, 128·passes) bf16 tile of residual splits; summing the
        split lanes reconstructs f32(x) to ~2^(-8·passes).
        """
        loc = loc_ref[0, 0]                            # (cap_r, 128)
        cap_r = loc.shape[0]
        ids3 = jax.lax.broadcasted_iota(
            jnp.int32, (cap_r, LANE, LANE), 2)
        oh_a = ((loc // LANE)[:, :, None] == ids3).astype(jnp.bfloat16)
        g = jax.lax.dot_general(
            oh_a, x_ref[0],
            (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # (cap_r, 128, 128·passes)
        ghl = g[..., :LANE]
        for p in range(1, passes):
            ghl = ghl + g[..., p * LANE:(p + 1) * LANE]
        sel = jnp.where((loc % LANE)[:, :, None] == ids3, ghl, 0.0)
        w_ref[0, 0] = jnp.sum(sel, axis=2) * val_ref[0, 0]

    return _gather_kernel


def _make_scatter_kernel(g_src: int, passes: int):
    def _scatter_kernel(loc_ref, w_ref, y_ref, acc_ref):
        """Phase 3, one cell: acc += oh_cᵀ @ (oh_d ⊙ [w splits]) — a
        double contraction over both slot dims of the (cap_r, 128)
        tile."""
        gs = pl.program_id(1)
        loc = loc_ref[0, 0]                            # (cap_r, 128)
        w = w_ref[0, 0]
        cap_r = loc.shape[0]
        ids3 = jax.lax.broadcasted_iota(
            jnp.int32, (cap_r, LANE, LANE), 2)
        oh_c = ((loc // LANE)[:, :, None] == ids3).astype(jnp.bfloat16)
        mask = (loc % LANE)[:, :, None] == ids3
        rhs = jnp.concatenate(
            [jnp.where(mask, wp[:, :, None], 0.0)
             for wp in _bf16_split(w, passes)],
            axis=2).astype(jnp.bfloat16)       # lossless: bf16-grid values
        # Mosaic's matmul takes exactly one contracting dim per side:
        # collapse the (cap_r, 128) slot dims (contiguous merge) and
        # contract over dim 0 of both operands
        t = jax.lax.dot_general(
            oh_c.reshape(cap_r * LANE, LANE),
            rhs.reshape(cap_r * LANE, passes * LANE),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (128, 128·passes)

        @pl.when(gs == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        th = t[:, :LANE]
        for p in range(1, passes):
            th = th + t[:, p * LANE:(p + 1) * LANE]
        acc_ref[:] += th

        @pl.when(gs == g_src - 1)
        def _flush():
            y_ref[0] = acc_ref[:]

    return _scatter_kernel


@functools.lru_cache(maxsize=32)
def _routed_runner(g_s: int, g_d: int, cap: int, passes: int,
                   interpret: bool):
    """pallas_call pair bound to a plan's static shape. Tables are
    (G_s, G_d, cap//128, 128)."""
    cap_r = cap // LANE
    cell = (1, 1, cap_r, LANE)

    gather = pl.pallas_call(  # matlint: disable=ML009 legacy routed-SpMV reference kernel, unported to the registry this round (kept as a reference formulation)
        _make_gather_kernel(passes),
        grid=(g_s, g_d),
        in_specs=[
            pl.BlockSpec(cell, lambda gs, gd: (gs, gd, 0, 0)),
            pl.BlockSpec(cell, lambda gs, gd: (gs, gd, 0, 0)),
            pl.BlockSpec((1, LANE, passes * LANE), lambda gs, gd: (gs, 0, 0)),
        ],
        out_specs=pl.BlockSpec(cell, lambda gs, gd: (gs, gd, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((g_s, g_d, cap_r, LANE),
                                       jnp.float32),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )
    # destination-major iteration; the (gs, gd) index maps read the
    # source-major tables directly — the shuffle is this index map
    scatter = pl.pallas_call(  # matlint: disable=ML009 legacy routed-SpMV reference kernel, unported to the registry this round (kept as a reference formulation)
        _make_scatter_kernel(g_s, passes),
        grid=(g_d, g_s),
        in_specs=[
            pl.BlockSpec(cell, lambda gd, gs: (gs, gd, 0, 0)),
            pl.BlockSpec(cell, lambda gd, gs: (gs, gd, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, LANE, LANE), lambda gd, gs: (gd, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((g_d, LANE, LANE), jnp.float32),
        scratch_shapes=[pltpu.VMEM((LANE, LANE), jnp.float32)],
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )
    return gather, scatter


def routed_apply(plan_static, arrays, x: jax.Array, passes: int = 2,
                 interpret: bool = False) -> jax.Array:
    """Traceable body: y = A·x. ``plan_static`` is (n_rows, n_cols, g_s,
    g_d, cap); ``arrays`` is plan.arrays(). Safe inside jit/fori_loop.

    ``passes`` sets the bf16 residual-split depth on both value sides:
    2 → ~2^-16 relative error (default), 3 → f32-faithful (~2^-24).
    """
    n_rows, n_cols, g_s, g_d, cap = plan_static
    loc_src, loc_dst, val = arrays[:3]
    gather, scatter = _routed_runner(g_s, g_d, cap, passes, interpret)

    xf = x.astype(jnp.float32)
    xp = jnp.pad(xf, (0, g_s * SPAN - n_cols))
    x2 = jnp.concatenate(
        [p.reshape(g_s, LANE, LANE) for p in _bf16_split(xp, passes)],
        axis=-1).astype(jnp.bfloat16)          # lossless: bf16-grid values

    w = gather(loc_src, val, x2)
    y = scatter(loc_dst, w).reshape(-1)[:n_rows]
    if len(arrays) > 3:
        ov_r, ov_c, ov_v = arrays[3:]
        from matrel_tpu.ops.spmv import gather_1d
        w_ov = gather_1d(xf, ov_c) * ov_v
        y = y + jax.ops.segment_sum(w_ov, ov_r, num_segments=n_rows,
                                    indices_are_sorted=True)
    return y


_routed_jitted = jax.jit(routed_apply, static_argnums=(0, 3, 4))  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)


def routed_spmv(plan: RoutedSpMVPlan, x: jax.Array, passes: int = 2,
                interpret: bool = False) -> jax.Array:
    """y = A·x (convenience wrapper; jit-cached per plan shape)."""
    static = (plan.n_rows, plan.n_cols, plan.g_src, plan.g_dst, plan.cap)
    return _routed_jitted(static, plan.arrays(), x, passes, interpret)
