"""Dispatchable sparse-kernel registry — the ONE seam for SpGEMM
kernels (ROADMAP item 5; JITSPMM, arXiv:2312.05639).

The engine's S×S multiply used to make exactly one hardcoded choice:
XLA gather/segment-sum vs the single scalar-prefetch Pallas kernel,
gated by ``config.spgemm_density_threshold``. This module replaces that
two-way branch with a REGISTRY of kernels, each declaring the sparsity
STRUCTURE classes it is specialized for (ir/stats classifiers over the
block edge lists), so that

* the planner can stamp a ``spgemm_kernel`` choice from cost estimates,
* the round-4 autotuner can MEASURE registered variants per
  (shape class, structure class, backend) and persist winners exactly
  like matmul strategies (``spgemm|<class>|<structure>|...`` keys),
* MV110 can statically verify every stamped kernel id is in-registry
  and admissible for the stamped structure class, and
* future GPU/multi-backend kernels land HERE, not in a new branch
  (the matlint ML009 "one seam" rule keeps it that way).

Registered vocabulary (every kernel computes the exact same tile-stack
product; variants differ only in schedule, so any of them is
correctness-preserving on any structure):

  xla_gather       gather + batched tile GEMM + segment_sum (XLA; the
                   legacy fallback, admissible everywhere)
  pallas_generic   the original scalar-prefetch kernel, one pair per
                   grid step (the behavior-preserving Pallas default)
  pallas_band      row_band home: pair runs are short and uniform, so
                   pairs are pre-gathered at BUILD time into a
                   CONTIGUOUS grouped table (sequential DMA, no
                   per-pair prefetch indirection) and each grid step
                   retires G pairs as ONE (bs, G·bs)x(G·bs, bs) MXU
                   contraction — G× fewer grid steps
  pallas_cluster   clustered_tile home: same grouped schedule with a
                   LARGER accumulate group over the cluster's long
                   slot runs (bigger VMEM working set, fewer flushes)
  pallas_powerlaw  powerlaw_coo home: output rows BUCKETED by pair
                   count — light rows run a small group, hub rows a
                   large one — so the MXU is never padded to the
                   heaviest row's run length

Selection order (``select_kernel``): config override (the soak/degrade
forcing knob) > measured autotune winner (``config.autotune``) >
registry cost model (a specialized kernel is nominated ONLY on its
home structure class; on "generic" the legacy choice stands
bit-identically) > legacy default.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from matrel_tpu.config import MatrelConfig, default_config, pallas_enabled

# -- registry ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """One registered SpGEMM kernel.

    ``structures`` are the HOME classes the registry's cost model
    nominates it for; ``universal`` marks the legacy entries admissible
    on every class. ``group`` is the pair-group factor G of the grouped
    schedule (0 = XLA path, 1 = one pair per step); ``bucket_split``
    (powerlaw only) is the run length at which an output row moves
    from the light bucket to the heavy one."""

    kernel_id: str
    structures: Tuple[str, ...]
    needs_pallas: bool
    group: int
    description: str
    universal: bool = False
    bucket_split: int = 0


REGISTRY: Dict[str, KernelSpec] = {}

#: Test/obs hook: how many kernel selections ran. The bit-identity
#: contract says ZERO when ``spgemm_density_threshold = 0`` (nothing
#: dispatches, so nothing may consult the registry).
_LOOKUPS = {"count": 0}

#: VMEM budget the grouped variants may spend on ONE (a, b) block pair
#: (double-buffered by Mosaic); bounds G at big block sizes so a
#: bs=512 group never blows the 16 MiB core budget.
VMEM_PAIR_BUDGET_BYTES = 8 * 1024 * 1024


def register_kernel(spec: KernelSpec) -> None:
    REGISTRY[spec.kernel_id] = spec


def kernel_ids() -> Tuple[str, ...]:
    return tuple(REGISTRY)


def get_kernel(kernel_id: str) -> KernelSpec:
    return REGISTRY[kernel_id]


def grouped_factor(bs: int, requested: int) -> int:
    """Effective pair-group G for a grouped variant at this block size:
    the requested factor clamped so a double-buffered (bs, G·bs) +
    (G·bs, bs) f32 block pair fits VMEM_PAIR_BUDGET_BYTES."""
    cap = int(VMEM_PAIR_BUDGET_BYTES // max(2 * bs * bs * 4, 1))
    return max(1, min(requested, cap))


def _pallas_eligible(bs: int, npairs: int) -> bool:
    """ops/spgemm.py's 8-sublane eligibility rule — lazily imported so
    there is exactly ONE copy (the soak-seed-50114 class of fix must
    never have to land in two places)."""
    from matrel_tpu.ops import spgemm as spgemm_lib
    return spgemm_lib.pallas_eligible(bs, npairs)


def admissible(kernel_id: str, bs: int, npairs: int,
               config: Optional[MatrelConfig] = None) -> bool:
    """Can this kernel RUN for a (bs, npairs) SpGEMM under this config?
    Pallas entries need the pallas gate (real TPU or interpret mode)
    and the 8-sublane block rule (the pallas_spmm lesson, soak seed
    50114); grouped entries additionally need a VMEM-feasible G >= 2
    (G == 1 would be the generic schedule with extra padding)."""
    spec = REGISTRY.get(kernel_id)
    if spec is None:
        return False
    cfg = config or default_config()
    if spec.needs_pallas:
        if not pallas_enabled(cfg):
            return False
        if not _pallas_eligible(bs, npairs):
            return False
        if spec.group > 1 and grouped_factor(bs, spec.group) < 2:
            return False
    return True


def legacy_default(bs: int, npairs: int,
                   config: Optional[MatrelConfig] = None) -> str:
    """EXACTLY the pre-registry two-way choice: the scalar-prefetch
    Pallas kernel where eligible, the XLA gather path otherwise — the
    bit-identity anchor for the default config."""
    cfg = config or default_config()
    if pallas_enabled(cfg) and _pallas_eligible(bs, npairs):
        return "pallas_generic"
    return "xla_gather"


def select_kernel(structure: str, bs: int, npairs: int,
                  config: Optional[MatrelConfig] = None,
                  side: Optional[int] = None,
                  mesh=None) -> Tuple[str, str]:
    """(kernel_id, source) for one SpGEMM. ``source`` records WHY (the
    choose_strategy_ex contract): "override" (config forcing knob —
    soak batteries and the degradation ladder), "measured" (autotune
    table winner for this (shape, structure, backend) class — the
    MV106 measured-stamp precedent), "model" (a specialized kernel on
    its home structure class), "default" (the legacy two-way choice,
    bit-identical to the pre-registry engine)."""
    cfg = config or default_config()
    _LOOKUPS["count"] += 1
    ov = cfg.spgemm_kernel_override
    if ov:
        if ov not in REGISTRY:
            raise ValueError(
                f"spgemm_kernel_override {ov!r} is not a registered "
                f"kernel (have {kernel_ids()})")
        if admissible(ov, bs, npairs, cfg):
            return ov, "override"
        return legacy_default(bs, npairs, cfg), "default"
    if cfg.autotune and mesh is not None and side:
        from matrel_tpu.parallel import autotune
        best = autotune.lookup_or_measure_spgemm(side, structure, bs,
                                                 mesh, cfg)
        if best is not None and admissible(best, bs, npairs, cfg):
            return best, "measured"
    for kid, spec in REGISTRY.items():
        if (not spec.universal and structure in spec.structures
                and admissible(kid, bs, npairs, cfg)):
            return kid, "model"
    return legacy_default(bs, npairs, cfg), "default"


# -- fused epilogue hooks (whole-plan fusion, docs/FUSION.md) ---------------
# The ``apply_dense``-style epilogue seam: when a fused region absorbs a
# consumer chain into its producer SpGEMM (ir/fusion.py), the chain
# reaches the kernel HERE — per structure class, WITHOUT forking kernel
# bodies. Each hook names how the epilogue is applied to the kernel's
# output:
#
#   "tilewise"  the epilogue runs over the [n_out, bs, bs] OUTPUT TILE
#               STACK before the dense scatter — nnzb·bs² elements
#               instead of n·m. Only legal for zero-preserving,
#               shape-polymorphic chains (scalar mul / pow>0 — the
#               executor's epilogue_elementwise flag proves it); the
#               untouched tiles stay exact zeros so the scatter's
#               padded region is still exact.
#   "dense"     the epilogue runs over the scattered padded dense
#               output (always legal; the conservative default).
#
# Registering a specialized mode for a new structure class is one
# ``register_epilogue_hook`` call — the ML009 "one seam" discipline
# extended to epilogues (MV111 verifies the stamps that route here).

EPILOGUE_MODES = ("tilewise", "dense")

_EPILOGUE_HOOKS: Dict[str, str] = {}


def register_epilogue_hook(structure: str, mode: str) -> None:
    if mode not in EPILOGUE_MODES:
        raise ValueError(
            f"epilogue mode must be one of {EPILOGUE_MODES}, "
            f"got {mode!r}")
    _EPILOGUE_HOOKS[structure] = mode


def epilogue_mode(structure: str, elementwise_ok: bool) -> str:
    """The application mode for one fused SpGEMM epilogue: the
    structure class's registered hook, demoted to "dense" whenever the
    chain is not provably zero-preserving shape-polymorphic
    (``elementwise_ok`` False) — correctness never rides the
    registration."""
    if not elementwise_ok:
        return "dense"
    return _EPILOGUE_HOOKS.get(structure, "dense")


def apply_tile_epilogue(tiles, epilogue):
    """Run a zero-preserving pointwise epilogue over the output tile
    stack (the "tilewise" hook body — one place, every kernel)."""
    return epilogue(tiles)


# -- structure classification (memoised per operand) ------------------------


def structure_of_matrix(S) -> str:
    """Structure class of one BlockSparseMatrix, memoised on the matrix
    (its tile lists are immutable — the pair_structure cache idiom)."""
    memo = getattr(S, "_structure_memo", None)
    if memo is not None:
        return memo
    from matrel_tpu.ir import stats
    gr, gc = S.grid
    cls = stats.classify_block_structure(np.asarray(S.block_rows),
                                         np.asarray(S.block_cols),
                                         gr, gc)
    S._structure_memo = cls
    return cls


def structure_of_child(child, bs: int) -> str:
    """Structure class of an S×S matmul OPERAND node (sparse_leaf or
    coo_leaf). COO leaves are classified at the dispatch block size
    from their bucketed tile keys — one O(nnz) numpy pass, memoised
    per block size (the _block_density_memo idiom)."""
    m = child.attrs["matrix"]
    if child.kind == "sparse_leaf":
        return structure_of_matrix(m)
    memo = getattr(m, "_structure_memo", None)
    if memo is not None and memo[0] == bs:
        return memo[1]
    from matrel_tpu.ir import stats
    gr = math.ceil(m.shape[0] / bs)
    gc = math.ceil(m.shape[1] / bs)
    keys = np.unique((np.asarray(m.rows, np.int64) // bs) * gc
                     + np.asarray(m.cols, np.int64) // bs)
    cls = stats.classify_block_structure(keys // gc, keys % gc, gr, gc)
    m._structure_memo = (bs, cls)
    return cls


def pair_class_of(A, B) -> str:
    """Structure class of a BlockSparseMatrix operand pair (the
    ops-level entry; the expr-level one is
    executor.spgemm_kernel_choice)."""
    from matrel_tpu.ir import stats
    return stats.pair_structure_class(structure_of_matrix(A),
                                      structure_of_matrix(B))


# -- kernel implementations -------------------------------------------------
# Every builder returns ``run(a_blocks, b_blocks, slots, pa, pb) ->
# [n_out, bs, bs] tile stack`` — the uniform contract ops/spgemm.py's
# runner cache dispatches through.


def _make_pair_kernel(precision, npairs):
    """The original scalar-prefetch kernel: one (A tile, B tile) pair
    per grid step, f32 VMEM accumulate, one flush per slot run."""
    from jax.experimental import pallas as pl

    def kern(slots, pa, pb, a_ref, b_ref, out_ref, acc_ref):
        i = pl.program_id(0)
        s = slots[i]
        first = jnp.logical_or(i == 0,
                               slots[jnp.maximum(i - 1, 0)] != s)
        last = jnp.logical_or(
            i == npairs - 1, slots[jnp.minimum(i + 1, npairs - 1)] != s)

        @pl.when(first)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        acc_ref[:] += jax.lax.dot(
            a_ref[0], b_ref[0], precision=precision,
            preferred_element_type=jnp.float32)

        @pl.when(last)
        def _flush():
            out_ref[0] = acc_ref[:].astype(out_ref.dtype)

    return kern


def _pallas_precision(out_dtype):
    # bf16 payloads run the MXU's native pass; see pallas_spmm
    return (jax.lax.Precision.DEFAULT if out_dtype == jnp.bfloat16
            else jax.lax.Precision.HIGHEST)


def _build_pallas_generic(bs, npairs, n_out, out_dtype, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from matrel_tpu.utils import compat

    prec = _pallas_precision(out_dtype)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,                 # slots, pa, pb
        grid=(npairs,),
        in_specs=[
            pl.BlockSpec((1, bs, bs), lambda i, slots, pa, pb: (pa[i], 0, 0)),
            pl.BlockSpec((1, bs, bs), lambda i, slots, pa, pb: (pb[i], 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, bs, bs), lambda i, slots, pa, pb: (slots[i], 0, 0)),
        scratch_shapes=[pltpu.VMEM((bs, bs), jnp.float32)],
    )
    kernel = pl.pallas_call(
        _make_pair_kernel(prec, npairs),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_out, bs, bs), out_dtype),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )

    @jax.jit  # matlint: disable=ML010 registry runner — the sanctioned kernel seam's own dispatch program
    def run(a_blocks, b_blocks, slots, pa, pb):
        return kernel(slots, pa, pb, a_blocks.astype(out_dtype),
                      b_blocks.astype(out_dtype))

    return run


def _build_xla_gather(n_out, out_dtype, cfg):
    prec = getattr(jax.lax.Precision, cfg.matmul_precision.upper(),
                   jax.lax.Precision.HIGHEST)

    @jax.jit  # matlint: disable=ML010 registry runner — the sanctioned kernel seam's own dispatch program
    def run(a_blocks, b_blocks, slots, pa, pb):
        common = jnp.promote_types(a_blocks.dtype, b_blocks.dtype)
        ga = jnp.take(a_blocks.astype(common), pa, axis=0)
        gb = jnp.take(b_blocks.astype(common), pb, axis=0)
        part = jax.lax.dot_general(
            ga, gb, (((2,), (1,)), ((0,), (0,))),       # batched tile GEMM
            precision=prec, preferred_element_type=jnp.float32)
        tiles = jax.ops.segment_sum(part, slots, num_segments=n_out)
        return tiles.astype(out_dtype)

    return run


def _grouped_tables(slot: np.ndarray, n_out: int, G: int,
                    npairs: int) -> Tuple[np.ndarray, np.ndarray]:
    """(src, group_slot) for the grouped schedule: each output slot's
    pair run padded to a multiple of G with SENTINEL pairs (index
    ``npairs`` — the appended zero tile), so every grid step retires
    exactly G pairs of its one slot. ``src[j]`` is the pair feeding
    position j of the padded layout; ``group_slot[g]`` the output slot
    of group g. Pairs arrive slot-sorted (pair_structure's contract)."""
    counts = np.bincount(slot, minlength=n_out).astype(np.int64)
    gcounts = np.maximum(-(-counts // G), 1)
    offsets = np.zeros(n_out + 1, np.int64)
    np.cumsum(gcounts * G, out=offsets[1:])
    src = np.full(int(offsets[-1]), npairs, np.int64)
    starts = np.zeros(n_out + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    pos = offsets[slot] + (np.arange(slot.size, dtype=np.int64)
                           - starts[slot])
    src[pos] = np.arange(slot.size, dtype=np.int64)
    group_slot = np.repeat(np.arange(n_out, dtype=np.int32),
                           gcounts.astype(np.int64))
    return src, group_slot


def _make_grouped_kernel(precision, n_groups):
    """Grouped schedule: one grid step retires G pairs of one output
    slot as a single (bs, G·bs)x(G·bs, bs) MXU contraction over the
    PRE-GATHERED contiguous payload (built eagerly once per operand
    pair — the pallas_spmm payload-memo idiom). G× fewer grid steps
    and no per-pair prefetch indirection; sentinel pairs multiply zero
    tiles and contribute nothing."""
    from jax.experimental import pallas as pl

    def kern(gslots, a_ref, b_ref, out_ref, acc_ref):
        i = pl.program_id(0)
        s = gslots[i]
        first = jnp.logical_or(i == 0,
                               gslots[jnp.maximum(i - 1, 0)] != s)
        last = jnp.logical_or(
            i == n_groups - 1,
            gslots[jnp.minimum(i + 1, n_groups - 1)] != s)

        @pl.when(first)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        acc_ref[:] += jax.lax.dot(
            a_ref[0], b_ref[0], precision=precision,
            preferred_element_type=jnp.float32)

        @pl.when(last)
        def _flush():
            out_ref[0] = acc_ref[:].astype(out_ref.dtype)

    return kern


def _bake_grouped(a_masked, b_masked, pa, pb, src, bs, G, out_dtype):
    """Pre-gather the pair payloads into grouped kernel order, EAGERLY
    (ensure_compile_time_eval — traced baking would poison the runner
    cache with tracers, the spmm transpose-memo lesson): A groups land
    as (n_groups, bs, G·bs) row-concatenated tiles, B groups as
    (n_groups, G·bs, bs) stacks, so one jax.lax.dot per step contracts
    the whole group."""
    n_groups = src.size // G
    with jax.ensure_compile_time_eval():
        az = jnp.concatenate(
            [a_masked.astype(out_dtype),
             jnp.zeros((1, bs, bs), out_dtype)])
        bz = jnp.concatenate(
            [b_masked.astype(out_dtype),
             jnp.zeros((1, bs, bs), out_dtype)])
        pa_ext = np.concatenate(
            [np.asarray(pa, np.int64), [a_masked.shape[0]]])
        pb_ext = np.concatenate(
            [np.asarray(pb, np.int64), [b_masked.shape[0]]])
        ga = jnp.take(az, jnp.asarray(pa_ext[src]), axis=0)
        ga = ga.reshape(n_groups, G, bs, bs).transpose(0, 2, 1, 3) \
            .reshape(n_groups, bs, G * bs)
        gb = jnp.take(bz, jnp.asarray(pb_ext[src]), axis=0) \
            .reshape(n_groups, G * bs, bs)
        # DEFAULT placement, not the payload stacks' committed
        # replicated sharding: replicated-committed inputs make the
        # (non-partitionable) pallas_call execute once PER REPLICA —
        # measured 9× on the 8-device CPU mesh. The consumer
        # (spgemm/apply_dense) re-applies its sharding constraint to
        # the output as it always did.
        ga = jnp.asarray(np.asarray(ga))
        gb = jnp.asarray(np.asarray(gb))
    return ga, gb


def _grouped_call(bs, G, n_groups, n_out, out_dtype, interpret,
                  local_out=None):
    """The pallas_call of one grouped bucket. ``local_out`` (powerlaw
    buckets) compacts the output stack to the bucket's own slots."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from matrel_tpu.utils import compat

    prec = _pallas_precision(out_dtype)
    out_n = local_out if local_out is not None else n_out
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,                 # group_slot
        grid=(n_groups,),
        in_specs=[
            pl.BlockSpec((1, bs, G * bs), lambda i, gs: (i, 0, 0)),
            pl.BlockSpec((1, G * bs, bs), lambda i, gs: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bs, bs), lambda i, gs: (gs[i], 0, 0)),
        scratch_shapes=[pltpu.VMEM((bs, bs), jnp.float32)],
    )
    return pl.pallas_call(
        _make_grouped_kernel(prec, n_groups),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((out_n, bs, bs), out_dtype),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )


def _adaptive_group(counts: np.ndarray, requested: int, bs: int) -> int:
    """Effective G for one grouped schedule: the MEDIAN slot-run
    length, clamped by the spec's request and the VMEM budget. A fixed
    G pads every short run to the group width (measured 17× SLOWER
    than the generic kernel on a band whose runs are 2–3 pairs — the
    very padding pathology the powerlaw bucketing exists to avoid), so
    the group tracks what the structure actually offers; floor 2
    (G == 1 is the generic schedule with extra copies)."""
    if counts.size == 0:
        return 2
    med = int(np.median(counts[counts > 0])) if np.any(counts > 0) else 1
    return max(2, min(requested, grouped_factor(bs, requested),
                      max(med, 2)))


def _build_grouped(A, B, bs, pairs, n_out, out_dtype, interpret, G):
    """Band/cluster builder: ONE grouped schedule over all slots."""
    from matrel_tpu.ops import spgemm as spgemm_lib
    slot, pa, pb = pairs
    counts = np.bincount(np.asarray(slot, np.int64), minlength=n_out)
    G = _adaptive_group(counts, G, bs)
    src, group_slot = _grouped_tables(np.asarray(slot, np.int64), n_out,
                                      G, int(np.asarray(pa).size))
    ga, gb = _bake_grouped(spgemm_lib._edge_masked(A),
                           spgemm_lib._edge_masked(B),
                           pa, pb, src, bs, G, out_dtype)
    kernel = _grouped_call(bs, G, group_slot.size, n_out, out_dtype,
                           interpret)

    @jax.jit  # matlint: disable=ML010 registry runner — the sanctioned kernel seam's own dispatch program
    def _run(gs, a, b):
        return kernel(gs, a, b)

    gs_dev = jnp.asarray(group_slot)

    def run(a_blocks, b_blocks, slots, pa_, pb_):
        # per-call args are identical by construction (the runner cache
        # keys on both operand ids); the grouped payload was baked from
        # the same masked stacks at build time
        del a_blocks, b_blocks, slots, pa_, pb_
        return _run(gs_dev, ga, gb)

    run.consumes_args = False    # baked: callers may skip transfers
    return run


def _build_band(A, B, bs, pairs, n_out, out_dtype, interpret, wmax,
                out_rows, out_cols):
    """Band builder — WALK THE DIAGONAL: per A block-row the k-band
    (the row's contiguous contraction tiles) and the output col-band
    are both narrow, so ONE grid step computes the row's ENTIRE output
    band as a single (bs, Wa·bs)x(Wa·bs, Rc·bs) MXU contraction over
    CONTIGUOUSLY BAKED row strips (sequential DMA down the diagonal —
    no scalar-prefetch indirection, no revisit accumulation, no
    predicates). Grid = block-rows × column chunks: orders of
    magnitude fewer steps than one-pair-per-step, which is where both
    the Mosaic grid overhead and the interpret-mode cost live. Exactly
    the schedule that would drown a power-law shape (every row padded
    to the hub width) — which is why it is the row_band
    specialization, not the default. Rows whose bands exceed the
    VMEM-feasible width fall back to the grouped schedule."""
    from jax.experimental import pallas as pl
    from matrel_tpu.utils import compat
    from matrel_tpu.ops import spgemm as spgemm_lib

    out_rows = np.asarray(out_rows, np.int64)
    out_cols = np.asarray(out_cols, np.int64)
    a_rows = np.asarray(A.block_rows, np.int64)
    a_cols = np.asarray(A.block_cols, np.int64)
    b_rows = np.asarray(B.block_rows, np.int64)
    b_cols = np.asarray(B.block_cols, np.int64)
    gr = A.grid[0]
    gcb = B.grid[1]

    def _span(idx, vals, size):
        lo = np.full(size, np.iinfo(np.int64).max)
        hi = np.full(size, -1)
        np.minimum.at(lo, idx, vals)
        np.maximum.at(hi, idx, vals)
        return lo, hi

    kmin, kmax = _span(a_rows, a_cols, gr)
    cmin, cmax = _span(out_rows, out_cols, gr)
    live = kmax >= 0
    wa = int(max((kmax - kmin + 1)[live].max(initial=1), 1))
    rr = int(max((cmax - cmin + 1)[live &
                                   (cmax >= 0)].max(initial=1), 1))
    # VMEM feasibility: the A strip + one B chunk + the out chunk,
    # f32, double-buffered by Mosaic — chunk the output band when it
    # does not fit, fall back entirely when even Rc = 1 does not
    budget = VMEM_PAIR_BUDGET_BYTES // 4
    rc = int(min(rr, max(budget // max(wa * bs * bs, 1) - 1, 0)))
    if rc < 1 or wa > grouped_factor(bs, max(wmax, 2)) * 2:
        return _build_grouped(A, B, bs, pairs, n_out, out_dtype,
                              interpret, wmax)
    nchunks = -(-rr // rc)

    def _lookup(rows, cols, gc_):
        keys = rows * gc_ + cols
        order = np.argsort(keys)
        return keys[order], order

    akeys, aorder = _lookup(a_rows, a_cols, A.grid[1])
    bkeys, border = _lookup(b_rows, b_cols, gcb)

    def _find(keys_sorted, order, want, nnzb):
        """payload index per wanted key, nnzb (the appended zero tile)
        where absent."""
        pos = np.searchsorted(keys_sorted, want)
        pos = np.clip(pos, 0, keys_sorted.size - 1)
        hit = keys_sorted[pos] == want
        return np.where(hit, order[pos], nnzb).astype(np.int64)

    rows_i = np.arange(gr)
    k_of = np.clip(kmin, 0, None)[:, None] + np.arange(wa)[None, :]
    k_valid = k_of <= np.where(live, kmax, -1)[:, None]
    a_want = rows_i[:, None] * A.grid[1] + np.clip(k_of, 0,
                                                   A.grid[1] - 1)
    a_idx = _find(akeys, aorder, a_want.ravel(), A.nnzb)
    a_idx = np.where(k_valid.ravel(), a_idx, A.nnzb)

    c_of = np.clip(cmin, 0, None)[:, None] \
        + np.arange(nchunks * rc)[None, :]
    c_valid = c_of <= np.where(cmax >= 0, cmax, -1)[:, None]
    b_want = (np.repeat(k_of[:, :, None], nchunks * rc, axis=2) * gcb
              + np.clip(c_of, 0, gcb - 1)[:, None, :])
    b_ok = k_valid[:, :, None] & c_valid[:, None, :]
    b_idx = _find(bkeys, border, b_want.ravel(), B.nnzb)
    b_idx = np.where(b_ok.ravel(), b_idx, B.nnzb)

    with jax.ensure_compile_time_eval():
        az = jnp.concatenate(
            [spgemm_lib._edge_masked(A).astype(out_dtype),
             jnp.zeros((1, bs, bs), out_dtype)])
        bz = jnp.concatenate(
            [spgemm_lib._edge_masked(B).astype(out_dtype),
             jnp.zeros((1, bs, bs), out_dtype)])
        # A strips (gr, bs, wa·bs); B strips (gr·nchunks, wa·bs, rc·bs)
        ga = jnp.take(az, jnp.asarray(a_idx), axis=0) \
            .reshape(gr, wa, bs, bs).transpose(0, 2, 1, 3) \
            .reshape(gr, bs, wa * bs)
        gb = jnp.take(bz, jnp.asarray(b_idx), axis=0) \
            .reshape(gr, wa, nchunks, rc, bs, bs) \
            .transpose(0, 2, 1, 4, 3, 5) \
            .reshape(gr * nchunks, wa * bs, rc * bs)
        # default placement (see _bake_grouped)
        ga = jnp.asarray(np.asarray(ga))
        gb = jnp.asarray(np.asarray(gb))
        # out slot -> flat (row, chunk, col-in-chunk) tile position
        sel = (out_rows * nchunks * rc
               + (out_cols - np.clip(cmin, 0, None)[out_rows]))
        sel_dev = jnp.asarray(sel)

    prec = _pallas_precision(out_dtype)

    def kern(a_ref, b_ref, out_ref):
        out_ref[0] = jax.lax.dot(
            a_ref[0], b_ref[0], precision=prec,
            preferred_element_type=jnp.float32).astype(out_ref.dtype)

    kernel = pl.pallas_call(
        kern,
        grid=(gr, nchunks),
        in_specs=[
            pl.BlockSpec((1, bs, wa * bs), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, wa * bs, rc * bs),
                         lambda i, j: (i * nchunks + j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bs, rc * bs),
                               lambda i, j: (i * nchunks + j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((gr * nchunks, bs, rc * bs),
                                       out_dtype),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )

    @jax.jit  # matlint: disable=ML010 registry runner — the sanctioned kernel seam's own dispatch program
    def _run(a, b, sel_):
        rowout = kernel(a, b)
        flat = rowout.reshape(gr * nchunks, bs, rc, bs) \
            .transpose(0, 2, 1, 3).reshape(gr * nchunks * rc, bs, bs)
        return jnp.take(flat, sel_, axis=0)

    def run(a_blocks, b_blocks, slots, pa_, pb_):
        del a_blocks, b_blocks, slots, pa_, pb_
        return _run(ga, gb, sel_dev)

    run.consumes_args = False    # baked: callers may skip transfers
    return run


def _build_bucketed(A, B, bs, pairs, n_out, out_dtype, interpret,
                    g_light, g_heavy, split):
    """Powerlaw builder: output slots bucketed by pair-run length —
    light rows (run <= split) pad only to g_light, hub rows run the
    wide g_heavy group — then one tile-level scatter recombines. The
    "never pad the MXU to the max row" schedule."""
    from matrel_tpu.ops import spgemm as spgemm_lib
    slot, pa, pb = pairs
    slot = np.asarray(slot, np.int64)
    pa = np.asarray(pa)
    pb = np.asarray(pb)
    counts = np.bincount(slot, minlength=n_out)
    heavy_slots = np.nonzero(counts > split)[0]
    light_slots = np.nonzero(counts <= split)[0]
    a_m = spgemm_lib._edge_masked(A)
    b_m = spgemm_lib._edge_masked(B)

    buckets = []
    for slots_sel, G in ((light_slots, g_light), (heavy_slots, g_heavy)):
        if slots_sel.size == 0:
            continue
        G = _adaptive_group(counts[slots_sel], G, bs)
        # compact this bucket's pairs onto local slot ids (slot-sorted
        # order is preserved, so the grouped tables stay run-coherent)
        local_of = np.full(n_out, -1, np.int64)
        local_of[slots_sel] = np.arange(slots_sel.size)
        mask = local_of[slot] >= 0
        bslot = local_of[slot[mask]]
        bpa, bpb = pa[mask], pb[mask]
        src, group_slot = _grouped_tables(bslot, int(slots_sel.size), G,
                                          int(bpa.size))
        ga, gb = _bake_grouped(a_m, b_m, bpa, bpb, src, bs, G,
                               out_dtype)
        kernel = _grouped_call(bs, G, group_slot.size, n_out, out_dtype,
                               interpret, local_out=int(slots_sel.size))
        buckets.append((kernel, jnp.asarray(group_slot), ga, gb,
                        jnp.asarray(slots_sel.astype(np.int32))))

    kernels = [b[0] for b in buckets]

    @jax.jit  # matlint: disable=ML010 registry runner — the sanctioned kernel seam's own dispatch program
    def _run(*flat):
        # baked arrays arrive as ARGUMENTS, never closed-over: a
        # zero-arg jit would trace the multi-GB payload stacks as
        # embedded constants (compile-memory + HBM duplication — the
        # _build_grouped/_build_band calling convention)
        out = jnp.zeros((n_out, bs, bs), out_dtype)
        for i, kernel in enumerate(kernels):
            gs, ga, gb, ids = flat[4 * i:4 * i + 4]
            out = out.at[ids].set(kernel(gs, ga, gb))
        return out

    flat_args = tuple(x for b in buckets for x in b[1:])

    def run(a_blocks, b_blocks, slots, pa_, pb_):
        del a_blocks, b_blocks, slots, pa_, pb_
        return _run(*flat_args)

    run.consumes_args = False    # baked: callers may skip transfers
    return run


def build_runner(kernel_id: str, A, B, cfg: MatrelConfig,
                 interpret: bool, pairs, n_out: int, out_dtype):
    """Build the device runner for one registered kernel over one
    operand pair — the single constructor ops/spgemm.py's runner cache
    calls. ``pairs`` is the host (slot, pa, pb, out_rows, out_cols)
    structure from pair_structure (slot-sorted; the band schedule also
    reads the output tile coordinates)."""
    spec = REGISTRY[kernel_id]
    bs = A.block_size
    slot, pa, pb, out_rows, out_cols = pairs
    npairs = int(np.asarray(pa).size)
    pairs3 = (slot, pa, pb)
    if kernel_id == "xla_gather":
        return _build_xla_gather(n_out, out_dtype, cfg)
    if kernel_id == "pallas_generic":
        return _build_pallas_generic(bs, npairs, n_out, out_dtype,
                                     interpret)
    G = grouped_factor(bs, spec.group)
    if spec.bucket_split > 0:
        return _build_bucketed(A, B, bs, pairs3, n_out, out_dtype,
                               interpret,
                               g_light=max(2, grouped_factor(bs, 2)),
                               g_heavy=G,
                               split=spec.bucket_split)
    if kernel_id == "pallas_band":
        return _build_band(A, B, bs, pairs3, n_out, out_dtype,
                           interpret, spec.group, out_rows, out_cols)
    return _build_grouped(A, B, bs, pairs3, n_out, out_dtype,
                          interpret, G)


# -- structure-shaped operand synthesis (autotune probes, bench, soak) ------

#: Minimum tiles a synthetic hub row carries (keeps the powerlaw probe
#: skewed even on tiny dry grids).
POWERLAW_PROBE_HUB_MIN = 12


def synthesize_structure(structure: str, n: int, bs: int, mesh,
                         seed: int = 0, dtype="float32"):
    """A BlockSparseMatrix whose tile layout EXHIBITS one structure
    class — the shared generator behind the autotune measurement
    probes, ``bench.py --sparse-kernels`` and the soak battery, so all
    three measure the population the classifier actually bins."""
    from matrel_tpu.core.sparse import BlockSparseMatrix
    from jax.sharding import NamedSharding, PartitionSpec as P

    gr = gc = max(2, math.ceil(n / bs))
    rng = np.random.default_rng(seed)
    if structure == "row_band":
        bw = 5                     # tile offsets -2..2 (stencil-ish)
        r = np.repeat(np.arange(gr), bw)
        c = r + np.tile(np.arange(bw) - bw // 2, gr)
        keep = (c >= 0) & (c < gc)
        rows, cols = r[keep], c[keep]
    elif structure == "clustered_tile":
        ncl = max(2, gr // 8)
        cb = 4
        rows_l, cols_l = [], []
        for _ in range(ncl):
            cr = int(rng.integers(0, max(gr - cb, 1)))
            cc = int(rng.integers(0, max(gc - cb, 1)))
            ii, jj = np.meshgrid(np.arange(cb), np.arange(cb),
                                 indexing="ij")
            rows_l.append(cr + ii.ravel())
            cols_l.append(cc + jj.ravel())
        rows = np.concatenate(rows_l)
        cols = np.concatenate(cols_l)
    elif structure == "powerlaw_coo":
        hubs = max(2, gr // 16)
        hub_rows = rng.choice(gr, size=hubs, replace=False)
        rows_l = [np.repeat(hub_rows,
                            max(gc // 2, POWERLAW_PROBE_HUB_MIN))]
        cols_l = [rng.integers(0, gc, rows_l[0].size)]
        rows_l.append(np.arange(gr))
        cols_l.append(rng.integers(0, gc, gr))
        rows = np.concatenate(rows_l)
        cols = np.concatenate(cols_l)
    else:
        nnzb = max(4, 2 * gr)
        flat = rng.choice(gr * gc, size=min(nnzb, gr * gc),
                          replace=False)
        rows, cols = flat // gc, flat % gc
    keys = np.unique(rows.astype(np.int64) * gc
                     + cols.astype(np.int64))
    trows = (keys // gc).astype(np.int32)
    tcols = (keys % gc).astype(np.int32)
    payload = jnp.asarray(
        rng.standard_normal((keys.size, bs, bs)).astype(np.float32),
        dtype=dtype)
    rep = NamedSharding(mesh, P())
    return BlockSparseMatrix(
        blocks=jax.device_put(payload, rep),
        block_rows=jax.device_put(trows, rep),
        block_cols=jax.device_put(tcols, rep),
        shape=(gr * bs, gc * bs), block_size=bs, mesh=mesh)


# -- vocabulary -------------------------------------------------------------

register_kernel(KernelSpec(
    kernel_id="xla_gather", structures=(), needs_pallas=False, group=0,
    universal=True,
    description="gather + batched tile GEMM + segment_sum (XLA; "
                "legacy fallback, admissible everywhere)"))
register_kernel(KernelSpec(
    kernel_id="pallas_generic", structures=(), needs_pallas=True,
    group=1, universal=True,
    description="scalar-prefetch pair kernel, one pair per grid step "
                "(the pre-registry Pallas default)"))
register_kernel(KernelSpec(
    kernel_id="pallas_band", structures=("row_band",),
    needs_pallas=True, group=8,
    description="contiguous pre-gathered pair groups along the "
                "diagonal; G pairs per step as one widened MXU "
                "contraction"))
register_kernel(KernelSpec(
    kernel_id="pallas_cluster", structures=("clustered_tile",),
    needs_pallas=True, group=16,
    description="wide accumulate groups over the cluster's long slot "
                "runs (larger VMEM working set, fewer flushes)"))
register_kernel(KernelSpec(
    kernel_id="pallas_powerlaw", structures=("powerlaw_coo",),
    needs_pallas=True, group=8, bucket_split=4,
    description="output rows bucketed by pair count: light rows pad "
                "to a small group, hub rows run the wide one"))

# fused-epilogue hooks per structure class: the home classes of the
# specialized kernels apply zero-preserving epilogues TILE-WISE (their
# output stacks are far smaller than the dense grid — band: O(gr·bw)
# tiles, powerlaw: hub-dominated); "generic" keeps the conservative
# dense application, bit-matching the legacy post-scatter order.
register_epilogue_hook("row_band", "tilewise")
register_epilogue_hook("clustered_tile", "tilewise")
register_epilogue_hook("powerlaw_coo", "tilewise")
register_epilogue_hook("generic", "dense")
