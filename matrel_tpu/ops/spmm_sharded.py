"""Mesh-sharded block-sparse SpMM: the tile stack distributed over devices.

The single-chip SpMM (ops/spmm.py) REPLICATES the sparse operand — the
BMM-style broadcast plan, right for tile stacks that fit one chip's HBM.
At pod scale (the reference's 100k-class matrices grown to 1M+, or many
resident matrices) the stack itself must shard. This module is the
CPMM/RMM-flavoured plan for the sparse side:

* The output block-row space is cut into ``mesh.size`` EQUAL contiguous
  ranges (static shapes: every device owns gr_pad/P row-blocks). Each
  device holds exactly the tiles whose block_row falls in its range,
  zero-padded to the per-device maximum tile count — each device stores
  ~nnzb/P tiles instead of all of them.

* Inside ``shard_map``: per-device gather of the REPLICATED dense
  operand's row-blocks, one batched MXU matmul over the local stack,
  segment-sum into the local row range — zero collectives so far — then
  ONE tiled ``all_gather`` assembles the output rows over ICI
  (SURVEY.md §2 "Distributed comm backend": RMM's cogroup ≙ all_gather).

Balance note: contiguous equal row ranges balance tile counts to ~±√
for uniformly scattered sparsity; pathologically row-clustered stacks
pad toward the densest device, which the padding_ratio surfaces.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.core import padding
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.core.sparse import BlockSparseMatrix


@dataclasses.dataclass
class ShardedBlockSparseMatrix:
    """Row-range-decomposed tile stack. ``blocks`` is (P·cap, bs, bs)
    sharded on axis 0 over every mesh axis; ``brow_loc`` holds each
    tile's block-row index LOCAL to its device's range; padded slots
    carry zero payloads at (0, 0)."""
    blocks: jax.Array       # (P·cap, bs, bs), sharded axis 0
    brow_loc: jax.Array     # (P·cap,) int32, sharded
    bcols: jax.Array        # (P·cap,) int32, sharded
    shape: Tuple[int, int]
    block_size: int
    rows_per_dev: int       # block-rows per device (gr_pad / P)
    cap: int                # tiles per device (max, padded)
    nnzb: int               # true tile count (pre-padding)
    mesh: Mesh
    padding_ratio: float

    @property
    def grid(self) -> Tuple[int, int]:
        bs = self.block_size
        return (-(-self.shape[0] // bs), -(-self.shape[1] // bs))

    def multiply(self, other):
        """Eager sharded SpMM (the lazy IR keeps single-chip plans;
        sharded stacks are an explicit scale-out choice)."""
        return spmm_sharded(self, other)

    def __repr__(self):
        return (f"ShardedBlockSparseMatrix(shape={self.shape}, "
                f"bs={self.block_size}, nnzb={self.nnzb}, "
                f"devices={self.mesh.size}, cap/dev={self.cap})")


def shard_block_sparse(S: BlockSparseMatrix,
                       mesh: Optional[Mesh] = None
                       ) -> ShardedBlockSparseMatrix:
    """Distribute S's tile stack over ``mesh`` (default: S.mesh)."""
    mesh = mesh or S.mesh
    p = mesh.size
    bs = S.block_size
    gr, _ = S.grid
    gr_pad = -(-gr // p) * p
    rows_per_dev = gr_pad // p

    host_rows = np.asarray(S.block_rows)
    host_cols = np.asarray(S.block_cols)
    if host_rows.size and np.any(np.diff(host_rows) < 0):
        # the contiguous-slot assignment below assumes the row-major
        # stack order every constructor produces; a hand-built unsorted
        # stack would silently land tiles in wrong slots
        order = np.argsort(host_rows, kind="stable")
        host_rows, host_cols = host_rows[order], host_cols[order]
        S = dataclasses.replace(
            S, blocks=S.blocks[jnp.asarray(order)],
            block_rows=jnp.asarray(host_rows.astype(np.int32)),
            block_cols=jnp.asarray(host_cols.astype(np.int32)))
    dev_of = host_rows // rows_per_dev
    counts = np.bincount(dev_of, minlength=p)
    cap = max(1, int(counts.max()))

    # per-device slot assignment (tiles are row-major sorted, so each
    # device's tiles are contiguous in the stack)
    starts = np.zeros(p + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    slot = np.arange(S.nnzb, dtype=np.int64) - starts[dev_of]

    src = np.full((p, cap), S.nnzb, np.int64)      # sentinel → zero tile
    src[dev_of, slot] = np.arange(S.nnzb)
    brow_loc = np.zeros((p, cap), np.int32)
    bcols = np.zeros((p, cap), np.int32)
    brow_loc[dev_of, slot] = (host_rows % rows_per_dev).astype(np.int32)
    bcols[dev_of, slot] = host_cols.astype(np.int32)

    axes = tuple(mesh.axis_names)
    sh1 = NamedSharding(mesh, P(axes))
    sh3 = NamedSharding(mesh, P(axes, None, None))
    src_d = jnp.asarray(src.reshape(-1))
    blocks = jax.jit(  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)
        lambda b: jax.lax.with_sharding_constraint(
            jnp.concatenate([b, jnp.zeros((1, bs, bs), b.dtype)])[src_d],
            sh3))(S.blocks)
    return ShardedBlockSparseMatrix(
        blocks=blocks,
        brow_loc=jax.device_put(brow_loc.reshape(-1), sh1),  # matlint: disable=ML008 host-built tile metadata placed on its sharded layout at plan build
        bcols=jax.device_put(bcols.reshape(-1), sh1),  # matlint: disable=ML008 host-built tile metadata placed on its sharded layout at plan build
        shape=tuple(S.shape), block_size=bs,
        rows_per_dev=rows_per_dev, cap=cap, nnzb=S.nnzb, mesh=mesh,
        padding_ratio=p * cap / max(S.nnzb, 1))


@functools.lru_cache(maxsize=32)
def _sharded_spmm_runner(mesh, bs: int, gc: int, rows_per_dev: int,
                         cap: int, pm: int, out_pshape, precision):
    from matrel_tpu.utils.compat import shard_map

    axes = tuple(mesh.axis_names)

    def kernel(blocks, brow_loc, bcols, dd):
        # per-device shards: blocks (cap, bs, bs), indices (cap,), dd
        # replicated (gc·bs, pm)
        dblocks = dd.reshape(gc, bs, pm)
        gathered = jnp.take(dblocks, bcols, axis=0)          # (cap, bs, pm)
        partial = jax.lax.dot_general(
            blocks, gathered,
            (((2,), (1,)), ((0,), (0,))),
            precision=precision,
            preferred_element_type=jnp.float32)              # (cap, bs, pm)
        local = jax.ops.segment_sum(partial, brow_loc,
                                    num_segments=rows_per_dev)
        local = local.reshape(rows_per_dev * bs, pm)
        return jax.lax.all_gather(local, axes, axis=0, tiled=True)

    fn = shard_map(
        kernel, mesh=mesh,
        in_specs=(P(axes, None, None), P(axes), P(axes), P()),
        out_specs=P(), check_vma=False)

    @jax.jit  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)
    def run(blocks, brow_loc, bcols, dd):
        want_rows = gc * bs
        if dd.shape[0] < want_rows:
            dd = jnp.pad(dd, ((0, want_rows - dd.shape[0]), (0, 0)))
        dd = jax.lax.with_sharding_constraint(
            dd[:want_rows], NamedSharding(mesh, P()))
        out = fn(blocks, brow_loc, bcols, dd)
        out = out[: out_pshape[0], : out_pshape[1]].astype(blocks.dtype)
        if out.shape != tuple(out_pshape):
            out = jnp.pad(out, ((0, out_pshape[0] - out.shape[0]),
                                (0, out_pshape[1] - out.shape[1])))
        return jax.lax.with_sharding_constraint(
            out, padding.canonical_sharding(tuple(out_pshape), mesh))

    return run


def spmm_sharded(S: ShardedBlockSparseMatrix, D,
                 config: Optional[MatrelConfig] = None) -> BlockMatrix:
    """C = S @ D with the tile stack sharded over S.mesh."""
    cfg = config or default_config()
    if isinstance(D, BlockMatrix):
        dd, d_shape = D.data, D.shape
    else:
        D = jnp.asarray(D)
        dd, d_shape = D, tuple(D.shape)
    n, k = S.shape
    if d_shape[0] != k:
        raise ValueError(f"spmm shape mismatch: {S.shape} x {d_shape}")
    m = d_shape[1]
    mesh = S.mesh
    out_pshape = padding.padded_shape((n, m), mesh)
    prec = getattr(jax.lax.Precision, cfg.matmul_precision.upper(),
                   jax.lax.Precision.HIGHEST)
    run = _sharded_spmm_runner(mesh, S.block_size, S.grid[1],
                               S.rows_per_dev, S.cap, dd.shape[1],
                               tuple(out_pshape), prec)
    data = run(S.blocks, S.brow_loc, S.bcols, dd)
    return BlockMatrix.from_array(
        data, (n, m), mesh,
        padding.canonical_spec(tuple(data.shape), mesh),
        nnz=None, block_size=S.block_size)
