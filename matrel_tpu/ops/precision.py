"""Precision-tiered matmul lowering — the multi-pass decompositions
behind the planner's tier vocabulary (parallel/planner.PRECISION_TIERS;
docs/PRECISION.md).

The scheme is arXiv:2112.09017's split summation: decompose each f32
operand into bf16 slices (hi = bf16(x), lo = bf16(x − hi) — the same
residual construction as ops/gram.hi_lo_split and spmv_routed's
``_bf16_split``) and accumulate the significant cross-products in f32
on the MXU. Keeping hi·hi + hi·lo + lo·hi (3 passes) drops only the
lo·lo term, whose relative magnitude is ~2^-16 — f32-class accuracy at
bf16 MXU rate. The int tiers cast integer-valued f32 operands onto the
integer MXU paths (int8 inputs, int32 accumulate) and keep the int32
result, so integer algebra (triangle counts, PageRank iteration
counts, boolean semiring joins) stays EXACT end to end.

Every pass goes through the caller-supplied ``mm`` — the planner's
chosen shard_map strategy recipe (strategies.run_matmul) — so tiering
composes with distribution: a bf16x3 cpmm is three cpmm passes, each
moving half-width operand bytes over the same collective schedule.
"""

from __future__ import annotations

from typing import Callable, List

import jax
import jax.numpy as jnp

Array = jax.Array


def bf16_slices(x: Array, k: int) -> List[Array]:
    """f32 → k bf16 residual slices with Σ slices ≈ x (error ~2^(-8k)
    relative). k=2 delegates to :func:`ops.gram.hi_lo_split` — the ONE
    cast-and-subtract residual construction (two copies of the split
    numerics would drift; cf. spmv_routed._bf16_split's interpret-mode
    caveat, which masks mantissas for exactly that reason)."""
    from matrel_tpu.ops.gram import hi_lo_split
    if k == 2:
        return list(hi_lo_split(x))
    parts: List[Array] = []
    r = x.astype(jnp.float32)
    for _ in range(k):
        p = r.astype(jnp.bfloat16)
        parts.append(p)
        r = r - p.astype(jnp.float32)
    return parts


def tiered_matmul(tier: str, a: Array, b: Array,
                  mm: Callable[[Array, Array], Array]) -> Array:
    """One matmul at a stamped precision tier.

    ``mm(p, q)`` is the strategy's product of two operand PAYLOADS; it
    must accumulate wide (strategies._acc_dtype: bf16 inputs → f32,
    integer inputs → int32) — true for every run_matmul recipe. The
    bf16 tiers return the f32 accumulation; the int tiers return the
    int32 result (exact while products/sums fit int32).
    """
    if tier == "bf16x1":
        return mm(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16))
    if tier == "bf16x3":
        a_hi, a_lo = bf16_slices(a, 2)
        b_hi, b_lo = bf16_slices(b, 2)
        # the three significant cross-products, f32-accumulated; lo·lo
        # (~2^-16 relative) is the dropped term
        return mm(a_hi, b_hi) + mm(a_hi, b_lo) + mm(a_lo, b_hi)
    if tier in ("int32", "int8"):
        cast = jnp.int8 if tier == "int8" else jnp.int32
        # integral operands hold exact integers in f32, so the cast is
        # exact; the chooser only stamps int tiers on proven-integral
        # operands (stats.infer_integral) or an explicit dtype ask
        return mm(a.astype(cast), b.astype(cast))
    if tier == "f32":
        return mm(a, b)
    raise ValueError(f"unknown precision tier {tier!r} "
                     f"(vocabulary: parallel/planner.PRECISION_TIERS)")
