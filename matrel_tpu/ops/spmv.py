"""TPU-idiomatic sparse matrix-vector product over edge lists (SpMV).

The reference's graph workloads run matvecs through Spark's shuffle
(SURVEY.md §3.5: the per-round shuffle dominates PageRank). The naive TPU
translation — ``r[src]`` gather + ``segment_sum`` scatter-add — hits XLA's
serialized scalar gather/scatter path (~150M rows/s measured on v5e: 67 ms
for a 10M gather, 88 ms for the matching scatter). Neither the MXU nor the
VPU has per-lane random access, so this module reshapes the irregular ops
into the two forms the hardware executes well:

* **Width-W row gather** (``gather_1d``): XLA's TPU gather runs ~3.3×
  faster per row when each row is W≥8 elements wide (measured: 10M rows at
  20 ms for W∈[8,128] vs 66 ms for W=1). So gather width-8 rows and select
  the wanted lane with a precomputed one-hot — the select is cheap VPU work.

* **Blocked one-hot MXU scatter** (``EdgeSpMVPlan``): destination indices,
  pre-sorted and padded into fixed-capacity rows of 512-node blocks, are
  factored as ``off = hi*16 + lo``; the segment sum becomes a batched
  ``dot_general`` of two one-hot factors:

      y[b, hi, lo] = Σ_c OH_hi[b, c, hi] · (OH_lo[b, c, lo] · w[b, c])

  All FLOPs ride the MXU; there is no scatter anywhere. Per-edge weights
  (e.g. 1/outdeg for PageRank) are folded into the gather-select table for
  free.

Everything is static-shaped per plan (one compile per graph), matching the
reference's plan-per-query model. Plans whose padding would blow past
``max_padding`` (heavy-tailed degree distributions) fall back partially via
a small overflow COO handled by ``segment_sum``, or entirely (build returns
None) so callers can use the plain path.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

WIDTH = 8        # gather row width (measured flat cost for 8..128 on v5e)
BLOCK = 512      # scatter block: nodes per one-hot block row
HI = 32          # off = hi*LO + lo one-hot factor sizes; HI*LO == BLOCK
LO = 16

# probed once at import (os.umask is process-global; toggling it per save
# would race concurrent file creation in other threads)
_UMASK = os.umask(0)
os.umask(_UMASK)


def _ext_table(x: jax.Array, width: int = WIDTH) -> jax.Array:
    """Pad a 1-D table to (rows, width) with ≥1 zero row so index ``n``
    (the sentinel) and any padded slot read 0."""
    n = x.shape[0]
    rows = n // width + 1
    pad = rows * width - n
    return jnp.concatenate([x, jnp.zeros((pad,), x.dtype)]).reshape(
        rows, width)


def gather_1d(table: jax.Array, idx: jax.Array,
              width: int = WIDTH) -> jax.Array:
    """``table[idx]`` for a 1-D table, via width-row gather + one-hot select.

    ~3.3× faster than the scalar gather on TPU for large ``idx``; exact
    (the select is a VPU multiply by a 0/1 mask, no matmul rounding).
    ``idx == table.shape[0]`` is a valid sentinel reading 0.
    """
    t2 = _ext_table(table, width)
    hi, lo = idx // width, idx % width
    g = jnp.take(t2, hi, axis=0)                       # (..., width)
    sel = (lo[..., None] == jnp.arange(width, dtype=lo.dtype)
           ).astype(table.dtype)
    return jnp.sum(g * sel, axis=-1)


@dataclasses.dataclass
class EdgeSpMVPlan:
    """Compiled layout for ``y[i] = Σ_{e: rows[e]=i} vals[e] · x[cols[e]]``.

    The host build stores only compact per-slot integers (~13 bytes/slot);
    the fat one-hot tables (~192 bytes/slot) are expanded ON DEVICE once,
    lazily — host↔device transfer through the axon tunnel is the scarce
    resource (~60 MB/s measured), not HBM.

    Shapes: B = #row blocks, C = per-block capacity.
      src8    (B, C) int32 — width-row index of x per padded edge slot
      lane    (B, C) int8  — cols[e] % WIDTH
      off     (B, C) int32 — rows[e] % block
      val     (B, C) f32   — vals[e] (0 in padded slots)
    Materialized device tables:
      sel (B, C, WIDTH) f32; oh_hi (B, C, block//LO) f32; oh_lo (B, C, LO).
    Overflow: optional (cols, rows, vals) COO for edges beyond capacity,
    rows sorted ascending, handled by segment_sum.
    """
    n_rows: int
    n_cols: int
    block: int
    capacity: int
    src8: "np.ndarray | jax.Array"    # host until expansion/shard_plan
    lane: Optional["np.ndarray | jax.Array"]
    off: Optional["np.ndarray | jax.Array"]
    val: Optional["np.ndarray | jax.Array"]
    ov_cols: Optional[jax.Array]
    ov_rows: Optional[jax.Array]
    ov_vals: Optional[jax.Array]
    padding_ratio: float
    _tables: Optional[tuple] = dataclasses.field(default=None, repr=False)
    _spmm_tables: Optional[tuple] = dataclasses.field(default=None,
                                                      repr=False)

    @property
    def overflow(self):
        """Overflow COO triple (cols, rows, vals), or () when none."""
        return (() if self.ov_cols is None
                else (self.ov_cols, self.ov_rows, self.ov_vals))

    def arrays(self):
        """Flat device-array tuple for passing through jit boundaries.
        First call expands the one-hot tables on device (one fused jitted
        program; ~130 MB shipped instead of ~2.4 GB). The compact tables
        stay HOST numpy until then, so ``shard_plan`` can place them
        sharded without ever materialising on a single device."""
        ov = () if self.ov_cols is None else (self.ov_cols, self.ov_rows,
                                              self.ov_vals)
        if self._tables is None:
            src8 = jnp.asarray(self.src8)        # no-op if pre-placed
            sel, oh_hi, oh_lo = _expand_tables(self.block // LO)(
                src8, jnp.asarray(self.lane), jnp.asarray(self.off),
                jnp.asarray(self.val))
            if isinstance(sel, jax.core.Tracer):
                # called inside an outer trace (executor lowering): the
                # expansion was staged and returned tracers — caching
                # them would poison the plan for every later use
                return (src8, sel, oh_hi, oh_lo) + ov
            self.src8 = src8
            self._tables = (src8, sel, oh_hi, oh_lo)
            # compact host tables are KEPT (~9 B/slot of host RAM): the
            # compact-table Pallas path (ops/pallas_spmv.py) reads them,
            # and dropping them made path order matter
        return self._tables + ov

    def spmm_extra(self, arrays=None):
        """(src_full, val) tables for the k-wide SpMM path, derived once
        from the expanded tables (src8·W + the lane sel marks; padded
        slots have all-zero sel, so they read a real-but-ignored row —
        val 0 kills the contribution). In-trace callers pass their
        already-staged ``arrays`` so the expansion isn't staged twice."""
        if self._spmm_tables is None:
            src8, sel = (arrays or self.arrays())[:2]
            tables = _derive_spmm_tables(src8, sel)
            if isinstance(tables[0], jax.core.Tracer):
                return tables                # in-trace: don't cache
            self._spmm_tables = tables
        return self._spmm_tables


@jax.jit  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)
def _derive_spmm_tables(src8, sel):
    lane = jnp.argmax(sel != 0.0, axis=-1).astype(jnp.int32)
    src_full = src8 * WIDTH + lane
    val = jnp.sum(sel, axis=-1)
    return src_full, val


@functools.lru_cache(maxsize=8)
def _expand_tables(hi_n: int):
    @jax.jit  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)
    def expand(src8, lane, off, val):
        sel = jnp.where(
            lane[..., None] == jnp.arange(WIDTH, dtype=lane.dtype),
            val[..., None], 0.0)
        oh_hi = ((off // LO)[..., None] ==
                 jnp.arange(hi_n, dtype=off.dtype)).astype(jnp.float32)
        oh_lo = ((off % LO)[..., None] ==
                 jnp.arange(LO, dtype=off.dtype)).astype(jnp.float32)
        return sel, oh_hi, oh_lo

    return expand


def build_spmv_plan(rows, cols, vals=None, n_rows: int = None,
                    n_cols: int = None, *, block: int = BLOCK,
                    capacity_quantile: float = 0.995,
                    max_padding: float = 4.0,
                    max_slots: Optional[int] = None
                    ) -> Optional[EdgeSpMVPlan]:
    """Host-side plan build (numpy, once per graph).

    Capacity is the ``capacity_quantile`` of per-block edge counts rounded
    up to a multiple of 128; edges past it go to the overflow COO. Returns
    None when even that layout pads worse than ``max_padding``× the edge
    count, or when the padded slot count exceeds ``max_slots`` (the
    expanded device tables cost ~224 B/slot of HBM — pass a cap when the
    caller would rather fall back than spend that) — callers should then
    use the plain segment_sum path.
    """
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    m = rows.shape[0]
    if n_rows is None:
        n_rows = int(rows.max()) + 1 if m else 1
    if n_cols is None:
        n_cols = int(cols.max()) + 1 if m else 1
    if vals is not None:
        vals = np.asarray(vals, dtype=np.float32)
    if block % LO:
        raise ValueError("block must be a multiple of LO")
    if m and (rows.min() < 0 or rows.max() >= n_rows
              or cols.min() < 0 or cols.max() >= n_cols):
        raise ValueError("edge indices out of bounds for "
                         f"({n_rows}, {n_cols})")

    nb = -(-n_rows // block)
    from matrel_tpu.utils import native
    cnt = native.spmv_counts(rows, block, nb)
    use_native = cnt is not None
    if not use_native:
        cnt = np.bincount(rows // block, minlength=nb)
    if m == 0:
        cap = 128
    else:
        cap_q = int(np.quantile(cnt[cnt > 0], capacity_quantile)) \
            if (cnt > 0).any() else 0
        cap = max(128, -(-cap_q // 128) * 128)
    # Refuse only when padding hurts at scale: small plans are cheap no
    # matter the ratio, so the gate needs both the relative and an
    # absolute (1M padded slots) threshold. Callers fall back to the
    # plain segment_sum path on None.
    if m and nb * cap > max_padding * m and nb * cap > (1 << 20):
        return None
    if max_slots is not None and nb * cap > max_slots:
        return None
    n_ov = int(np.maximum(cnt - cap, 0).sum())

    filled = native.spmv_fill(rows, cols, vals, n_cols, block, nb, cap,
                              WIDTH, n_ov) if use_native else None
    if filled is not None:
        # Native single-pass counting-sort fill (O(m), no argsort —
        # slot order within a block is input order; the one-hot
        # contraction is order-agnostic so results match the numpy path)
        src8, lane, off, val, ov_r64, ov_c64, ov_v = filled
    else:
        src8, lane, off, val, ov_r64, ov_c64, ov_v = _numpy_fill(
            rows, cols, vals, m, n_cols, block, nb, cap, cnt)

    if n_ov:
        ov_c = jnp.asarray(ov_c64, jnp.int32)
        ov_r = jnp.asarray(ov_r64, jnp.int32)
        ov_v = jnp.asarray(ov_v, jnp.float32)
    else:
        ov_c = ov_r = ov_v = None

    # compact tables stay host-side numpy; they move to device (default
    # placement or sharded via shard_plan) at expansion time
    return EdgeSpMVPlan(
        n_rows=n_rows, n_cols=n_cols, block=block, capacity=cap,
        src8=np.ascontiguousarray(src8, np.int32),
        lane=np.ascontiguousarray(lane, np.int8),
        off=np.ascontiguousarray(off, np.int32),
        val=np.ascontiguousarray(val, np.float32),
        ov_cols=ov_c, ov_rows=ov_r, ov_vals=ov_v,
        padding_ratio=(nb * cap + n_ov) / max(m, 1))


def _numpy_fill(rows, cols, vals, m, n_cols, block, nb, cap, cnt):
    """Pure-numpy plan fill (fallback when the native library is
    unavailable): stable argsort by row, then fancy-indexed scatters."""
    if vals is None:
        vals = np.ones((m,), np.float32)
    order = np.argsort(rows, kind="stable")
    rows_s, cols_s, vals_s = rows[order], cols[order], vals[order]
    blk = rows_s // block
    starts = np.zeros(nb + 1, np.int64)
    np.cumsum(cnt, out=starts[1:])
    slot = np.arange(m, dtype=np.int64) - starts[blk]
    in_main = slot < cap

    src_pad = np.full((nb, cap), n_cols, np.int64)   # sentinel -> reads 0
    val_pad = np.zeros((nb, cap), np.float32)
    off_pad = np.zeros((nb, cap), np.int64)
    b_main, s_main = blk[in_main], slot[in_main]
    src_pad[b_main, s_main] = cols_s[in_main]
    val_pad[b_main, s_main] = vals_s[in_main]
    off_pad[b_main, s_main] = rows_s[in_main] % block
    return ((src_pad // WIDTH).astype(np.int32),
            (src_pad % WIDTH).astype(np.int8),
            off_pad.astype(np.int32), val_pad,
            rows_s[~in_main], cols_s[~in_main], vals_s[~in_main])


def _onehot_contrib(src8, sel, oh_hi, oh_lo, x_ext) -> jax.Array:
    """The core contraction: flat (B·block,) partial sums for the blocks
    these tables describe. ``x_ext`` is the width-padded 2-D table of x."""
    g = jnp.take(x_ext, src8, axis=0)                  # (B, C, W) row gather
    w = jnp.sum(g * sel, axis=-1)                      # exact f32 select
    # MXU segment-sum: batch B, contract C. bf16_3x ≈ f32 accuracy at 3
    # passes; the one-hots are exact in bf16.
    contrib = jax.lax.dot_general(
        oh_hi, oh_lo * w[..., None],
        (((1,), (1,)), ((0,), (0,))),
        precision=jax.lax.Precision.HIGH)              # (B, HI', LO)
    return contrib.reshape(-1)


def _overflow_add(y, ov, x, n_rows):
    """Accumulate the overflow COO triple (cols, rows, vals)."""
    ov_c, ov_r, ov_v = ov
    w_ov = gather_1d(x.astype(jnp.float32), ov_c) * ov_v
    return y + jax.ops.segment_sum(w_ov, ov_r, num_segments=n_rows,
                                   indices_are_sorted=True)


def spmv_apply(plan_static, arrays, x: jax.Array) -> jax.Array:
    """Traceable body: y = A·x given a plan. ``plan_static`` is the
    (n_rows, n_cols, block) tuple; ``arrays`` is plan.arrays(). Safe to
    call inside jit/fori_loop with the arrays as loop-invariant args."""
    n_rows, n_cols, block = plan_static
    src8, sel, oh_hi, oh_lo = arrays[:4]
    y = _onehot_contrib(src8, sel, oh_hi, oh_lo,
                        _ext_table(x.astype(jnp.float32)))[:n_rows]
    if len(arrays) > 4:
        y = _overflow_add(y, arrays[4:], x, n_rows)
    return y


_SPMM_B_CHUNK = 128   # blocks per scatter chunk: bounds the (chunk, C,
                      # LO·k) one-hot⊗w intermediate to a few hundred MB


def spmm_apply(plan_static, arrays, extra, X: jax.Array) -> jax.Array:
    """Traceable k-wide SpMM body: Y = A·X for dense X (n_cols, k).

    One shared row gather serves every column (vs k full passes of
    ``spmv_apply``); the scatter contracts oh_hi against (oh_lo ⊗ w)
    per B-chunk so the widened one-hot never materialises whole.
    Traffic scales ~linearly in k; callers chunk very wide X.
    """
    n_rows, n_cols, block = plan_static
    _, _, oh_hi, oh_lo = arrays[:4]
    src_full, val = extra
    k = X.shape[1]
    x_ext = jnp.concatenate(
        [X.astype(jnp.float32), jnp.zeros((WIDTH, k), jnp.float32)])
    g = jnp.take(x_ext, src_full, axis=0)              # (B, C, k)
    w = g * val[..., None]
    nb, cap = src_full.shape
    ch = min(_SPMM_B_CHUNK, max(nb, 1))   # don't pad tiny plans up to 128
    nch = -(-nb // ch)
    pad = nch * ch - nb

    def pad_b(a):
        if pad == 0:
            return a
        return jnp.concatenate(
            [a, jnp.zeros((pad, *a.shape[1:]), a.dtype)])

    hh = pad_b(oh_hi).reshape(nch, ch, cap, -1)
    ll = pad_b(oh_lo).reshape(nch, ch, cap, LO)
    ww = pad_b(w).reshape(nch, ch, cap, k)

    def chunk(args):
        h, l, v = args
        rhs = (l[..., :, None] * v[..., None, :]).reshape(ch, cap, LO * k)
        return jax.lax.dot_general(
            h, rhs, (((1,), (1,)), ((0,), (0,))),
            precision=jax.lax.Precision.HIGH)          # (ch, H, LO·k)

    out = jax.lax.map(chunk, (hh, ll, ww))             # (nch, ch, H, LO·k)
    y = out.reshape(nch * ch, -1, LO, k).reshape(-1, k)[:n_rows]
    if len(arrays) > 4:
        y = _overflow_add_wide(y, arrays[4:], X, n_rows)
    return y


def _overflow_add_wide(y, ov, X, n_rows):
    """k-wide overflow COO accumulation of the (cols, rows, vals)
    triple. Overflow indices are always real columns (< n_cols —
    sentinels never overflow), so gather straight from X, no padded
    copy."""
    ov_c, ov_r, ov_v = ov
    w_ov = jnp.take(X.astype(jnp.float32), ov_c, axis=0) * ov_v[:, None]
    return y + jax.ops.segment_sum(w_ov, ov_r, num_segments=n_rows,
                                   indices_are_sorted=True)


_spmm_jitted = jax.jit(spmm_apply, static_argnums=0)  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)


def spmm(plan: EdgeSpMVPlan, X: jax.Array,
         col_chunk: int = 64) -> jax.Array:
    """Y = A·X for dense X (n_cols, k), k columns processed ``col_chunk``
    at a time (scatter traffic grows linearly in k). k == 1 takes the
    matvec kernel — its width-8 row gather beats spmm's width-1."""
    X = jnp.asarray(X, jnp.float32)
    static = (plan.n_rows, plan.n_cols, plan.block)
    if X.shape[1] == 0:
        return jnp.zeros((plan.n_rows, 0), jnp.float32)
    if X.shape[1] == 1:
        return spmv(plan, X[:, 0])[:, None]
    outs = [_spmm_jitted(static, plan.arrays(), plan.spmm_extra(),
                         X[:, j:j + col_chunk])
            for j in range(0, X.shape[1], col_chunk)]
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)


def spmv_sharded_apply(plan_static, arrays, x: jax.Array,
                       mesh) -> jax.Array:
    """Traceable body for a MESH-SHARDED plan, to be called INSIDE a
    ``shard_map`` over all of ``mesh``'s axes: ``arrays`` tables arrive as
    per-device shards (the device's slice of destination blocks), x is
    replicated; one tiled all_gather assembles the output. Overflow COO
    is replicated — every device computes it identically (it is small by
    construction)."""
    n_rows, n_cols, block = plan_static
    src8, sel, oh_hi, oh_lo = arrays[:4]
    axes = tuple(mesh.axis_names)
    y_loc = _onehot_contrib(src8, sel, oh_hi, oh_lo,
                            _ext_table(x.astype(jnp.float32)))
    y = jax.lax.all_gather(y_loc, axes, axis=0, tiled=True)[:n_rows]
    if len(arrays) > 4:
        y = _overflow_add(y, arrays[4:], x, n_rows)
    return y


def spmm_sharded_apply(plan_static, arrays, extra, X: jax.Array,
                       mesh) -> jax.Array:
    """k-wide variant of ``spmv_sharded_apply`` (call inside shard_map
    over all mesh axes): per-device block-slice contraction of the
    replicated X, one tiled all_gather of the (n, k) result."""
    n_rows, n_cols, block = plan_static
    axes = tuple(mesh.axis_names)
    # local contribution: full spmm body minus overflow/slicing
    y_loc = spmm_apply((block * arrays[0].shape[0], n_cols, block),
                       arrays[:4], extra, X)
    y = jax.lax.all_gather(y_loc, axes, axis=0, tiled=True)[:n_rows]
    if len(arrays) > 4:
        y = _overflow_add_wide(y, arrays[4:], X, n_rows)
    return y


@functools.lru_cache(maxsize=32)
def _sharded_spmm_runner(plan_static, mesh, has_overflow: bool):
    from matrel_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)
    table_specs = sharded_table_specs(axes, 7 if has_overflow else 4)
    # the spmm extra tables are derived from sharded tables elementwise,
    # so they carry the same block-axis sharding
    in_specs = (table_specs[:4]
                + (P(axes, None), P(axes, None))   # src_full, val
                + (P(),)                            # X replicated
                + table_specs[4:])

    def kernel(src8, sel, oh_hi, oh_lo, src_full, val, x, *ov):
        arrays = (src8, sel, oh_hi, oh_lo) + ov
        return spmm_sharded_apply(plan_static, arrays, (src_full, val),
                                  x, mesh)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=in_specs,  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)
                             out_specs=P(), check_vma=False))


def spmm_sharded(plan: EdgeSpMVPlan, X: jax.Array, mesh,
                 col_chunk: int = 64) -> jax.Array:
    """Y = A·X over a mesh-sharded plan (see ``shard_plan``)."""
    X = jnp.asarray(X, jnp.float32)
    if X.shape[1] == 0:
        return jnp.zeros((plan.n_rows, 0), jnp.float32)
    if X.shape[1] == 1:
        return spmv_sharded(plan, X[:, 0], mesh)[:, None]
    arrays = plan.arrays()
    extra = plan.spmm_extra(arrays)
    run = _sharded_spmm_runner((plan.n_rows, plan.n_cols, plan.block),
                               mesh, len(arrays) > 4)
    outs = []
    for j in range(0, X.shape[1], col_chunk):
        outs.append(run(*arrays[:4], *extra, X[:, j:j + col_chunk],
                        *arrays[4:]))
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)


def compact_pad_fills(n_cols: int) -> dict:
    """Sentinel fill values for padded slots/blocks of the compact
    layout, shared by every sharding path: src8 rows point at the
    zero sentinel row of _ext_table, val 0 kills any contribution."""
    return {"src8": n_cols // WIDTH, "lane": n_cols % WIDTH,
            "off": 0, "val": 0.0}


def shard_plan(plan: EdgeSpMVPlan, mesh) -> EdgeSpMVPlan:
    """Row-decompose a plan over all devices of ``mesh``: the block axis
    pads to the device count and the compact tables are placed with
    ``P((axes...), None)`` sharding; the one-hot expansion (elementwise)
    preserves it, so each device holds ~1/P of the ~224 B/slot tables.
    Use with ``spmv_sharded_apply`` inside shard_map. Must be called
    before the plan's tables are expanded."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if plan._tables is not None:
        raise ValueError("shard_plan must run before table expansion "
                         "(call it on a freshly built plan)")
    axes = tuple(mesh.axis_names)
    p = mesh.size
    nb, cap = plan.src8.shape
    nb_pad = -(-nb // p) * p
    pad = nb_pad - nb

    def padded(a, fill):
        if pad == 0:
            return np.asarray(a)
        return np.concatenate(
            [np.asarray(a),
             np.full((pad, *a.shape[1:]), fill, np.asarray(a).dtype)])

    fills = compact_pad_fills(plan.n_cols)
    sh2 = NamedSharding(mesh, P(axes, None))
    return dataclasses.replace(
        plan,
        src8=jax.device_put(padded(plan.src8, fills["src8"]), sh2),  # matlint: disable=ML008 host-built compact table placed on its sharded layout at plan build
        lane=jax.device_put(padded(plan.lane, fills["lane"]), sh2),  # matlint: disable=ML008 host-built compact table placed on its sharded layout at plan build
        off=jax.device_put(padded(plan.off, fills["off"]), sh2),  # matlint: disable=ML008 host-built compact table placed on its sharded layout at plan build
        val=jax.device_put(padded(plan.val, fills["val"]), sh2))  # matlint: disable=ML008 host-built compact table placed on its sharded layout at plan build


_spmv_jitted = jax.jit(spmv_apply, static_argnums=0)  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)


def spmv(plan: EdgeSpMVPlan, x: jax.Array) -> jax.Array:
    """y = A·x (convenience wrapper; jit-cached per plan shape)."""
    return _spmv_jitted((plan.n_rows, plan.n_cols, plan.block),
                        plan.arrays(), x)


def sharded_table_specs(axes, n_arrays: int):
    """PartitionSpecs for plan.arrays() under the row decomposition:
    the four tables sharded on the block axis, overflow COO replicated."""
    from jax.sharding import PartitionSpec as P
    specs = (P(axes, None), P(axes, None, None), P(axes, None, None),
             P(axes, None, None))
    if n_arrays > 4:
        specs = specs + (P(), P(), P())
    return specs


@functools.lru_cache(maxsize=32)
def _sharded_spmv_runner(plan_static, mesh, has_overflow: bool):
    from matrel_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)
    table_specs = sharded_table_specs(axes, 7 if has_overflow else 4)
    in_specs = table_specs[:4] + (P(),) + table_specs[4:]  # x after tables

    def kernel(src8, sel, oh_hi, oh_lo, x, *ov):
        return spmv_sharded_apply(plan_static, (src8, sel, oh_hi, oh_lo)
                                  + ov, x, mesh)

    # check_vma=False: the tiled all_gather output is value-identical on
    # every device but typed "varying", which the replication check
    # cannot statically see through
    return jax.jit(shard_map(  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)
        kernel, mesh=mesh, in_specs=in_specs, out_specs=P(),
        check_vma=False))


def spmv_sharded(plan: EdgeSpMVPlan, x: jax.Array, mesh) -> jax.Array:
    """y = A·x over a mesh-sharded plan (see ``shard_plan``): each device
    contracts its slice of destination blocks against the replicated x;
    one tiled all_gather of the (n,) result rides ICI."""
    arrays = plan.arrays()
    run = _sharded_spmv_runner((plan.n_rows, plan.n_cols, plan.block),
                               mesh, len(arrays) > 4)
    return run(*arrays[:4], jnp.asarray(x, jnp.float32), *arrays[4:])


# -- plan persistence --------------------------------------------------------


def save_plan(path: str, plan: EdgeSpMVPlan) -> None:
    """Persist a plan's compact layout (one .npz). The expensive build
    (host sort/fill) is skipped on load; table expansion (or the compact
    executor's device copy) happens on the loading process's device.
    Plans keep their compact tables for life, so saving works before OR
    after any executor has used the plan."""
    payload = dict(
        # trailing fields: format version + the WIDTH/LO constants baked
        # into src8/lane/off at build time — loading under different
        # constants must fail loudly, not gather from wrong rows
        meta=np.asarray([plan.n_rows, plan.n_cols, plan.block,
                         plan.capacity, 1, WIDTH, LO], np.int64),
        padding_ratio=np.asarray([plan.padding_ratio], np.float64),
        src8=np.asarray(plan.src8), lane=np.asarray(plan.lane),
        off=np.asarray(plan.off), val=np.asarray(plan.val))
    if plan.ov_rows is not None:
        payload.update(ov_rows=np.asarray(plan.ov_rows),
                       ov_cols=np.asarray(plan.ov_cols),
                       ov_vals=np.asarray(plan.ov_vals))
    import tempfile
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(os.path.abspath(path)),
                               suffix=".tmp")
    try:
        os.fchmod(fd, 0o666 & ~_UMASK)  # mkstemp's 0600 ignores the umask
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_plan(path: str) -> EdgeSpMVPlan:
    """Load a plan saved by ``save_plan``."""
    with np.load(path) as z:
        meta = [int(v) for v in z["meta"]]
        n_rows, n_cols, block, cap = meta[:4]
        version, width, lo = (meta[4:7] if len(meta) >= 7 else (0, -1, -1))
        if version != 1 or width != WIDTH or lo != LO:
            raise ValueError(
                f"plan file {path!r} was saved with format v{version} "
                f"(WIDTH={width}, LO={lo}); this build expects v1 "
                f"(WIDTH={WIDTH}, LO={LO}) — rebuild the plan")
        has_ov = "ov_rows" in z.files
        return EdgeSpMVPlan(
            n_rows=n_rows, n_cols=n_cols, block=block, capacity=cap,
            src8=z["src8"], lane=z["lane"], off=z["off"], val=z["val"],
            ov_rows=jnp.asarray(z["ov_rows"]) if has_ov else None,
            ov_cols=jnp.asarray(z["ov_cols"]) if has_ov else None,
            ov_vals=jnp.asarray(z["ov_vals"]) if has_ov else None,
            padding_ratio=float(z["padding_ratio"][0]))
