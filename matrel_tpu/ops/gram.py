"""Symmetric 2-pass bf16 Gram split — the ONE implementation of the
round-3 identity (docs/ROUND3.md floor analysis) shared by the
executor's AᵀA/AAᵀ lowering and the streaming linreg workload.

For f32 x split as x = hi + lo (bf16 each), the three products XLA's
precision=HIGH keeps (hi·hi, hi·lo, lo·hi; lo·lo dropped) collapse in a
GRAM to two MXU passes plus a k×k transpose, because the cross terms
are transposes of each other: xᵀx ≈ hiᵀhi + hiᵀlo + (hiᵀlo)ᵀ. Same
three products, identical accuracy class, 33% fewer matmul FLOPs — an
optimization XLA's generic dot cannot apply because it does not know
both operands are the same matrix.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def hi_lo_split(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """f32 → (hi, lo) bf16 pair with x ≈ hi + lo (standard bf16x3
    residual construction)."""
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def symmetric_gram(x: jax.Array,
                   mm: Callable[[jax.Array, jax.Array], jax.Array]
                   ) -> jax.Array:
    """The 2-pass symmetric Gram of f32 ``x``.

    ``mm(p, q)`` is the caller's (possibly distributed) product of the
    two bf16 operand PAYLOADS — it owns the orientation (xᵀ·x via
    einsum or explicit transposes, x·xᵀ likewise) and must accumulate
    in f32 (preferred_element_type / _acc_dtype). The result of
    ``mm(hi, lo)`` must be the cross term whose TRANSPOSE is the other
    cross term — true for both Gram orientations.
    """
    hi, lo = hi_lo_split(x)
    hihi = mm(hi, hi)
    hilo = mm(hi, lo)
    return hihi + hilo + hilo.T
