"""Block-sparse × block-sparse MatMul (SpGEMM) — tile-intersection.

The densify fallback for S×S multiplies (executor.py's fallthrough)
materialises one operand fully and pays SpMM FLOPs over the WHOLE dense
width — at the flagship 1%-block-density scale that is ~100× the memory
and FLOPs the sparse structure requires. This module multiplies the two
TILE MAPS instead:

* Structure (host, numpy, once per operand pair): intersect the tile
  lists on the contraction block index — pair (ia, ib) exists iff
  A.block_cols[ia] == B.block_rows[ib]. Output tiles are the distinct
  (A.block_rows[ia], B.block_cols[ib]) keys; pairs are sorted by output
  tile so accumulation is a segment-sum (XLA) or a consecutive-run VMEM
  accumulate (Pallas). The expected output tile count is exactly what
  ``ir/stats.matmul_density`` estimates at block granularity — the same
  estimator the executor's dispatch threshold reads.

* Compute (device): gather both payload stacks by the pair lists, ONE
  batched MXU matmul over [npairs, bs, bs] tiles, segment-sum into the
  output tile stack. Dense bs×bs tiles keep the MXU at full speed — the
  sparsity is exploited BETWEEN tiles, never inside one.

* Kernels (device): dispatched through the REGISTRY
  (ops/kernel_registry.py, round 11 — docs/SPARSE_KERNELS.md): the XLA
  gather path and the original scalar-prefetch Pallas kernel are the
  universal entries, joined by per-structure Pallas variants (band
  diagonal-walk, grouped cluster accumulate, powerlaw run-length
  bucketing) selected by the operand pair's classified structure, a
  measured autotune winner, or config.spgemm_kernel_override. On
  GENERIC-classified pairs (and wherever Pallas is unavailable) the
  unforced/unmeasured selection is bit-identical to the historical
  two-way choice; home-structure pairs get their specialized schedule
  (numerically equivalent — different accumulation order).

* Sharded wrapper (style of ops/spmm_sharded.py): output tiles cut into
  ``mesh.size`` equal contiguous slot ranges; each device owns the
  pairs landing in its range (zero-padded to the per-device cap, with
  sentinel pairs pointing at an appended zero tile), computes its local
  output sub-stack with zero collectives, then ONE tiled all_gather
  assembles the output tile stack.

Both operand tile stacks stay replicated (the single-chip SpMM plan's
broadcast side); nothing here ever materialises a dense operand.
"""

from __future__ import annotations

import math
import weakref
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from matrel_tpu.config import (MatrelConfig, default_config,
                               resolve_interpret)
from matrel_tpu.core import padding
from matrel_tpu.core.sparse import BlockSparseMatrix


# -- host structure ---------------------------------------------------------


def pair_structure(a_rows: np.ndarray, a_cols: np.ndarray,
                   b_rows: np.ndarray, b_cols: np.ndarray,
                   gc_out: int) -> Tuple[np.ndarray, np.ndarray,
                                         np.ndarray, np.ndarray,
                                         np.ndarray]:
    """Tile-intersection pair lists for C = A·B.

    Returns ``(pa, pb, slot, out_rows, out_cols)``: pair ``t``
    multiplies A tile ``pa[t]`` by B tile ``pb[t]`` into output tile
    ``slot[t]`` of the (out_rows, out_cols) tile set; pairs are sorted
    by slot (row-major output order). All int32, possibly empty.
    """
    a_rows = np.asarray(a_rows, np.int64)
    a_cols = np.asarray(a_cols, np.int64)
    b_rows = np.asarray(b_rows, np.int64)
    b_cols = np.asarray(b_cols, np.int64)
    # constructors keep stacks row-major sorted, but a hand-built B may
    # not be — sort defensively (searchsorted needs sorted keys)
    if b_rows.size and np.any(np.diff(b_rows) < 0):
        border = np.argsort(b_rows, kind="stable")
    else:
        border = None
    brs = b_rows if border is None else b_rows[border]
    starts = np.searchsorted(brs, a_cols, side="left")
    ends = np.searchsorted(brs, a_cols, side="right")
    counts = ends - starts
    total = int(counts.sum())
    empty = (np.zeros(0, np.int32),) * 3 + (np.zeros(0, np.int32),) * 2
    if total == 0:
        return empty
    pa = np.repeat(np.arange(a_rows.size, dtype=np.int64), counts)
    cum = np.zeros(a_rows.size + 1, np.int64)
    np.cumsum(counts, out=cum[1:])
    pb = (np.arange(total, dtype=np.int64) - np.repeat(cum[:-1], counts)
          + np.repeat(starts, counts))
    if border is not None:
        pb = border[pb]
    key = a_rows[pa] * gc_out + b_cols[pb]
    uniq, slot = np.unique(key, return_inverse=True)
    order = np.argsort(slot, kind="stable")
    return (pa[order].astype(np.int32), pb[order].astype(np.int32),
            slot.ravel()[order].astype(np.int32),
            (uniq // gc_out).astype(np.int32),
            (uniq % gc_out).astype(np.int32))


def _out_dtype(A: BlockSparseMatrix, B: BlockSparseMatrix,
               cfg: MatrelConfig):
    """Match the executor's dense-matmul dtype policy: f32 accumulate,
    cast back to the common input dtype under keep_input_dtype."""
    if cfg.keep_input_dtype and A.dtype == B.dtype:
        return A.dtype
    return jnp.float32


def _check_shapes(A: BlockSparseMatrix, B: BlockSparseMatrix) -> None:
    if A.shape[1] != B.shape[0]:
        raise ValueError(f"spgemm shape mismatch: {A.shape} x {B.shape}")
    if A.block_size != B.block_size:
        raise ValueError(
            f"spgemm needs matching block sizes, got {A.block_size} "
            f"vs {B.block_size} — rebuild one operand on the other's "
            f"grid (BlockSparseMatrix.from_numpy/from_coo_arrays)")


# -- runner cache (ops/spmm.py idiom: keyed on both operand ids, purged
# when EITHER matrix is collected so baked pair tables don't pin HBM) ------

_RUNNER_CACHE: dict = {}
_STRUCT_CACHE: dict = {}
_FINALIZER_IDS: set = set()


def _purge_runners(sid: int) -> None:
    _FINALIZER_IDS.discard(sid)
    for cache in (_RUNNER_CACHE, _STRUCT_CACHE):
        for k in [k for k in cache if sid in k[:2]]:
            del cache[k]


def _register_purge(S) -> None:
    if id(S) not in _FINALIZER_IDS:
        _FINALIZER_IDS.add(id(S))
        weakref.finalize(S, _purge_runners, id(S))


def _pair_structure_cached(A: BlockSparseMatrix, B: BlockSparseMatrix):
    """The 'once per operand pair' half of the module contract: the
    host intersection (pair_structure) for an (A, B) pair is cached
    keyed on both operand identities — an iterative workload re-runs
    only the device compute, not the O(pairs·log pairs) numpy
    structure work. Purged with the runners when either matrix is
    collected (review r6: only the runner was cached before)."""
    key = (id(A), id(B))
    hit = _STRUCT_CACHE.get(key)
    if hit is not None:
        return hit
    out = pair_structure(
        np.asarray(A.block_rows), np.asarray(A.block_cols),
        np.asarray(B.block_rows), np.asarray(B.block_cols), B.grid[1])
    _STRUCT_CACHE[key] = out
    _register_purge(A)
    _register_purge(B)
    return out


def pallas_eligible(bs: int, npairs: int) -> bool:
    """Every Pallas block here spans the full trailing (bs, bs) dims of
    its array, which Mosaic always accepts, but sub-8-sublane tiles
    still break the kernel's layout assumptions (the pallas_spmm
    lesson, soak seed 50114) — gate on the sublane multiple."""
    return bs % 8 == 0 and npairs > 0


def _tiles_runner(A, B, cfg, interpret, pairs, n_out, out_dtype,
                  kernel=None):
    """Cached device runner producing the output TILE STACK from the
    two payload stacks + pair tables — now a REGISTRY dispatch
    (ops/kernel_registry.py): the chosen kernel id comes from the
    caller (the executor passes the planner's ``spgemm_kernel`` stamp)
    or from the registry's own selection over the operand pair's
    structure class. With nothing stamped, measured or overridden, a
    GENERIC-classified pair selects bit-identically to the historical
    two-way choice (Pallas on real TPU / forced interpret when
    eligible, XLA gather/segment-sum otherwise); home-structure pairs
    get their specialized schedule — same product, different
    accumulation order."""
    from matrel_tpu.ops import kernel_registry as kr
    pa = pairs[1]
    npairs = int(np.asarray(pa).size)
    kid = kernel
    if kid is None:
        structure = kr.pair_class_of(A, B)
        kid, _ = kr.select_kernel(structure, A.block_size, npairs, cfg,
                                  side=max(A.shape[0], A.shape[1],
                                           B.shape[1]),
                                  mesh=A.mesh)
    elif not kr.admissible(kid, A.block_size, npairs, cfg):
        kid = kr.legacy_default(A.block_size, npairs, cfg)
    key = (id(A), id(B), npairs, n_out, str(out_dtype), kid,
           interpret, cfg.matmul_precision)
    run = _RUNNER_CACHE.get(key)
    if run is not None:
        return run
    run = kr.build_runner(kid, A, B, cfg, interpret, pairs, n_out,
                          out_dtype)
    _RUNNER_CACHE[key] = run
    _register_purge(A)
    _register_purge(B)
    return run


def _edge_masked(S: BlockSparseMatrix):
    """Payload stack with the logical-edge overhang zeroed.

    On ragged shapes the last block row/column overhangs the logical
    region, and tiles there may carry nonzeros beyond the edge —
    ``BlockSparseMatrix.random`` fills whole tiles
    (``from_numpy``/``from_coo_arrays`` zero-pad, so they are already
    clean). A dense SpMM partner is zero-padded there, so overhang
    always multiplied zeros; in S×S BOTH operands carry it:
    contraction-edge garbage × garbage lands in KEPT output entries
    (caught by the ragged verify probe), and output-edge garbage ×
    valid values would leak into the padded region the executor's
    zero-padding invariant promises is exact zeros. Masking both edges
    makes every product tile exactly the logical values. Eager
    (ensure_compile_time_eval — a traced mask would poison the memo
    with tracers, the spmm transpose-memo lesson) and memoised on the
    matrix."""
    bs = S.block_size
    rmod = S.shape[0] % bs
    cmod = S.shape[1] % bs
    if rmod == 0 and cmod == 0:
        return S.blocks
    memo = getattr(S, "_spgemm_edge_memo", None)
    if memo is not None:
        return memo
    blocks = S.blocks
    with jax.ensure_compile_time_eval():
        if rmod:
            idx = np.nonzero(np.asarray(S.block_rows)
                             == S.shape[0] // bs)[0]
            if idx.size:
                blocks = blocks.at[jnp.asarray(idx), rmod:, :].set(0)
        if cmod:
            idx = np.nonzero(np.asarray(S.block_cols)
                             == S.shape[1] // bs)[0]
            if idx.size:
                blocks = blocks.at[jnp.asarray(idx), :, cmod:].set(0)
    S._spgemm_edge_memo = blocks
    return blocks


# -- public API -------------------------------------------------------------


def spgemm_tiles(A: BlockSparseMatrix, B: BlockSparseMatrix,
                 config: Optional[MatrelConfig] = None,
                 interpret=None, kernel: Optional[str] = None):
    """C = A·B as (tiles, out_rows, out_cols): the output tile stack
    [n_out, bs, bs] plus its coordinates on the (gr_A, gc_B) grid.
    Neither operand is densified; empty intersection yields one zero
    tile at (0, 0) (the BlockSparseMatrix empty convention).
    ``kernel`` forces one registered kernel id (the executor passes
    the planner's stamp; None lets the registry select)."""
    cfg = config or default_config()
    _check_shapes(A, B)
    interp = resolve_interpret(interpret, cfg)
    pa, pb, slot, out_rows, out_cols = _pair_structure_cached(A, B)
    out_dtype = _out_dtype(A, B, cfg)
    if pa.size == 0:
        tiles = jnp.zeros((1, A.block_size, A.block_size), out_dtype)
        return tiles, np.zeros(1, np.int32), np.zeros(1, np.int32)
    n_out = int(out_rows.size)
    run = _tiles_runner(A, B, cfg, interp,
                        (slot, pa, pb, out_rows, out_cols), n_out,
                        out_dtype, kernel=kernel)
    if getattr(run, "consumes_args", True):
        tiles = run(_edge_masked(A), _edge_masked(B),
                    jnp.asarray(slot), jnp.asarray(pa),
                    jnp.asarray(pb))
    else:
        # baked specialized runners replay their pre-gathered payload;
        # uploading npairs-sized tables per call would be pure dead
        # work on the repeated-query hot path
        tiles = run(None, None, None, None, None)
    return tiles, out_rows, out_cols


def spgemm(A: BlockSparseMatrix, B: BlockSparseMatrix,
           config: Optional[MatrelConfig] = None,
           interpret=None, kernel: Optional[str] = None
           ) -> BlockSparseMatrix:
    """C = A·B with a SPARSE result: only the tile intersections are
    computed and only the nonzero output tiles are stored."""
    cfg = config or default_config()
    tiles, out_rows, out_cols = spgemm_tiles(A, B, cfg,
                                             interpret=interpret,
                                             kernel=kernel)
    rep = NamedSharding(A.mesh, P())
    return BlockSparseMatrix(
        blocks=jax.lax.with_sharding_constraint(tiles, rep)
        if A.mesh.size > 1 else tiles,
        block_rows=jax.device_put(out_rows, rep),
        block_cols=jax.device_put(out_cols, rep),
        shape=(A.shape[0], B.shape[1]),
        block_size=A.block_size, mesh=A.mesh)


def apply_dense(A: BlockSparseMatrix, B: BlockSparseMatrix,
                config: Optional[MatrelConfig] = None,
                interpret=None, kernel: Optional[str] = None,
                epilogue=None, epilogue_elementwise: bool = False
                ) -> jax.Array:
    """Trace-compatible SpGEMM for the executor: the product scattered
    into a PADDED dense array with canonical sharding (what every other
    lowering hands its consumer). The scatter is the only dense
    materialisation — it is the op's OUTPUT, not an operand.

    ``epilogue`` is the fused-region slot (ir/fusion.py /
    docs/FUSION.md): the absorbed consumer chain reaches the kernel
    seam through the registry's per-structure epilogue hook
    (``kernel_registry.epilogue_mode``) — zero-preserving pointwise
    chains (``epilogue_elementwise`` True, the executor's proof) may
    run TILE-WISE over the output stack before the scatter on
    structure classes registered "tilewise"; everything else applies
    to the scattered dense output. No kernel body is forked either
    way."""
    from matrel_tpu.ops import kernel_registry as kr
    cfg = config or default_config()
    tiles, out_rows, out_cols = spgemm_tiles(A, B, cfg,
                                             interpret=interpret,
                                             kernel=kernel)
    if epilogue is not None:
        mode = kr.epilogue_mode(kr.pair_class_of(A, B),
                                epilogue_elementwise)
        if mode == "tilewise":
            tiles = kr.apply_tile_epilogue(tiles, epilogue)
            epilogue = None          # consumed before the scatter
    n, m = A.shape[0], B.shape[1]
    bs = A.block_size
    gr = math.ceil(n / bs)
    gc = math.ceil(m / bs)
    mesh = A.mesh
    pshape = padding.padded_shape((n, m), mesh)
    sharding = padding.canonical_sharding(pshape, mesh)

    full = jnp.zeros((gr, gc, bs, bs), dtype=tiles.dtype)
    full = full.at[jnp.asarray(out_rows), jnp.asarray(out_cols)].set(tiles)
    dense = full.transpose(0, 2, 1, 3).reshape(gr * bs, gc * bs)
    dense = dense[: pshape[0], : pshape[1]]
    if dense.shape != pshape:
        dense = jnp.pad(dense, ((0, pshape[0] - dense.shape[0]),
                                (0, pshape[1] - dense.shape[1])))
    # tiles can overhang the logical edge on ragged shapes; their
    # overhang region is exact zeros because _edge_masked scrubs both
    # operands' edge tiles (products of clean operands are clean), so
    # no re-mask is needed — and the padded region BEYOND the tile
    # grid is zeros from jnp.pad already.
    if epilogue is not None:         # the conservative "dense" hook
        dense = epilogue(dense)
    return jax.lax.with_sharding_constraint(dense, sharding)


# -- sharded wrapper (ops/spmm_sharded.py style) ----------------------------


def spgemm_sharded(A: BlockSparseMatrix, B: BlockSparseMatrix,
                   config: Optional[MatrelConfig] = None
                   ) -> BlockSparseMatrix:
    """Scale-out SpGEMM: the PAIR list distributed over A.mesh.

    Output tile slots are cut into ``mesh.size`` equal contiguous
    ranges; each device owns exactly the pairs landing in its range
    (zero-padded to the per-device cap with sentinel pairs that hit an
    appended zero tile), computes its local output sub-stack with ZERO
    collectives, then one tiled all_gather assembles the stack — the
    same balance/padding contract as shard_block_sparse."""
    from matrel_tpu.utils.compat import shard_map
    cfg = config or default_config()
    _check_shapes(A, B)
    mesh = A.mesh
    p = mesh.size
    bs = A.block_size
    pa, pb, slot, out_rows, out_cols = _pair_structure_cached(A, B)
    out_dtype = _out_dtype(A, B, cfg)
    if pa.size == 0:
        rep = NamedSharding(mesh, P())
        return BlockSparseMatrix(
            blocks=jax.device_put(np.zeros((1, bs, bs),
                                           np.dtype(out_dtype)), rep),
            block_rows=jax.device_put(np.zeros(1, np.int32), rep),
            block_cols=jax.device_put(np.zeros(1, np.int32), rep),
            shape=(A.shape[0], B.shape[1]), block_size=bs, mesh=mesh)

    n_out = int(out_rows.size)
    spd = -(-n_out // p)                 # output slots per device
    dev_of = slot // spd
    counts = np.bincount(dev_of, minlength=p)
    cap = max(1, int(counts.max()))
    starts = np.zeros(p + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    offs = np.arange(pa.size, dtype=np.int64) - starts[dev_of]
    # sentinel pairs multiply appended zero tiles → contribute nothing
    pa_d = np.full((p, cap), A.nnzb, np.int32)
    pb_d = np.full((p, cap), B.nnzb, np.int32)
    slot_d = np.zeros((p, cap), np.int32)
    pa_d[dev_of, offs] = pa
    pb_d[dev_of, offs] = pb
    slot_d[dev_of, offs] = (slot % spd).astype(np.int32)

    axes = tuple(mesh.axis_names)
    sh1 = NamedSharding(mesh, P(axes))
    prec = getattr(jax.lax.Precision, cfg.matmul_precision.upper(),
                   jax.lax.Precision.HIGHEST)
    common = jnp.promote_types(A.dtype, B.dtype)

    def kernel(ab, bb, pa_l, pb_l, slot_l):
        ga = jnp.take(ab, pa_l, axis=0)              # (cap, bs, bs)
        gb = jnp.take(bb, pb_l, axis=0)
        part = jax.lax.dot_general(
            ga, gb, (((2,), (1,)), ((0,), (0,))),
            precision=prec, preferred_element_type=jnp.float32)
        local = jax.ops.segment_sum(part, slot_l, num_segments=spd)
        return jax.lax.all_gather(local, axes, axis=0, tiled=True)

    fn = shard_map(kernel, mesh=mesh,
                   in_specs=(P(), P(), P(axes), P(axes), P(axes)),
                   out_specs=P(), check_vma=False)

    @jax.jit  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)
    def run(ab, bb, pa_l, pb_l, slot_l):
        ab = jnp.concatenate(
            [ab.astype(common), jnp.zeros((1, bs, bs), common)])
        bb = jnp.concatenate(
            [bb.astype(common), jnp.zeros((1, bs, bs), common)])
        tiles = fn(ab, bb, pa_l, pb_l, slot_l)[:n_out]
        return tiles.astype(out_dtype)

    tiles = run(_edge_masked(A), _edge_masked(B),
                jax.device_put(pa_d.reshape(-1), sh1),  # matlint: disable=ML008 host-built pair-table placed on its sharded layout at plan build
                jax.device_put(pb_d.reshape(-1), sh1),  # matlint: disable=ML008 host-built pair-table placed on its sharded layout at plan build
                jax.device_put(slot_d.reshape(-1), sh1))  # matlint: disable=ML008 host-built pair-table placed on its sharded layout at plan build
    rep = NamedSharding(mesh, P())
    return BlockSparseMatrix(
        blocks=jax.lax.with_sharding_constraint(tiles, rep),
        block_rows=jax.device_put(out_rows, rep),
        block_cols=jax.device_put(out_cols, rep),
        shape=(A.shape[0], B.shape[1]), block_size=bs, mesh=mesh)
