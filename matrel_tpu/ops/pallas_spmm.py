"""Pallas TPU kernel for block-sparse × dense MatMul.

The hot op of BASELINE row 4, hand-scheduled: the sparse tile list drives a
scalar-prefetched grid, so the kernel DMAs exactly the dense row-blocks the
nonzero tiles touch — no gather materialisation, no segment-sum pass, and
revisit-accumulation directly in the output VMEM block.

Grid: (m_tiles, nnzb) — tile index varies fastest, so all sparse tiles are
processed consecutively for a fixed output column tile, and output blocks
are revisited consecutively for runs of equal block_rows (the tile list is
row-major sorted; TPU grids execute sequentially, which makes the
accumulate-in-place safe).

Tile payloads stay in the input dtype (bf16 friendly); accumulation is f32
in the MXU via preferred_element_type.
"""

from __future__ import annotations

import jax

from matrel_tpu.utils import compat
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import NamedSharding

from matrel_tpu.config import MatrelConfig


def _make_kernel(precision, nnzb):
    def _kernel(brows, bcols, blocks_ref, d_ref, out_ref, acc_ref):
        i = pl.program_id(1)  # sparse-tile index (fastest)
        row = brows[i]
        first_visit = jnp.logical_or(i == 0,
                                     brows[jnp.maximum(i - 1, 0)] != row)
        last_visit = jnp.logical_or(
            i == nnzb - 1, brows[jnp.minimum(i + 1, nnzb - 1)] != row)

        # Accumulate row-runs in an f32 VMEM scratch; the HBM-backed out
        # block is written ONCE per run (bf16 revisit-rounding avoided
        # without paying f32 write-back traffic per visit).
        @pl.when(first_visit)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        tile = blocks_ref[0]          # [bs, bs]
        dtile = d_ref[0]              # [bs, tm]
        acc_ref[:] += jax.lax.dot(
            tile, dtile,
            precision=precision,
            preferred_element_type=jnp.float32,
        )

        @pl.when(last_visit)
        def _flush():
            out_ref[:] = acc_ref[:].astype(out_ref.dtype)

    return _kernel


def _pick_tm(pm: int) -> int:
    """Output column-tile width: whole padded m if small, else 512-wide
    strips (same policy as make_spmm's grid construction)."""
    tm = pm if pm <= 512 else 512
    while pm % tm != 0:
        tm //= 2
        if tm < 128:
            return pm
    return tm


def pallas_eligible(S, pm: int) -> bool:
    """Mosaic requires each block's last two dims to be MULTIPLES of
    (8, 128) respectively, or equal the array's dims. The out block is
    (bs, tm) on (gr·bs, pm); tiny or odd block sizes (the fuzzer's bs=4
    caught this on real TPU) must fall back to the XLA path. bf16
    payloads at bs=8/16/24 were probed on-chip (2026-07-30) and compile
    fine, so the 8-sublane rule is not
    dtype-widened here. The tm conjunct is currently always true by
    _pick_tm's contract (pm itself or a multiple of 128) — kept as a
    guard should that policy change."""
    bs = S.block_size
    gr = S.grid[0]
    tm = _pick_tm(pm)
    return ((bs % 8 == 0 or gr == 1)
            and (tm % 128 == 0 or tm == pm))


def make_spmm(S, pm, out_pshape, d_spec, out_sharding, cfg: MatrelConfig,
              interpret: bool = False):
    """Build a jitted SpMM runner bound to S's static tile metadata."""
    import numpy as np

    bs = S.block_size
    gr, gc = S.grid

    # Every output row-block must be visited at least once or its VMEM block
    # is never initialised: statically append one zero tile per empty row
    # and re-sort row-major so revisit-accumulation stays consecutive.
    host_rows = np.asarray(S.block_rows)
    host_cols = np.asarray(S.block_cols)
    empty_rows = np.setdiff1d(np.arange(gr, dtype=np.int32), host_rows)
    all_rows = np.concatenate([host_rows, empty_rows]).astype(np.int32)
    all_cols = np.concatenate(
        [host_cols, np.zeros_like(empty_rows)]).astype(np.int32)
    perm = np.lexsort((all_cols, all_rows))
    all_rows, all_cols = all_rows[perm], all_cols[perm]
    n_pad_tiles = len(empty_rows)
    # position of each combined tile in the original payload stack; padded
    # tiles point at index nnzb (the appended zero tile)
    src = np.concatenate([np.arange(S.nnzb), np.full(n_pad_tiles, S.nnzb)])
    src = src[perm].astype(np.int32)
    nnzb = S.nnzb + n_pad_tiles
    # output column tile: whole padded m if small, else 512-wide strips
    tm = _pick_tm(pm)
    m_tiles = pm // tm

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,           # block_rows, block_cols
        grid=(m_tiles, nnzb),
        in_specs=[
            pl.BlockSpec((1, bs, bs), lambda j, i, brows, bcols: (i, 0, 0)),
            pl.BlockSpec((1, bs, tm), lambda j, i, brows, bcols: (bcols[i], 0, j)),
        ],
        out_specs=pl.BlockSpec((bs, tm), lambda j, i, brows, bcols: (brows[i], j)),
        scratch_shapes=[pltpu.VMEM((bs, tm), jnp.float32)],
    )

    out_dtype = S.blocks.dtype
    # bf16 payloads run the MXU's native single pass; asking Mosaic for
    # fp32 contract precision on bf16 operands is both pointless (inputs
    # carry bf16 information) and rejected ("Bad lhs type"). f32 payloads
    # keep full-f32 MXU passes.
    precision = (jax.lax.Precision.DEFAULT if out_dtype == jnp.bfloat16
                 else jax.lax.Precision.HIGHEST)
    kernel = pl.pallas_call(  # matlint: disable=ML009 legacy SpMM kernel, unported to the registry this round (block-sparse x DENSE path; registry covers S x S)
        _make_kernel(precision, nnzb),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((gr * bs, pm), out_dtype),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )

    # The tile stack is static per matrix: permute it into kernel order
    # ONCE at build time. Doing this inside `run` cost ~2 ms/call at
    # BASELINE row-4 scale — as much as the kernel itself (measured
    # 2026-07-30: full SpMM 2.19 ms, in-jit permutation alone 1.9 ms).
    # The permuted payload depends only on the matrix, not on pm/d_spec,
    # so it is memoised ON S and shared by every runner for that matrix
    # (one ~tile-stack-sized copy per matrix, not per cache key); it
    # dies with S. ensure_compile_time_eval keeps the build eager even
    # when the cache miss happens inside an outer jit trace — otherwise
    # tracers leak into the cached closure and every later independent
    # trace over the same matrix crashes. The closures below capture
    # values, never S itself, so the runner cache's weakref eviction
    # (ops/spmm.py) can free everything when the matrix dies.
    baked_blocks = S.blocks
    memo = getattr(S, "_pallas_payload_memo", None)
    if memo is None or memo[0] is not baked_blocks:
        # memo[0] identity check: a runner built AFTER an S.blocks
        # reassignment must not reuse a payload permuted from the old
        # stack (the per-runner guard below only protects runners built
        # BEFORE the reassignment)
        with jax.ensure_compile_time_eval():
            payload_prepared = jnp.concatenate(
                [baked_blocks,
                 jnp.zeros((1, bs, bs), baked_blocks.dtype)])[
                     jnp.asarray(src)]
            rows_d, cols_d = jnp.asarray(all_rows), jnp.asarray(all_cols)
        S._pallas_payload_memo = (baked_blocks, payload_prepared,
                                  rows_d, cols_d)
    else:
        _, payload_prepared, rows_d, cols_d = memo
    mesh = S.mesh

    @jax.jit  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)
    def _run(payload, rows, cols, dd):
        dd = jax.lax.with_sharding_constraint(dd, NamedSharding(mesh, d_spec))
        want_rows = gc * bs
        if dd.shape[0] < want_rows:
            dd = jnp.pad(dd, ((0, want_rows - dd.shape[0]), (0, 0)))
        # mesh padding can exceed the tile grid's extent (small k on a
        # big mesh — soak seed 50114); the excess rows are exact zeros
        # by the padding invariant — same unconditional slice as
        # _xla_spmm and the sharded runner
        dblocks = dd[:want_rows].reshape(gc, bs, pm)
        out = kernel(rows, cols, payload, dblocks)
        out = out[: out_pshape[0], : out_pshape[1]]
        if out.shape != out_pshape:
            out = jnp.pad(out, ((0, out_pshape[0] - out.shape[0]),
                                (0, out_pshape[1] - out.shape[1])))
        return jax.lax.with_sharding_constraint(out, out_sharding)

    def run(blocks, brows, bcols, dd):
        if blocks is not baked_blocks:
            # the XLA fallback honors a reassigned S.blocks; this path
            # bakes it, so diverge loudly instead of silently
            raise ValueError(
                "S.blocks was reassigned after the SpMM runner was built; "
                "construct a new BlockSparseMatrix instead of mutating")
        del brows, bcols  # baked into the prepared payload at build
        return _run(payload_prepared, rows_d, cols_d, dd)

    return run
