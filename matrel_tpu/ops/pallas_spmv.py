"""Pallas compact-table SpMV: the one-hot scatter without stored one-hots.

The expanded EdgeSpMVPlan tables (ops/spmv.py) cost ~224 B per padded
edge slot in HBM — sel (32 B) + oh_hi (128 B) + oh_lo (64 B) — which is
~2.4 GB for a 10M-edge graph and the reason the PageRank plan cache is
byte-capped. The one-hots only exist because XLA's ``dot_general`` needs
materialised operands; inside a Pallas kernel they can be GENERATED in
VMEM from the compact layout the plan build already produces
(src8/lane/off/val, ~13 B/slot) and never touch HBM.

Pipeline per matvec (``spmv_compact``):

  1. XLA: width-8 row gather + fused lane-select
     ``w[b,c] = x_ext[src8[b,c], lane[b,c]] · val[b,c]`` — the compare
     mask fuses into the multiply-reduce, nothing extra materialises.
  2. Pallas, grid over blocks: generate ``oh_hi`` (C, HI') bf16 and the
     w-carrying rhs (C, LO·passes) in VMEM (w carved into bf16 residual
     parts by mantissa masking — f32-faithful at passes=3, see
     ops/spmv_routed.py for why masking, not casts), one MXU contraction
     ``oh_hiᵀ @ rhs`` per block, write the (HI', LO) output tile.
  3. XLA: overflow-COO accumulation (unchanged contract).

This executor reads an EdgeSpMVPlan's compact host tables (kept on
device via a small memo). It is the DEFAULT on real TPU backends for
COOMatrix matvec/matmat, the DSL's single-device COO matmuls, and
pagerank_edges; CPU and GSPMD multi-device executor programs keep the
expanded XLA path (pallas_call has no SPMD partitioning rule — the
shard_map variants below are the multi-device form). Measured trade
(BASELINE row 5 graph): ~17× smaller device tables (13 B/slot vs ~224).
"""

from __future__ import annotations

import functools

import jax

from matrel_tpu.utils import compat
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from matrel_tpu.ops import spmv as spmv_lib
from matrel_tpu.ops.spmv_routed import _bf16_split

LANE = 128


def _make_scatter_kernel(hi_n: int, lo: int, passes: int):
    def kernel(off_ref, w_ref, y_ref):
        # slots ride the MINOR (128-lane) axis throughout: masks with a
        # <128 minor dim lane-pad 4-8x on the VPU and cost more than the
        # stored tables they replace (measured 45 ms vs 29 at BASELINE
        # row-5 scale before this layout)
        off = off_ref[0]                                 # (cr, 128)
        w = w_ref[0]
        cr = off.shape[0]
        ids_hi = jax.lax.broadcasted_iota(
            jnp.int32, (cr, hi_n, LANE), 1)
        oh_hi = ((off // lo)[:, None, :] == ids_hi).astype(jnp.bfloat16)
        ids_lo = jax.lax.broadcasted_iota(
            jnp.int32, (cr, lo, LANE), 1)
        mask = (off % lo)[:, None, :] == ids_lo
        rhs = jnp.concatenate(
            [jnp.where(mask, wp[:, None, :], 0.0)
             for wp in _bf16_split(w, passes)],
            axis=1).astype(jnp.bfloat16)                 # (cr,lo·p,128)
        t = jax.lax.dot_general(
            oh_hi, rhs,
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)          # (cr,hi_n,lo·p)
        ts = jnp.sum(t, axis=0)                          # (hi_n, lo·p)
        th = ts[:, :lo]
        for p in range(1, passes):
            th = th + ts[:, p * lo:(p + 1) * lo]
        y_ref[0] = th

    return kernel


@functools.lru_cache(maxsize=32)
def _compact_runner(nb: int, cap: int, block: int, lo: int, passes: int,
                    interpret: bool):
    hi_n = block // lo
    cr = cap // LANE
    scatter = pl.pallas_call(  # matlint: disable=ML009 legacy SpMV scatter kernel, unported to the registry this round (autotuned via the spmv| table rows)
        _make_scatter_kernel(hi_n, lo, passes),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, cr, LANE), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, cr, LANE), lambda b: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hi_n, lo), lambda b: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nb, hi_n, lo), jnp.float32),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )
    return scatter


def compact_tables(plan: spmv_lib.EdgeSpMVPlan):
    """Device copies of the plan's compact layout, memoised on the plan
    (the plan keeps its compact host tables even after expanded-path
    use, so path order never matters)."""
    dev = getattr(plan, "_compact_dev", None)
    if dev is None:
        nb, cap = np.asarray(plan.src8).shape
        if cap % LANE:
            raise ValueError(f"capacity {cap} not a multiple of {LANE}")
        cr = cap // LANE
        shp = (nb, cr, LANE)
        # lane stays int8 on device (the kernel compares it against an
        # iota of its own dtype): 13 B/slot total, as advertised.
        # Eager even when first called from inside an executor trace —
        # the memo must hold COMMITTED arrays, not tracers (a cached
        # tracer escapes its trace and poisons every later use of this
        # plan; found by the single-device interpret CI test)
        with jax.ensure_compile_time_eval():
            dev = (jnp.asarray(np.asarray(plan.src8).reshape(shp)),
                   jnp.asarray(np.asarray(plan.lane).reshape(shp)),
                   jnp.asarray(np.asarray(plan.off).reshape(shp)),
                   jnp.asarray(np.asarray(plan.val).reshape(shp)))
        plan._compact_dev = dev
    return dev


def compact_apply(plan_static, tables, ov, x: jax.Array,
                  passes: int = 3, interpret: bool = False) -> jax.Array:
    """Traceable body: y = A·x from compact tables. ``plan_static`` is
    (n_rows, n_cols, block, lo); ``tables`` from compact_tables(); ``ov``
    the overflow COO tuple (possibly empty)."""
    n_rows, n_cols, block, lo = plan_static
    src8, lane, off, val = tables
    nb, cr, _ = src8.shape
    x_ext = spmv_lib._ext_table(x.astype(jnp.float32))
    g = jnp.take(x_ext, src8, axis=0)                    # (nb,cr,128,W)
    sel = lane[..., None] == jnp.arange(spmv_lib.WIDTH, dtype=lane.dtype)
    w = jnp.sum(g * sel, axis=-1) * val                  # fused select
    scatter = _compact_runner(nb, cr * LANE, block, lo, passes,
                              interpret)
    y = scatter(off, w).reshape(-1)[:n_rows]
    if ov:
        y = spmv_lib._overflow_add(y, ov, x, n_rows)
    return y


_compact_jitted = jax.jit(compact_apply, static_argnums=(0, 4, 5))  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)


def compact_apply_chunked(plan_static, tables, ov, x: jax.Array,
                          passes: int = 3, chunks: int = 4,
                          interpret: bool = False) -> jax.Array:
    """EXPERIMENTAL gather/scatter pipelining variant of compact_apply
    (VERDICT r3 #6: attack the ~6 ms/round schedule gap between the
    27.1 ms round and the ~21 ms gather-engine floor).

    The baseline runs ONE full-graph gather then ONE full-graph Pallas
    scatter, serialised by the w dependency. Here the block axis is
    split into ``chunks`` stripes and each stripe's gather feeds its own
    scatter call: chunk i+1's gather has no dependency on chunk i's
    scatter, giving XLA's scheduler the freedom to interleave the
    memory-bound gather with the MXU-bound scatter, and shrinking the
    live (slots, W) gather intermediate by chunks×. Numerics identical
    to compact_apply (same kernel, same tables, per-block accumulation
    is independent across stripes). Measured by
    tools/pagerank_overlap.py on chip; the stop rule (write the
    negative result if <10% over baseline) lives there."""
    n_rows, n_cols, block, lo = plan_static
    src8, lane, off, val = tables
    nb, cr, _ = src8.shape
    x_ext = spmv_lib._ext_table(x.astype(jnp.float32))
    step = -(-nb // max(chunks, 1))
    sel_iota = jnp.arange(spmv_lib.WIDTH, dtype=lane.dtype)
    parts = []
    for s in range(0, nb, step):
        e = min(s + step, nb)
        g = jnp.take(x_ext, src8[s:e], axis=0)           # (c,cr,128,W)
        sel = lane[s:e, ..., None] == sel_iota
        w = jnp.sum(g * sel, axis=-1) * val[s:e]
        scatter = _compact_runner(e - s, cr * LANE, block, lo, passes,
                                  interpret)
        parts.append(scatter(off[s:e], w))
    y = jnp.concatenate(parts, axis=0).reshape(-1)[:n_rows]
    if ov:
        y = spmv_lib._overflow_add(y, ov, x, n_rows)
    return y


# -- mesh-sharded ------------------------------------------------------------
# Unlike the executor's GSPMD programs (where pallas_call has no SPMD
# partitioning rule), shard_map hands the kernel per-device shapes, so
# the compact scatter runs unchanged on each device's slice of blocks:
# ~13 B/slot / P per device, one tiled all_gather of the result.


def shard_compact_tables(plan: spmv_lib.EdgeSpMVPlan, mesh):
    """Row-decompose the compact tables over every device of ``mesh``
    (block axis padded to the device count with sentinel slots).
    Memoised per (plan, mesh) — by mesh EQUALITY, matching the runner
    cache, so rebuilding an equal Mesh per call reuses the transfer."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    memo = getattr(plan, "_compact_sharded", None)
    if memo is None:
        memo = {}
        plan._compact_sharded = memo
    dev = memo.get(mesh)
    if dev is not None:
        return dev
    nb, cap = np.asarray(plan.src8).shape
    if cap % LANE:
        raise ValueError(f"capacity {cap} not a multiple of {LANE}")
    p = mesh.size
    nb_pad = -(-nb // p) * p
    pad = nb_pad - nb
    fills = spmv_lib.compact_pad_fills(plan.n_cols)

    def padded(a, fill, dtype):
        a = np.asarray(a)
        if pad:
            a = np.concatenate(
                [a, np.full((pad, cap), fill, a.dtype)])
        return a.reshape(nb_pad, cap // LANE, LANE).astype(dtype)

    sh = NamedSharding(mesh, P(tuple(mesh.axis_names), None, None))
    # eager even when called from inside a trace (the executor's
    # Lowerer): the memo must hold COMMITTED arrays, not tracers — a
    # cached tracer would escape its trace and poison every later
    # compile that reuses this plan on the same mesh
    with jax.ensure_compile_time_eval():
        dev = (jax.device_put(padded(plan.src8, fills["src8"], np.int32),
                              sh),
               jax.device_put(padded(plan.lane, fills["lane"], np.int8),
                              sh),
               jax.device_put(padded(plan.off, fills["off"], np.int32),
                              sh),
               jax.device_put(padded(plan.val, fills["val"], np.float32),
                              sh))
    memo[mesh] = dev
    return dev


def _compact_sharded_body(apply_fn, overflow_fn, plan_static, tables,
                          ov, x, axes, passes, interpret) -> jax.Array:
    """Shared shard-local sequence: per-device compact apply on this
    device's block-row slice → tiled all_gather → slice padding →
    replicated-overflow add."""
    n_rows, n_cols, block, lo = plan_static
    src8 = tables[0]
    y_loc = apply_fn(
        (src8.shape[0] * block, n_cols, block, lo), tables, (), x,
        passes, interpret)
    y = jax.lax.all_gather(y_loc, axes, axis=0, tiled=True)[:n_rows]
    if ov:
        y = overflow_fn(y, ov, x, n_rows)
    return y


def compact_sharded_apply(plan_static, tables, ov, x, axes,
                          passes: int = 3,
                          interpret: bool = False) -> jax.Array:
    """Per-device sharded compact matvec — call INSIDE a shard_map over
    ``axes``: ``tables`` arrive as this device's block slice, x
    replicated; one tiled all_gather assembles the result; overflow COO
    is replicated and added after the gather. Shared by the standalone
    runner here and pagerank's power-iteration loop."""
    return _compact_sharded_body(compact_apply, spmv_lib._overflow_add,
                                 plan_static, tables, ov, x, axes,
                                 passes, interpret)


def compact_sharded_matmat_apply(plan_static, tables, ov, X, axes,
                                 passes: int = 3,
                                 interpret: bool = False) -> jax.Array:
    """The k-wide sibling of compact_sharded_apply (Y = A·X inside a
    shard_map). Lets the executor keep the 13 B/slot tables on every
    mesh size instead of falling back to the expanded XLA tables."""
    return _compact_sharded_body(compact_matmat_apply,
                                 spmv_lib._overflow_add_wide,
                                 plan_static, tables, ov, X, axes,
                                 passes, interpret)


def compact_sharded_specs(axes, n_ov: int):
    """shard_map in_specs for (tables..., x, overflow...)."""
    from jax.sharding import PartitionSpec as P
    return (P(axes, None, None),) * 4 + (P(),) + (P(),) * n_ov


@functools.lru_cache(maxsize=32)
def _compact_sharded_runner(plan_static, mesh, passes: int, n_ov: int,
                            interpret: bool):
    from matrel_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)

    def kernel(src8, lane, off, val, x, *ov):
        return compact_sharded_apply(plan_static,
                                     (src8, lane, off, val), ov, x,
                                     axes, passes, interpret)

    return jax.jit(shard_map(kernel, mesh=mesh,  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)
                             in_specs=compact_sharded_specs(axes, n_ov),
                             out_specs=P(), check_vma=False))


def _resolve_interpret(interpret) -> bool:
    """None → config (the shared resolver in config.py)."""
    from matrel_tpu.config import resolve_interpret
    return resolve_interpret(interpret)


def spmv_compact_sharded(plan: spmv_lib.EdgeSpMVPlan, x: jax.Array,
                         mesh, passes: int = 3,
                         interpret=None) -> jax.Array:
    """y = A·x with compact tables sharded over ``mesh``."""
    interpret = _resolve_interpret(interpret)
    tables = shard_compact_tables(plan, mesh)
    ov = plan.overflow
    run = _compact_sharded_runner(
        (plan.n_rows, plan.n_cols, plan.block, spmv_lib.LO), mesh,
        passes, len(ov), interpret)
    return run(*tables, jnp.asarray(x, jnp.float32), *ov)


# -- k-wide (SpMM) -----------------------------------------------------------

_COL_CHUNK = 8          # lo·passes·chunk = 256 lanes in the rhs concat


def _make_scatter_kernel_k(hi_n: int, lo: int, passes: int, k: int):
    def kernel(off_ref, w_ref, y_ref):
        off = off_ref[0]                                 # (cr, 128)
        w = w_ref[0]                                     # (cr, k, 128)
        cr = off.shape[0]
        ids_hi = jax.lax.broadcasted_iota(
            jnp.int32, (cr, hi_n, LANE), 1)
        oh_hi = ((off // lo)[:, None, :] == ids_hi).astype(jnp.bfloat16)
        ids_lo = jax.lax.broadcasted_iota(
            jnp.int32, (cr, lo, LANE), 1)
        mask = (off % lo)[:, None, :] == ids_lo          # shared by cols
        # pass-major part order: the per-pass fold below is then two
        # (hi, k·lo) slices at 128-aligned offsets — Mosaic rejects the
        # 4D minor-dim reshape a column-major order would need
        splits = [_bf16_split(w[:, j, :], passes) for j in range(k)]
        parts = [jnp.where(mask, splits[j][pi][:, None, :], 0.0)
                 for pi in range(passes) for j in range(k)]
        rhs = jnp.concatenate(parts, axis=1).astype(
            jnp.bfloat16)                                # (cr,p·k·lo,128)
        t = jax.lax.dot_general(
            oh_hi, rhs,
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)          # (cr,hi,p·k·lo)
        ts = jnp.sum(t, axis=0)                          # (hi, p·k·lo)
        th = ts[:, :k * lo]
        for pi in range(1, passes):
            th = th + ts[:, pi * k * lo:(pi + 1) * k * lo]
        y_ref[0] = th                                    # (hi, k·lo)

    return kernel


@functools.lru_cache(maxsize=32)
def _compact_runner_k(nb: int, cap: int, block: int, lo: int,
                      passes: int, k: int, interpret: bool):
    hi_n = block // lo
    cr = cap // LANE
    return pl.pallas_call(  # matlint: disable=ML009 legacy SpMV scatter kernel, unported to the registry this round (autotuned via the spmv| table rows)
        _make_scatter_kernel_k(hi_n, lo, passes, k),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, cr, LANE), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, cr, k, LANE), lambda b: (b, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hi_n, k * lo), lambda b: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nb, hi_n, k * lo), jnp.float32),
        compiler_params=compat.tpu_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )


def compact_matmat_apply(plan_static, tables, ov, X: jax.Array,
                         passes: int = 3,
                         interpret: bool = False) -> jax.Array:
    """Traceable body: Y = A·X for dense X (n_cols, k). One shared
    full-index gather serves every column; the scatter masks are built
    once per block and contracted against all of a chunk's columns."""
    n_rows, n_cols, block, lo = plan_static
    src8, lane, off, val = tables
    nb, cr, _ = src8.shape
    k = X.shape[1]
    k_pad = -(-k // _COL_CHUNK) * _COL_CHUNK   # full chunks: the kernel's
    src_full = src8 * spmv_lib.WIDTH + lane.astype(jnp.int32)
    # sentinel src_full == n_cols must read 0 (padded slots); zero
    # columns pad k to the chunk width (sliced off at the end)
    X_pad = jnp.concatenate(
        [X.astype(jnp.float32),
         jnp.zeros((spmv_lib.WIDTH, k), jnp.float32)])
    if k_pad != k:
        X_pad = jnp.pad(X_pad, ((0, 0), (0, k_pad - k)))
    outs = []
    for j0 in range(0, k_pad, _COL_CHUNK):
        kc = _COL_CHUNK
        g = jnp.take(X_pad[:, j0:j0 + kc], src_full, axis=0)
        w = (g * val[..., None]).transpose(0, 1, 3, 2)   # (nb,cr,kc,128)
        scatter = _compact_runner_k(nb, cr * LANE, block, lo, passes,
                                    kc, interpret)
        y = scatter(off, w)                              # (nb,hi,kc·lo)
        y = y.reshape(nb, block // lo, kc, lo).transpose(0, 1, 3, 2)
        outs.append(y.reshape(-1, kc)[:n_rows])
    Y = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
    Y = Y[:, :k]
    if ov:
        Y = spmv_lib._overflow_add_wide(Y, ov, X, n_rows)
    return Y


_compact_matmat_jitted = jax.jit(compact_matmat_apply,  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)
                                 static_argnums=(0, 4, 5))


def spmm_compact(plan: spmv_lib.EdgeSpMVPlan, X: jax.Array,
                 passes: int = 3, interpret=None) -> jax.Array:
    """Y = A·X via compact tables (see spmv_compact). k == 1 takes the
    matvec kernel (its width-8 gather beats the full-index one).
    passes=3 is f32-faithful — the same fidelity as the expanded path it
    replaces; pass 2 only where ranking-grade error is acceptable."""
    interpret = _resolve_interpret(interpret)
    X = jnp.asarray(X, jnp.float32)
    if X.shape[1] == 0:
        return jnp.zeros((plan.n_rows, 0), jnp.float32)
    if X.shape[1] == 1:
        return spmv_compact(plan, X[:, 0], passes=passes,
                            interpret=interpret)[:, None]
    tables = compact_tables(plan)
    static = (plan.n_rows, plan.n_cols, plan.block, spmv_lib.LO)
    return _compact_matmat_jitted(static, tables, plan.overflow, X,
                                  passes, interpret)


def spmv_compact(plan: spmv_lib.EdgeSpMVPlan, x: jax.Array,
                 passes: int = 3, interpret=None) -> jax.Array:
    """y = A·x via the compact-table Pallas scatter (opt-in; see module
    docstring). Numerically ~f32 at passes=3."""
    interpret = _resolve_interpret(interpret)
    tables = compact_tables(plan)
    static = (plan.n_rows, plan.n_cols, plan.block, spmv_lib.LO)
    return _compact_jitted(static, tables, plan.overflow, x, passes,
                           interpret)
