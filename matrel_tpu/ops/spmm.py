"""Block-sparse × dense MatMul (SpMM) — the BASELINE row-4 op.

Portable XLA path: gather the dense operand's row-blocks for each sparse
tile, one batched MXU matmul over the tile stack, segment-sum partial
products into output row-blocks. Everything is static-shaped; the MXU sees
one big [nnzb, bs, bs] × [nnzb, bs, m] batch — exactly the shape it likes.

Distribution: the sparse operand (tile stack) is replicated — the broadcast
side of a BMM-style plan (SURVEY.md §2 BMM) — and the dense operand is
column-sharded, so each device computes full rows × its column slice with
ZERO execution-time collectives.

The Pallas fast path (ops/pallas_spmm.py) replaces the gather+segment-sum
with scalar-prefetched DMA when running on real TPU.
"""

from __future__ import annotations

import weakref
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.core import mesh as mesh_lib, padding
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.core.sparse import BlockSparseMatrix


def _resolve_interpret(interpret, cfg) -> bool:
    """None → config (the shared resolver in config.py)."""
    from matrel_tpu.config import resolve_interpret
    return resolve_interpret(interpret, cfg)


# Runner cache: make_spmm/_xla_spmm build a fresh jitted closure per call,
# which would recompile on every spmm() of the same matrix (jit caches by
# function identity). Key on the static pieces of the plan. Runner
# closures capture values from S but never S itself, and a weakref
# finalizer purges a matrix's entries when it is collected — the Pallas
# runner bakes a permuted copy of the whole tile stack, so entries
# outliving their matrix would pin ~2× the stack in HBM per matrix.
_RUNNER_CACHE: dict = {}
_FINALIZER_IDS: set = set()


def _purge_runners(sid: int) -> None:
    _FINALIZER_IDS.discard(sid)
    for k in [k for k in _RUNNER_CACHE if k[0] == sid]:
        del _RUNNER_CACHE[k]


def _cached_runner(S, pm, out_pshape, d_spec, out_sharding, cfg, interpret,
                   explicit_interpret):
    key = (id(S), pm, out_pshape, str(d_spec), cfg.use_pallas,
           cfg.matmul_precision, interpret, explicit_interpret)
    run = _RUNNER_CACHE.get(key)
    if run is None:
        # compiled (non-interpret) Pallas only on a real TPU backend:
        # the resolved ``interpret`` flag already carries the
        # pallas_interpret forcing, and an explicit interpret=False on
        # CPU must fall through to XLA, never lower Mosaic on CPU
        use_pallas = interpret or (
            cfg.use_pallas
            and jax.default_backend() in ("tpu", "axon"))
        if use_pallas:
            from matrel_tpu.ops import pallas_spmm
            # ONLY an EXPLICIT interpret=True skips the eligibility
            # gate (tests drive deliberately tiny blocks); config-driven
            # interpret (pallas_interpret) must still respect it —
            # ineligible stacks (e.g. bs=4) break the kernel's layout
            # assumptions in ANY mode (found by soak seed 50114)
            use_pallas = ((interpret and explicit_interpret)
                          or pallas_spmm.pallas_eligible(S, pm))
        if use_pallas:
            run = pallas_spmm.make_spmm(S, pm, out_pshape, d_spec,
                                        out_sharding, cfg, interpret=interpret)
        else:
            run = _xla_spmm(S, pm, out_pshape, d_spec, out_sharding, cfg)
        _RUNNER_CACHE[key] = run
        if id(S) not in _FINALIZER_IDS:
            _FINALIZER_IDS.add(id(S))
            weakref.finalize(S, _purge_runners, id(S))
    return run


def _dense_spec(pm: int, mesh) -> P:
    x, y = mesh.axis_names
    gx, gy = mesh_lib.mesh_grid_shape(mesh)
    if pm % (gx * gy) == 0 and pm >= gx * gy:
        return P(None, (x, y))
    return P()


def apply(S: BlockSparseMatrix, dd: jax.Array,
          d_shape: Tuple[int, int],
          config: Optional[MatrelConfig] = None,
          interpret=None, epilogue=None) -> jax.Array:
    """Trace-compatible SpMM: S (static metadata) × dense padded array
    ``dd`` of logical shape ``d_shape``. Returns the padded product with
    canonical output sharding.

    ``epilogue`` is the fused-region slot (ir/fusion.py /
    docs/FUSION.md): a traceable callable applied to the padded product
    inside the SAME traced computation, so an absorbed consumer chain
    compiles as the SpMM's epilogue instead of its own dispatch. The
    runner itself is epilogue-agnostic (one cached kernel per matrix,
    never forked per epilogue); None keeps the historical path
    bit-identically."""
    cfg = config or default_config()
    n, k = S.shape
    k2, m = d_shape
    if k != k2:
        raise ValueError(f"spmm shape mismatch: {S.shape} x {d_shape}")
    explicit_interpret = interpret is not None
    interpret = _resolve_interpret(interpret, cfg)
    mesh = S.mesh
    out_pshape = padding.padded_shape((n, m), mesh)
    out_sharding = padding.canonical_sharding(out_pshape, mesh)
    pm = dd.shape[1]
    d_spec = _dense_spec(pm, mesh)
    run = _cached_runner(S, pm, out_pshape, d_spec, out_sharding, cfg,
                         interpret, explicit_interpret)
    out = run(S.blocks, S.block_rows, S.block_cols, dd)
    return out if epilogue is None else epilogue(out)


def spmm(S: BlockSparseMatrix, D: BlockMatrix,
         config: Optional[MatrelConfig] = None,
         interpret=None) -> BlockMatrix:
    """C = S @ D with S block-sparse (n×k), D dense (k×m)."""
    cfg = config or default_config()
    n, _ = S.shape
    _, m = D.shape
    data = apply(S, D.data, D.shape, cfg, interpret=interpret)
    return BlockMatrix.from_array(
        data, (n, m), S.mesh,
        padding.canonical_spec(tuple(data.shape), S.mesh),
        nnz=None, block_size=S.block_size)


def _xla_spmm(S, pm, out_pshape, d_spec, out_sharding, cfg):
    bs = S.block_size
    gr, gc = S.grid
    mesh = S.mesh
    prec = getattr(jax.lax.Precision, cfg.matmul_precision.upper(),
                   jax.lax.Precision.HIGHEST)

    @jax.jit  # matlint: disable=ML010 pre-seam ops runner cache — the porting worklist (the ML009 legacy-kernel idiom)
    def run(blocks, brows, bcols, dd):
        dd = jax.lax.with_sharding_constraint(dd, NamedSharding(mesh, d_spec))
        want_rows = gc * bs
        if dd.shape[0] < want_rows:
            dd = jnp.pad(dd, ((0, want_rows - dd.shape[0]), (0, 0)))
        dblocks = dd[: want_rows].reshape(gc, bs, pm)
        gathered = jnp.take(dblocks, bcols, axis=0)        # [nnzb, bs, pm]
        partial = jax.lax.dot_general(
            blocks, gathered,
            (((2,), (1,)), ((0,), (0,))),                   # batched tile GEMM
            precision=prec,
            preferred_element_type=jnp.float32)             # [nnzb, bs, pm]
        summed = jax.ops.segment_sum(partial, brows, num_segments=gr)
        out = summed.reshape(gr * bs, pm).astype(blocks.dtype)
        out = out[: out_pshape[0], : out_pshape[1]]
        if out.shape != out_pshape:
            out = jnp.pad(out, ((0, out_pshape[0] - out.shape[0]),
                                (0, out_pshape[1] - out.shape[1])))
        return jax.lax.with_sharding_constraint(out, out_sharding)

    return run


def spmv(S: BlockSparseMatrix, v: BlockMatrix,
         config: Optional[MatrelConfig] = None) -> BlockMatrix:
    """Sparse matrix × vector — the PageRank building block."""
    return spmm(S, v, config)
