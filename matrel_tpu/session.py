"""MatrelSession — the entry point, analogue of the reference's
``MatfastSession`` (SURVEY.md §2 "Session & catalog", §3.1).

The reference subclasses SparkSession and installs its own analyzer /
optimizer / planner into the session state; executors register with the
cluster manager. Here the session owns the device mesh (the "cluster"), the
config (the SparkConf analogue), a tiny named-matrix catalog, and the
optimize→plan→jit pipeline, plus a compiled-plan cache keyed by expression
structure so repeated actions don't re-trace (the Spark query-cache
analogue).
"""

from __future__ import annotations

import hashlib
import itertools
import logging
import os
import threading
import time
import types
from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np
from jax.sharding import Mesh

from matrel_tpu import executor as executor_lib
from matrel_tpu.config import MatrelConfig, default_config, normalize_sla
from matrel_tpu.core import mesh as mesh_lib
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir.expr import MatExpr, as_expr
from matrel_tpu.obs import export as export_lib
from matrel_tpu.obs import provenance as provenance_lib
from matrel_tpu.obs import slo as slo_lib
from matrel_tpu.obs import trace as trace_lib
from matrel_tpu.resilience import breaker as breaker_lib
from matrel_tpu.resilience import brownout as brownout_lib
from matrel_tpu.resilience import degrade as degrade_lib
from matrel_tpu.resilience import errors as rerrors
from matrel_tpu.resilience import faults as faults_lib
from matrel_tpu.resilience import retry as retry_lib
from matrel_tpu.resilience.retry import RetryPolicy
from matrel_tpu.serve import mqo as mqo_lib
from matrel_tpu.serve import replan as replan_lib
from matrel_tpu.serve.result_cache import (CacheEntry, ResultCache,
                                           result_nbytes)
from matrel_tpu.utils import lockdep

log = logging.getLogger("matrel_tpu")

_active: Optional["MatrelSession"] = None


_deadline_left = retry_lib.deadline_left

_query_seq = itertools.count()


class MatrelSession:
    """Owns mesh + config + catalog; compiles and runs matrix queries."""

    def __init__(self, mesh: Optional[Mesh] = None,
                 config: Optional[MatrelConfig] = None):
        self.config = config or default_config()
        # concurrency sanitizer (utils/lockdep.py;
        # docs/CONCURRENCY.md): armed BEFORE any of this session's
        # locks construct, so they all come back instrumented. Off
        # (the default) this is one false branch — the seam keeps
        # returning raw threading primitives and zero lockdep objects
        # exist (poisoned-init test-enforced). The emit hook is wired
        # after the obs attributes exist (end of __init__).
        if self.config.lockdep_enable:
            lockdep.enable(
                raise_on_violation=self.config.lockdep_raise)
        self.mesh = mesh or mesh_lib.make_mesh(
            self.config.mesh_shape, self.config.mesh_axis_names)
        self.catalog: dict[str, BlockMatrix] = {}
        # LRU plan cache: every cached plan pins its hoisted sparse
        # payloads (extra_args) in device HBM and its leaf matrices via
        # leaf_order — unbounded growth OOMs long-lived sessions, so
        # least-recently-used plans evict at the config's plan-count /
        # hoisted-byte bounds
        self._plan_cache: "OrderedDict[str, executor_lib.CompiledPlan]" \
            = OrderedDict()
        self._plan_cache_bytes = 0
        self._plan_cache_evicted = 0
        self._event_log = None      # lazily built (obs_level != "off")
        # serving layer (matrel_tpu/serve/): cross-query result cache
        # (inert until config.result_cache_max_bytes > 0) and the async
        # submit pipeline (worker built on first submit). The lock
        # keeps the plan cache consistent when the pipeline's admission
        # worker and the caller's thread compile concurrently.
        self._result_cache = ResultCache()
        self._serve = None
        self._compile_lock = lockdep.make_rlock("session.compile")
        # durable spill hierarchy (serve/spill.py; docs/DURABILITY.md):
        # host/disk tiers under the result cache + the warm-restart
        # snapshot index — None for the default config (spill_enable
        # off: the structural zero-object contract, poisoned-init
        # test-enforced; spill._CONSTRUCTED stays 0)
        self._spill = None
        if self.config.spill_enable:
            from matrel_tpu.serve.spill import SpillManager
            self._spill = SpillManager(self)
            self._spill.emit = self._emit_spill_event
            self._result_cache.attach_spill(self._spill)
        # multi-query optimization (serve/mqo.py; docs/SERVING.md):
        # cross-query CSE + plan templates — None for the default
        # config (cse_enable off: the structural zero-object contract,
        # poisoned-init test-enforced; mqo._CONSTRUCTED stays 0)
        self._mqo = None
        # obs tier 2 (obs/trace.py): the flight-recorder ring is
        # independent of obs_level (always-cheap post-mortem trail);
        # the tracer exists iff ANY span consumer does — with neither,
        # compute()'s fast path never creates a span object at all
        fr_cap = self.config.obs_flight_recorder
        self._flight = (trace_lib.FlightRecorder(fr_cap)
                        if fr_cap > 0 else None)
        self._tracer = (trace_lib.Tracer(self._obs_emit)
                        if (self._flight is not None
                            or self.config.obs_level != "off")
                        else None)
        # overload control plane (docs/OVERLOAD.md): adaptive brownout
        # controller + per-plan-class circuit breakers — both None for
        # the default config (the structural zero-object contract the
        # faults harness set: nothing constructed, nothing consulted)
        self._brownout = brownout_lib.from_config(self.config)
        self._breakers = breaker_lib.BreakerRegistry.from_config(
            self.config)
        # incremental view maintenance (serve/ivm.py; docs/IVM.md):
        # the delta plane is built lazily on the FIRST register_delta
        # — generation 0 means it was never used, every result-cache
        # key keeps the historical format, and zero delta-plane
        # objects exist (the brownout/breaker zero-object contract)
        self._delta_plane = None
        self._delta_gen = 0
        # live telemetry plane (obs/slo.py, obs/export.py;
        # docs/OBSERVABILITY.md tier 3): per-tenant SLO burn-rate
        # monitors + the in-process metrics endpoint — both None for
        # the default config (no slo_targets / port 0: zero monitor
        # objects, zero exporter threads — the brownout/breaker
        # structural-zero contract, test-enforced). The exporter is
        # built LAST: its handler snapshots session state, so every
        # subsystem it reads must already exist.
        self._slo = slo_lib.from_config(self.config,
                                        emit=self._emit_alert_event)
        # multi-slice serving fleet (serve/fleet.py; docs/FLEET.md):
        # built lazily on the first submit when config.fleet_slices
        # >= 1 — None for the default config (the structural
        # zero-object contract: no slice sessions, no directory,
        # poisoned-init test-enforced). _slice_tag marks THIS session
        # as slice N of a fleet: its obs events carry the tag so the
        # per-slice roll-up can attribute them.
        self._fleet = None
        self._slice_tag: Optional[int] = None
        # fleet device arbitration (serve/fleet.py): an RLock SHARED
        # by the parent and every slice session whose execution
        # domains overlap — collective programs from two sessions
        # sharing devices must never be in flight together (colliding
        # run-ids over the same device list deadlock the
        # cross-program rendezvous; the classic multi-program
        # collective hazard). None (the default) = plain async
        # dispatch, bit-identical.
        self._exec_lock = None
        # answer provenance ledger (obs/provenance.py;
        # docs/OBSERVABILITY.md tier 4): None for the default config
        # (obs_provenance = 0 — the brownout/breaker structural-zero
        # contract: no ledger, no record objects, poisoned-init
        # test-enforced). When on, every served answer appends one
        # lineage record here and emits a ``provenance`` event.
        self._prov = provenance_lib.from_config(self.config)
        # cost-model re-plan controller (serve/replan.py;
        # docs/COST_MODEL.md): watches the query event stream and
        # turns a firing DRIFT rank-order flag into a coefficient
        # re-calibration + background re-warm of the affected cached
        # plans — None unless config.coeff_replan_enable (the
        # structural-zero contract: replan._CONSTRUCTED stays 0,
        # poisoned-init test-enforced)
        self._replan = replan_lib.from_config(self.config, self)
        self._exporter = export_lib.from_config(self)
        # lockdep diagnostics ride the ONE obs funnel as ``lockdep``
        # events (event log + flight ring; history --summary rolls
        # them up, --check fails on inversions). Wired last: the
        # funnel reads _slice_tag/_flight, which now exist.
        if self.config.lockdep_enable:
            lockdep.set_emit(
                lambda rec: self._obs_emit("lockdep", rec))

    # -- builder (MatfastSession.builder().getOrCreate() analogue) ---------

    class Builder:
        def __init__(self):
            self._cfg = default_config()
            self._mesh = None
            self._explicit_cfg = False

        def config(self, **kw) -> "MatrelSession.Builder":
            self._cfg = self._cfg.replace(**kw)
            self._explicit_cfg = True
            return self

        def mesh(self, mesh: Mesh) -> "MatrelSession.Builder":
            self._mesh = mesh
            return self

        def get_or_create(self) -> "MatrelSession":
            global _active
            if _active is None:
                _active = MatrelSession(self._mesh, self._cfg)
                return _active
            # a live session wins — but silently ignoring an
            # explicitly-requested different config/mesh hands the
            # caller settings they did not ask for
            if self._explicit_cfg and self._cfg != _active.config:
                log.warning(
                    "MatrelSession.builder(): a session already exists; "
                    "ignoring the requested config (differs from the "
                    "live session's — call reset_session() first to "
                    "rebuild with new settings)")
            if self._mesh is not None and self._mesh != _active.mesh:
                log.warning(
                    "MatrelSession.builder(): a session already exists; "
                    "ignoring the requested mesh (differs from the live "
                    "session's — call reset_session() first)")
            return _active

    @staticmethod
    def builder() -> "MatrelSession.Builder":
        return MatrelSession.Builder()

    # -- catalog (matrix tables, SQL-facing names) -------------------------

    def register(self, name: str, matrix: BlockMatrix) -> None:
        old = self.catalog.get(name)
        self.catalog[name] = matrix
        if self._fleet is not None and old is not matrix:
            # fleet write-through (docs/FLEET.md): the table
            # replicates into every slice, slice caches invalidate
            # through each slice session's own rebind path, and
            # directory records naming it drop. Gated like the
            # single-controller rebind below: an idempotent
            # re-register of the SAME object is a no-op there and
            # must be one here too — unconditional it would wipe the
            # directory and every slice cache and re-replicate the
            # table on every no-op call
            self._fleet.on_register(name, matrix)
        if old is not None and old is not matrix:
            # catalog REBIND: every cached result computed from the old
            # binding is stale the moment the name means something else
            # — drop them (and their pinned device bytes) now, not at
            # some later false hit. Dep sets are transitive, so results
            # built FROM cached intermediates of the old binding drop
            # too. Safe when the cache is off/empty (no-op). With a
            # brownout controller the invalidated entries move to the
            # bounded STALE graveyard instead: rung 2 may serve them to
            # queries declaring a staleness_ms tolerance
            # (docs/OVERLOAD.md); the default path drops them exactly
            # as before.
            self._result_cache.invalidate_deps(
                {id(old)},
                keep_stale=self._brownout is not None,
                stale_max=self.config.result_cache_max_entries,
                stale_max_bytes=self.config.result_cache_max_bytes)
            if self._spill is not None:
                # restored snapshot entries carry dep NAMES, not ids
                # (serve/spill.py): the rebind kill reaches them by
                # name — the id cascade above already covered the
                # live host/disk tiers
                self._spill.invalidate_names({name})

    def table(self, name: str) -> BlockMatrix:
        return self.catalog[name]

    def register_delta(self, name: str, delta, kind: str = "auto"
                       ) -> dict:
        """Rebind a catalog name to ``A + ΔA`` and MAINTAIN dependent
        cached results instead of invalidating them (incremental view
        maintenance — serve/ivm.py, ir/delta.py; docs/IVM.md).

        ``delta`` is the update in whichever form the caller has it:
        ``(rows, cols[, vals])`` edge arrays or a COOMatrix (``kind=
        "coo"``), a ``(U, V)`` pair with ``ΔA = U·Vᵀ`` (``kind=
        "lowrank"``), or a same-shaped array (``kind="dense"``);
        ``kind="auto"`` disambiguates by shape. Each cached entry
        depending on the old binding is patched in place through the
        delta algebra where a rule applies AND the patch prices below
        recompute (``config.delta_patch_mode``; a measured autotune
        ``ivm|`` winner overrides the estimate); everything else falls
        back to exactly the historical transitive kill, so answers are
        never wrong — at worst a repeat pays recompute like today.

        Patched entries carry ``delta:<gen>|`` provenance in their
        cache keys and a composed error bound MV113 verifies against
        fresh execution. Returns the maintenance summary (also emitted
        as a ``delta`` obs event)."""
        old = self.catalog.get(name)
        if old is None:
            raise KeyError(
                f"register_delta: {name!r} is not a bound catalog "
                f"name — register() it first")
        from matrel_tpu.ir import delta as delta_lib
        d = delta_lib.as_delta(delta, old, kind, self.config)
        with self._compile_lock:
            if self._delta_plane is None:
                from matrel_tpu.serve.ivm import DeltaPlane
                self._delta_plane = DeltaPlane(self)
            out = self._delta_plane.apply(name, old, d)
        if self._fleet is not None:
            # fleet slices hold REPLICAS of the old binding: the delta
            # plane patched the parent's caches in place, but a slice
            # replica cannot be patched remotely — re-replicate the
            # new binding (slice caches invalidate through their own
            # rebind path, directory records naming it drop). Answers
            # stay correct; a slice repeat pays one recompute.
            self._fleet.on_register(name, self.catalog[name])
        # SLO feed (obs/slo.py): patch latency reports under the
        # pseudo-tenant "ivm", so a dashboard stream's maintenance
        # path can carry its own latency objective (docs/IVM.md
        # events are the offline view of the same number). No-op
        # without a declared ivm target.
        if self._slo is not None and isinstance(out.get("ms"),
                                                (int, float)):
            self._slo.observe_latency(slo_lib.IVM_TENANT,
                                      float(out["ms"]))
        return out

    def save_catalog(self, directory: str,
                     step: Optional[int] = None) -> str:
        """Persist every registered table (atomic step dir, sharding
        metadata included) — the session-level face of the checkpoint
        subsystem, so a catalog survives process restarts the way the
        reference's persisted tables do. ``step`` defaults to the NEXT
        step in the directory (a fixed default like 0 would be GC'd by
        the keep-k policy the moment older saves carry higher steps).
        Returns the step path."""
        from matrel_tpu.utils.checkpoint import CheckpointManager
        mgr = CheckpointManager(directory, config=self.config)
        if step is None:
            step = mgr.next_step()
        return mgr.save(step, matrices=dict(self.catalog))

    def load_catalog(self, directory: str,
                     step: Optional[int] = None) -> list:
        """Restore tables saved by save_catalog into this session's
        catalog (sharding-preserving, existing names overwritten).
        Returns the restored names; empty directory → empty list."""
        from matrel_tpu.utils.checkpoint import CheckpointManager
        got = CheckpointManager(directory,
                                config=self.config).restore(self.mesh,
                                                            step)
        if got is None:
            return []
        _step, mats, _arrays, _state = got
        # through register(), not a bare dict update: an overwritten
        # name is a catalog REBIND, and cached results computed from
        # the old binding must invalidate here exactly as they do for
        # an explicit register() (serve/result_cache.py contract)
        for name in sorted(mats):
            self.register(name, mats[name])
        return sorted(mats)

    # -- durable state (serve/spill.py; docs/DURABILITY.md) -----------------

    def save_state(self, directory: Optional[str] = None) -> dict:
        """Snapshot this session's durable state — catalog bindings
        (the checkpoint step format), the result-cache index (entries
        with catalog-name-computable keys, frozen as sha1-verified
        disk artifacts), the fleet directory, MQO template keys, and
        the autotune/drift tables — under ``directory`` (default
        ``config.state_dir``; neither set raises ValueError). A later
        :meth:`restore` in a NEW process comes back serving warm:
        repeats thaw the frozen entries instead of recomputing.
        Without ``spill_enable`` only the catalog + tables persist
        (cached results are skipped, counted in the summary) — the
        zero-object default stays zero. Returns the save summary,
        also emitted as a ``spill`` event (op ``save_state``)."""
        from matrel_tpu.serve import spill as spill_lib
        with self._compile_lock:
            out = spill_lib.save_state(self, directory)
        self._emit_spill_event({"op": "save_state", **out})
        return out

    def restore(self, directory: Optional[str] = None) -> dict:
        """Warm-restart this session from a :meth:`save_state`
        snapshot: catalog restored through :meth:`register`, tables
        written if absent, the result-cache index seeded into the
        spill hierarchy's restored tier (requires ``spill_enable``;
        entries thaw lazily on first consult, paying only the priced
        transfer), the fleet directory re-seeded as affinity hints,
        MQO template keys re-indexed. ROBUST: a corrupt/truncated
        snapshot (or any single bad component) warns and cold-starts
        — restore never crashes a restart; a disk-tier entry failing
        its sha1 later surfaces as a per-entry miss (typed
        ``SnapshotCorruption`` internally), never a wrong answer.
        Returns the restore summary, also emitted as a ``spill``
        event (op ``restore``)."""
        from matrel_tpu.serve import spill as spill_lib
        with self._compile_lock:
            out = spill_lib.load_snapshot(self, directory)
        self._emit_spill_event({"op": "restore", **out})
        return out

    # -- constructors bound to this session's mesh/config ------------------

    def from_numpy(self, arr: np.ndarray, **kw) -> BlockMatrix:
        return BlockMatrix.from_numpy(arr, mesh=self.mesh, config=self.config, **kw)

    def random(self, shape: Tuple[int, int], **kw) -> BlockMatrix:
        return BlockMatrix.random(shape, mesh=self.mesh, config=self.config, **kw)

    def zeros(self, shape: Tuple[int, int], **kw) -> BlockMatrix:
        return BlockMatrix.zeros(shape, mesh=self.mesh, config=self.config, **kw)

    def eye(self, n: int, **kw) -> BlockMatrix:
        return BlockMatrix.eye(n, mesh=self.mesh, config=self.config, **kw)

    # -- actions ------------------------------------------------------------

    def compile(self, expr: MatExpr,
                precision: Optional[str] = None
                ) -> executor_lib.CompiledPlan:
        e = as_expr(expr)
        return self._compile_entry(e, sla=self._resolve_sla(precision,
                                                            e))[0]

    # -- precision SLA resolution (docs/PRECISION.md) ----------------------

    def _resolve_sla(self, precision, e: Optional[MatExpr] = None) -> str:
        """One query's effective precision SLA: the explicit
        ``precision=`` argument beats a SQL ``PRECISION '...'`` clause
        (stamped out-of-band by sql.parse_sql) beats the session
        default (config.precision_sla)."""
        if precision is not None:
            return normalize_sla(precision)
        sql_sla = getattr(e, "_sql_precision", None) if e is not None \
            else None
        if sql_sla is not None:
            return sql_sla            # parse_sql already normalised
        return self.config.precision_sla

    def _sla_config(self, sla: str) -> MatrelConfig:
        """The config a query at this SLA compiles under — the session
        config itself when they agree (the common case: no dataclass
        churn on the hot path)."""
        if sla == self.config.precision_sla:
            return self.config
        return self.config.replace(precision_sla=sla)

    def _compile_entry(self, e: MatExpr, sla: Optional[str] = None,
                       rung: int = 0
                       ) -> Tuple[executor_lib.CompiledPlan, bool, str]:
        """(plan, cache_hit, key) — the compile path with its cache
        outcome exposed, so compute() can emit hit/miss events without
        a second key computation. ``rung`` > 0 compiles a DEGRADED
        retry attempt (resilience/degrade.py): the config loses the
        rung's features and the key gains the ``degr:<rung>|`` prefix,
        so a degraded plan never shares a cache slot with the stamped
        original (the axisw/prec prefix idiom)."""
        sla = sla if sla is not None else self.config.precision_sla
        # fault site "compile" (resilience/faults.py): free when off
        faults_lib.check("compile", self.config)
        key, pins = _plan_key(e)
        key = (degrade_lib.key_prefix(rung) + self._axisw_prefix()
               + self._coeff_prefix() + _prec_prefix(sla) + key)
        with self._compile_lock:
            plan = self._plan_cache.get(key)
            if plan is not None:
                self._plan_cache.move_to_end(key)
                return plan, True, key
            try:
                plan = executor_lib.compile_expr(
                    e, self.mesh,
                    degrade_lib.apply_rung(self._sla_config(sla), rung))
            except Exception as ex:
                # post-mortem trail BEFORE the error propagates: a
                # VerificationError / compile failure in the field
                # leaves the flight-recorder artifact, not just the
                # exception string (no-op when the recorder is off)
                self._flight_auto_dump(ex)
                raise
            # pin every id()-keyed object on the cached plan: a garbage-
            # collected object's address can be REUSED by CPython, and a
            # later distinct object at the recycled address would falsely
            # hit this entry. Pinning the expr alone is not enough — a
            # REBOUND module global referenced by a predicate is no longer
            # reachable from the expr, so its old value is pinned
            # explicitly via the collected pins list.
            plan._cache_pin = (e, pins)
            if rung:
                # the rung rides the plan so obs events / explain say
                # WHICH ladder step produced this attempt's plan
                plan.meta["degrade"] = degrade_lib.rung_meta(rung)
            self._plan_cache[key] = plan
            self._plan_cache_bytes += _plan_bytes(plan)
            self._evict_plans()
            return plan, False, key

    def _axisw_prefix(self) -> str:
        """Topology weights change which strategies get stamped, so
        weighted and unweighted plans must never share a cache entry
        (the detection path can flip weights without any config field
        changing — the expression key alone is not enough). Unweighted
        keys keep the historical format (empty prefix)."""
        wts = mesh_lib.axis_weights(self.mesh, self.config)
        if wts == (1.0, 1.0):
            return ""
        return f"axisw:{wts[0]:g}x{wts[1]:g}|"

    def _coeff_epoch(self) -> Optional[str]:
        """The coefficient epoch in force (parallel/coeffs.epoch — a
        digest of the drift table's blended ratios), or None with
        coeff_planner_enable off. Rides every query record and
        provenance capture, so obs can always say which coefficients
        priced an answer's plan (docs/COST_MODEL.md)."""
        if not self.config.coeff_planner_enable:
            return None
        from matrel_tpu.obs import drift as drift_lib
        from matrel_tpu.parallel import coeffs as coeffs_lib
        return coeffs_lib.epoch(drift_lib.table_path(self.config))

    def _coeff_prefix(self) -> str:
        """Coefficient-epoch plan-key isolation (the axisw/prec/delta
        prefix idiom; docs/COST_MODEL.md): plans ranked under
        different learned coefficients must never share a cache slot —
        a re-calibration (serve/replan.py) bumps the epoch, so every
        affected entry invalidates LAZILY: old plans keep serving
        in-flight queries, new lookups miss and recompile under the
        corrected coefficients. Empty with coeff_planner_enable off
        (the historical key format, bit-identical)."""
        ep = self._coeff_epoch()
        return "" if ep is None else f"coeffv:{ep}|"

    def _compile_multi_entry(self, roots: List[MatExpr],
                             sla: Optional[str] = None,
                             rung: int = 0
                             ) -> Tuple["executor_lib.MultiPlan", bool,
                                        List[str]]:
        """(multiplan, cache_hit, per-root keys) — the MultiPlan twin
        of :meth:`_compile_entry`. Compiled MultiPlans participate in
        the SAME session plan cache (one LRU, one byte budget — their
        hoisted payloads pin HBM exactly like single plans'), keyed on
        the SORTED unique root keys plus the axis-weight prefix, so a
        batch resubmitted in any order (or with duplicate roots) hits
        instead of recompiling every call. The cached plan remembers
        its root-key order (``_root_keys``) so callers can map outputs
        back to their own root order."""
        sla = sla if sla is not None else self.config.precision_sla
        # fault site "compile": the MultiPlan twin shares the site
        faults_lib.check("compile", self.config)
        keyed = []
        pins_all: list = []
        for e in roots:
            k, p = _plan_key(e)
            keyed.append(k)
            pins_all.extend(p)
        uniq: "OrderedDict[str, MatExpr]" = OrderedDict()
        for k, e in zip(keyed, roots):
            uniq.setdefault(k, e)
        skeys = sorted(uniq)
        mkey = ("multi:" + degrade_lib.key_prefix(rung)
                + self._axisw_prefix() + self._coeff_prefix()
                + _prec_prefix(sla) + "||".join(skeys))
        with self._compile_lock:
            plan = self._plan_cache.get(mkey)
            if plan is not None:
                self._plan_cache.move_to_end(mkey)
                return plan, True, keyed
            try:
                plan = executor_lib.compile_exprs(
                    [uniq[k] for k in skeys], self.mesh,
                    degrade_lib.apply_rung(self._sla_config(sla), rung))
            except Exception as ex:
                self._flight_auto_dump(ex)   # same trail as the
                raise                        # single-plan entry
            if rung:
                plan.meta["degrade"] = degrade_lib.rung_meta(rung)
            plan._cache_pin = (tuple(uniq[k] for k in skeys), pins_all)
            plan._root_keys = tuple(skeys)
            self._plan_cache[mkey] = plan
            self._plan_cache_bytes += _plan_bytes(plan)
            self._evict_plans()
            return plan, False, keyed

    def _evict_plans(self) -> None:
        """Drop least-recently-used plans past the config bounds. The
        byte budget counts hoisted payloads (extra_args) — the device
        memory a cached plan pins beyond its leaves."""
        cfg = self.config
        while self._plan_cache and (
                len(self._plan_cache) > cfg.plan_cache_max_plans
                or self._plan_cache_bytes > cfg.plan_cache_max_bytes):
            if len(self._plan_cache) == 1 and \
                    len(self._plan_cache) <= cfg.plan_cache_max_plans:
                break    # never evict the sole (just-inserted) plan
            _, old = self._plan_cache.popitem(last=False)
            self._plan_cache_bytes -= _plan_bytes(old)
            self._plan_cache_evicted += 1
        self._plan_cache_bytes = max(self._plan_cache_bytes, 0)

    def plan_cache_info(self) -> dict:
        """Cache observability: entry count + pinned hoisted bytes +
        lifetime eviction count."""
        return {"plans": len(self._plan_cache),
                "hoisted_bytes": self._plan_cache_bytes,
                "evicted": self._plan_cache_evicted}

    def _replan_warm(self, classes) -> dict:
        """Proactively recompile cached plans whose matmul decisions
        touch the given shape classes, under the CURRENT coefficient
        epoch (serve/replan.py's background thread calls this after a
        re-calibration). Correctness never depends on it — the
        ``coeffv:`` key prefix already makes every post-bump lookup
        miss and recompile lazily; this pass just pays the compiles
        off the query path. Each entry re-warms from its pinned root
        expr(s) at the session default SLA / rung 0 — SLA-variant and
        degraded entries re-warm lazily on first use (a warm is an
        optimization, so fidelity loss there costs one compile, never
        an answer). Old-epoch entries stay until LRU eviction: an
        in-flight query holding one is never invalidated under it."""
        from matrel_tpu.obs import drift as drift_lib
        with self._compile_lock:
            snapshot = list(self._plan_cache.values())
        matched = warmed = 0
        for plan in snapshot:
            pin = getattr(plan, "_cache_pin", None)
            if pin is None:
                continue
            try:
                decs = executor_lib.plan_matmul_decisions(plan)
            except Exception:  # matlint: disable=ML007 best-effort warm census — an unreadable plan is skipped; the lazy coeffv: miss still re-plans it
                continue
            if not any(drift_lib.shape_class(d.get("dims") or ())
                       in classes for d in decs):
                continue
            matched += 1
            roots = pin[0]
            try:
                if isinstance(roots, tuple):
                    self._compile_multi_entry(list(roots))
                else:
                    self._compile_entry(roots)
                warmed += 1
            except Exception:
                log.warning("replan: warm recompile failed",
                            exc_info=True)
        return {"matched": matched, "replanned": warmed}

    # -- cross-query result cache (matrel_tpu/serve/) ----------------------

    def _rc_enabled(self) -> bool:
        return self.config.result_cache_max_bytes > 0

    def result_cache_info(self) -> dict:
        """``plan_cache_info``-style surface for the materialized-result
        cache: entries, pinned device bytes, hit/miss/interior-hit,
        eviction and invalidation counts."""
        info = self._result_cache.info()
        info["max_bytes"] = self.config.result_cache_max_bytes
        info["max_entries"] = self.config.result_cache_max_entries
        return info

    def _rc_key_prefix(self, sla: str) -> str:
        """The full result-cache key prefix of one query: the delta
        GENERATION prefix (``delta:<gen>|`` — docs/IVM.md; empty until
        ``register_delta`` is ever used, so the historical key format
        is bit-identical) composed with the precision-tier isolation
        prefix. Generations partition the cache the way SLAs do: a
        patched entry from generation N can never answer a query at
        N+1 without having been re-patched (or re-executed)."""
        gen = self._delta_gen
        return (("" if not gen else f"delta:{gen}|")
                + _prec_prefix(sla))

    def _rc_admit(self, e: MatExpr, prefix: str = ""):
        """One result-cache admission for a query: (entry-or-None,
        root key, pins, possibly-substituted expr). ONE structural walk
        (_plan_key_spans) serves both the root-level consult — a hit
        answers without compiling or executing anything — and, on a
        miss, every interior probe of the substitution pass.

        ``prefix`` carries the query's precision-tier isolation
        (_prec_prefix): every consult, interior probe AND insertion
        keys under it, so a ``"fast"`` entry can never answer an
        ``"exact"`` query (or vice versa) — accuracy SLAs partition
        the cache, they do not share it."""
        # fault site "rc_probe": a faulting cache consult is exactly
        # what the ladder's rung-4 bypass exists to route around
        faults_lib.check("rc_probe", self.config)
        parts, pins, spans = _plan_key_spans(e)
        key = prefix + "|".join(parts)
        ent = self._result_cache.lookup(key)
        if ent is None and self._spill is not None \
                and self._spill.restored_count():
            # warm restart (docs/DURABILITY.md): a restored snapshot's
            # name-keyed index may hold this query's value frozen at
            # disk tier — thaw it, and the repeat pays a priced
            # transfer instead of a recompute
            ent = self._rc_thaw_restored(e, prefix, key)
        if ent is not None:
            return ent, key, pins, e
        return None, key, pins, self._rc_substitute(e, parts, spans,
                                                    prefix)

    def _rc_thaw_restored(self, e: MatExpr, prefix: str, key: str):
        """Consult the restored-snapshot index on a cache miss: the
        session-independent NAME key (placement.fleet_key — catalog
        names, not id()s) is the only key format that survives a
        process boundary. A thaw re-resolves dep names against the
        LIVE catalog, re-inserts under the query's live structural
        key (so the next repeat is a plain HBM hit), and corrects the
        miss the first-level lookup already counted. Precision tiers
        stay isolated: the entry thaws only for a query under the
        same ``prec:`` token it was cached under."""
        from matrel_tpu.serve import placement as placement_lib
        nk = placement_lib.fleet_key(
            e, {id(m): n for n, m in self.catalog.items()})
        if nk is None:
            return None
        # the prec component of the admission prefix (the delta:<gen>|
        # part, when present, always precedes it and ends at its "|")
        prec = (prefix.split("|", 1)[1]
                if prefix.startswith("delta:") else prefix)
        ent = self._spill.thaw_restored(nk, prec, self.catalog.get)
        if ent is None:
            return None
        self._result_cache.note_restored_hit()
        self._result_cache.put(key, ent,
                               self.config.result_cache_max_bytes,
                               self.config.result_cache_max_entries)
        return ent

    def _rc_leaf(self, ent: CacheEntry) -> MatExpr:
        """Lift a cache entry into planning as an already-laid-out
        LEAF: ``infer_layout`` reads the cached result's real
        PartitionSpec and ``comm_cost`` credits the reuse — the whole
        subplan it replaces is never re-priced, never re-executed. The
        ``result_cache`` stamp records what the cache promised (layout/
        dtype at insertion) so the MV107 pass can prove the plan and
        the cache still agree, plus the transitive dep ids consumers
        fold into their own invalidation sets."""
        from matrel_tpu.ir import expr as expr_mod
        stamp = {
            "key_hash": ent.key_hash,
            "layout": ent.layout,
            "dtype": ent.dtype,
            "deps": sorted(ent.dep_ids),
        }
        if ent.delta_gen:
            # IVM provenance (docs/IVM.md): the consumed value was
            # delta-PATCHED, not freshly executed — MV113's static
            # half checks the stamp's coherence, its dynamic half
            # re-proves the value against fresh execution
            stamp["delta"] = {"gen": ent.delta_gen,
                              "rule": ent.delta_rule,
                              "err_bound": ent.err_bound}
        if ent.fleet:
            # fleet provenance (docs/FLEET.md): the consumed value was
            # REPLICATED from another slice's cache — MV114 re-checks
            # the owning slice's recorded layout/dtype against the
            # entry's own claims (the MV107 stale-stamp idiom across
            # slices)
            stamp["fleet"] = dict(ent.fleet)
        if ent.spill:
            # spill provenance (docs/DURABILITY.md): the consumed
            # value was THAWED from a lower tier — MV117 re-checks
            # the stamped legs against the step vocabulary and the
            # peak-HBM budget claim
            stamp["spill"] = dict(ent.spill)
        node = expr_mod.leaf(ent.result).with_attrs(result_cache=stamp)
        if self._prov is not None:
            # lineage threading (obs tier 4): the consumed entry's
            # provenance stamp rides the substitution leaf so MV115
            # can cross-check it against the result_cache stamp — the
            # attrs write lives in the ledger (ML015's one seam)
            node = self._prov.stamp_leaf(node, ent)
        return node

    def _rc_substitute(self, e: MatExpr, parts: Optional[list] = None,
                       spans: Optional[dict] = None,
                       prefix: str = "") -> MatExpr:
        """Replace every cached INTERIOR subexpression with its result
        leaf (top-down; a hit stops the descent — everything under it
        is already paid for). The root is the caller's business
        (:meth:`_rc_admit`). ``parts``/``spans`` come from the
        admission's single ``_plan_key_spans`` walk, so each interior
        probe is a slice join, not a fresh subtree walk; a bare call
        (tests, external callers) computes its own. ``prefix`` is the
        admission's precision-tier isolation prefix — interior probes
        only ever hit entries computed under the SAME SLA."""
        if not e.children:
            return e
        if parts is None or spans is None:
            parts, _pins, spans = _plan_key_spans(e)
        new_children = []
        changed = False
        for c in e.children:
            if not c.children and c.kind in ("leaf", "sparse_leaf",
                                             "coo_leaf"):
                new_children.append(c)
                continue
            s, t = spans[c.uid]
            ent = self._result_cache.probe(
                prefix + "|".join(parts[s:t]))
            if ent is not None:
                new_children.append(self._rc_leaf(ent))
                changed = True
                continue
            nc = self._rc_substitute(c, parts, spans, prefix)
            changed = changed or (nc is not c)
            new_children.append(nc)
        return e.with_children(tuple(new_children)) if changed else e

    def _rc_deps(self, e: MatExpr) -> frozenset:
        """id() of every SOURCE matrix a query's result depends on —
        ordinary leaves contribute their matrix, result-cache leaves
        their recorded (transitive) dep set, so invalidating a rebound
        catalog matrix cascades through derived entries."""
        deps: set = set()

        def walk(n: MatExpr):
            if n.kind == "leaf":
                rc = n.attrs.get("result_cache")
                if rc is not None:
                    deps.update(rc["deps"])
                    return
                cse = n.attrs.get("cse")
                if cse is not None:
                    # a hoisted shared interior carries its own
                    # transitive dep set (serve/mqo.py) — consumers
                    # fold it in so rebinding any source matrix under
                    # the hoist cascades into every consumer's entry
                    deps.update(cse["deps"])
                    return
                deps.add(id(n.attrs["matrix"]))
                return
            if n.kind in ("sparse_leaf", "coo_leaf"):
                deps.add(id(n.attrs["matrix"]))
                return
            for c in n.children:
                walk(c)

        walk(e)
        return frozenset(deps)

    def _rc_stale_probe(self, e: MatExpr, sla: str,
                        staleness_ms: Optional[float]):
        """Brownout rung-2 consult (docs/OVERLOAD.md): the STALE
        result-cache entry for this query, iff the query declared a
        ``staleness_ms`` tolerance its age fits. Same structural key +
        precision prefix as a live consult, so a stale "fast" result
        can never answer an "exact" query either."""
        if (not self._rc_enabled() or not staleness_ms
                or staleness_ms <= 0):
            return None
        parts, _pins, _spans = _plan_key_spans(e)
        key = self._rc_key_prefix(sla) + "|".join(parts)
        return self._result_cache.lookup_stale(key, staleness_ms)

    def _rc_insert(self, key: str, pins: list, executed: MatExpr,
                   out: BlockMatrix, orig: Optional[MatExpr] = None,
                   prec: str = "", plan=None,
                   prov: Optional[dict] = None) -> None:
        """Cache one executed query result under its structural key.
        ``executed`` is the (possibly substituted) tree that actually
        ran — its leaves name the dep matrices; ``pins`` are the key's
        id()-referenced objects (kept alive with the entry so the key
        can never falsely hit a recycled address). ``orig`` is the
        PRE-substitution query tree (what the delta plane derives
        patches from — docs/IVM.md); ``prec`` the tier prefix the key
        carries; ``plan`` supplies the stamped tier's error bound so
        patched descendants compose bounds from the right floor."""
        from matrel_tpu.parallel import planner
        from matrel_tpu.ir import expr as expr_mod
        bound = 0.0
        if plan is not None:
            bound = float(((plan.meta or {}).get("precision") or {})
                          .get("est_rel_err_bound") or 0.0)
        ent = CacheEntry(
            key_hash=hashlib.sha1(key.encode()).hexdigest()[:16],
            result=out,
            pins=tuple(pins),
            dep_ids=self._rc_deps(executed),
            layout=planner._layout_of(expr_mod.leaf(out), self.mesh),
            dtype=str(np.dtype(out.dtype)),
            nbytes=result_nbytes(out),
            expr=orig if orig is not None else executed,
            prec=prec,
            err_bound=bound,
        )
        if prov is not None and self._prov is not None:
            # lineage stamp (obs tier 4): the producing query's ledger
            # record names this entry and vice versa — the write
            # itself lives in the ledger (the ML015 one-seam idiom)
            self._prov.stamp_entry(ent, prov["path"],
                                   prov["query_id"])
        self._result_cache.put(key, ent,
                               self.config.result_cache_max_bytes,
                               self.config.result_cache_max_entries)

    # -- multi-query optimization (serve/mqo.py; docs/SERVING.md) -----------

    def _cse_on(self) -> bool:
        return bool(self.config.cse_enable)

    def _mqo_state(self) -> "mqo_lib.MqoState":
        if self._mqo is None:
            self._mqo = mqo_lib.MqoState(self.config)
        return self._mqo

    def mqo_info(self) -> dict:
        """``plan_cache_info``-style surface for the multi-query
        optimizer: template count, lifetime template hits/inserts,
        hoisted-interior counts. All zeros (and no state constructed)
        with ``cse_enable`` off."""
        if self._mqo is None:
            return {"templates": 0, "template_hits": 0,
                    "template_inserts": 0, "cse_hoisted": 0,
                    "cse_batches": 0}
        return self._mqo.info()

    def _tpl_prefix(self, sla: str, rung: int) -> str:
        """Template keys compose the SAME isolation prefixes as
        concrete plan keys (``degr:``/``axisw:``/``coeffv:``/``prec:``
        — the _compile_entry idiom): a degraded or fast-SLA template
        can never serve a pristine exact query, because the probes
        never share a key namespace."""
        return (degrade_lib.key_prefix(rung) + self._axisw_prefix()
                + self._coeff_prefix() + _prec_prefix(sla))

    def _template_probe(self, e: MatExpr, sla: str, rung: int):
        """(plan, concrete key, bindings) when a cached template can
        serve this query by REBINDING its dense leaves — None when the
        concrete plan-cache entry exists (that path owns its hit-rate
        accounting and pays no rebind), the tree is
        template-ineligible, no template matches, or sound bindings
        cannot be formed (a shared template leaf facing two distinct
        matrices — miss, never a guess)."""
        prefix = self._tpl_prefix(sla, rung)
        key, _pins = _plan_key(e)
        ckey = prefix + key
        with self._compile_lock:
            if ckey in self._plan_cache:
                return None
            try:
                akey, _tp, leaves = mqo_lib.template_key(e)
            except KeyError:
                return None
            st = self._mqo_state()
            ent = st.get_template(prefix + akey)
            if ent is None or not mqo_lib.rebindable(ent):
                return None
            (ak0, uids), = ent.slots
            if ak0 != akey or len(uids) != len(leaves):
                return None
            bindings: dict = {}
            for u, l in zip(uids, leaves):
                m = l.attrs["matrix"]
                prev = bindings.get(u)
                if prev is not None and prev is not m:
                    return None
                bindings[u] = m
            st.template_hits += 1
            return ent.plan, ckey, bindings

    def _template_insert(self, e: MatExpr, plan, sla: str,
                         rung: int) -> None:
        """Record a freshly compiled single plan as a rebindable
        template. Guarded by :func:`mqo_lib.rebindable`: when the
        optimizer dropped or re-created a dense leaf (fresh uid), the
        recorded uids and the program's real binding order disagree —
        a rebind would silently feed stale data, so no template is
        stored (the only cost is no speedup)."""
        try:
            akey, tp, leaves = mqo_lib.template_key(e)
        except KeyError:
            return
        ent = mqo_lib.TemplateEntry(
            plan=plan, slots=((akey, tuple(l.uid for l in leaves)),),
            pins=tuple(tp))
        if not mqo_lib.rebindable(ent):
            return
        with self._compile_lock:
            st = self._mqo_state()
            st.put_template(self._tpl_prefix(sla, rung) + akey, ent)
            st.template_inserts += 1

    def _template_probe_multi(self, roots: List[MatExpr], sla: str,
                              rung: int):
        """(plan, per-root concrete keys, pos, bindings) when a cached
        MultiPlan template matches this batch modulo dense-leaf
        bindings — the :meth:`_template_probe` twin. Roots pair to
        template slots by ABSTRACT key (structurally identical roots
        are interchangeable programs — any assignment within an
        abstract-key group is sound as long as ``pos`` routes each
        concrete root to its assigned slot's output)."""
        prefix = self._tpl_prefix(sla, rung)
        keyed = []
        for e in roots:
            k, _p = _plan_key(e)
            keyed.append(k)
        uniq: "OrderedDict[str, MatExpr]" = OrderedDict()
        for k, e in zip(keyed, roots):
            uniq.setdefault(k, e)
        skeys = sorted(uniq)
        mkey = "multi:" + prefix + "||".join(skeys)
        with self._compile_lock:
            if mkey in self._plan_cache:
                return None
            try:
                ab = {}
                for k in skeys:
                    ak, _tp, lv = mqo_lib.template_key(uniq[k])
                    ab[k] = (ak, lv)
            except KeyError:
                return None
            st = self._mqo_state()
            ent = st.get_template(
                "multi:" + prefix
                + "||".join(sorted(ak for ak, _lv in ab.values())))
            if ent is None or not mqo_lib.rebindable(ent):
                return None
            slot_pool: dict = {}
            for s, (ak, _uids) in enumerate(ent.slots):
                slot_pool.setdefault(ak, []).append(s)
            pos: dict = {}
            bindings: dict = {}
            for k in skeys:
                ak, lv = ab[k]
                pool = slot_pool.get(ak)
                if not pool:
                    return None
                s = pool.pop(0)
                uids = ent.slots[s][1]
                if len(uids) != len(lv):
                    return None
                for u, l in zip(uids, lv):
                    m = l.attrs["matrix"]
                    prev = bindings.get(u)
                    if prev is not None and prev is not m:
                        return None
                    bindings[u] = m
                pos[k] = s
            if any(slot_pool.values()):
                return None     # template has roots this batch lacks
            st.template_hits += len(roots)
            return ent.plan, keyed, pos, bindings

    def _template_insert_multi(self, plan, sla: str,
                               rung: int) -> None:
        """Record a freshly compiled MultiPlan as a rebindable
        template. The plan's pinned uniq roots (``_cache_pin``) ARE
        plan-root order, so slot order matches the program's output
        order by construction."""
        roots = plan._cache_pin[0]
        try:
            slots = []
            pins: list = []
            for e in roots:
                ak, tp, lv = mqo_lib.template_key(e)
                slots.append((ak, tuple(l.uid for l in lv)))
                pins.extend(tp)
        except KeyError:
            return
        ent = mqo_lib.TemplateEntry(plan=plan, slots=tuple(slots),
                                    pins=tuple(pins))
        if not mqo_lib.rebindable(ent):
            return
        with self._compile_lock:
            st = self._mqo_state()
            st.put_template(
                "multi:" + self._tpl_prefix(sla, rung)
                + "||".join(sorted(ak for ak, _u in slots)), ent)
            st.template_inserts += 1

    def _cse_hoist_batch(self, pend: list, sla: str, rung: int,
                         rc: bool) -> Tuple[list, int]:
        """Hoist the shared interiors of one pending batch into a
        compute-once MultiPlan, then substitute each result into its
        consumers as an already-laid-out ``cse``-stamped leaf (the
        result-cache interior-hit shape — ``infer_layout``/``comm_cost``
        credit the reuse, ``matmul_decisions`` marks ``cse_operands``).
        With the result cache on the hoisted results ALSO insert under
        their interior structural keys, so cross-time reuse, fleet
        replication and the provenance ledger ride the existing paths
        — and rebinding any source matrix under a hoist invalidates
        every consumer entry through the transitive dep sets. Returns
        (substituted pend, hoist count)."""
        from matrel_tpu.ir import expr as expr_mod
        from matrel_tpu.parallel import planner
        entries = []
        for _i, e in pend:
            parts, _pins, spans = _plan_key_spans(e)
            entries.append((e, parts, spans))
        hoists = mqo_lib.choose_hoists(entries,
                                       self.config.cse_min_uses)
        if not hoists:
            return pend, 0
        st = self._mqo_state()
        # the hoisted interiors are their own micro-batch: one
        # MultiPlan (plan-cache AND template participation — a
        # steady-state dashboard batch rebinding fresh leaves
        # recompiles nothing at all), one dispatch, one fusion domain
        with trace_lib.span("cse.hoist", shared=len(hoists)):
            hexprs = [h.expr for h in hoists]
            bindings = None
            tpl = self._template_probe_multi(hexprs, sla, rung)
            if tpl is not None:
                plan, hkeys, pos, bindings = tpl
            else:
                plan, p_hit, hkeys = self._compile_multi_entry(
                    hexprs, sla=sla, rung=rung)
                pos = {k: j for j, k in enumerate(plan._root_keys)}
                if not p_hit:
                    self._template_insert_multi(plan, sla, rung)
            faults_lib.check("execute", self.config)
            outs = self._arbitrated_run(plan, bindings=bindings)
        rc_prefix = self._rc_key_prefix(sla)
        leaf_of: dict = {}
        for h, hk in zip(hoists, hkeys):
            out = outs[pos[hk]]
            full = rc_prefix + h.key
            stamp = {
                "key_hash": hashlib.sha1(
                    full.encode()).hexdigest()[:16],
                "layout": planner._layout_of(expr_mod.leaf(out),
                                             self.mesh),
                "dtype": str(np.dtype(out.dtype)),
                "deps": sorted(self._rc_deps(h.expr)),
                "uses": h.uses,
            }
            node = expr_mod.leaf(out).with_attrs(cse=stamp)
            summary = None
            if self._prov is not None:
                summary = self._prov_capture(
                    "cse_hoist", full, sla, rung=rung, expr=h.expr,
                    result=out, executed=h.expr, plan=plan,
                    strategies=executor_lib.multiplan_root_decisions(
                        plan)[pos[hk]])
            if rc:
                # the interior key is EXACTLY what a later query's
                # _rc_substitute probe computes for a matching subtree
                # (the spans contract), so the hoisted result serves
                # cross-time interior hits too
                _k2, p2 = _plan_key(h.expr)
                self._rc_insert(full, p2, h.expr, out, orig=h.expr,
                                prec=_prec_prefix(sla), plan=plan,
                                prov=summary)
            for u in h.uids:
                leaf_of[u] = node
        new_pend = []
        for (i, e), _entry in zip(pend, entries):
            se = mqo_lib.substitute(e, leaf_of)
            if se is not e:
                # MV116's dynamic-verify feed: (original, substituted)
                # — re-executing both fresh proves substituted ≡
                # unshared over real traffic
                st.remember(e, se)
            new_pend.append((i, se))
        st.cse_hoisted += len(hoists)
        st.cse_batches += 1
        return new_pend, len(hoists)

    # -- observability (obs/ — the SparkListener analogue) ------------------

    def _obs_enabled(self) -> bool:
        return self.config.obs_level != "off"

    def _obs_event_log(self):
        from matrel_tpu.obs.events import EventLog, resolve_path
        path = resolve_path(self.config.obs_event_log)
        max_bytes = self.config.obs_event_log_max_bytes
        if (self._event_log is None or self._event_log.path != path
                or self._event_log.max_bytes != max_bytes):
            self._event_log = EventLog(path, max_bytes=max_bytes)
        return self._event_log

    def _obs_emit(self, kind: str, record: dict) -> None:
        """The ONE emission funnel for session events AND finished
        spans: JSONL event log when obs is on, flight-recorder ring
        when configured — each independently (flight recording with
        obs off keeps spans in memory only; the ring then holds the
        bare record stamped the way the log would have)."""
        if self._slice_tag is not None and "slice" not in record:
            # fleet attribution (docs/FLEET.md): every event a slice
            # session emits carries its slice id, so history's
            # per-slice roll-up (and top) can tell the slices apart
            # in the shared log. Non-fleet sessions are unchanged.
            record = {**record, "slice": self._slice_tag}
        full = None
        if self._obs_enabled():
            full = self._obs_event_log().emit(kind, record)
        if self._flight is not None:
            if full is None:
                from matrel_tpu.obs.events import SCHEMA_VERSION
                full = {"schema": SCHEMA_VERSION,
                        "ts": round(time.time(), 3), "kind": kind}  # matlint: disable=ML006 record timestamp — mirrors EventLog.emit's stamp for ring-only records
                full.update(record)
            self._flight.add(full)

    # -- answer provenance ledger (obs/provenance.py — tier 4) --------------

    def _prov_capture(self, path: str, key: str, sla: str,
                      rung: int = 0, expr=None, result=None, ent=None,
                      executed=None, plan=None, strategies=None,
                      fleet=None, stale=None, mesh=None,
                      config=None) -> Optional[dict]:
        """One lineage record + ``provenance`` event per served
        answer. Callers guard on ``self._prov is not None`` (the off
        path must not even assemble arguments); capture failures are
        swallowed like every other obs emission — lineage must never
        fail the answer it describes. The record keeps the compile
        config the answer was produced under (SLA + degrade rung), so
        audit replay reconstructs the producing configuration."""
        try:
            cfg = config if config is not None else \
                degrade_lib.apply_rung(self._sla_config(sla), rung)
            summary = self._prov.capture(
                path, key, sla, rung=rung, expr=expr, result=result,
                ent=ent, executed=executed, plan=plan,
                strategies=strategies,
                mesh=mesh if mesh is not None else self.mesh,
                config=cfg, fleet=fleet, stale=stale,
                coeff_epoch=self._coeff_epoch())
            self._obs_emit("provenance", summary)
            return summary
        except Exception:
            log.warning("obs: provenance record dropped",
                        exc_info=True)
            return None

    def _prov_capture_stale(self, e: MatExpr, ent,
                            meta: dict) -> None:
        """Rung-2 stale-serve capture (serve/pipeline.py): recompute
        the structural key (the probe's own walk is gone by now —
        only paid when the ledger is on) and record the staleness
        grant the answer was served under. ``meta`` is the queue
        tuple's ``AdmissionQueue.entry_provenance`` projection."""
        sla = meta.get("sla") or self.config.precision_sla
        parts, _pins, _spans = _plan_key_spans(e)
        key = self._rc_key_prefix(sla) + "|".join(parts)
        stale = {"staleness_ms": float(meta.get("staleness_ms")
                                       or 0.0)}
        if meta.get("tenant"):
            stale["tenant"] = meta["tenant"]
        self._prov_capture("stale", key, sla, ent=ent, stale=stale)

    def why(self, query=None, last: int = 10) -> list:
        """Lineage of recently served answers (obs tier 4,
        docs/OBSERVABILITY.md): the JSON-safe summary dicts of the
        in-memory ledger, newest last — ``python -m matrel_tpu why``
        renders the same records from the event log. ``query`` filters
        by key/key-hash substring or ledger query id, or by the ANSWER
        itself (a BlockMatrix matches by identity). Empty when
        ``config.obs_provenance`` is 0."""
        if self._prov is None:
            return []
        if query is None:
            recs = self._prov.last(last)
        elif isinstance(query, BlockMatrix):
            recs = [r for r in self._prov.records()
                    if r.result is query]
        else:
            recs = self._prov.find(str(query))
        return [r.summary for r in recs]

    def provenance_info(self) -> dict:
        """``plan_cache_info``-style surface for the ledger."""
        if self._prov is None:
            return {"records": 0, "cap": 0, "captured": 0,
                    "chains": 0}
        return self._prov.info()

    # -- flight recorder (obs/trace.py — post-mortem ring) ------------------

    def dump_flight_recorder(self, path: Optional[str] = None,
                             reason: str = "explicit",
                             error: Optional[str] = None
                             ) -> Optional[str]:
        """Write the flight-recorder ring as a JSON artifact and return
        its path (None when the recorder is off). The automatic dump
        sites (VerificationError, compile failure, serve-batch
        failure) route through here too."""
        if self._flight is None:
            return None
        p = (path or self.config.obs_flight_recorder_path
             or trace_lib.DEFAULT_FLIGHT_PATH)
        return self._flight.dump(p, reason, error=error)

    def _flight_auto_dump(self, ex: BaseException,
                          reason: Optional[str] = None) -> None:
        """Best-effort dump on a failure path — a post-mortem artifact
        must never mask (or replace) the original exception."""
        if self._flight is None:
            return
        if reason is None:
            from matrel_tpu.analysis import VerificationError
            reason = ("verification_error"
                      if isinstance(ex, VerificationError)
                      else "compile_failure")
        try:
            p = self.dump_flight_recorder(reason=reason,
                                          error=repr(ex)[:500])
            log.warning("flight recorder dumped to %s (%s)", p, reason)
        except Exception:
            log.warning("flight recorder dump failed", exc_info=True)

    def _emit_query_event(self, e: MatExpr, plan, hit: bool, key: str,
                          execute_ms: float, first_execution: bool,
                          out: BlockMatrix, matmuls=None,
                          rule_hits=None, batch=None,
                          tenant: Optional[str] = None,
                          cache_label: Optional[str] = None) -> None:
        """One event-log record + metrics-registry updates per query run.
        Assembled entirely OUTSIDE jitted code, from data the compile
        path already produced (plan.meta) — the only device sync the obs
        path adds is the one execute-time block in compute().

        ``matmuls``/``rule_hits`` override the plan-level derivations
        for batched (MultiPlan) roots: each root's record carries ITS
        matmul decisions, and rewrite-rule hits are attributed to one
        root only so history's roll-up never double-counts a compile.
        ``batch`` tags records produced by one micro-batched admission
        (``{"size": N, "index": i}``; execute_ms is then the batch
        wall amortised per root).

        ``cache_label`` overrides the hit/miss vocabulary — a
        plan-template hit (serve/mqo.py) records ``"template_hit"``
        with optimize/trace FORCED to 0.0: unlike a plan-cache hit
        (whose record describes the plan that ran), the template
        contract is that steady-state traffic pays ZERO optimize/trace
        this query, and the event is the proof the acceptance test
        reads."""
        from matrel_tpu.obs.metrics import REGISTRY
        meta = plan.meta or {}
        if matmuls is None:
            matmuls = executor_lib.plan_matmul_decisions(plan)
        sql_hash = getattr(e, "_sql_hash", None)
        record = {
            "query_id": f"q{os.getpid()}-{next(_query_seq)}",
            "source": "sql" if sql_hash else "dsl",
            "source_hash": sql_hash
            or hashlib.sha1(key.encode()).hexdigest()[:16],
            "root_kind": e.kind,
            "cache": cache_label or ("hit" if hit else "miss"),
            "optimize_ms": (0.0 if cache_label == "template_hit"
                            else meta.get("optimize_ms")),
            "trace_ms": (0.0 if cache_label == "template_hit"
                         else meta.get("trace_ms")),
            # compile-scoped: a cache hit ran no rewrite rules, so hit
            # records carry {} and history's roll-up counts real
            # optimizer work (optimize_ms/trace_ms DO repeat on hits —
            # they describe the plan, "cache" says no compile ran)
            "rule_hits": (rule_hits if rule_hits is not None
                          else ({} if hit else meta.get("rule_hits",
                                                        {}))),
            "matmuls": matmuls,
            "execute_ms": round(execute_ms, 3),
            "first_execution": first_execution,
            "out_shape": list(out.shape),
            "out_nnz": out.nnz,
            "plan_cache": self.plan_cache_info(),
        }
        if batch is not None:
            record["batch"] = batch
        if tenant:
            # multi-tenant attribution (docs/OVERLOAD.md): absent for
            # untagged queries, so historical records are unchanged
            record["tenant"] = tenant
        if meta.get("fusion"):
            # plan-level fusion roll-up (executor._fusion_meta):
            # regions, member census, est saved dispatches/HBM — the
            # `history --summary` fusion line's feed. Absent with
            # fusion off (the bit-identity obs contract).
            record["fusion"] = meta["fusion"]
        if self._rc_enabled():
            record["result_cache"] = self._result_cache.info()
        import jax
        # backend rides every query record so the drift auditor can
        # calibrate per backend (a CPU ms and a TPU ms must never
        # blend into one ratio)
        record["backend"] = jax.default_backend()
        if self.config.coeff_planner_enable:
            # which coefficient epoch priced this answer's plan — the
            # history cost-model roll-up's feed (absent with the loop
            # off: the bit-identity obs contract, docs/COST_MODEL.md)
            record["coeff_epoch"] = self._coeff_epoch()
        self._obs_emit("query", record)
        if self._replan is not None:
            # feed the re-plan controller AFTER emission: it sees the
            # same record the log does (backend + matmuls included),
            # and its own failure can never drop the query event
            self._replan.observe(record)
        REGISTRY.counter("query.count").inc()
        REGISTRY.counter("plan_cache.hit" if hit
                         else "plan_cache.miss").inc()
        if cache_label == "template_hit":
            REGISTRY.counter("mqo.template_hit").inc()
        REGISTRY.gauge("plan_cache.plans").set(len(self._plan_cache))
        REGISTRY.gauge("plan_cache.hoisted_bytes").set(
            self._plan_cache_bytes)
        REGISTRY.gauge("plan_cache.evicted").set(
            self._plan_cache_evicted)
        REGISTRY.histogram("query.execute_ms").observe(execute_ms)
        if not hit:
            if meta.get("optimize_ms") is not None:
                REGISTRY.histogram("query.optimize_ms").observe(
                    meta["optimize_ms"])
            # compile-scoped like optimize_ms: rules fire once per
            # compile, not per run
            for rule, n in meta.get("rule_hits", {}).items():
                REGISTRY.counter(f"optimizer.rule.{rule}").inc(n)
        for d in matmuls:
            REGISTRY.counter(f"planner.strategy.{d['strategy']}").inc()

    def _emit_verify_event(self, plan) -> None:
        """One ``verify`` record per observed query run (obs_level on
        AND verify_plans on): the diagnostic codes the compile-time
        verifier produced for this plan — empty codes = verified clean.
        Cache hits re-report the compile-time findings (the record
        describes the plan that ran, "cache" on the query record says
        no new verify happened)."""
        diags = (plan.meta or {}).get("diagnostics")
        if diags is None:
            return        # verifier was off when this plan compiled
        from matrel_tpu.obs.metrics import REGISTRY
        self._obs_emit("verify", {
            "mode": self.config.verify_plans,
            "count": len(diags),
            "errors": sum(1 for d in diags if d["severity"] == "error"),
            "codes": sorted({d["code"] for d in diags}),
        })
        REGISTRY.counter("verify.count").inc()
        if diags:
            REGISTRY.counter("verify.diagnostics").inc(len(diags))

    def verify(self, expr: MatExpr) -> list:
        """Run the static plan verifier (matrel_tpu/analysis/) on this
        expression's OPTIMIZED, strategy-annotated plan and return the
        diagnostic list — regardless of ``config.verify_plans`` (that
        gate controls the compile path; this is the on-demand surface).
        Planning only: nothing is traced, jitted, or executed."""
        from matrel_tpu import analysis
        from matrel_tpu.ir import rules
        from matrel_tpu.parallel import planner
        e = as_expr(expr)
        grid = mesh_lib.mesh_grid_shape(self.mesh)
        opt = planner.annotate_strategies(
            rules.optimize(e, self.config, grid=grid, mesh=self.mesh),
            self.mesh, self.config)
        return analysis.verify_plan(opt, self.mesh, self.config)

    def _emit_rc_hit_event(self, e: MatExpr, key: str,
                           out: BlockMatrix,
                           tenant: Optional[str] = None) -> None:
        """Query record for a WHOLE-query result-cache hit: nothing
        compiled, nothing executed — the record says so (``cache:
        "rc_hit"``, no matmuls, zero execute) and carries the cache
        snapshot the hit came from."""
        from matrel_tpu.obs.metrics import REGISTRY
        sql_hash = getattr(e, "_sql_hash", None)
        self._obs_emit("query", {
            **({"tenant": tenant} if tenant else {}),
            "query_id": f"q{os.getpid()}-{next(_query_seq)}",
            "source": "sql" if sql_hash else "dsl",
            "source_hash": sql_hash
            or hashlib.sha1(key.encode()).hexdigest()[:16],
            "root_kind": e.kind,
            "cache": "rc_hit",
            "optimize_ms": None,
            "trace_ms": None,
            "rule_hits": {},
            "matmuls": [],
            "execute_ms": 0.0,
            "first_execution": False,
            "out_shape": list(out.shape),
            "out_nnz": out.nnz,
            "plan_cache": self.plan_cache_info(),
            "result_cache": self._result_cache.info(),
        })
        REGISTRY.counter("query.count").inc()
        REGISTRY.counter("result_cache.hit").inc()

    def _emit_delta_event(self, record: dict) -> None:
        """One ``delta`` record per register_delta (obs on / flight
        recorder on; no-op otherwise — the default path emits nothing):
        the maintenance summary — entries patched / killed / rekeyed,
        per-rule census, modelled FLOPs saved — the ``history
        --summary`` IVM roll-up's feed. Never fails the register."""
        if not self._obs_enabled() and self._flight is None:
            return
        from matrel_tpu.obs.metrics import REGISTRY
        try:
            rec = dict(record)
            if self._rc_enabled():
                rec["result_cache"] = self._result_cache.info()
            self._obs_emit("delta", rec)
            REGISTRY.counter("ivm.registered").inc()
            REGISTRY.counter("ivm.patched").inc(
                record.get("patched", 0))
            REGISTRY.counter("ivm.killed").inc(record.get("killed", 0))
        except Exception:
            log.warning("obs: delta event dropped", exc_info=True)

    def _emit_spill_event(self, record: dict) -> None:
        """One ``spill`` record per tier move (demote / promote /
        thaw — serve/spill.py's emit hook) and per save_state/restore
        (op ``save_state``/``restore``): the measured transfer legs
        the drift auditor calibrates ``spill:<leg>`` rows from and
        the ``history --summary`` spill/restart roll-up's feed. Obs
        on / flight recorder on; no-op otherwise — the default path
        emits nothing. Never fails the cache operation."""
        if not self._obs_enabled() and self._flight is None:
            return
        from matrel_tpu.obs.metrics import REGISTRY
        try:
            self._obs_emit("spill", dict(record))
            REGISTRY.counter(
                f"spill.{record.get('op') or 'op'}").inc()
        except Exception:
            log.warning("obs: spill event dropped", exc_info=True)

    def _emit_serve_event(self, record: dict) -> None:
        """One ``serve`` record per micro-batched admission (obs on
        only): batch size, queue-wait per query, result-cache state,
        in-flight depth — the roll-up ``history --summary`` turns into
        QPS / hit ratio / queue-latency percentiles."""
        from matrel_tpu.obs.metrics import REGISTRY
        record = dict(record)
        record["result_cache"] = self._result_cache.info()
        self._obs_emit("serve", record)
        REGISTRY.counter("serve.batches").inc()
        REGISTRY.counter("serve.queries").inc(
            record.get("batch_size", 0))
        for w in record.get("queue_wait_ms") or ():
            REGISTRY.histogram("serve.queue_wait_ms").observe(w)
        REGISTRY.gauge("result_cache.entries").set(
            record["result_cache"]["entries"])
        REGISTRY.gauge("result_cache.bytes").set(
            record["result_cache"]["bytes"])

    def _emit_alert_event(self, record: dict) -> None:
        """One ``alert`` record per SLO alert TRANSITION (obs/slo.py
        fire/clear edges — never steady state): tenant, objective,
        burn rates, attainment. Lands in the event log when obs is on
        AND in the flight-recorder ring whenever the ring exists —
        REGARDLESS of ``obs_level`` (the _obs_emit funnel's existing
        split): an alert edge is exactly the record a post-mortem
        needs. Never fails the query/outcome that triggered it."""
        from matrel_tpu.obs.metrics import REGISTRY
        try:
            self._obs_emit("alert", record)
            REGISTRY.counter(
                "slo.alerts.fired" if record.get("state") == "firing"
                else "slo.alerts.cleared").inc()
            REGISTRY.gauge("slo.alerts.active").set(
                record.get("active", 0))
        except Exception:   # the never-fail obs contract
            log.warning("obs: alert event dropped", exc_info=True)

    def _emit_overload_event(self, record: dict) -> None:
        """One ``overload`` record per admission cycle while the
        control plane is active (serve/pipeline.py assembles it:
        rung, tenant depths/waits, shed/purge/stale deltas, breaker
        state) — the feed for ``history --summary``'s overload
        roll-up. Never fails a query."""
        from matrel_tpu.obs.metrics import REGISTRY
        try:
            self._obs_emit("overload", record)
            REGISTRY.gauge("overload.rung").set(
                record.get("rung", 0))
        except Exception:
            log.warning("obs: overload event dropped", exc_info=True)

    def _arbitrated_run(self, plan, bindings=None):
        """Dispatch one compiled program under the fleet's execution
        arbitration (see ``_exec_lock``): dispatch-to-COMPLETION is
        serialized across the sessions sharing the lock, because an
        async dispatch would leave the program's collectives in
        flight when the lock dropped — exactly the overlap the lock
        exists to prevent. Cache hits, planning and admission never
        come here, so the fleet's host-side parallelism survives;
        only device programs serialize. Without a lock (every
        non-fleet session) this IS ``plan.run()``. ``bindings`` rebinds
        dense leaves by uid (plan-template hits — serve/mqo.py)."""
        # sanctioned dispatch point (utils/lockdep.py): with the
        # sanitizer on, any lock held HERE that is not declared
        # dispatch_ok (the fleet exec arbitration is, by design) is a
        # HeldAcrossDispatch diagnostic — the PR 8 drain-wedge class
        # caught at runtime. One flag check when off.
        lockdep.note_dispatch("session.dispatch")
        if self._exec_lock is None:
            return plan.run(bindings=bindings)
        with self._exec_lock:
            out = plan.run(bindings=bindings)
            for o in (out if isinstance(out, (list, tuple))
                      else (out,)):
                o.data.block_until_ready()
            return out

    def _emit_placement_event(self, record: dict) -> None:
        """One ``placement`` record per fleet-routed submission
        (serve/fleet.py assembles it: mode, routed target, directory
        outcome, coefficient provenance, the two cost estimates) —
        the feed for ``history --summary``'s fleet roll-up. Never
        fails a query."""
        from matrel_tpu.obs.metrics import REGISTRY
        try:
            self._obs_emit("placement", record)
            REGISTRY.counter(
                f"fleet.placed.{record.get('routed', '?')}").inc()
        except Exception:
            log.warning("obs: placement event dropped", exc_info=True)

    def _emit_fleet_event(self, record: dict) -> None:
        """One ``fleet`` record per fleet lifecycle event (slice
        kill/failover, hot-entry migration, priced-out migration) —
        carried with the fleet snapshot so offline replay can
        reconstruct the fleet's state transitions."""
        from matrel_tpu.obs.metrics import REGISTRY
        try:
            rec = dict(record)
            if self._fleet is not None:
                rec["fleet"] = {
                    "placed": dict(self._fleet.placed),
                    "failovers": self._fleet.failovers,
                    "migrations": self._fleet.migrations,
                }
            self._obs_emit("fleet", rec)
            REGISTRY.counter(
                f"fleet.event.{record.get('event', '?')}").inc()
        except Exception:
            log.warning("obs: fleet event dropped", exc_info=True)

    def _run_observed(self, e: MatExpr, plan, hit: bool, key: str,
                      tenant: Optional[str] = None, bindings=None,
                      cache_label: Optional[str] = None) -> BlockMatrix:
        """Execute one compiled plan with the obs timing/emission
        wrapper (the obs-on half of compute()). ``bindings``/
        ``cache_label`` are the plan-template hit channel
        (serve/mqo.py): fresh leaves rebound into the cached program,
        and the query record saying so (``cache: "template_hit"``)."""
        first = not getattr(plan, "_obs_executed", False)
        # phase(): the one timing mechanism — the duration lands in the
        # query record AND (tracer active here) as an "execute" span
        with trace_lib.phase("query.execute",
                             cache=cache_label
                             or ("hit" if hit else "miss")) as sp:
            out = self._arbitrated_run(plan, bindings=bindings)
            out.data.block_until_ready()
        execute_ms = sp.dur_ms
        plan._obs_executed = True
        try:
            self._emit_query_event(e, plan, hit, key, execute_ms, first,
                                   out, tenant=tenant,
                                   cache_label=cache_label)
            self._emit_verify_event(plan)
        except Exception:   # the result is already computed — keep the
            # never-fail-a-query contract (obs/events.py) even when
            # record ASSEMBLY breaks, not just the file write
            log.warning("obs: query event dropped", exc_info=True)
        return out

    def compute(self, expr: MatExpr,
                precision: Optional[str] = None,
                deadline_ms: Optional[float] = None,
                tenant: Optional[str] = None) -> BlockMatrix:
        """Execute one query. ``precision`` is the per-query accuracy
        SLA ("exact"/"high"/"fast"/explicit dtype — docs/PRECISION.md);
        None defers to a SQL PRECISION clause, then
        ``config.precision_sla``. ``deadline_ms`` is the per-query
        deadline (None defers to ``config.deadline_ms``; expiry raises
        the typed ``DeadlineExceeded`` — docs/RESILIENCE.md).
        ``tenant`` tags the query's obs records for the multi-tenant
        roll-up (admission fairness itself lives in the async
        ``submit`` pipeline — docs/OVERLOAD.md)."""
        e = as_expr(expr)
        sla = self._resolve_sla(precision, e)
        # resilience gate (retry/deadline/fault-injection): None for
        # the default config + no per-call deadline — the resilient
        # path is never entered and costs nothing
        pol = RetryPolicy.from_config(self.config, deadline_ms)
        rc = self._rc_enabled()
        if self._breakers is None:
            return self._compute_dispatch(e, sla, pol, rc, tenant)
        # circuit breakers (resilience/breaker.py): an OPEN plan class
        # fails fast typed; terminal outcomes feed the class's health
        bclass = self._breakers.plan_class(e)
        self._breakers.admit(bclass)
        try:
            out = self._compute_dispatch(e, sla, pol, rc, tenant)
        except Exception as ex:
            self._breakers.record(
                bclass,
                False if breaker_lib.counts_as_failure(ex) else None)
            raise
        self._breakers.record(bclass, True)
        return out

    def _compute_dispatch(self, e: MatExpr, sla: str,
                          pol: Optional[RetryPolicy], rc: bool,
                          tenant: Optional[str]) -> BlockMatrix:
        """compute() behind the breaker gate: the resilient / fast /
        observed three-way the engine has always had."""
        if pol is not None:
            return self._compute_resilient(e, rc, sla, pol,
                                           tenant=tenant)
        if (not rc and not self._obs_enabled()
                and self._tracer is None and not self._cse_on()):
            # the production path: zero event assembly, zero extra
            # device syncs, zero span objects, zero cache-key walks
            # beyond the plan cache's own (the obs_level="off" /
            # result_cache_max_bytes=0 / flight-recorder-off /
            # cse-off contract bench.py relies on; with cse_enable a
            # single query must still reach the template probe/insert
            # seam in _compute_observed)
            return self._arbitrated_run(
                self._compile_entry(e, sla=sla)[0])
        # per-thread tracer activation: executor compile phases and
        # every span below parent-link into this query's trail
        with trace_lib.activate(self._tracer), \
                trace_lib.span("query", root_kind=e.kind):
            return self._compute_observed(e, rc, sla, tenant=tenant)

    def _compute_observed(self, e: MatExpr, rc: bool,
                          sla: Optional[str] = None,
                          rung: int = 0,
                          tenant: Optional[str] = None) -> BlockMatrix:
        """compute() behind the fast-path gate: result-cache admission,
        compile, execute — each scoped by a tracing span. ``rung`` is
        the resilient path's degradation-ladder step (0 = none)."""
        sla = sla if sla is not None else self.config.precision_sla
        key = pins = None
        orig = e
        if rc:
            with trace_lib.span("rc.probe") as sp:
                ent, key, pins, e = self._rc_admit(
                    e, self._rc_key_prefix(sla))
                sp.set(hit=ent is not None)
            if ent is not None:
                # repeated query: answered from the materialized-result
                # cache — no optimize, no trace, no device work
                if self._obs_enabled():
                    try:
                        self._emit_rc_hit_event(e, key, ent.result,
                                                tenant=tenant)
                    except Exception:
                        log.warning("obs: query event dropped",
                                    exc_info=True)
                if self._prov is not None:
                    self._prov_capture("rc_hit", key, sla, rung=rung,
                                       ent=ent)
                return ent.result
        bindings = cache_label = None
        with trace_lib.span("plan"):
            # plan-template probe (serve/mqo.py): a structurally
            # identical query modulo dense-leaf bindings rebinds into
            # the cached template's program — zero optimize/trace
            tpl = (self._template_probe(e, sla, rung)
                   if self._cse_on() else None)
            if tpl is not None:
                plan, pkey, bindings = tpl
                hit, cache_label = True, "template_hit"
            else:
                plan, hit, pkey = self._compile_entry(e, sla=sla,
                                                      rung=rung)
                if self._cse_on() and not hit:
                    self._template_insert(e, plan, sla, rung)
        # fault site "execute": the host-side dispatch point — the main
        # retryable site (per attempt, unlike the trace-time sites)
        faults_lib.check("execute", self.config)
        if self._obs_enabled():
            out = self._run_observed(e, plan, hit, pkey, tenant=tenant,
                                     bindings=bindings,
                                     cache_label=cache_label)
        else:
            # flight-recorder-only tier: the span marks DISPATCH (JAX
            # async — deliberately no added sync; always-cheap)
            with trace_lib.span("query.execute"):
                out = self._arbitrated_run(plan, bindings=bindings)
        summary = None
        if self._prov is not None:
            # capture BEFORE the cache insert so the new CacheEntry's
            # stamp can carry this record's query id (the ancestry
            # link `why` follows from a later hit back to its producer)
            summary = self._prov_capture(
                "execute", key if key is not None else pkey, sla,
                rung=rung, expr=orig, result=out, executed=e,
                plan=plan)
        if rc:
            self._rc_insert(key, pins, e, out, orig=orig,
                            prec=_prec_prefix(sla), plan=plan,
                            prov=summary)
        return out

    # -- resilient execution (matrel_tpu/resilience/) ----------------------

    def _compute_resilient(self, e: MatExpr, rc: bool, sla: str,
                           pol: RetryPolicy,
                           should_abort=None,
                           tenant: Optional[str] = None) -> BlockMatrix:
        """The attempt loop: run the query; on a TRANSIENT failure
        (errors.classify) retry with backoff, climbing one rung of the
        plan-degradation ladder per retry (resilience/degrade.py) —
        rung 4 additionally bypasses the result cache. Deterministic
        failures, exhausted attempts, and expired deadlines propagate
        typed. Cancellation (``should_abort``) is honored between
        attempts — a running XLA dispatch is never interrupted."""
        deadline = pol.deadline()
        attempt = 0
        rung = 0
        while True:
            deadline.raise_if_expired()
            try:
                with trace_lib.activate(self._tracer), \
                        trace_lib.span("query", root_kind=e.kind,
                                       attempt=attempt, rung=rung):
                    out = self._compute_observed(
                        e, rc and rung < degrade_lib.RC_BYPASS_RUNG,
                        sla, rung=rung, tenant=tenant)
                # deadline holds on SUCCESS too: a result delivered
                # past the SLA raises typed, matching submit()'s
                # late-batch semantics (one meaning per knob)
                deadline.raise_if_expired()
                return out
            except Exception as ex:
                self._emit_fault_event(ex, scope="query")
                if not pol.should_retry(ex, attempt):
                    raise
                attempt += 1
                rung, escalated = degrade_lib.next_rung(rung)
                self._emit_retry_event(ex, attempt, rung,
                                       scope="query")
                if escalated:
                    self._emit_degrade_event(rung, ex, scope="query")
                pol.backoff_sleep(attempt, deadline,
                                  should_abort=should_abort)

    def _emit_fault_event(self, ex: BaseException, scope: str) -> None:
        """One ``fault`` record per failure the resilient path caught
        (obs on / flight recorder on; no-op otherwise). Injected
        faults carry their site/kind so the chaos drill and history
        roll-up can attribute them."""
        rec = {"scope": scope, "error": type(ex).__name__,
               "classification": rerrors.classify(ex),
               "message": str(ex)[:200]}
        if isinstance(ex, rerrors.InjectedFault):
            rec["site"] = ex.site
            rec["injected"] = True
        try:
            self._obs_emit("fault", rec)
        except Exception:
            log.warning("obs: fault event dropped", exc_info=True)

    def _emit_retry_event(self, ex: BaseException, attempt: int,
                          rung: int, scope: str) -> None:
        try:
            self._obs_emit("retry", {
                "scope": scope, "attempt": attempt, "rung": rung,
                "rung_label": degrade_lib.rung_label(rung),
                "error": type(ex).__name__})
        except Exception:
            log.warning("obs: retry event dropped", exc_info=True)

    def _emit_degrade_event(self, rung: int, ex: BaseException,
                            scope: str) -> None:
        try:
            self._obs_emit("degrade", {
                "scope": scope, "rung": rung,
                "rung_label": degrade_lib.rung_label(rung),
                "cause": type(ex).__name__})
        except Exception:
            log.warning("obs: degrade event dropped", exc_info=True)

    # alias: the reference's Dataset actions read as "run the query"
    run = compute

    # -- micro-batched admission + async pipeline (serve/) -----------------

    def run_many(self, exprs, precision: Optional[str] = None,
                 deadline_ms: Optional[float] = None,
                 tenant: Optional[str] = None,
                 _queue_wait_ms=None,
                 _inflight_depth: int = 0,
                 _tenants=None,
                 _brownout_rung: Optional[int] = None
                 ) -> List[BlockMatrix]:
        """Execute several queries as ONE micro-batched admission: the
        batch compiles into a single MultiPlan (one fusion and CSE
        domain, shared leaf transfers — duplicate roots dedupe on their
        structural key) that participates in the session plan cache, so
        a recurring batch recompiles nothing. With the result cache on,
        whole-query hits never reach the batch at all and interior hits
        enter planning as already-laid-out leaves. Results come back in
        input order.

        ``precision`` is the batch-level accuracy SLA — ONE MultiPlan
        means one planning config, so the whole batch shares it (the
        serve pipeline groups mixed-SLA submissions into same-SLA
        batches before calling here).

        ``deadline_ms`` is the BATCH deadline (None defers to
        ``config.deadline_ms``): expiry between retry attempts raises
        the typed ``DeadlineExceeded`` for the whole batch.

        ``tenant`` tags the whole batch for the multi-tenant obs
        roll-up (the serve pipeline instead passes per-query
        ``_tenants``).

        The underscore parameters are the serve pipeline's channel for
        queue-wait/in-flight/tenant/brownout observability; direct
        callers leave them alone."""
        es = [as_expr(x) for x in exprs]
        if not es:
            return []
        if _tenants is None and tenant:
            _tenants = [tenant] * len(es)
        sla = (normalize_sla(precision) if precision is not None
               else self.config.precision_sla)
        pol = RetryPolicy.from_config(self.config, deadline_ms)
        if pol is not None:
            return self._run_many_resilient(es, sla, pol,
                                            _queue_wait_ms,
                                            _inflight_depth,
                                            _tenants=_tenants,
                                            _brownout_rung=_brownout_rung)
        rc = self._rc_enabled()
        obs = self._obs_enabled()
        with trace_lib.activate(self._tracer), \
                trace_lib.span("serve.batch", size=len(es)) as sp_batch:
            return self._run_many_observed(es, rc, obs, sp_batch,
                                           _queue_wait_ms,
                                           _inflight_depth, sla,
                                           _tenants=_tenants,
                                           _brownout_rung=_brownout_rung)

    def _run_many_resilient(self, es, sla: str, pol: RetryPolicy,
                            _queue_wait_ms, _inflight_depth,
                            should_abort=None, _tenants=None,
                            _brownout_rung: Optional[int] = None
                            ) -> List[BlockMatrix]:
        """``_compute_resilient``'s batch twin: the whole MultiPlan
        retries as one unit, climbing the same ladder (poison-query
        ISOLATION is the serve worker's bisection, not this loop —
        a direct run_many call is one caller asking for one batch)."""
        deadline = pol.deadline()
        attempt = 0
        rung = 0
        while True:
            deadline.raise_if_expired(context="batch")
            rc = (self._rc_enabled()
                  and rung < degrade_lib.RC_BYPASS_RUNG)
            obs = self._obs_enabled()
            try:
                with trace_lib.activate(self._tracer), \
                        trace_lib.span("serve.batch", size=len(es),
                                       attempt=attempt,
                                       rung=rung) as sp_batch:
                    outs = self._run_many_observed(
                        es, rc, obs, sp_batch, _queue_wait_ms,
                        _inflight_depth, sla, rung=rung,
                        _tenants=_tenants,
                        _brownout_rung=_brownout_rung)
                # SLA semantics match _compute_resilient/submit: a
                # batch finishing past its deadline raises typed
                deadline.raise_if_expired(context="batch")
                return outs
            except Exception as ex:
                self._emit_fault_event(ex, scope="batch")
                if not pol.should_retry(ex, attempt):
                    raise
                attempt += 1
                rung, escalated = degrade_lib.next_rung(rung)
                self._emit_retry_event(ex, attempt, rung,
                                       scope="batch")
                if escalated:
                    self._emit_degrade_event(rung, ex, scope="batch")
                pol.backoff_sleep(attempt, deadline,
                                  should_abort=should_abort)

    def _run_many_observed(self, es, rc, obs, sp_batch, _queue_wait_ms,
                           _inflight_depth,
                           sla: Optional[str] = None,
                           rung: int = 0, _tenants=None,
                           _brownout_rung: Optional[int] = None
                           ) -> List[BlockMatrix]:
        sla = sla if sla is not None else self.config.precision_sla

        def _tenant_of(i):
            return (_tenants[i] if _tenants is not None
                    and i < len(_tenants) else None)
        results: dict = {}
        rc_meta: dict = {}
        pend: list = []
        for i, e in enumerate(es):
            orig = e
            if rc:
                with trace_lib.span("rc.probe", index=i) as sp:
                    ent, key, pins, e = self._rc_admit(
                        e, self._rc_key_prefix(sla))
                    sp.set(hit=ent is not None)
                if ent is not None:
                    results[i] = ent.result
                    if obs:
                        try:
                            self._emit_rc_hit_event(
                                e, key, ent.result,
                                tenant=_tenant_of(i))
                        except Exception:
                            log.warning("obs: query event dropped",
                                        exc_info=True)
                    if self._prov is not None:
                        self._prov_capture("rc_hit", key, sla,
                                           rung=rung, ent=ent)
                    continue
                rc_meta[i] = (key, pins, orig)
            pend.append((i, e))
        execute_ms = 0.0
        plan_hit = None
        cse_hoisted = 0
        tpl_hit = False
        if pend:
            if self._cse_on() and len(pend) > 1:
                # cross-query CSE (serve/mqo.py): shared interiors of
                # the batch compute once; consumers re-enter planning
                # with cse-stamped leaves
                pend, cse_hoisted = self._cse_hoist_batch(pend, sla,
                                                          rung, rc)
            bindings = None
            with trace_lib.span("plan", roots=len(pend)):
                tpl = (self._template_probe_multi(
                    [e for _, e in pend], sla, rung)
                    if self._cse_on() else None)
                if tpl is not None:
                    plan, keys, pos, bindings = tpl
                    plan_hit = tpl_hit = True
                else:
                    plan, plan_hit, keys = self._compile_multi_entry(
                        [e for _, e in pend], sla=sla, rung=rung)
                    pos = {k: j
                           for j, k in enumerate(plan._root_keys)}
                    if self._cse_on() and not plan_hit:
                        self._template_insert_multi(plan, sla, rung)
            # fault site "execute" — per batch attempt (host side)
            faults_lib.check("execute", self.config)
            # the batch's execute span: under obs the sync happens
            # INSIDE it (dur = device wall); flight-recorder-only runs
            # mark dispatch without adding a sync
            with trace_lib.span("serve.execute",
                                executed=len(pend)) as sp_ex:
                outs = self._arbitrated_run(plan, bindings=bindings)
                if obs:
                    for o in outs:
                        o.data.block_until_ready()
            if obs:
                execute_ms = sp_ex.dur_ms or 0.0
            first = not getattr(plan, "_obs_executed", False)
            plan._obs_executed = True
            for j, ((i, e), k) in enumerate(zip(pend, keys)):
                out = outs[pos[k]]
                results[i] = out
                summary = None
                if self._prov is not None:
                    if rc:
                        p_key, _p, p_orig = rc_meta[i]
                    else:
                        p_key, p_orig = k, e
                    summary = self._prov_capture(
                        "execute", p_key, sla, rung=rung,
                        expr=p_orig, result=out, executed=e,
                        plan=plan,
                        strategies=executor_lib.multiplan_root_decisions(
                            plan)[pos[k]])
                if rc:
                    key, pins, orig = rc_meta[i]
                    self._rc_insert(key, pins, e, out, orig=orig,
                                    prec=_prec_prefix(sla), plan=plan,
                                    prov=summary)
                if obs:
                    try:
                        per_root = executor_lib.multiplan_root_decisions(
                            plan)
                        self._emit_query_event(
                            e, plan, bool(plan_hit), k,
                            execute_ms / max(len(pend), 1), first, out,
                            matmuls=per_root[pos[k]],
                            # one root carries the batch's compile-time
                            # rule hits; the rest {} — the roll-up sums
                            rule_hits=({} if (j > 0 or plan_hit)
                                       else (plan.meta or {}).get(
                                           "rule_hits", {})),
                            batch={"size": len(es), "index": i},
                            tenant=_tenant_of(i),
                            cache_label=("template_hit" if tpl_hit
                                         else None))
                    except Exception:
                        log.warning("obs: query event dropped",
                                    exc_info=True)
            if obs:
                try:
                    self._emit_verify_event(plan)
                except Exception:
                    log.warning("obs: verify event dropped",
                                exc_info=True)
        if obs:
            try:
                record = {
                    "batch_size": len(es),
                    "executed": len(pend),
                    "rc_hits": len(es) - len(pend),
                    "plan_cache_hit": plan_hit,
                    "queue_wait_ms": _queue_wait_ms,
                    "inflight_depth": _inflight_depth,
                    "execute_ms": round(execute_ms, 3),
                    "wall_ms": round(sp_batch.elapsed_ms() or 0.0, 3),
                }
                if _tenants is not None:
                    # per-tenant batch census (docs/OVERLOAD.md):
                    # absent for untagged batches — historical records
                    # unchanged
                    census: dict = {}
                    for t in _tenants:
                        key_t = t or ""
                        census[key_t] = census.get(key_t, 0) + 1
                    record["tenants"] = census
                if _brownout_rung:
                    record["brownout_rung"] = _brownout_rung
                if self._cse_on():
                    # MQO deltas (docs/OBSERVABILITY.md): absent with
                    # cse off — historical serve records unchanged
                    record["cse_hoisted"] = cse_hoisted
                    record["template_hits"] = (len(pend) if tpl_hit
                                               else 0)
                self._emit_serve_event(record)
            except Exception:
                log.warning("obs: serve event dropped", exc_info=True)
        return [results[i] for i in range(len(es))]

    def submit(self, expr, precision: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None,
               staleness_ms: Optional[float] = None):
        """Asynchronous query admission: returns a
        ``concurrent.futures.Future`` resolving to the BlockMatrix.
        Concurrent submissions coalesce into micro-batches
        (``config.serve_max_batch``) and JAX's async dispatch overlaps
        device execution with host planning of the next batch, bounded
        by ``config.serve_max_inflight`` (serve/pipeline.py).
        ``precision`` rides each submission: the admission worker only
        coalesces SAME-SLA queries into one MultiPlan, so a "fast"
        neighbour can never change an "exact" query's numerics.

        ``deadline_ms`` rides each submission too (None defers to
        ``config.deadline_ms``): a future whose deadline expires while
        queued — or whose batch finishes past it — resolves with the
        typed ``DeadlineExceeded``. Submitting into a CLOSED pipeline
        raises the typed ``PipelineClosed``; a full bounded queue
        (per-tenant ``config.serve_tenant_queue_max`` quota first,
        then the global ``config.serve_queue_max``) raises the typed
        ``AdmissionShed``.

        ``tenant`` names the submitting tenant for weighted-fair
        admission (``config.serve_tenant_weights`` —
        docs/OVERLOAD.md); ``staleness_ms`` declares how old a STALE
        result-cache answer this query tolerates (consumed only at
        brownout rung >= 2; None/0 = never served stale).

        With ``config.fleet_slices >= 1`` the submission routes
        through the multi-slice serving fleet (serve/fleet.py;
        docs/FLEET.md): placement decides slice-local vs spanning
        execution, the global directory answers repeats from ANY
        slice's cache, and a dead slice's queue fails over. The
        default (0) runs the historical single-controller pipeline
        bit-identically."""
        e = as_expr(expr)
        if deadline_ms is None and self.config.deadline_ms > 0:
            deadline_ms = self.config.deadline_ms
        sla = self._resolve_sla(precision, e)
        if self.config.fleet_slices >= 1:
            return self._ensure_fleet().submit(
                e, sla, deadline_ms=deadline_ms, tenant=tenant,
                staleness_ms=staleness_ms)
        return self._submit_pipeline(e, sla, deadline_ms=deadline_ms,
                                     tenant=tenant,
                                     staleness_ms=staleness_ms)

    def _ensure_serve(self):
        """This session's (lazily built) admission pipeline."""
        if self._serve is None:
            from matrel_tpu.serve.pipeline import ServePipeline
            # under the lock: two concurrent FIRST submissions must not
            # each build a pipeline — the loser's would be orphaned
            # (invisible to serve_drain/close, its queue never drained)
            with self._compile_lock:
                if self._serve is None:
                    self._serve = ServePipeline(self)
        return self._serve

    def _ensure_fleet(self):
        if self._fleet is None:
            from matrel_tpu.serve.fleet import FleetController
            with self._compile_lock:     # the _ensure_serve discipline
                if self._fleet is None:
                    self._fleet = FleetController(self)
        return self._fleet

    def _submit_pipeline(self, e: MatExpr, sla: str,
                         deadline_ms: Optional[float] = None,
                         tenant: Optional[str] = None,
                         staleness_ms: Optional[float] = None):
        """The single-controller admission path — submit()'s historical
        body, also the fleet's SPAN executor (a span-placed query is
        one program over the full mesh, i.e. exactly this pipeline)."""
        return self._ensure_serve().submit(e, sla,
                                           deadline_ms=deadline_ms,
                                           tenant=tenant,
                                           staleness_ms=staleness_ms)

    def fleet_info(self) -> Optional[dict]:
        """Fleet observability snapshot (None when the fleet is off or
        not yet built): per-slice state, directory counters, placement
        census, migration/failover counts (docs/FLEET.md)."""
        return self._fleet.info() if self._fleet is not None else None

    def serve_drain(self, timeout: Optional[float] = None) -> None:
        """Block until every submitted query has been dispatched and
        every in-flight batch has materialised. ``timeout`` (seconds)
        bounds the wait: a wedged admission worker raises the typed
        ``DrainTimeout`` instead of hanging the caller forever; the
        queue state is untouched, so a later drain can still finish.
        ONE absolute deadline spans the fleet AND the parent pipeline
        — the documented bound holds however many waits run."""
        t_end = (None if timeout is None
                 else retry_lib.now() + timeout)
        if self._fleet is not None:
            self._fleet.drain(timeout=_deadline_left(t_end))
        if self._serve is not None:
            self._serve.drain(timeout=_deadline_left(t_end))

    def serve_close(self, timeout: Optional[float] = None) -> None:
        """Drain then stop the admission worker. A later ``submit``
        raises the typed ``PipelineClosed`` (never enqueues into a
        dead worker). Also stops the live metrics exporter when one
        is running — "done serving" frees the port deterministically
        (a GC finalizer covers sessions that are simply dropped).
        Like :meth:`serve_drain`, ``timeout`` is ONE shared absolute
        deadline across the fleet and parent waits."""
        t_end = (None if timeout is None
                 else retry_lib.now() + timeout)
        # teardown must not stop at the first typed failure: a wedged
        # slice's DrainTimeout would otherwise leave the parent
        # pipeline's worker running and the metrics port bound until
        # GC — the exporter EADDRINUSE class. Close everything, then
        # let the first failure propagate.
        try:
            if self._fleet is not None:
                self._fleet.close(timeout=_deadline_left(t_end))
        finally:
            try:
                if self._serve is not None:
                    self._serve.close(timeout=_deadline_left(t_end))
            finally:
                if self._exporter is not None:
                    self._exporter.stop()

    def explain(self, expr: MatExpr, physical: bool = True,
                analyze: bool = False,
                precision: Optional[str] = None) -> str:
        """Logical, optimized AND physical plan text. With ``physical``
        (default) the expression is compiled (cached — a following
        compute() reuses the plan), so the optimized section carries
        the chosen matmul strategies / join schemes and a collectives
        summary — the reference's EXPLAIN shows its physical operators
        the same way. ``physical=False`` skips compilation.

        ``analyze=True`` (or ``config.obs_level == "analyze"``) RUNS
        the plan once per-op (eager, each node synced and wall-clocked)
        plus once fused, and appends the measured tree — per-op
        milliseconds next to each matmul's chosen strategy and the
        model's estimated ICI bytes (obs/analyze.py; the reference's
        Spark-UI stage-timeline-next-to-plan view). Off-hot-path by
        construction: nothing is measured unless asked."""
        e = as_expr(expr)
        if not physical:
            if analyze:
                # contradictory ask: measuring requires a compiled plan
                # (the config-level "analyze" default just degrades)
                raise ValueError(
                    "explain(analyze=True) requires physical=True")
            return e.explain(self.config)
        from matrel_tpu.ir.expr import pretty
        head = "== Logical plan ==\n" + pretty(e)
        try:
            plan = self.compile(e, precision=precision)
            text = head + "\n" + plan.explain()
        except Exception as ex:  # EXPLAIN must not fail on exotic plans
            # fall back to the PRE-COMPUTED logical text only: when the
            # failure happened inside optimize(), e.explain() would
            # re-run the optimizer and re-raise the same exception
            return head + f"\n== Physical plan unavailable: {ex!r} =="
        # static-verifier findings next to the physical plan they
        # describe (the reference's EXPLAIN shows analyzer output the
        # same way). Compile-time diagnostics are reused when the
        # verify_plans gate already produced them; otherwise EXPLAIN
        # runs the passes itself — it is off the hot path by contract.
        try:
            from matrel_tpu import analysis
            diags = (plan.meta or {}).get("diagnostics")
            if diags is None:
                # the PLAN's config, not the session's: a per-query
                # precision SLA must be verified against the SLA the
                # plan was actually compiled under (MV108)
                diags = analysis.verify_plan(plan.optimized, self.mesh,
                                             plan.config)
            else:
                diags = [analysis.Diagnostic(**d) for d in diags]
            text += "\n== Verifier ==\n" + analysis.render(diags)
        except Exception as ex:     # verification must not fail EXPLAIN
            text += f"\n== Verifier unavailable: {ex!r} =="
        if analyze or self.config.obs_level == "analyze":
            from matrel_tpu.obs import analyze as analyze_mod
            try:
                per_op, _eager = analyze_mod.measure_per_op(plan)
                fused = analyze_mod.measure_fused(plan)
                text += "\n" + analyze_mod.render(plan, per_op, fused)
                if self._obs_enabled():
                    # the drift auditor's highest-fidelity feed: the
                    # measured per-op tree joined to the SAME plan's
                    # decision records, one `analyze` event per run
                    try:
                        self._obs_emit("analyze",
                                       analyze_mod.analyze_record(
                                           plan, per_op, fused))
                    except Exception:
                        log.warning("obs: analyze event dropped",
                                    exc_info=True)
            except Exception as ex:   # analysis must not fail EXPLAIN
                text += f"\n== Analysis unavailable: {ex!r} =="
        return text

    def sql(self, query: str) -> MatExpr:
        """SQL-ish entry point over registered matrix tables (the reference's
        SQL surface, SURVEY.md §2 'SQL entry point'). See sql.py."""
        from matrel_tpu.sql import parse_sql
        return parse_sql(query, self)

    def explain_sql(self, query: str, analyze: bool = False) -> str:
        """Optimized-plan text for a SQL query — the EXPLAIN analogue
        (strategies, join schemes and value-join kinds included).
        ``analyze=True`` appends the measured per-op tree (EXPLAIN
        ANALYZE)."""
        return self.explain(self.sql(query), analyze=analyze)


def _prec_prefix(sla: str) -> str:
    """Cache-key prefix isolating precision tiers (the axisw-prefix
    idiom): plan-cache AND result-cache keys for a non-default SLA
    never collide with default-SLA keys or with each other, so a
    ``"fast"`` plan/result can never answer an ``"exact"`` query.
    "default" keeps the historical key format (empty prefix)."""
    return "" if sla == "default" else f"prec:{sla}|"


def _plan_bytes(plan: executor_lib.CompiledPlan) -> int:
    """Device bytes a cached plan pins beyond its leaf matrices: the
    hoisted constant payloads shipped as call-time args. Computed from
    shape/dtype — jax 0.9 TypedNdArray consts lack .nbytes."""
    total = 0
    for a in plan.extra_args:
        try:
            total += int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
        except (AttributeError, TypeError):
            pass
    return total


def _fn_token(fn, pins: list, seen: frozenset = frozenset()) -> str:
    """Cache-key token for a callable attr. Distinct predicates/merges MUST
    key differently — dropping them (pre-round-3 behaviour) made the second
    of two same-shaped queries silently return the first's cached result.
    Preference order: an attached source key (sql.py tags its compiled
    lambdas, so identical query text still HITS the cache), then a
    code+closure+globals+defaults fingerprint (stable across re-created
    lambdas with the same behaviour), then id(). EVERY object keyed by
    id() is appended to ``pins``, which the session attaches to the
    cached plan: a pinned object's address cannot be garbage-collected
    and reused, so an id-based token can never falsely hit."""
    key = getattr(fn, "__matrel_key__", None)
    if key is not None:
        return f"fnkey:{key}"
    code = getattr(fn, "__code__", None)
    if code is None:
        pins.append(fn)
        return f"fnid:{id(fn)}"
    if id(fn) in seen:
        # recursive reference (fn reachable from its own globals or
        # closure) — key the back-edge by pinned id to terminate
        pins.append(fn)
        return f"fnrec:{id(fn)}"
    seen = seen | {id(fn)}
    parts = [code.co_code.hex(), repr(code.co_consts), repr(code.co_names)]
    # bound-method instance state is part of the behaviour: two
    # Thresh(t).pred with different t share code/closure/globals and
    # would otherwise collide (round-3 advisor finding — the second
    # query silently returned the first's cached result)
    self_obj = getattr(fn, "__self__", None)
    if self_obj is not None:
        parts.append("self:" + _attr_token(self_obj, pins, seen))
    for cell in (getattr(fn, "__closure__", None) or ()):
        try:
            parts.append(_attr_token(cell.cell_contents, pins, seen))
        except Exception:
            pins.append(cell)
            parts.append(f"cell:{id(cell)}")
    # referenced globals are part of the behaviour: `thr = 0.5;
    # lambda v: v > thr` re-created after `thr = -0.5` has identical
    # code/consts/names and must NOT key identically. Names are
    # collected TRANSITIVELY through nested code objects (an inner
    # lambda/genexp reads the same __globals__ but its names live on
    # its own code constant, not the outer co_names). Scalars and small
    # containers key by value (so in-place mutation of a global list of
    # thresholds re-keys at the next query); modules/builtins by name
    # (stable); anything else by identity (pinned — a REBOUND global's
    # old value would otherwise free and its address recycle into a
    # false hit).
    g = getattr(fn, "__globals__", None) or {}
    for name in sorted(_code_names(code)):
        if name in g:
            v = g[name]
            if isinstance(v, types.ModuleType):
                parts.append(f"{name}=mod:{v.__name__}")
            else:
                parts.append(f"{name}=" + _attr_token(v, pins, seen))
    # defaults go through _attr_token, NOT bare repr: a default object
    # with a state-independent custom __repr__ would otherwise collide.
    # kw-only defaults are behaviour too — factory-made functions
    # differing only in them must not collide (round-3 advisor finding)
    parts.append(_attr_token(tuple(getattr(fn, "__defaults__", None)
                                   or ()), pins, seen))
    parts.append(_attr_token(getattr(fn, "__kwdefaults__", None) or {},
                             pins, seen))
    digest = hashlib.sha1("|".join(parts).encode()).hexdigest()[:16]
    return f"fncode:{digest}"


def _code_names(code) -> set:
    """co_names of a code object UNION those of every nested code
    object (inner lambdas, genexps, nested defs share __globals__)."""
    names = set(code.co_names)
    for c in code.co_consts:
        if isinstance(c, types.CodeType):
            names |= _code_names(c)
    return names


#: Containers above this many elements key by identity+length instead
#: of by value: re-walking a huge module-level list on EVERY plan-cache
#: lookup would turn an O(1) query into O(container) (advisor r4).
_VALUE_KEY_MAX_ELEMS = 256


def _attr_token(v, pins: list, seen: frozenset = frozenset()) -> str:
    """Encode ANY attr value into the plan key — nothing is dropped.
    Containers (tuple/list/dict/set) key by VALUE, so in-place mutation
    of e.g. a global threshold list or dict is re-read at the next query
    and correctly misses the cache. Cyclic containers terminate: a
    container reached again inside its own walk keys the back-edge by
    pinned id. Unknown object types key by identity (and are pinned):
    conservative (may miss the cache) but never shares a plan between
    distinct semantics. Caveats: in-place mutation of an id-keyed OBJECT
    (not a container) between queries is unsupported for cached
    predicates — rebind a fresh object instead; containers above
    ``_VALUE_KEY_MAX_ELEMS`` elements key by pinned identity + length
    (the value-walk would cost O(container) per lookup), so in-place
    mutation of an OVERSIZED container that keeps its length also
    requires rebinding — growth/shrinkage still re-keys via the length."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return repr(v)
    if callable(v):
        return _fn_token(v, pins, seen)
    if isinstance(v, (tuple, list, dict, set, frozenset)):
        if len(v) > _VALUE_KEY_MAX_ELEMS:
            pins.append(v)
            return f"bigcont:{type(v).__name__}:{id(v)}:len{len(v)}"
        if id(v) in seen:
            pins.append(v)
            return f"cyc:{id(v)}"
        seen = seen | {id(v)}
    if isinstance(v, (tuple, list)):
        return "[" + ",".join(_attr_token(x, pins, seen) for x in v) + "]"
    if isinstance(v, dict):
        try:
            items = sorted(v.items())
        except TypeError:
            items = sorted(v.items(), key=lambda kv: repr(kv[0]))
        return "{" + ",".join(
            _attr_token(k, pins, seen) + ":" + _attr_token(x, pins, seen)
            for k, x in items) + "}"
    if isinstance(v, (set, frozenset)):
        return "{" + ",".join(
            sorted(_attr_token(x, pins, seen) for x in v)) + "}"
    pins.append(v)
    return f"obj:{type(v).__name__}:{id(v)}"


def _plan_key_spans(e: MatExpr, leaf_token=None
                    ) -> Tuple[list, list, dict]:
    """(parts, pins, spans) in ONE walk. ``"|".join(parts)`` is the
    root's structural key; ``spans[uid] = (start, end)`` slices
    ``parts`` so that ``"|".join(parts[start:end])`` is EXACTLY the
    standalone key of that subtree (the emission is pre-order with a
    closing part, so a subtree's parts are one contiguous run). This
    is what lets the result cache probe every interior node of a query
    without re-walking each subtree through ``_attr_token`` — O(nodes)
    key work per admission instead of O(nodes x depth).

    ``leaf_token`` (serve/placement.py) substitutes the leaf-part
    emission: ``leaf_token(node) -> str or None`` replaces the
    id()-based leaf tokens with session-independent ones (catalog
    names — the fleet directory's cross-slice key), ``None`` meaning
    the leaf has no stable name and the whole key is ineligible
    (signalled by raising :class:`KeyError` from the walk). Interior
    tokens are byte-identical either way — ONE structural-walk
    implementation for every key the engine makes."""
    parts: list = []
    pins: list = []
    spans: dict = {}

    def walk(n: MatExpr):
        start = len(parts)
        if n.kind in ("leaf", "sparse_leaf", "coo_leaf"):
            if leaf_token is not None:
                tok = leaf_token(n)
                if tok is None:
                    raise KeyError(n.kind)
                parts.append(tok)
                spans[n.uid] = (start, len(parts))
                return
        if n.kind == "leaf":
            m = n.attrs["matrix"]
            pins.append(m)
            parts.append(f"leaf:{id(m)}:{m.shape}:{m.spec}")
        elif n.kind in ("sparse_leaf", "coo_leaf"):
            # sparse payloads are captured as CONSTANTS in the compiled
            # program — the cache key must carry the matrix identity or two
            # same-shaped sparse matrices would share one plan
            m = n.attrs["matrix"]
            pins.append(m)
            parts.append(f"{n.kind}:{id(m)}:{m.shape}")
        else:
            attrs = {k: _attr_token(v, pins)
                     for k, v in sorted(n.attrs.items())}
            parts.append(f"{n.kind}:{n.shape}:{attrs}(")
            for c in n.children:
                walk(c)
            parts.append(")")
        spans[n.uid] = (start, len(parts))

    walk(e)
    return parts, pins, spans


def _plan_key(e: MatExpr) -> Tuple[str, list]:
    """(key, pins): pins is every object the key references by id() —
    matrices, raw callables, their id-keyed globals/cells. The caller
    must keep pins alive as long as the key maps to a cached plan."""
    parts, pins, _spans = _plan_key_spans(e)
    return "|".join(parts), pins


def get_or_create_session() -> MatrelSession:
    return MatrelSession.builder().get_or_create()


def reset_session() -> None:
    global _active
    _active = None
