"""MatrelSession — the entry point, analogue of the reference's
``MatfastSession`` (SURVEY.md §2 "Session & catalog", §3.1).

The reference subclasses SparkSession and installs its own analyzer /
optimizer / planner into the session state; executors register with the
cluster manager. Here the session owns the device mesh (the "cluster"), the
config (the SparkConf analogue), a tiny named-matrix catalog, and the
optimize→plan→jit pipeline, plus a compiled-plan cache keyed by expression
structure so repeated actions don't re-trace (the Spark query-cache
analogue).
"""

from __future__ import annotations

import hashlib
import itertools
import logging
import os
import time
import types
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np
from jax.sharding import Mesh

from matrel_tpu import executor as executor_lib
from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.core import mesh as mesh_lib
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir.expr import MatExpr, as_expr

log = logging.getLogger("matrel_tpu")

_active: Optional["MatrelSession"] = None

_query_seq = itertools.count()


class MatrelSession:
    """Owns mesh + config + catalog; compiles and runs matrix queries."""

    def __init__(self, mesh: Optional[Mesh] = None,
                 config: Optional[MatrelConfig] = None):
        self.config = config or default_config()
        self.mesh = mesh or mesh_lib.make_mesh(
            self.config.mesh_shape, self.config.mesh_axis_names)
        self.catalog: dict[str, BlockMatrix] = {}
        # LRU plan cache: every cached plan pins its hoisted sparse
        # payloads (extra_args) in device HBM and its leaf matrices via
        # leaf_order — unbounded growth OOMs long-lived sessions, so
        # least-recently-used plans evict at the config's plan-count /
        # hoisted-byte bounds
        self._plan_cache: "OrderedDict[str, executor_lib.CompiledPlan]" \
            = OrderedDict()
        self._plan_cache_bytes = 0
        self._plan_cache_evicted = 0
        self._event_log = None      # lazily built (obs_level != "off")

    # -- builder (MatfastSession.builder().getOrCreate() analogue) ---------

    class Builder:
        def __init__(self):
            self._cfg = default_config()
            self._mesh = None
            self._explicit_cfg = False

        def config(self, **kw) -> "MatrelSession.Builder":
            self._cfg = self._cfg.replace(**kw)
            self._explicit_cfg = True
            return self

        def mesh(self, mesh: Mesh) -> "MatrelSession.Builder":
            self._mesh = mesh
            return self

        def get_or_create(self) -> "MatrelSession":
            global _active
            if _active is None:
                _active = MatrelSession(self._mesh, self._cfg)
                return _active
            # a live session wins — but silently ignoring an
            # explicitly-requested different config/mesh hands the
            # caller settings they did not ask for
            if self._explicit_cfg and self._cfg != _active.config:
                log.warning(
                    "MatrelSession.builder(): a session already exists; "
                    "ignoring the requested config (differs from the "
                    "live session's — call reset_session() first to "
                    "rebuild with new settings)")
            if self._mesh is not None and self._mesh != _active.mesh:
                log.warning(
                    "MatrelSession.builder(): a session already exists; "
                    "ignoring the requested mesh (differs from the live "
                    "session's — call reset_session() first)")
            return _active

    @staticmethod
    def builder() -> "MatrelSession.Builder":
        return MatrelSession.Builder()

    # -- catalog (matrix tables, SQL-facing names) -------------------------

    def register(self, name: str, matrix: BlockMatrix) -> None:
        self.catalog[name] = matrix

    def table(self, name: str) -> BlockMatrix:
        return self.catalog[name]

    def save_catalog(self, directory: str,
                     step: Optional[int] = None) -> str:
        """Persist every registered table (atomic step dir, sharding
        metadata included) — the session-level face of the checkpoint
        subsystem, so a catalog survives process restarts the way the
        reference's persisted tables do. ``step`` defaults to the NEXT
        step in the directory (a fixed default like 0 would be GC'd by
        the keep-k policy the moment older saves carry higher steps).
        Returns the step path."""
        from matrel_tpu.utils.checkpoint import CheckpointManager
        mgr = CheckpointManager(directory)
        if step is None:
            step = mgr.next_step()
        return mgr.save(step, matrices=dict(self.catalog))

    def load_catalog(self, directory: str,
                     step: Optional[int] = None) -> list:
        """Restore tables saved by save_catalog into this session's
        catalog (sharding-preserving, existing names overwritten).
        Returns the restored names; empty directory → empty list."""
        from matrel_tpu.utils.checkpoint import CheckpointManager
        got = CheckpointManager(directory).restore(self.mesh, step)
        if got is None:
            return []
        _step, mats, _arrays, _state = got
        self.catalog.update(mats)
        return sorted(mats)

    # -- constructors bound to this session's mesh/config ------------------

    def from_numpy(self, arr: np.ndarray, **kw) -> BlockMatrix:
        return BlockMatrix.from_numpy(arr, mesh=self.mesh, config=self.config, **kw)

    def random(self, shape: Tuple[int, int], **kw) -> BlockMatrix:
        return BlockMatrix.random(shape, mesh=self.mesh, config=self.config, **kw)

    def zeros(self, shape: Tuple[int, int], **kw) -> BlockMatrix:
        return BlockMatrix.zeros(shape, mesh=self.mesh, config=self.config, **kw)

    def eye(self, n: int, **kw) -> BlockMatrix:
        return BlockMatrix.eye(n, mesh=self.mesh, config=self.config, **kw)

    # -- actions ------------------------------------------------------------

    def compile(self, expr: MatExpr) -> executor_lib.CompiledPlan:
        return self._compile_entry(as_expr(expr))[0]

    def _compile_entry(self, e: MatExpr
                       ) -> Tuple[executor_lib.CompiledPlan, bool, str]:
        """(plan, cache_hit, key) — the compile path with its cache
        outcome exposed, so compute() can emit hit/miss events without
        a second key computation."""
        key, pins = _plan_key(e)
        wts = mesh_lib.axis_weights(self.mesh, self.config)
        if wts != (1.0, 1.0):
            # topology weights change which strategies get stamped, so
            # weighted and unweighted plans must never share a cache
            # entry (the detection path can flip weights without any
            # config field changing — the expression key alone is not
            # enough). Unweighted keys keep the historical format.
            key = f"axisw:{wts[0]:g}x{wts[1]:g}|{key}"
        plan = self._plan_cache.get(key)
        if plan is not None:
            self._plan_cache.move_to_end(key)
            return plan, True, key
        plan = executor_lib.compile_expr(e, self.mesh, self.config)
        # pin every id()-keyed object on the cached plan: a garbage-
        # collected object's address can be REUSED by CPython, and a
        # later distinct object at the recycled address would falsely
        # hit this entry. Pinning the expr alone is not enough — a
        # REBOUND module global referenced by a predicate is no longer
        # reachable from the expr, so its old value is pinned
        # explicitly via the collected pins list.
        plan._cache_pin = (e, pins)
        self._plan_cache[key] = plan
        self._plan_cache_bytes += _plan_bytes(plan)
        self._evict_plans()
        return plan, False, key

    def _evict_plans(self) -> None:
        """Drop least-recently-used plans past the config bounds. The
        byte budget counts hoisted payloads (extra_args) — the device
        memory a cached plan pins beyond its leaves."""
        cfg = self.config
        while self._plan_cache and (
                len(self._plan_cache) > cfg.plan_cache_max_plans
                or self._plan_cache_bytes > cfg.plan_cache_max_bytes):
            if len(self._plan_cache) == 1 and \
                    len(self._plan_cache) <= cfg.plan_cache_max_plans:
                break    # never evict the sole (just-inserted) plan
            _, old = self._plan_cache.popitem(last=False)
            self._plan_cache_bytes -= _plan_bytes(old)
            self._plan_cache_evicted += 1
        self._plan_cache_bytes = max(self._plan_cache_bytes, 0)

    def plan_cache_info(self) -> dict:
        """Cache observability: entry count + pinned hoisted bytes +
        lifetime eviction count."""
        return {"plans": len(self._plan_cache),
                "hoisted_bytes": self._plan_cache_bytes,
                "evicted": self._plan_cache_evicted}

    # -- observability (obs/ — the SparkListener analogue) ------------------

    def _obs_enabled(self) -> bool:
        return self.config.obs_level != "off"

    def _obs_event_log(self):
        from matrel_tpu.obs.events import EventLog, resolve_path
        path = resolve_path(self.config.obs_event_log)
        if self._event_log is None or self._event_log.path != path:
            self._event_log = EventLog(path)
        return self._event_log

    def _emit_query_event(self, e: MatExpr, plan, hit: bool, key: str,
                          execute_ms: float, first_execution: bool,
                          out: BlockMatrix) -> None:
        """One event-log record + metrics-registry updates per query run.
        Assembled entirely OUTSIDE jitted code, from data the compile
        path already produced (plan.meta) — the only device sync the obs
        path adds is the one execute-time block in compute()."""
        from matrel_tpu.obs.metrics import REGISTRY
        meta = plan.meta or {}
        matmuls = executor_lib.plan_matmul_decisions(plan)
        sql_hash = getattr(e, "_sql_hash", None)
        record = {
            "query_id": f"q{os.getpid()}-{next(_query_seq)}",
            "source": "sql" if sql_hash else "dsl",
            "source_hash": sql_hash
            or hashlib.sha1(key.encode()).hexdigest()[:16],
            "root_kind": e.kind,
            "cache": "hit" if hit else "miss",
            "optimize_ms": meta.get("optimize_ms"),
            "trace_ms": meta.get("trace_ms"),
            # compile-scoped: a cache hit ran no rewrite rules, so hit
            # records carry {} and history's roll-up counts real
            # optimizer work (optimize_ms/trace_ms DO repeat on hits —
            # they describe the plan, "cache" says no compile ran)
            "rule_hits": {} if hit else meta.get("rule_hits", {}),
            "matmuls": matmuls,
            "execute_ms": round(execute_ms, 3),
            "first_execution": first_execution,
            "out_shape": list(out.shape),
            "out_nnz": out.nnz,
            "plan_cache": self.plan_cache_info(),
        }
        self._obs_event_log().emit("query", record)
        REGISTRY.counter("query.count").inc()
        REGISTRY.counter("plan_cache.hit" if hit
                         else "plan_cache.miss").inc()
        REGISTRY.gauge("plan_cache.plans").set(len(self._plan_cache))
        REGISTRY.gauge("plan_cache.hoisted_bytes").set(
            self._plan_cache_bytes)
        REGISTRY.gauge("plan_cache.evicted").set(
            self._plan_cache_evicted)
        REGISTRY.histogram("query.execute_ms").observe(execute_ms)
        if not hit:
            if meta.get("optimize_ms") is not None:
                REGISTRY.histogram("query.optimize_ms").observe(
                    meta["optimize_ms"])
            # compile-scoped like optimize_ms: rules fire once per
            # compile, not per run
            for rule, n in meta.get("rule_hits", {}).items():
                REGISTRY.counter(f"optimizer.rule.{rule}").inc(n)
        for d in matmuls:
            REGISTRY.counter(f"planner.strategy.{d['strategy']}").inc()

    def _emit_verify_event(self, plan) -> None:
        """One ``verify`` record per observed query run (obs_level on
        AND verify_plans on): the diagnostic codes the compile-time
        verifier produced for this plan — empty codes = verified clean.
        Cache hits re-report the compile-time findings (the record
        describes the plan that ran, "cache" on the query record says
        no new verify happened)."""
        diags = (plan.meta or {}).get("diagnostics")
        if diags is None:
            return        # verifier was off when this plan compiled
        from matrel_tpu.obs.metrics import REGISTRY
        self._obs_event_log().emit("verify", {
            "mode": self.config.verify_plans,
            "count": len(diags),
            "errors": sum(1 for d in diags if d["severity"] == "error"),
            "codes": sorted({d["code"] for d in diags}),
        })
        REGISTRY.counter("verify.count").inc()
        if diags:
            REGISTRY.counter("verify.diagnostics").inc(len(diags))

    def verify(self, expr: MatExpr) -> list:
        """Run the static plan verifier (matrel_tpu/analysis/) on this
        expression's OPTIMIZED, strategy-annotated plan and return the
        diagnostic list — regardless of ``config.verify_plans`` (that
        gate controls the compile path; this is the on-demand surface).
        Planning only: nothing is traced, jitted, or executed."""
        from matrel_tpu import analysis
        from matrel_tpu.ir import rules
        from matrel_tpu.parallel import planner
        e = as_expr(expr)
        grid = mesh_lib.mesh_grid_shape(self.mesh)
        opt = planner.annotate_strategies(
            rules.optimize(e, self.config, grid=grid, mesh=self.mesh),
            self.mesh, self.config)
        return analysis.verify_plan(opt, self.mesh, self.config)

    def compute(self, expr: MatExpr) -> BlockMatrix:
        e = as_expr(expr)
        if not self._obs_enabled():
            # the production path: zero event assembly, zero extra
            # device syncs (the obs_level="off" contract bench.py
            # relies on)
            return self.compile(e).run()
        plan, hit, key = self._compile_entry(e)
        first = not getattr(plan, "_obs_executed", False)
        t0 = time.perf_counter()
        out = plan.run()
        out.data.block_until_ready()
        execute_ms = (time.perf_counter() - t0) * 1e3
        plan._obs_executed = True
        try:
            self._emit_query_event(e, plan, hit, key, execute_ms, first,
                                   out)
            self._emit_verify_event(plan)
        except Exception:   # the result is already computed — keep the
            # never-fail-a-query contract (obs/events.py) even when
            # record ASSEMBLY breaks, not just the file write
            log.warning("obs: query event dropped", exc_info=True)
        return out

    # alias: the reference's Dataset actions read as "run the query"
    run = compute

    def explain(self, expr: MatExpr, physical: bool = True,
                analyze: bool = False) -> str:
        """Logical, optimized AND physical plan text. With ``physical``
        (default) the expression is compiled (cached — a following
        compute() reuses the plan), so the optimized section carries
        the chosen matmul strategies / join schemes and a collectives
        summary — the reference's EXPLAIN shows its physical operators
        the same way. ``physical=False`` skips compilation.

        ``analyze=True`` (or ``config.obs_level == "analyze"``) RUNS
        the plan once per-op (eager, each node synced and wall-clocked)
        plus once fused, and appends the measured tree — per-op
        milliseconds next to each matmul's chosen strategy and the
        model's estimated ICI bytes (obs/analyze.py; the reference's
        Spark-UI stage-timeline-next-to-plan view). Off-hot-path by
        construction: nothing is measured unless asked."""
        e = as_expr(expr)
        if not physical:
            if analyze:
                # contradictory ask: measuring requires a compiled plan
                # (the config-level "analyze" default just degrades)
                raise ValueError(
                    "explain(analyze=True) requires physical=True")
            return e.explain(self.config)
        from matrel_tpu.ir.expr import pretty
        head = "== Logical plan ==\n" + pretty(e)
        try:
            plan = self.compile(e)
            text = head + "\n" + plan.explain()
        except Exception as ex:  # EXPLAIN must not fail on exotic plans
            # fall back to the PRE-COMPUTED logical text only: when the
            # failure happened inside optimize(), e.explain() would
            # re-run the optimizer and re-raise the same exception
            return head + f"\n== Physical plan unavailable: {ex!r} =="
        # static-verifier findings next to the physical plan they
        # describe (the reference's EXPLAIN shows analyzer output the
        # same way). Compile-time diagnostics are reused when the
        # verify_plans gate already produced them; otherwise EXPLAIN
        # runs the passes itself — it is off the hot path by contract.
        try:
            from matrel_tpu import analysis
            diags = (plan.meta or {}).get("diagnostics")
            if diags is None:
                diags = analysis.verify_plan(plan.optimized, self.mesh,
                                             self.config)
            else:
                diags = [analysis.Diagnostic(**d) for d in diags]
            text += "\n== Verifier ==\n" + analysis.render(diags)
        except Exception as ex:     # verification must not fail EXPLAIN
            text += f"\n== Verifier unavailable: {ex!r} =="
        if analyze or self.config.obs_level == "analyze":
            from matrel_tpu.obs import analyze as analyze_mod
            try:
                text += "\n" + analyze_mod.explain_analyzed(plan)
            except Exception as ex:   # analysis must not fail EXPLAIN
                text += f"\n== Analysis unavailable: {ex!r} =="
        return text

    def sql(self, query: str) -> MatExpr:
        """SQL-ish entry point over registered matrix tables (the reference's
        SQL surface, SURVEY.md §2 'SQL entry point'). See sql.py."""
        from matrel_tpu.sql import parse_sql
        return parse_sql(query, self)

    def explain_sql(self, query: str, analyze: bool = False) -> str:
        """Optimized-plan text for a SQL query — the EXPLAIN analogue
        (strategies, join schemes and value-join kinds included).
        ``analyze=True`` appends the measured per-op tree (EXPLAIN
        ANALYZE)."""
        return self.explain(self.sql(query), analyze=analyze)


def _plan_bytes(plan: executor_lib.CompiledPlan) -> int:
    """Device bytes a cached plan pins beyond its leaf matrices: the
    hoisted constant payloads shipped as call-time args. Computed from
    shape/dtype — jax 0.9 TypedNdArray consts lack .nbytes."""
    total = 0
    for a in plan.extra_args:
        try:
            total += int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
        except (AttributeError, TypeError):
            pass
    return total


def _fn_token(fn, pins: list, seen: frozenset = frozenset()) -> str:
    """Cache-key token for a callable attr. Distinct predicates/merges MUST
    key differently — dropping them (pre-round-3 behaviour) made the second
    of two same-shaped queries silently return the first's cached result.
    Preference order: an attached source key (sql.py tags its compiled
    lambdas, so identical query text still HITS the cache), then a
    code+closure+globals+defaults fingerprint (stable across re-created
    lambdas with the same behaviour), then id(). EVERY object keyed by
    id() is appended to ``pins``, which the session attaches to the
    cached plan: a pinned object's address cannot be garbage-collected
    and reused, so an id-based token can never falsely hit."""
    key = getattr(fn, "__matrel_key__", None)
    if key is not None:
        return f"fnkey:{key}"
    code = getattr(fn, "__code__", None)
    if code is None:
        pins.append(fn)
        return f"fnid:{id(fn)}"
    if id(fn) in seen:
        # recursive reference (fn reachable from its own globals or
        # closure) — key the back-edge by pinned id to terminate
        pins.append(fn)
        return f"fnrec:{id(fn)}"
    seen = seen | {id(fn)}
    parts = [code.co_code.hex(), repr(code.co_consts), repr(code.co_names)]
    # bound-method instance state is part of the behaviour: two
    # Thresh(t).pred with different t share code/closure/globals and
    # would otherwise collide (round-3 advisor finding — the second
    # query silently returned the first's cached result)
    self_obj = getattr(fn, "__self__", None)
    if self_obj is not None:
        parts.append("self:" + _attr_token(self_obj, pins, seen))
    for cell in (getattr(fn, "__closure__", None) or ()):
        try:
            parts.append(_attr_token(cell.cell_contents, pins, seen))
        except Exception:
            pins.append(cell)
            parts.append(f"cell:{id(cell)}")
    # referenced globals are part of the behaviour: `thr = 0.5;
    # lambda v: v > thr` re-created after `thr = -0.5` has identical
    # code/consts/names and must NOT key identically. Names are
    # collected TRANSITIVELY through nested code objects (an inner
    # lambda/genexp reads the same __globals__ but its names live on
    # its own code constant, not the outer co_names). Scalars and small
    # containers key by value (so in-place mutation of a global list of
    # thresholds re-keys at the next query); modules/builtins by name
    # (stable); anything else by identity (pinned — a REBOUND global's
    # old value would otherwise free and its address recycle into a
    # false hit).
    g = getattr(fn, "__globals__", None) or {}
    for name in sorted(_code_names(code)):
        if name in g:
            v = g[name]
            if isinstance(v, types.ModuleType):
                parts.append(f"{name}=mod:{v.__name__}")
            else:
                parts.append(f"{name}=" + _attr_token(v, pins, seen))
    # defaults go through _attr_token, NOT bare repr: a default object
    # with a state-independent custom __repr__ would otherwise collide.
    # kw-only defaults are behaviour too — factory-made functions
    # differing only in them must not collide (round-3 advisor finding)
    parts.append(_attr_token(tuple(getattr(fn, "__defaults__", None)
                                   or ()), pins, seen))
    parts.append(_attr_token(getattr(fn, "__kwdefaults__", None) or {},
                             pins, seen))
    digest = hashlib.sha1("|".join(parts).encode()).hexdigest()[:16]
    return f"fncode:{digest}"


def _code_names(code) -> set:
    """co_names of a code object UNION those of every nested code
    object (inner lambdas, genexps, nested defs share __globals__)."""
    names = set(code.co_names)
    for c in code.co_consts:
        if isinstance(c, types.CodeType):
            names |= _code_names(c)
    return names


#: Containers above this many elements key by identity+length instead
#: of by value: re-walking a huge module-level list on EVERY plan-cache
#: lookup would turn an O(1) query into O(container) (advisor r4).
_VALUE_KEY_MAX_ELEMS = 256


def _attr_token(v, pins: list, seen: frozenset = frozenset()) -> str:
    """Encode ANY attr value into the plan key — nothing is dropped.
    Containers (tuple/list/dict/set) key by VALUE, so in-place mutation
    of e.g. a global threshold list or dict is re-read at the next query
    and correctly misses the cache. Cyclic containers terminate: a
    container reached again inside its own walk keys the back-edge by
    pinned id. Unknown object types key by identity (and are pinned):
    conservative (may miss the cache) but never shares a plan between
    distinct semantics. Caveats: in-place mutation of an id-keyed OBJECT
    (not a container) between queries is unsupported for cached
    predicates — rebind a fresh object instead; containers above
    ``_VALUE_KEY_MAX_ELEMS`` elements key by pinned identity + length
    (the value-walk would cost O(container) per lookup), so in-place
    mutation of an OVERSIZED container that keeps its length also
    requires rebinding — growth/shrinkage still re-keys via the length."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return repr(v)
    if callable(v):
        return _fn_token(v, pins, seen)
    if isinstance(v, (tuple, list, dict, set, frozenset)):
        if len(v) > _VALUE_KEY_MAX_ELEMS:
            pins.append(v)
            return f"bigcont:{type(v).__name__}:{id(v)}:len{len(v)}"
        if id(v) in seen:
            pins.append(v)
            return f"cyc:{id(v)}"
        seen = seen | {id(v)}
    if isinstance(v, (tuple, list)):
        return "[" + ",".join(_attr_token(x, pins, seen) for x in v) + "]"
    if isinstance(v, dict):
        try:
            items = sorted(v.items())
        except TypeError:
            items = sorted(v.items(), key=lambda kv: repr(kv[0]))
        return "{" + ",".join(
            _attr_token(k, pins, seen) + ":" + _attr_token(x, pins, seen)
            for k, x in items) + "}"
    if isinstance(v, (set, frozenset)):
        return "{" + ",".join(
            sorted(_attr_token(x, pins, seen) for x in v)) + "}"
    pins.append(v)
    return f"obj:{type(v).__name__}:{id(v)}"


def _plan_key(e: MatExpr) -> Tuple[str, list]:
    """(key, pins): pins is every object the key references by id() —
    matrices, raw callables, their id-keyed globals/cells. The caller
    must keep pins alive as long as the key maps to a cached plan."""
    parts = []
    pins: list = []

    def walk(n: MatExpr):
        if n.kind == "leaf":
            m = n.attrs["matrix"]
            pins.append(m)
            parts.append(f"leaf:{id(m)}:{m.shape}:{m.spec}")
            return
        if n.kind in ("sparse_leaf", "coo_leaf"):
            # sparse payloads are captured as CONSTANTS in the compiled
            # program — the cache key must carry the matrix identity or two
            # same-shaped sparse matrices would share one plan
            m = n.attrs["matrix"]
            pins.append(m)
            parts.append(f"{n.kind}:{id(m)}:{m.shape}")
            return
        attrs = {k: _attr_token(v, pins) for k, v in sorted(n.attrs.items())}
        parts.append(f"{n.kind}:{n.shape}:{attrs}(")
        for c in n.children:
            walk(c)
        parts.append(")")

    walk(e)
    return "|".join(parts), pins


def get_or_create_session() -> MatrelSession:
    return MatrelSession.builder().get_or_create()


def reset_session() -> None:
    global _active
    _active = None
