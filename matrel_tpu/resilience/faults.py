"""Deterministic, seeded fault injection at the engine's choke points.

The chaos-engineering half of the resilience layer: a spec string
(``config.fault_inject``) describes WHICH instrumented site faults,
WHAT kind, and WHEN (per-call probability from a seeded stream, or an
exact nth-call trigger) — so a failure schedule is reproducible
bit-for-bit across runs, the property every chaos test in
tests/test_resilience.py and tools/chaos_drill.py rests on.

Spec grammar (semicolon-separated rules)::

    site:kind[:p=0.25][:n=3][:max=5]

    site  ∈ SITES (below) or "all" (every site)
    kind  ∈ {"transient", "fatal"}  — drives errors.classify
    p=F   per-call fire probability, drawn from a per-rule RNG seeded
          by (config.fault_inject_seed, site, rule index)
    n=K   fire exactly on the K-th check of that site (1-based)
    max=M cap total fires for the rule (p-rules default unbounded,
          n-rules fire once by construction)

Exactly one of p=/n= per rule. Parsing is VALIDATED at config
construction — a typo'd site name must fail loudly, not silently
inject nothing.

Instrumented sites (each named after the choke point it lives at)::

    compile      session._compile_entry / _compile_multi_entry
    lower        the executor's single annotate() dispatch site
                 (fires at trace time — a compile-path fault)
    strategy     strategies.run_matmul entry (trace time)
    execute      the session's plan.run() dispatch (host side,
                 per attempt — the main retryable site)
    rc_probe     session._rc_admit (result-cache consult)
    serve_admit  the serve pipeline's admission worker
    checkpoint   CheckpointManager save/restore IO

The OFF contract is structural: with ``config.fault_inject == ""``
(the default) :func:`check` returns after one string truthiness test
and NO injector, rule, or RNG object is ever constructed —
tests/test_resilience.py poisons ``FaultInjector.__init__`` to prove
it. Injectors are memoised per (spec, seed) process-wide so the
executor/strategy/checkpoint sites — which see only a config, never a
session — share one deterministic schedule with the session sites.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional

from matrel_tpu.resilience.errors import InjectedFault
from matrel_tpu.utils import lockdep

#: The instrumented-site vocabulary (see module docstring).
SITES = ("compile", "lower", "strategy", "execute", "rc_probe",
         "serve_admit", "checkpoint")

KINDS = ("transient", "fatal")


class FaultRule:
    """One parsed spec rule with its per-rule seeded stream + counters.

    Counters are per-rule, not per-injector: two rules on one site each
    see every check of that site and fire independently."""

    __slots__ = ("site", "kind", "p", "n", "max_fires", "spec",
                 "calls", "fires", "_rng")

    def __init__(self, site: str, kind: str, p: Optional[float],
                 n: Optional[int], max_fires: Optional[int],
                 spec: str, seed: int, index: int):
        self.site = site
        self.kind = kind
        self.p = p
        self.n = n
        self.max_fires = max_fires if max_fires is not None else (
            1 if n is not None else None)
        self.spec = spec
        self.calls = 0
        self.fires = 0
        # per-rule stream: determinism survives reordering of OTHER
        # rules in the spec (each rule's draws depend only on its own
        # site/index/seed and its own call sequence)
        self._rng = random.Random(f"{seed}|{site}|{index}|{spec}")

    def should_fire(self) -> bool:
        self.calls += 1
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if self.n is not None:
            fire = self.calls == self.n
        else:
            fire = self._rng.random() < self.p
        if fire:
            self.fires += 1
        return fire


def parse_spec(spec: str) -> List[dict]:
    """Validate + normalise a fault spec into rule dicts. Raises
    ``ValueError`` on any malformed rule (config.__post_init__ calls
    this so a typo fails at construction, the obs_level precedent)."""
    rules: List[dict] = []
    for part in (p.strip() for p in spec.split(";")):
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(
                f"fault_inject rule {part!r} needs at least site:kind")
        site, kind = fields[0].strip(), fields[1].strip()
        if site != "all" and site not in SITES:
            raise ValueError(
                f"fault_inject site {site!r} not in {SITES + ('all',)}")
        if kind not in KINDS:
            raise ValueError(
                f"fault_inject kind {kind!r} not in {KINDS}")
        p = n = max_fires = None
        for opt in fields[2:]:
            opt = opt.strip()
            if not opt:
                continue
            k, _, v = opt.partition("=")
            if k == "p":
                p = float(v)
                if not (0.0 < p <= 1.0):
                    raise ValueError(
                        f"fault_inject p={v} must be in (0, 1]")
            elif k == "n":
                n = int(v)
                if n < 1:
                    raise ValueError(
                        f"fault_inject n={v} must be >= 1")
            elif k == "max":
                max_fires = int(v)
                if max_fires < 1:
                    raise ValueError(
                        f"fault_inject max={v} must be >= 1")
            else:
                raise ValueError(
                    f"fault_inject option {opt!r} unknown "
                    f"(p=/n=/max=)")
        if (p is None) == (n is None):
            raise ValueError(
                f"fault_inject rule {part!r} needs exactly one of "
                f"p= or n=")
        sites = SITES if site == "all" else (site,)
        for s in sites:
            rules.append({"site": s, "kind": kind, "p": p, "n": n,
                          "max": max_fires, "spec": part})
    return rules


class FaultInjector:
    """The rules of one (spec, seed) pair with their live counters.
    ``check(site)`` raises :class:`InjectedFault` when a rule fires;
    thread-safe (the serve worker and the caller's thread share one
    schedule)."""

    def __init__(self, spec: str, seed: int):
        self.spec = spec
        self.seed = seed
        self._lock = lockdep.make_lock("resilience.fault_plan")
        self._by_site: Dict[str, List[FaultRule]] = {}
        for i, r in enumerate(parse_spec(spec)):
            rule = FaultRule(r["site"], r["kind"], r["p"], r["n"],
                             r["max"], r["spec"], seed, i)
            self._by_site.setdefault(r["site"], []).append(rule)

    def check(self, site: str) -> None:
        rules = self._by_site.get(site)
        if not rules:
            return
        with self._lock:
            # EVERY rule sees every check of its site before anything
            # raises — one rule firing must not skew a sibling rule's
            # call count (an n=K rule fires on the site's K-th check
            # regardless of what other rules did); the first firing
            # rule in spec order wins the raise
            first = None
            for rule in rules:
                if rule.should_fire() and first is None:
                    first = rule
            if first is not None:
                raise InjectedFault(site, first.kind, first.calls,
                                    rule=first.spec)

    def stats(self) -> Dict[str, dict]:
        """Per-site {calls, fires} — the chaos drill's coverage
        evidence (every instrumented site must actually be checked AND
        must actually have fired under the drill's schedule)."""
        out: Dict[str, dict] = {}
        with self._lock:
            for site, rules in self._by_site.items():
                out[site] = {
                    "calls": max(r.calls for r in rules),
                    "fires": sum(r.fires for r in rules),
                }
        return out


_REGISTRY: Dict[tuple, FaultInjector] = {}
_REGISTRY_LOCK = lockdep.make_lock("resilience.fault_registry")


def injector_for(config) -> Optional[FaultInjector]:
    """The process-shared injector for a config's (spec, seed), or
    None when injection is off. Shared so every site — session-level
    or module-level — advances ONE deterministic schedule."""
    spec = getattr(config, "fault_inject", "") if config is not None \
        else ""
    if not spec:
        return None
    key = (spec, getattr(config, "fault_inject_seed", 0))
    inj = _REGISTRY.get(key)
    if inj is None:
        with _REGISTRY_LOCK:
            inj = _REGISTRY.get(key)
            if inj is None:
                inj = _REGISTRY[key] = FaultInjector(*key)
    return inj


def check(site: str, config) -> None:
    """The one call every instrumented choke point makes. With the
    default config this is a single attribute read + truthiness test —
    no objects, no locks (the zero-overhead-when-off contract)."""
    if config is None or not getattr(config, "fault_inject", ""):
        return
    injector_for(config).check(site)


def reset() -> None:
    """Forget every injector's schedule state (tests: a fresh
    deterministic run needs fresh counters/streams)."""
    with _REGISTRY_LOCK:
        _REGISTRY.clear()
