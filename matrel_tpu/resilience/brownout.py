"""Adaptive brownout — trade fidelity for admission under sustained
overload, with hysteresis (docs/OVERLOAD.md).

The degradation ladder (resilience/degrade.py) answers "this QUERY
keeps failing"; brownout answers "the whole PLANE is saturated". A
load controller sampled once per admission cycle watches three
signals over a sliding window — queue depth, queue-wait p95,
deadline-miss rate — and climbs a cumulative rung ladder when any
signal holds above its ENTER threshold, descending only when every
signal falls below its (strictly lower) EXIT threshold and the dwell
has elapsed, so the ladder cannot flap on one noisy sample:

    rung 0  normal
    rung 1  tier-downshift: default-SLA queries compile under the
            "fast" precision tier (PR 7 tiers; results stay
            SLA-key-isolated — a browned-out result can never answer
            a later full-fidelity query)
    rung 2  + stale-serve: result-cache entries a catalog rebind
            marked STALE may answer queries that declare a
            ``staleness_ms`` tolerance (the query's own contract —
            nothing is served stale to a caller who didn't opt in)
    rung 3  + tenant-shed: lowest-weight tenants shed typed
            (AdmissionShed, scope="brownout") at submit

Every rung is a fidelity trade, never a correctness trade: rung 1
results carry the fast tier's documented error bound, rung 2 results
are exact answers to a slightly-old catalog, rung 3 refusals are
typed. The OFF contract is structural: :func:`from_config` returns
None for ``brownout_enable == False`` (the default) and no controller
object is ever constructed (poisoned-init test, the faults/breaker
precedent). ``clock`` is injectable for deterministic tests.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from matrel_tpu.obs.metrics import percentile
from matrel_tpu.utils import lockdep

#: The rung vocabulary (cumulative; labels ride obs events and docs).
MAX_RUNG = 3
TIER_RUNG = 1
STALE_RUNG = 2
SHED_RUNG = 3

RUNG_LABELS = {0: "normal", 1: "tier-downshift", 2: "stale-serve",
               3: "tenant-shed"}


def rung_label(rung: int) -> str:
    return RUNG_LABELS.get(rung, f"rung-{rung}")


def downshift_stamp(staleness_ms: Optional[float] = None) -> dict:
    """The brownout stamp a downshifted default-SLA query carries
    (expr root ``attrs["brownout"]``; MV112 verifies it). The stamped
    rung is the rung that AUTHORIZES the stamp's strongest claim —
    TIER_RUNG for a plain tier downshift, STALE_RUNG when a staleness
    tolerance rides along — NOT the controller's instantaneous rung:
    the plan's fidelity change is identical at rung 1 and rung 3, and
    keying it by the live rung would shatter the plan cache into one
    entry per rung for byte-identical programs. The staleness claim
    is the boolean ``stale_ok``, never the caller's raw tolerance
    value — the stamp forms the plan key, and distinct tolerances for
    byte-identical programs would shatter the cache the same way."""
    stamp = {"rung": (STALE_RUNG if staleness_ms else TIER_RUNG),
             "sla": "fast"}
    if staleness_ms:
        stamp["stale_ok"] = True
    return stamp


def from_config(config) -> Optional["LoadController"]:
    """None for the default config: the OFF path constructs nothing
    (the faults.check / BreakerRegistry.from_config precedent)."""
    if not getattr(config, "brownout_enable", False):
        return None
    return LoadController(config)


class LoadController:
    """The admission worker's load sensor + rung ladder. One
    ``observe()`` per admission cycle; ``rung()`` is what the worker
    acts on. Thread-safe (submit-side rung-3 sheds read the rung from
    the caller's thread while the worker observes)."""

    def __init__(self, config):
        self.window = int(config.brownout_window)
        self.dwell = int(config.brownout_dwell)
        self.wait_high = float(config.brownout_wait_high_ms)
        self.wait_low = float(config.brownout_wait_low_ms)
        self.depth_high = int(config.brownout_depth_high)
        self.depth_low = int(config.brownout_depth_low)
        self.miss_high = float(config.brownout_miss_high)
        self.miss_low = float(config.brownout_miss_low)
        self._lock = lockdep.make_lock("resilience.brownout")
        self._waits: deque = deque(maxlen=self.window)
        # per-query outcome bits over the window (1 = missed its
        # deadline, 0 = admitted fine) — the miss-RATE signal
        self._outcomes: deque = deque(maxlen=self.window)
        self._depth = 0
        self._rung = 0
        self._since_change = self.dwell   # first move needs no warmup
        self._samples = 0
        self.entered = 0                  # lifetime rung-up count
        self.exited = 0                   # lifetime rung-down count
        self.max_rung_seen = 0

    # -- sensing -----------------------------------------------------------

    def observe(self, depth: int, waits_ms=(), misses: int = 0,
                admitted: int = 0) -> int:
        """One admission cycle's sample: current queue depth, the
        cycle's queue waits, and its deadline misses vs admitted
        count. Re-evaluates the rung and returns it."""
        with self._lock:
            self._depth = int(depth)
            for w in waits_ms or ():
                self._waits.append(float(w))  # matlint: disable=ML013 the controller's own bounded sliding window — measurement IS this subsystem (the ML006 autotune precedent); its p95 reads through the shared sketch definition
            for _ in range(max(int(misses), 0)):
                self._outcomes.append(1)
            for _ in range(max(int(admitted), 0)):
                self._outcomes.append(0)
            self._samples += 1
            self._since_change += 1
            self._evaluate()
            return self._rung

    def _p95_wait(self) -> float:
        # the shared quantile definition (obs/metrics.percentile):
        # the threshold this signal is compared against is the same
        # number the SLO plane / endpoint / history report, within the
        # sketch's documented relative error
        est = percentile(self._waits, 0.95)
        return 0.0 if est is None else est

    def _miss_rate(self) -> float:
        if not self._outcomes:
            return 0.0
        return sum(self._outcomes) / len(self._outcomes)

    def _evaluate(self) -> None:
        """The hysteresis core: climb when ANY signal is hot, descend
        only when EVERY signal is cold — with ``dwell`` samples
        between moves. The separated enter/exit thresholds mean a
        signal between low and high HOLDS the current rung (neither
        climbs nor releases it) — that band is the hysteresis."""
        wait = self._p95_wait()
        miss = self._miss_rate()
        hot = (wait > self.wait_high or self._depth > self.depth_high
               or miss > self.miss_high)
        cold = (wait < self.wait_low and self._depth < self.depth_low
                and miss < self.miss_low)
        if self._since_change < self.dwell:
            return
        if hot and self._rung < MAX_RUNG:
            self._rung += 1
            self._since_change = 0
            self.entered += 1
            self.max_rung_seen = max(self.max_rung_seen, self._rung)
        elif cold and self._rung > 0:
            self._rung -= 1
            self._since_change = 0
            self.exited += 1

    # -- acting ------------------------------------------------------------

    def rung(self) -> int:
        with self._lock:
            return self._rung

    def snapshot(self) -> dict:
        """Obs-facing view (rides ``overload`` events)."""
        with self._lock:
            return {"rung": self._rung,
                    "rung_label": rung_label(self._rung),
                    "wait_p95_ms": round(self._p95_wait(), 3),
                    "queue_depth": self._depth,
                    "miss_rate": round(self._miss_rate(), 4),
                    "samples": self._samples,
                    "entered": self.entered,
                    "exited": self.exited,
                    "max_rung_seen": self.max_rung_seen}
