"""Plan-degradation ladder — what a RETRY is allowed to change.

A bare re-run clears genuinely transient faults (a flaky collective, a
momentary RESOURCE_EXHAUSTED), but the failure classes this engine has
actually hit in the field are config-sensitive: a measured autotune
winner that stopped being safe, a Pallas kernel that miscompiles on one
backend, a poisoned result-cache entry. So each retry attempt climbs
one rung of a CUMULATIVE ladder toward the most conservative plan the
engine has — every rung is semantics-preserving (same answer, slower),
which is what makes escalation safe to do blindly:

    rung 0  the stamped plan as compiled (no degradation)
    rung 1  drop measured autotune winners (cost model decides)
    rung 2  + force the safe `xla` strategy for every matmul
            (GSPMD picks its own decomposition — no hand collectives)
    rung 3  + disable Pallas kernels and SpGEMM dispatch (densify
            fallback; the XLA gather paths carry sparse matmuls),
            pin the sparse-kernel registry to its XLA generic entry
            (a forced specialized Pallas kernel must not survive the
            ladder), and force STAGED execution (fusion_enable off —
            a miscompiling fused region must not survive retry; the
            per-op path is the conservative anchor MV111's off-state
            contract guarantees is stamp-free)
    rung 4  + bypass the result cache for this attempt (a poisoned
            entry cannot answer the retry)

Rungs 1–3 act through the compile config (``apply_rung``), so the
degraded attempt recompiles under a ``degr:<rung>|``-prefixed plan key
— a degraded plan can never be served from (or inserted into) the
default-config cache slot, and the prefix idiom matches the axisw/prec
prefixes. The session stamps ``plan.meta["degrade"]`` and emits one
``degrade`` obs event per escalation so ``history --summary`` can roll
retry/degrade rates up next to the QPS numbers they tax.
"""

from __future__ import annotations

from typing import Tuple

#: Highest rung (also the result-cache bypass rung).
MAX_RUNG = 4

#: Rung at (and above) which the session bypasses the result cache.
RC_BYPASS_RUNG = 4

#: rung -> short label (plan.meta / obs events / docs).
RUNG_LABELS = {
    0: "none",
    1: "no-autotune",
    2: "xla-strategy",
    3: "no-kernels",
    4: "no-result-cache",
}


def rung_label(rung: int) -> str:
    return RUNG_LABELS.get(rung, f"rung-{rung}")


def rung_meta(rung: int) -> dict:
    """The rung's stamp record — one shape everywhere it rides
    (``plan.meta["degrade"]``, the ``degrade`` obs event, the answer
    ledger's lineage records)."""
    return {"rung": rung, "label": rung_label(rung)}


def apply_rung(config, rung: int):
    """The compile config of one degraded attempt — CUMULATIVE: rung N
    includes every restriction below it. Rung 0 returns the config
    object UNCHANGED (identity, not a copy — the bit-identity
    contract). Rung 4's result-cache bypass is the session's job (the
    cache is session state, not compile config); at the config level
    it equals rung 3."""
    if rung <= 0:
        return config
    kw = {"autotune": False}
    if rung >= 2:
        kw["strategy_override"] = "xla"
    if rung >= 3:
        kw["use_pallas"] = False
        kw["pallas_interpret"] = False
        kw["spgemm_density_threshold"] = 0.0
        # ALSO force the kernel registry to the XLA generic entry: a
        # base config carrying spgemm_kernel_override (a forced
        # specialized Pallas kernel — the soak/bench knob) would
        # otherwise survive every rung, so the very kernel the ladder
        # exists to escape kept being re-stamped on the degraded
        # attempt. Zeroing the threshold kills the expr-level
        # dispatch; the override pin covers direct ops-level callers
        # and makes the escape independent of admissibility gating.
        kw["spgemm_kernel_override"] = "xla_gather"
        # force staged execution: a base config running whole-plan
        # fusion would otherwise re-stamp the very fused region the
        # retry exists to escape (the kernel-override rationale, one
        # rung, same direction — toward the per-op path the engine
        # has always trusted)
        kw["fusion_enable"] = False
    return config.replace(**kw)


def key_prefix(rung: int) -> str:
    """Plan-cache key prefix for a degraded compile (the axisw/prec
    prefix idiom) — '' at rung 0 keeps the historical key format."""
    return "" if rung <= 0 else f"degr:{min(rung, MAX_RUNG)}|"


def next_rung(rung: int) -> Tuple[int, bool]:
    """(new rung, escalated?) — one step up the ladder, saturating."""
    if rung >= MAX_RUNG:
        return rung, False
    return rung + 1, True
