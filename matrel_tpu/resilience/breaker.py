"""Per-plan-class circuit breakers — fail fast when retrying stopped
helping (docs/OVERLOAD.md).

The retry ladder (resilience/retry.py + degrade.py) is the right
answer to a TRANSIENT fault; it is the wrong answer to a POISONED plan
class — a shape/kind whose every execution fails burns its full retry
budget (backoff sleeps included) on every query, and under load that
budget is stolen from the healthy classes queued behind it. The
breaker closes that hole: per plan class (the drift auditor's
``kind:shape-class`` key, so a poisoned 8k matmul class never shades
the healthy 512 class) it counts TERMINAL failures — failures that
already exhausted the retry budget — and past
``config.breaker_threshold`` consecutive ones it OPENS: further
queries of the class fail immediately with the typed
:class:`errors.CircuitOpen` carrying the half-open probe schedule.

State machine (the classic three states, transitions test-pinned)::

    closed ──(threshold consecutive terminal failures)──> open
    open   ──(cooldown_ms elapsed, next admit)──────────> half_open
    half_open admits `breaker_half_open_probes` probes:
        probe success ──> closed   (failure count reset)
        probe failure ──> open     (cooldown restarts)

Deadline expiries, admission sheds, cancellations and ``CircuitOpen``
itself never count as class failures (:func:`counts_as_failure`) — a
starved query says nothing about whether its PLAN is poisoned. A probe
whose outcome is such a non-counting error releases its probe slot
without a transition (``record(cls, None)``).

The OFF contract is structural: ``BreakerRegistry.from_config``
returns None for ``breaker_threshold == 0`` (the default) and no
breaker object is ever constructed (poisoned-init test, the
fault-injector precedent). ``clock`` is injectable so transition tests
are deterministic.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from matrel_tpu.resilience.errors import (AdmissionShed, CircuitOpen,
                                          DeadlineExceeded,
                                          DrainTimeout, PipelineClosed,
                                          QueryAborted)
from matrel_tpu.resilience.retry import now
from matrel_tpu.utils import lockdep

#: Failure types that say nothing about the PLAN CLASS: starvation,
#: backpressure and cancellation outcomes never trip a breaker.
_NON_CLASS_FAILURES = (DeadlineExceeded, AdmissionShed, QueryAborted,
                       PipelineClosed, DrainTimeout, CircuitOpen)

STATES = ("closed", "open", "half_open")


def counts_as_failure(exc: BaseException) -> bool:
    """True when a terminal failure should count against the plan
    class (everything except the starvation/backpressure taxonomy —
    injected faults DO count: they model exactly the poisoned-class
    failures the breaker exists for)."""
    return not isinstance(exc, _NON_CLASS_FAILURES)


def plan_class(expr) -> str:
    """The breaker's class key: root kind + the drift auditor's
    pow2 shape-class bucket (obs/drift.shape_class), so breaker state
    joins the same per-class vocabulary calibration rows use."""
    from matrel_tpu.obs.drift import shape_class
    try:
        dims = tuple(int(d) for d in (expr.shape or ()))
    except (TypeError, ValueError):
        dims = ()
    return f"{expr.kind}:{shape_class(dims)}"


class CircuitBreaker:
    """One plan class's breaker. NOT thread-safe on its own — the
    registry's lock covers every transition."""

    __slots__ = ("plan_class", "threshold", "cooldown_s", "probes",
                 "_clock", "state", "failures", "_open_until",
                 "_probes_out", "transitions")

    def __init__(self, plan_cls: str, threshold: int,
                 cooldown_ms: float, probes: int,
                 clock: Callable[[], float]):
        self.plan_class = plan_cls
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_ms) / 1e3
        self.probes = int(probes)
        self._clock = clock
        self.state = "closed"
        self.failures = 0          # consecutive terminal failures
        self._open_until = 0.0
        self._probes_out = 0
        self.transitions = {"open": 0, "half_open": 0, "close": 0}

    def admit(self) -> None:
        """Gate one query of this class: closed passes, open fails
        fast (typed, with the probe schedule), half-open passes up to
        the probe budget. An open breaker whose cooldown elapsed
        transitions to half-open HERE — the next query IS the probe."""
        if self.state == "closed":
            return
        t = self._clock()
        if self.state == "open":
            if t < self._open_until:
                raise CircuitOpen(self.plan_class,
                                  (self._open_until - t) * 1e3,
                                  self.probes)
            self.state = "half_open"
            self._probes_out = 0
            self.transitions["half_open"] += 1
        # half_open: admit up to the probe budget, fail the rest fast
        if self._probes_out < self.probes:
            self._probes_out += 1
            return
        raise CircuitOpen(self.plan_class, self.cooldown_s * 1e3,
                          self.probes)

    def record(self, ok: Optional[bool]) -> None:
        """One admitted query's terminal outcome. ``None`` = the
        outcome says nothing about the class (deadline/shed/abort):
        release the probe slot, no transition."""
        if ok is None:
            if self.state == "half_open" and self._probes_out > 0:
                self._probes_out -= 1
            return
        if ok:
            if self.state == "half_open":
                self.state = "closed"
                self.transitions["close"] += 1
                self._probes_out = 0
            self.failures = 0
            return
        if self.state == "half_open":
            self._trip()           # probe failure: cooldown restarts
            return
        self.failures += 1
        if self.state == "closed" and self.failures >= self.threshold:
            self._trip()

    def _trip(self) -> None:
        self.state = "open"
        self._open_until = self._clock() + self.cooldown_s
        self._probes_out = 0
        self.transitions["open"] += 1

    def snapshot(self) -> dict:
        return {"class": self.plan_class, "state": self.state,
                "failures": self.failures,
                "transitions": dict(self.transitions)}


class BreakerRegistry:
    """Thread-safe plan-class → breaker map (session-owned; the serve
    worker and the caller's thread share one view of class health).
    Breakers are created lazily on first admit, all in the closed
    state — an all-healthy session holds one dict and nothing else."""

    def __init__(self, threshold: int, cooldown_ms: float,
                 probes: int,
                 clock: Optional[Callable[[], float]] = None):
        self.threshold = int(threshold)
        self.cooldown_ms = float(cooldown_ms)
        self.probes = int(probes)
        self._clock = clock if clock is not None else now
        self._lock = lockdep.make_lock("resilience.breaker")
        self._by_class: Dict[str, CircuitBreaker] = {}

    @staticmethod
    def from_config(config, clock: Optional[Callable[[], float]] = None
                    ) -> Optional["BreakerRegistry"]:
        """None for the default config (breaker_threshold 0): the OFF
        path constructs nothing — the faults.check precedent."""
        if getattr(config, "breaker_threshold", 0) <= 0:
            return None
        return BreakerRegistry(config.breaker_threshold,
                               config.breaker_cooldown_ms,
                               config.breaker_half_open_probes,
                               clock=clock)

    plan_class = staticmethod(plan_class)

    def _get(self, plan_cls: str) -> CircuitBreaker:
        br = self._by_class.get(plan_cls)
        if br is None:
            br = self._by_class[plan_cls] = CircuitBreaker(
                plan_cls, self.threshold, self.cooldown_ms,
                self.probes, self._clock)
        return br

    def admit(self, plan_cls: str) -> None:
        with self._lock:
            self._get(plan_cls).admit()

    def record(self, plan_cls: str, ok: Optional[bool]) -> None:
        with self._lock:
            self._get(plan_cls).record(ok)

    def state(self, plan_cls: str) -> str:
        with self._lock:
            br = self._by_class.get(plan_cls)
            return br.state if br is not None else "closed"

    def snapshot(self) -> dict:
        """Obs-facing view: which classes are open/half-open now, plus
        CUMULATIVE transition counts (the overload event emitter turns
        these into per-cycle deltas)."""
        with self._lock:
            trans = {"open": 0, "half_open": 0, "close": 0}
            open_now, half_now = [], []
            for cls, br in self._by_class.items():
                for k in trans:
                    trans[k] += br.transitions[k]
                if br.state == "open":
                    open_now.append(cls)
                elif br.state == "half_open":
                    half_now.append(cls)
            return {"classes": len(self._by_class),
                    "open": sorted(open_now),
                    "half_open": sorted(half_now),
                    "transitions": trans}
