"""Typed error taxonomy for the serve-plane resilience layer.

The reference got fault tolerance for free from Spark's RDD lineage
recomputation (PAPER.md [P2]); the jax_graft rebuild dropped that
substrate, so recovery decisions must be made explicitly — and the
FIRST such decision is always "is this failure worth retrying?". This
module is the single authority for that classification:

- **transient** failures (device/runtime hiccups: RESOURCE_EXHAUSTED,
  collective timeouts, injected transients from the fault harness) are
  retry candidates — re-running the same work can succeed.
- **deterministic** failures (VerificationError, compile/shape/type
  errors, injected fatals) would fail identically on every attempt;
  retrying them burns the caller's deadline for nothing, so the retry
  policy re-raises them immediately.

Every resilience-surface error is TYPED (no bare RuntimeError strings):
callers catch `DeadlineExceeded`/`DrainTimeout`/`AdmissionShed`/
`PipelineClosed` by class, and the matlint ML007 rule exists precisely
so library code cannot quietly swallow-and-continue instead of raising
one of these.
"""

from __future__ import annotations

from typing import Optional


class ResilienceError(Exception):
    """Base for every typed error the resilience layer raises itself
    (injected faults, deadlines, sheds). External failures — XLA
    runtime errors, verification errors — keep their own types and are
    CLASSIFIED by :func:`classify` instead."""


class InjectedFault(ResilienceError):
    """A fault the seeded injection harness raised at an instrumented
    choke point (resilience/faults.py). ``transient`` drives the retry
    classification: transient injections model device hiccups and ARE
    retried; fatal ones model deterministic poison and are not."""

    def __init__(self, site: str, kind: str, call_index: int,
                 rule: Optional[str] = None):
        self.site = site
        self.kind = kind
        self.transient = kind == "transient"
        self.call_index = call_index
        self.rule = rule
        super().__init__(
            f"injected {kind} fault at site {site!r} "
            f"(call #{call_index}"
            + (f", rule {rule!r}" if rule else "") + ")")


class DeadlineExceeded(ResilienceError, TimeoutError):
    """A query's per-query deadline expired before it produced a
    result — raised at admission, between retry attempts, or when a
    backoff sleep would overshoot the deadline. Never retried."""

    def __init__(self, deadline_ms: float, elapsed_ms: float,
                 context: str = "query"):
        self.deadline_ms = deadline_ms
        self.elapsed_ms = elapsed_ms
        super().__init__(
            f"{context} deadline of {deadline_ms:.0f} ms exceeded "
            f"({elapsed_ms:.0f} ms elapsed)")


class QueryAborted(ResilienceError):
    """The caller cancelled (or the pipeline stopped) BETWEEN retry
    attempts — the sanctioned cancellation point: a running XLA
    dispatch cannot be interrupted, but the retry loop checks its
    abort hook before every new attempt."""


class DrainTimeout(ResilienceError, TimeoutError):
    """``session.serve_drain(timeout=...)`` gave up waiting on a wedged
    admission worker. The queue state is untouched — a later drain
    (or a healthy worker) can still finish the work."""

    def __init__(self, timeout_s: float, pending: int):
        self.timeout_s = timeout_s
        self.pending = pending
        super().__init__(
            f"serve drain timed out after {timeout_s:g} s "
            f"({pending} task(s) still unfinished)")


class PipelineClosed(ResilienceError):
    """``submit`` after ``close()``: the admission worker is stopped,
    so enqueueing would strand the future forever. Typed so callers
    can distinguish "session shut down" from a query failure."""


class AdmissionShed(ResilienceError):
    """Backpressure shed: the bounded admission queue is full (the
    global ``config.serve_queue_max`` bound, or — checked FIRST — this
    tenant's ``config.serve_tenant_queue_max`` quota), or the brownout
    controller's rung-3 tenant shed refused the submission. The
    submission is REFUSED rather than allowed to grow the queue
    without bound — the typed load-shedding contract protecting the
    queries already admitted. ``tenant`` names the shed tenant (None
    for the implicit single tenant); ``scope`` says which bound fired
    ("tenant" quota / "queue" global / "brownout" rung 3)."""

    def __init__(self, queue_max: int, tenant: Optional[str] = None,
                 scope: str = "queue"):
        self.queue_max = queue_max
        self.tenant = tenant
        self.scope = scope
        who = f" (tenant {tenant!r})" if tenant else ""
        if scope == "brownout":
            msg = (f"submission shed{who}: brownout rung 3 sheds "
                   f"lowest-weight tenants under sustained overload — "
                   f"retry later")
        elif scope == "tenant":
            msg = (f"per-tenant admission quota full{who} "
                   f"({queue_max} pending); submission shed — retry "
                   f"later or raise config.serve_tenant_queue_max")
        else:
            msg = (f"serve admission queue full ({queue_max} "
                   f"pending){who}; submission shed — retry later or "
                   f"raise config.serve_queue_max")
        super().__init__(msg)


class FleetSliceLost(ResilienceError):
    """A serving slice died (or was killed) with this query queued on
    it and the fleet could not re-admit it elsewhere — failover is
    off (``config.fleet_failover=False``), no surviving slice exists,
    or the query's leaves could not be rebound onto a survivor's
    catalog. The refusal is TYPED like every other fleet-plane
    failure: the caller knows the answer was never computed, never a
    silent drop (docs/FLEET.md failover semantics)."""

    def __init__(self, slice_id: int, detail: str = ""):
        self.slice_id = slice_id
        self.detail = detail
        super().__init__(
            f"serving slice {slice_id} lost"
            + (f": {detail}" if detail else "")
            + " — query could not be re-admitted onto a surviving "
              "slice")


class CircuitOpen(ResilienceError):
    """A plan class's circuit breaker is OPEN
    (resilience/breaker.py): the class kept failing after the retry
    budget, so further queries of that class fail FAST instead of
    burning compile/retry budget the healthy classes need. Carries
    the half-open probe schedule: ``retry_after_ms`` until the next
    probe window, ``probes`` allowed then. Never retried (retrying
    IS what the breaker exists to stop)."""

    def __init__(self, plan_class: str, retry_after_ms: float,
                 probes: int = 1):
        self.plan_class = plan_class
        self.retry_after_ms = retry_after_ms
        self.probes = probes
        super().__init__(
            f"circuit open for plan class {plan_class!r}: the class "
            f"kept failing past its retry budget — fails fast; "
            f"half-open probe window ({probes} probe(s)) in "
            f"{max(retry_after_ms, 0.0):.0f} ms")


class CheckpointCorruption(ResilienceError):
    """A checkpoint artifact failed its stored checksum (or its
    metadata does not parse): the restore refuses to hand back
    silently-corrupt arrays. The caller decides whether an older step
    is acceptable."""


class SnapshotCorruption(CheckpointCorruption):
    """A durable-state artifact (a disk-tier spill entry or a
    ``save_state()`` snapshot member — serve/spill.py,
    docs/DURABILITY.md) failed its stored sha1 or does not parse.
    Subclasses :class:`CheckpointCorruption` (same checksum
    discipline, same deterministic classification — never retried);
    the SESSION-level restore path catches it and cold-starts with a
    warning (a corrupt snapshot must never crash a restart), while a
    disk-tier THAW treats it as a cache miss: the entry drops, the
    query recomputes, the answer is never wrong."""

    def __init__(self, artifact: str, detail: str = ""):
        self.artifact = artifact
        self.detail = detail
        super().__init__(
            f"durable-state artifact {artifact!r} is corrupt"
            + (f": {detail}" if detail else "")
            + " — refusing to thaw silently-corrupt data")


#: Exception type names treated as transient runtime faults — the
#: device/runtime layer's own failure vocabulary (jax wraps XLA status
#: codes into these). Matched by NAME so the taxonomy works across jax
#: versions that move the classes between modules.
_TRANSIENT_TYPE_NAMES = frozenset({
    "XlaRuntimeError", "JaxRuntimeError", "InternalError",
})

#: Message substrings that mark an otherwise-ambiguous runtime error
#: transient: XLA status codes a retry can plausibly clear.
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED", "UNAVAILABLE",
    "ABORTED", "INTERNAL", "collective", "out of memory",
)


def is_transient(exc: BaseException) -> bool:
    """True when a retry of the SAME work can plausibly succeed."""
    if isinstance(exc, InjectedFault):
        return exc.transient
    if isinstance(exc, ResilienceError):
        # deadlines, sheds, closed pipelines, corruption: all
        # deterministic by construction — retrying cannot help
        return False
    name = type(exc).__name__
    if name == "VerificationError":
        # the static verifier's findings are properties of the PLAN —
        # identical on every attempt (the ladder may change the plan,
        # but that is an escalation decision, not a retry decision)
        return False
    if name in _TRANSIENT_TYPE_NAMES:
        return True
    if isinstance(exc, (MemoryError,)):
        return True
    if isinstance(exc, (ValueError, TypeError, KeyError,
                        NotImplementedError, AssertionError,
                        AttributeError, IndexError, ZeroDivisionError)):
        # compile/user/shape errors: deterministic
        return False
    msg = str(exc)
    return any(m in msg for m in _TRANSIENT_MARKERS)


def classify(exc: BaseException) -> str:
    """``"transient"`` or ``"deterministic"`` — the retry policy's one
    question. Unknown exception types classify DETERMINISTIC unless
    they carry a transient marker: silently retrying an unknown bug
    class would mask it (and burn deadline) instead of surfacing it."""
    return "transient" if is_transient(exc) else "deterministic"
