"""Retry policy: exponential backoff + jitter, per-query deadlines,
cancellation between attempts.

The policy answers three questions for the session's attempt loop
(session._compute_resilient / _run_many_resilient):

- **retry?** — only failures :func:`errors.classify` calls transient,
  and only while attempts remain (``config.retry_max_attempts``).
  VerificationError and compile/shape errors never retry;
  RESOURCE_EXHAUSTED-class runtime errors and injected transients do.
- **when?** — exponential backoff (``retry_backoff_ms`` ×
  ``retry_backoff_mult``^(attempt-1)) with symmetric jitter seeded per
  (config seed, per-policy nonce): concurrent queries draw DISTINCT
  jitter streams (they de-dogpile), while a pinned nonce reproduces a
  schedule exactly (tests).
- **until when?** — an absolute per-query deadline
  (``deadline_ms`` argument, else ``config.deadline_ms``). Expired
  BEFORE an attempt, or a backoff that would overshoot it, raises the
  typed :class:`errors.DeadlineExceeded`; a running XLA dispatch is
  never interrupted (deadlines are honored between attempts, the only
  place the host has control).

All wall-clock reads live HERE (the session/pipeline call these
helpers), which is why matlint's ML006 scope-exempts this module the
way it does parallel/autotune.py: deadline/backoff arithmetic IS this
subsystem's function, and its outcomes land in the event log as
``retry``/``degrade`` records rather than dying in local variables.
"""

from __future__ import annotations

import itertools
import random
import time
from typing import Callable, Optional

from matrel_tpu.resilience.errors import (DeadlineExceeded,
                                          QueryAborted, classify)

#: Per-policy nonce source: concurrent queries on one seed must NOT
#: share a jitter stream (identical streams would retry in lockstep —
#: the thundering herd jitter exists to break). A fixed nonce pins the
#: stream for tests.
_POLICY_SEQ = itertools.count()


def now() -> float:
    """The resilience layer's one clock (monotonic seconds)."""
    return time.monotonic()


def deadline_left(t_end: Optional[float]) -> Optional[float]:
    """Time left until an absolute :func:`now`-based deadline (None =
    unbounded) — the shared-budget form multi-step drains use so one
    documented timeout bounds the WHOLE call, not each sub-wait."""
    return None if t_end is None else max(t_end - now(), 0.0)


class Deadline:
    """An absolute per-query deadline. ``None``-budget deadlines never
    expire (the common case costs two attribute reads)."""

    __slots__ = ("budget_ms", "t0", "t_abs")

    def __init__(self, budget_ms: Optional[float]):
        self.budget_ms = budget_ms
        self.t0 = now()
        self.t_abs = (self.t0 + budget_ms / 1e3
                      if budget_ms is not None else None)

    def remaining_s(self) -> Optional[float]:
        if self.t_abs is None:
            return None
        return self.t_abs - now()

    def expired(self) -> bool:
        return self.t_abs is not None and now() >= self.t_abs

    def elapsed_ms(self) -> float:
        return (now() - self.t0) * 1e3

    def raise_if_expired(self, context: str = "query") -> None:
        if self.expired():
            raise DeadlineExceeded(self.budget_ms, self.elapsed_ms(),
                                   context=context)


class RetryPolicy:
    """One query's retry/backoff/deadline discipline. Built per
    resilient query (never on the default fast path) from the session
    config plus the per-call ``deadline_ms`` override."""

    def __init__(self, max_attempts: int, backoff_ms: float,
                 backoff_mult: float, jitter: float, seed: int,
                 deadline_ms: Optional[float] = None,
                 nonce: Optional[int] = None):
        self.max_attempts = int(max_attempts)
        self.backoff_ms = float(backoff_ms)
        self.backoff_mult = float(backoff_mult)
        self.jitter = float(jitter)
        self.deadline_ms = deadline_ms
        # seed ⊕ per-policy nonce: reproducible per (seed, nonce), but
        # two concurrent queries never draw the same jitter sequence
        if nonce is None:
            nonce = next(_POLICY_SEQ)
        self._rng = random.Random(f"retry|{seed}|{nonce}")

    @staticmethod
    def from_config(config, deadline_ms: Optional[float] = None
                    ) -> Optional["RetryPolicy"]:
        """The session's gate: None when the config (and call) ask for
        no resilience at all — the fast-path bit-identity contract."""
        dl = deadline_ms if deadline_ms is not None else (
            config.deadline_ms if config.deadline_ms > 0 else None)
        if (not config.fault_inject and config.retry_max_attempts == 0
                and dl is None):
            return None
        return RetryPolicy(config.retry_max_attempts,
                           config.retry_backoff_ms,
                           config.retry_backoff_mult,
                           config.retry_jitter,
                           config.fault_inject_seed,
                           deadline_ms=dl)

    def deadline(self) -> Deadline:
        return Deadline(self.deadline_ms)

    def should_retry(self, exc: BaseException, attempt: int) -> bool:
        """attempt is 0-based (the attempt that just FAILED)."""
        return (attempt < self.max_attempts
                and classify(exc) == "transient")

    def backoff_delay_s(self, attempt: int) -> float:
        """Delay before attempt N (1-based retry index): exponential
        base with symmetric seeded jitter, never negative."""
        base = (self.backoff_ms / 1e3
                * self.backoff_mult ** max(attempt - 1, 0))
        if self.jitter > 0.0:
            base *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(base, 0.0)

    def backoff_sleep(self, attempt: int, deadline: Deadline,
                      should_abort: Optional[Callable[[], bool]] = None
                      ) -> float:
        """Sleep the attempt's backoff, honoring deadline and
        cancellation: a sleep that would overshoot the deadline raises
        ``DeadlineExceeded`` NOW (don't burn the caller's budget
        sleeping toward certain failure), and an abort hook flipped
        while waiting raises ``QueryAborted`` — the between-attempts
        cancellation point. Returns the seconds actually slept."""
        delay = self.backoff_delay_s(attempt)
        rem = deadline.remaining_s()
        if rem is not None and delay >= rem:
            raise DeadlineExceeded(deadline.budget_ms,
                                   deadline.elapsed_ms(),
                                   context="retry backoff")
        if should_abort is not None and should_abort():
            raise QueryAborted(
                f"query aborted before retry attempt {attempt}")
        if delay > 0.0:
            time.sleep(delay)
        if should_abort is not None and should_abort():
            raise QueryAborted(
                f"query aborted before retry attempt {attempt}")
        return delay
