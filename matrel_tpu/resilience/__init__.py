"""Resilience layer — fault injection, retry/deadline policy, and the
plan-degradation ladder for the serve plane (docs/RESILIENCE.md).

The Spark-substrate fault tolerance the reference inherited (RDD
lineage recomputation) rebuilt as explicit mechanisms: a seeded
fault-injection harness at the engine's instrumented choke points
(:mod:`faults`), a typed transient/deterministic error taxonomy
(:mod:`errors`), retry with exponential backoff + per-query deadlines
(:mod:`retry`), and a semantics-preserving plan-degradation ladder
each retry climbs (:mod:`degrade`). The serve pipeline adds batch
bisection (poison-query isolation) and typed backpressure on top.
Round 13 adds the overload control plane's session halves
(docs/OVERLOAD.md): the adaptive brownout controller
(:mod:`brownout` — tier-downshift / stale-serve / tenant-shed rungs
with hysteresis) and per-plan-class circuit breakers
(:mod:`breaker` — typed ``CircuitOpen`` fail-fast for classes that
kept failing past the retry budget).

Default config: injects nothing, retries nothing, bit-identical plans
— every module here is inert until asked.
"""

from matrel_tpu.resilience.errors import (AdmissionShed,
                                          CheckpointCorruption,
                                          CircuitOpen,
                                          DeadlineExceeded,
                                          DrainTimeout, InjectedFault,
                                          PipelineClosed, QueryAborted,
                                          ResilienceError, classify,
                                          is_transient)
from matrel_tpu.resilience import (breaker, brownout, degrade, faults,
                                   retry)
from matrel_tpu.resilience.breaker import BreakerRegistry
from matrel_tpu.resilience.brownout import LoadController
from matrel_tpu.resilience.retry import Deadline, RetryPolicy

__all__ = [
    "AdmissionShed", "CheckpointCorruption", "CircuitOpen",
    "DeadlineExceeded", "DrainTimeout", "InjectedFault",
    "PipelineClosed", "QueryAborted", "ResilienceError", "classify",
    "is_transient", "Deadline", "RetryPolicy", "BreakerRegistry",
    "LoadController", "breaker", "brownout", "degrade", "faults",
    "retry",
]
