"""Resilience layer — fault injection, retry/deadline policy, and the
plan-degradation ladder for the serve plane (docs/RESILIENCE.md).

The Spark-substrate fault tolerance the reference inherited (RDD
lineage recomputation) rebuilt as explicit mechanisms: a seeded
fault-injection harness at the engine's instrumented choke points
(:mod:`faults`), a typed transient/deterministic error taxonomy
(:mod:`errors`), retry with exponential backoff + per-query deadlines
(:mod:`retry`), and a semantics-preserving plan-degradation ladder
each retry climbs (:mod:`degrade`). The serve pipeline adds batch
bisection (poison-query isolation) and typed backpressure on top.

Default config: injects nothing, retries nothing, bit-identical plans
— every module here is inert until asked.
"""

from matrel_tpu.resilience.errors import (AdmissionShed,
                                          CheckpointCorruption,
                                          DeadlineExceeded,
                                          DrainTimeout, InjectedFault,
                                          PipelineClosed, QueryAborted,
                                          ResilienceError, classify,
                                          is_transient)
from matrel_tpu.resilience import degrade, faults, retry
from matrel_tpu.resilience.retry import Deadline, RetryPolicy

__all__ = [
    "AdmissionShed", "CheckpointCorruption", "DeadlineExceeded",
    "DrainTimeout", "InjectedFault", "PipelineClosed", "QueryAborted",
    "ResilienceError", "classify", "is_transient",
    "Deadline", "RetryPolicy", "degrade", "faults", "retry",
]
