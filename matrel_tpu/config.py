"""Typed configuration for matrel_tpu.

The reference (purduedb/MatRel) configures itself through SparkConf key-value
pairs (``spark.matfast.*`` keys — block size, broadcast threshold; see
SURVEY.md §5 "Config / flag system"). The TPU-native equivalent is a small
frozen dataclass threaded through the session, overridable from environment
variables (``MATREL_*``) or a plain dict.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Mapping, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class MatrelConfig:
    """Global knobs for planning and execution.

    Attributes:
      block_size: logical tile edge used for block-level reasoning (sparsity
        masks, cost model granularity). The reference defaults to 1000x1000
        MLlib blocks; on TPU we default to 512, a multiple of the 128-lane
        MXU tiling.
      mesh_shape: (rows, cols) of the 2D device mesh. ``None`` → derive a
        near-square mesh from ``jax.device_count()``.
      mesh_axis_names: names of the two mesh axes.
      broadcast_threshold_bytes: operands smaller than this are planned as
        Broadcast-MM (replicated sharding) — the analogue of MatRel's
        broadcast-variable threshold.
      strategy_override: force one of {"bmm", "cpmm", "rmm", "auto"} for
        every matmul, bypassing the cost model. "auto" = cost-based.
      sparsity_threshold: density below which a matrix is considered sparse
        by the planner/cost model.
      spgemm_density_threshold: S×S matmuls whose ESTIMATED output block
        density (ir/stats.matmul_density at tile granularity) is below
        this dispatch the tile-intersection SpGEMM kernel
        (ops/spgemm.py) — neither operand is densified. At or above it
        the multiply falls back to the densify path (SpMM over a
        densified right operand), where the MXU's dense throughput wins.
        0 disables SpGEMM entirely.
      spgemm_kernel_override: force one REGISTERED SpGEMM kernel id
        (ops/kernel_registry.py — "xla_gather", "pallas_generic",
        "pallas_band", "pallas_cluster", "pallas_powerlaw") for every
        dispatching S×S multiply, bypassing the registry's structure
        classification, the autotune table and the cost model. The
        soak battery's forcing knob and the degradation ladder's
        rung-3 escape hatch (resilience/degrade.py forces
        "xla_gather" there so a miscompiling specialized Pallas
        kernel cannot survive the retry ladder). An inadmissible
        override (a Pallas id with Pallas unavailable) falls back to
        the legacy default; an UNKNOWN id raises at selection. ""
        (the default) disables forcing.
      comm_alpha_bytes: per-collective-STEP latency charge for the
        planner's comm model, in per-device byte-equivalents (the α of
        an α-β model; ~1 µs of v5e ICI ≈ 200 kB). Stepped strategies
        pay it per step — SUMMA's ring 2·(g−1) times, cpmm's
        reduce-scatter once, each nonzero reshard once — so small
        latency-bound multiplies (BASELINE row 2 class) stop ranking
        purely by bytes. 0 restores the β-only model. The chain DP's
        comm proxy stays β-only (its native mirror is
        equivalence-fuzzed against the alpha-free closed forms).
      default_dtype: dtype for constructors that don't specify one.
      matmul_precision: jax.lax precision for dot_general ("default",
        "high", "highest"). bfloat16 inputs + "highest" ≈ f32 accumulate.
      keep_input_dtype: cast matmul results back to the common input dtype
        (f32 accumulation on the MXU, bf16 storage in HBM — halves the
        write bandwidth of bf16 pipelines; XLA fuses the cast into the
        matmul epilogue).
      use_pallas: enable hand-written Pallas kernels where available.
      pallas_interpret: ALSO run the Pallas paths on non-TPU backends in
        interpret mode. Testing/debug only — interpret is slow and
        elides bf16 rounding on casts; never a fast path.
      chain_opt: enable the matrix-chain DP reorder.
      join_pair_cap_entries: refuse to MATERIALISE a join result larger
        than this many entries (the pair matrix of join_on_value; the
        merged output of join_on_rows / join_on_cols). Only aggregated
        VALUE-joins stream and are exempt — index joins always
        materialise their output and hit the cap even under an
        aggregate.
      join_bruteforce_max_pairs: cap on na*nb for aggregated value-joins
        with BLACK-BOX (callable) merge/predicate, which must enumerate
        pairs chunkwise. Structured predicates ("eq","lt",...) use the
        O(n log n) sort path and are exempt.
      join_chunk_entries: per-chunk entry budget for the black-box
        streaming enumeration (bounds the live tile).
      plan_cache_max_plans / plan_cache_max_bytes: LRU bounds on the
        session's compiled-plan cache. Each cached plan pins its
        hoisted sparse payloads (extra_args) in device memory; the
        byte budget counts those, the plan bound the rest. Least-
        recently-used plans evict first.
      rewrite_rules: enable the algebraic rewrite pass.
      donate_intermediates: donate chain intermediates to XLA where legal.
      autotune: let MEASURED strategy timings override the cost model's
        matmul pick (SURVEY.md §7 hard part: "detecting when XLA's
        choice beats the explicit paths"). On first sight of a shape
        class the admissible strategies are timed on-device once; the
        winner is cached in-process AND persisted to autotune_table_path
        so the measurement survives the session.
      autotune_table_path: JSON file for the persisted measurement
        table. Empty → ".matrel_autotune.json" in the working directory.
      autotune_max_dim: shapes with max(n,k,m) above this are never
        measured inline (measuring allocates two square operands of
        that size); the cost model keeps those.
      obs_level: query-lifecycle observability (matrel_tpu/obs/).
        "off" (default — the bench config: zero event emission, zero
        extra device syncs on the query path), "on" (one JSONL event
        record per session query run + metrics registry updates; event
        assembly happens outside jitted code), "analyze" (additionally
        per-op wall-clock on every explain — equivalent to passing
        ``analyze=True`` to ``session.explain``).
      obs_event_log: JSONL event-log path (the Spark event-log
        analogue). Empty → ".matrel_events.jsonl" in the working
        directory. Read it back with ``python -m matrel_tpu history``.
      obs_metrics_port: in-process live metrics endpoint
        (matrel_tpu/obs/export.py; docs/OBSERVABILITY.md tier 3) — a
        stdlib-only background HTTP server on 127.0.0.1 serving
        ``/metrics`` (Prometheus text format) and ``/json`` (a JSON
        snapshot of the metrics registry's sketches, SLO states,
        brownout rung, breaker states, result-cache/IVM counters and
        drift flags — what ``python -m matrel_tpu top`` polls). 0
        (the default) starts NOTHING: zero exporter threads, zero
        endpoint objects (test-enforced, the flight-recorder
        structural-off precedent).
      slo_targets: declarative per-tenant service-level objectives
        (matrel_tpu/obs/slo.py; docs/OBSERVABILITY.md tier 3) —
        ``"gold:p95_ms=50,avail=0.999;bronze:avail=0.99"``. Each
        objective is tracked with multi-window burn-rate alerting
        (Google-SRE style: the fast window catches an incident while
        it burns, the slow window confirms it is sustained; see
        slo_fast_window_s / slo_slow_window_s / slo_burn_threshold);
        alert TRANSITIONS emit an ``alert`` event and land in the
        flight-recorder ring regardless of ``obs_level``. Latency
        objectives (``p50_ms``/``p90_ms``/``p95_ms``/``p99_ms``)
        count a served query against its budget when it resolves
        slower than the target; ``avail`` counts sheds, deadline
        misses and terminal errors. The pseudo-tenant ``ivm`` is fed
        by ``register_delta`` patch latency. "" (the default)
        constructs NO monitor objects and the query path is
        bit-identical (test-enforced). Validated at construction.
      slo_fast_window_s / slo_slow_window_s: the two burn-rate
        windows (seconds; fast < slow, validated). Defaults 60 s /
        1800 s — the 1 m / 30 m pairing; the traffic harness shrinks
        them to fit its phases.
      slo_burn_threshold: burn-rate multiple (error-budget
        consumption rate vs the sustainable rate 1.0) at which an
        objective FIRES — both windows must exceed it. Default 14.4
        (the Google SRE fast-page number: 2% of a 30-day budget in
        an hour).
      slo_burn_exit: the alert CLEARS when the fast window's burn
        falls below this (< slo_burn_threshold, validated — the
        separation is the hysteresis, the brownout-threshold
        discipline). Default 1.0: clear only once the budget stops
        shrinking.
      obs_flight_recorder: capacity of the in-memory flight-recorder
        ring (obs/trace.py) — the last N span/event records, kept
        INDEPENDENTLY of ``obs_level`` (an always-cheap deque append;
        no I/O, no event assembly) and dumped to a JSON artifact on
        VerificationError / compile failure / serve-batch failure or
        an explicit ``session.dump_flight_recorder()``, so a field
        failure leaves a post-mortem trail instead of one error
        string. 0 (the default) disables the recorder entirely — with
        ``obs_level="off"`` the query path then creates no span
        objects at all (the bench contract, test-enforced).
      obs_flight_recorder_path: dump-artifact path for the flight
        recorder. Empty → ".matrel_flight.json" in the working
        directory.
      drift_table_path: JSON file for the cost-model drift auditor's
        persisted calibration table (obs/drift.py — per-(strategy,
        shape-class, backend) measured-vs-estimated ratios,
        maintained by ``history --drift``). Empty →
        ".matrel_drift.json" next to the autotune table's default.
      verify_plans: static plan verification (matrel_tpu/analysis/ —
        the pre-execution invariant checker). "off" (default: zero
        verifier work on the compile path), "warn" (run every pass
        after planning, log diagnostics, never fail the query), or
        "error" (raise analysis.VerificationError on any error-severity
        diagnostic BEFORE anything traces or runs on hardware — the
        array-redistribution-checker discipline of arXiv:2112.01075).
        ``session.verify(expr)`` and ``explain()`` run the passes
        regardless of this gate; it only controls the compile path.
      hbm_budget_bytes: per-device HBM budget the planner's
        admissibility gate and the verifier's feasibility pass check
        strategy working sets against (operand shards × replication
        factor + accumulator — VERDICT r5 Weak #3/Next #6). Default is
        a v5e chip's 16 GiB; 0 disables the gate (divisibility-only
        admissibility, the pre-round-6 behaviour). The xla fallback is
        never gated — GSPMD chooses its own decomposition.
      result_cache_max_bytes: byte budget for the session's cross-query
        MATERIALIZED-RESULT cache (matrel_tpu/serve/result_cache.py —
        the MatFast persist/RDD-cache analogue): executed query results
        are kept on device keyed by the CANONICAL STRUCTURAL plan key
        (session._plan_key — never id()-keyed, the ML005 hazard class),
        so a repeated query answers without compiling or executing and
        a query CONTAINING a previously-executed subplan enters
        planning with that subtree replaced by an already-laid-out
        leaf (infer_layout/comm_cost credit the reuse). LRU eviction
        past the budget; a catalog rebind invalidates every dependent
        entry. 0 (the default) disables the cache entirely and is
        bit-identical to the uncached behaviour — plans, results and
        the plan-snapshot corpus unchanged.
      result_cache_max_entries: entry-count bound on the result cache
        (LRU, like plan_cache_max_plans). The byte budget counts each
        entry's RESULT array, but an entry's pins also keep the
        query's INPUT matrices alive (the plan cache's pinning
        contract) — tiny results over huge ad-hoc inputs could
        otherwise retain unbounded device memory while staying "within
        budget". The count bound caps that retention.
      serve_max_batch: micro-batched admission width — the most queries
        ``session.submit``'s admission loop coalesces into one
        MultiPlan (one fusion/CSE domain, shared leaf transfers).
        ``session.run_many`` batches whatever it is handed; this knob
        bounds only the async pipeline's coalescing.
      serve_max_inflight: bound on dispatched-but-unsynced batches the
        async pipeline keeps in flight. JAX's async dispatch lets the
        host optimize/verify/trace query N+1 while the device executes
        query N; past this depth the admission loop blocks on the
        oldest batch so host planning never runs unboundedly ahead of
        the device.
      precision_sla: the session-default per-query accuracy SLA for
        precision-tiered matmul execution (parallel/planner.py tier
        chooser; docs/PRECISION.md). "default" (the default) disables
        tiering entirely — no tier is ever stamped and every lowering
        is bit-identical to the pre-tier engine (plan snapshots
        unchanged). The named SLAs: "exact" (no accuracy loss vs
        today's f32/HIGHEST path; integer-shaped workloads route to
        the exact int32 MXU path), "high" (~f32 accuracy allowed —
        the bf16 k-pass split-summation tier, arXiv:2112.09017),
        "fast" (single-pass bf16 MXU rate; documented bf16 error
        bound). An explicit dtype ("float32", "bfloat16", "bf16x3",
        "int32", "int8") pins the tier directly. Per-query override:
        ``session.run(expr, precision=...)`` (also run_many/submit,
        and SQL's ``... PRECISION 'fast'`` clause).
      precision_enable_bf16: allow the bf16 tiers (bf16x1/bf16x3) in
        the SLA chooser. Off → "high"/"fast" degrade to f32. Explicit
        dtype SLAs bypass the gate (an explicit ask is an ask).
      precision_enable_int: same gate for the integer-exact tiers
        (int32/int8).
      fault_inject: fault-injection spec for the resilience layer
        (matrel_tpu/resilience/faults.py; docs/RESILIENCE.md) —
        semicolon-separated ``site:kind[:p=F|:n=K][:max=M]`` rules
        raising typed ``InjectedFault`` at the engine's instrumented
        choke points (compile, lower, strategy, execute, rc_probe,
        serve_admit, checkpoint) on a DETERMINISTIC seeded schedule.
        "" (the default) injects nothing and constructs nothing
        (test-enforced). Validated at construction.
      fault_inject_seed: seed of the injection schedule's per-rule
        random streams (and the retry policy's backoff jitter) — same
        spec + same seed = bit-identical fault schedule.
      retry_max_attempts: how many RETRIES a failed query gets past
        its first attempt (resilience/retry.py). Only failures the
        typed taxonomy classifies transient (RESOURCE_EXHAUSTED-class
        runtime errors, injected transients) retry — VerificationError
        and compile/shape errors never do. Each retry climbs one rung
        of the plan-degradation ladder (resilience/degrade.py). 0
        (the default) retries nothing.
      retry_backoff_ms / retry_backoff_mult / retry_jitter:
        exponential-backoff schedule between attempts — base delay,
        per-attempt multiplier, and symmetric jitter fraction (seeded
        by fault_inject_seed, so schedules are reproducible).
      deadline_ms: session-default per-query deadline. A query that
        has not produced a result when it expires raises the typed
        ``DeadlineExceeded`` — checked at admission and BETWEEN retry
        attempts (a running XLA dispatch is never interrupted). 0 (the
        default) = no deadline; per-call override via
        ``session.run(expr, deadline_ms=...)`` (also run_many/submit).
      serve_queue_max: bound on the async pipeline's admission queue.
        A ``submit`` against a full queue raises the typed
        ``AdmissionShed`` instead of growing the queue without bound —
        load shedding that protects the queries already admitted. 0
        (the default) keeps the historical unbounded queue. Expired-
        deadline entries are PURGED (resolved typed) at the shed
        decision point before the bound is enforced, so a queue full
        of dead entries never sheds live traffic (docs/OVERLOAD.md).
      serve_tenant_weights: per-tenant weighted-fair-queuing weights
        for the admission worker (serve/admission.py;
        docs/OVERLOAD.md) — ``"gold:4,silver:2,bronze:1"``. With
        weights set, each tenant gets its own admission queue and the
        worker pops entries in stride-scheduled proportion to weight
        (the YARN/Spark fair-scheduler analogue of PAPER.md [P1]'s
        multi-tenant operating point), so one chatty tenant cannot
        monopolize a MultiPlan or starve the stream. "" (the default)
        keeps ONE implicit tenant and is bit-identical to the
        historical FIFO admission order. Tenants not named here get
        weight 1.0. Validated at construction.
      serve_tenant_queue_max: per-tenant admission-queue bound. A
        tenant at its cap sheds typed ``AdmissionShed(tenant=...)``
        BEFORE the global ``serve_queue_max`` bound is consulted —
        per-tenant quota protects every OTHER tenant's share of the
        queue. 0 (the default) = no per-tenant cap.
      brownout_enable: the adaptive brownout controller
        (resilience/brownout.py; docs/OVERLOAD.md). Off (the default)
        constructs NO controller object and the serve plane is
        bit-identical. On: the admission worker samples queue depth,
        queue-wait p95 and deadline-miss rate over a sliding window
        and climbs a cumulative rung ladder under sustained pressure —
        rung 1 downshifts default-SLA queries to the "fast" precision
        tier (results stay SLA-key-isolated), rung 2 serves
        result-cache entries a rebind marked STALE to queries that
        declare a ``staleness_ms`` tolerance, rung 3 sheds
        lowest-weight tenants (typed) — descending with hysteresis
        when every signal falls below the (separated) exit thresholds.
      brownout_window: sliding-window length (admission-cycle samples)
        the controller's statistics cover.
      brownout_dwell: minimum samples between rung moves — the
        hysteresis dwell that stops the ladder oscillating on one
        noisy sample.
      brownout_wait_high_ms / brownout_wait_low_ms: queue-wait p95
        enter/exit thresholds. Enter pressure when p95 exceeds high;
        the wait signal reads calm only below low (low < high,
        validated — the separation IS the hysteresis).
      brownout_depth_high / brownout_depth_low: queue-depth enter/exit
        thresholds (same contract).
      brownout_miss_high / brownout_miss_low: deadline-miss-rate
        enter/exit thresholds over the window (fractions in [0, 1],
        low < high).
      breaker_threshold: per-plan-class circuit breakers
        (resilience/breaker.py; docs/OVERLOAD.md). 0 (the default)
        constructs NO breaker objects. > 0: consecutive TERMINAL
        failures of one plan class (the drift auditor's
        kind + pow2-shape-class key) — failures that already exhausted
        the retry budget — open that class's breaker, and further
        queries of the class fail FAST with the typed ``CircuitOpen``
        (carrying the half-open probe schedule) instead of burning
        compile/retry budget the healthy classes need. After
        ``breaker_cooldown_ms`` the breaker goes half-open and admits
        ``breaker_half_open_probes`` probe queries: a probe success
        closes it, a probe failure re-opens it for another cooldown.
      breaker_cooldown_ms: open→half-open cooldown (must be > 0).
      breaker_half_open_probes: concurrent probe budget in half-open
        (>= 1).
      reshard_peak_budget_bytes: peak per-device bytes a layout change
        (reshard) may have live during any one step of its lowering
        (matrel_tpu/parallel/reshard.py; docs/RESHARD.md — the
        arXiv:2112.01075 bounded-redistribution discipline). 0 (the
        default) keeps the legacy single-constraint path bit-
        identically — XLA emits whatever one-shot collective it likes,
        no ReshardPlan object is ever constructed (test-enforced).
        > 0: cross-axis layout changes lower as a verified step
        sequence (per-axis all_to_all / staged gathers) whose peak
        footprint fits the budget, the planner prices reshards from
        the plan's real per-axis bytes, and MV109 proves every stamped
        reshard's peak fits — the knob that lets near-HBM-limit
        operands move at all instead of being refused by MV105.
      fusion_enable: whole-plan program fusion (matrel_tpu/ir/fusion.py;
        docs/FUSION.md). Off (the default) is bit-identical to the
        historical per-op path: no region is ever segmented, no
        FusedRegion object constructed (test-enforced), plan snapshots
        unchanged. On: the planner stamps fusable regions (elementwise
        chains, reductions, scalar epilogues absorbed into their
        producer matmul/SpGEMM) after ``annotate_strategies``; the
        executor lowers each region under ONE annotate() dispatch
        frame with the epilogue pushed into the producing kernel's
        epilogue slot, the region-program seam can emit one jitted
        program per region, matmul_decisions records the boundary
        (est saved dispatches / HBM bytes), and MV111 verifies every
        stamp. The degradation ladder's rung 3 forces this off so a
        miscompiling fused region cannot survive retry.
      cse_enable: admission-time multi-query optimization
        (matrel_tpu/serve/mqo.py; docs/SERVING.md). Off (the default)
        is bit-identical to the historical serve plane: no hoist or
        template object is ever constructed (test-enforced), every
        cache key keeps its historical format, plan snapshots
        unchanged. On: (1) cross-query CSE — a MultiPlan batch
        (``run_many`` / the admission worker's coalesced batches)
        detects interior subplans shared across its queries via the
        structural span keys, computes each exactly once, and feeds
        every consumer the result as an already-laid-out leaf (the
        result-cache interior-hit crediting, so ``infer_layout`` /
        ``comm_cost`` price the reuse); hoists happen only at fused-
        region boundaries (non-fusable kinds), so per-query epilogue
        chains keep fusing instead of being split. (2) plan-template
        reuse — queries structurally identical modulo dense-leaf
        bindings hit a template cache keyed on the leaf-ABSTRACTED
        structural key and rebind their leaves into the already-
        compiled program, paying zero optimize/trace (the IVM
        ``ivm_role`` rebinding seam generalized to serve traffic);
        the ``degr:``/``axisw:``/``prec:`` key-prefix idiom keeps
        degrade/topology/SLA isolation intact. MV116 verifies the
        stamps; shared results flow into the result cache with
        transitive dep sets so rebind invalidation cascades.
      cse_min_uses: occurrence threshold for hoisting one shared
        interior (>= 2: a "shared" node used once is just the query
        itself). Occurrences are counted across the whole batch,
        within-query duplicates included.
      cse_template_max: entry bound on the plan-template cache (LRU
        past it — a template is an affinity hint over the plan cache,
        never a correctness surface; eviction only costs a
        recompile).
      delta_patch_mode: how ``session.register_delta`` maintains
        dependent result-cache entries (serve/ivm.py; docs/IVM.md).
        "auto" (the default): patch when a delta rule applies AND the
        flop estimate (or a measured autotune ``ivm|`` winner, which
        overrides it) says the patch beats recompute — everything
        else falls back to the historical transitive kill. "force":
        patch every eligible entry regardless of pricing (test /
        bench forcing knob). "off": register_delta rebinds and kills
        like a plain register() — the escape hatch. Inert until
        register_delta is ever called: the default path constructs no
        delta objects and every cache key keeps its historical format
        (test-enforced bit-identity).
      delta_rank_max: largest factored rank a delta is worth keeping
        in thin ``U·Vᵀ`` form (ir/delta.py): a c-edge COO batch is
        exactly a rank-c update, and above this bound the thin
        products stop being thin — the delta then enters patches as
        its dense/sparse materialization (or prices out entirely).
      axis_cost_weights: per-mesh-axis relative inverse-bandwidth
        weights for the planner's comm model (core/mesh.MeshTopology):
        a collective leg over axis i is billed bytes × weights[i], so
        on a hierarchical ICI/DCN mesh the slow cross-slice axis is
        priced as expensive as it really is. The default (1.0, 1.0) is
        behaviour-preserving (every cost bit-identical to the flat
        model) AND doubles as "auto": when JAX exposes slice
        boundaries (device.slice_index on multi-slice TPU), the
        DCN-crossing axes are auto-weighted DCN_AXIS_WEIGHT. Setting
        anything ≠ (1.0, 1.0) is the calibration hook — it overrides
        detection (docs/TOPOLOGY.md).
      fleet_slices: multi-slice serving fleet (serve/fleet.py;
        docs/FLEET.md). 0 (the default) = off: no fleet objects are
        ever constructed and ``submit`` runs the historical
        single-controller pipeline bit-identically (test-enforced).
        >= 1 partitions the session mesh into that many serving
        slices (real ``device.slice_index`` boundaries when they
        match the count, contiguous virtual sub-meshes otherwise;
        degenerate shared-device slices when the mesh is too small),
        each with its own admission queue, worker, brownout state and
        slice-local result cache; ``session.submit`` routes each
        query through the fleet's placement policy.
      fleet_span_margin: placement bias toward slice-local execution:
        a query SPANS the whole mesh (one program over every slice,
        DCN-crossing collectives included) only when the byte model's
        estimated span cost is strictly below ``margin`` x the best
        slice-local estimate. 1.0 = neutral; < 1.0 demands a real
        win before paying DCN traffic (docs/FLEET.md placement
        derivation).
      fleet_directory_max: entry bound on the fleet's global
        structural-key directory (plan key -> owning slice). LRU past
        it — the directory is an affinity HINT, never a correctness
        surface, so eviction only costs a recompute.
      fleet_replicate_hits: remote-demand threshold for hot-entry
        replication: once a non-owning slice has taken this many
        directory hits on one key, the entry is replicated into it —
        priced and staged through the reshard planner under
        ``reshard_peak_budget_bytes`` (docs/FLEET.md migration
        pricing). 0 disables replication (directory hits still
        answer from the owning slice's cache).
      fleet_failover: dead/wedged-slice failover — a killed slice's
        queued entries re-admit onto surviving slices (deadlines and
        tenant attribution intact, refusals typed). Off = queued
        entries on a killed slice fail typed instead.
      fleet_placement_calibration: let the placement cost model read
        the drift auditor's calibration table
        (``drift_table_path``): per-(shape-class, backend, tier)
        measured ms/GFLOP + ms/MiB coefficients are consulted AHEAD
        of the analytic closed forms, provenance-stamped "measured"
        like autotune winners; classes with no calibration row fall
        back to the analytic model (docs/FLEET.md).
      obs_provenance: answer provenance ledger capacity (obs tier 4,
        docs/OBSERVABILITY.md). 0 (default) = off: zero ledger
        objects constructed, no lineage capture anywhere on the
        serve path (the brownout/breaker structural-zero contract).
        N > 0 keeps the last N per-answer lineage records in memory
        (``session.why()`` / ``python -m matrel_tpu why``) and emits
        each as a ``provenance`` event when the event log is on.
      obs_event_log_max_bytes: rotate the JSONL event log to a single
        ``.1`` sibling once it reaches this size. 0 (default) = never
        rotate (the historical unbounded-append behaviour,
        byte-identical). Readers stitch ``<log>.1`` + ``<log>``
        transparently, so rotation bounds the DISK while
        ``tail_bytes`` keeps bounding each read.
      lockdep_enable: runtime lock-order sanitizer
        (matrel_tpu/utils/lockdep.py; docs/CONCURRENCY.md). Off (the
        default) is bit-identical to the uninstrumented engine: the
        sanctioned lock constructors return raw threading primitives
        and ZERO lockdep objects are constructed (poisoned-init
        test-enforced, plan snapshots unchanged). On: every
        seam-constructed lock records per-thread acquisition stacks
        into a global lock-ORDER graph; inversions and
        held-across-dispatch violations are recorded as ``lockdep``
        obs events (and into the flight ring), rolled up by
        ``history --summary`` and fatal to ``--check``.
      lockdep_raise: escalate lockdep diagnostics from record-only to
        an immediate typed raise (LockOrderInversion /
        HeldAcrossDispatch) at the acquisition site — the race-drill
        and fixture-test mode. Requires ``lockdep_enable``.
      coeff_planner_enable: let the MAIN planner consult the drift
        auditor's calibrated ms/GFLOP + ms/MiB coefficients
        (parallel/coeffs.py — the seam; docs/COST_MODEL.md): strategy
        ranking and the chain DP's step cost price by measured ratios
        where every candidate has a warm row, falling back to the
        analytic closed forms otherwise; decisions are stamped
        ``cost: "measured"|"analytic"`` and plan-cache keys gain the
        ``coeffv:<epoch>|`` prefix so plans compiled under different
        coefficients never share a slot. Off (the default) is
        bit-identical: zero new objects, zero new key prefixes, zero
        new event fields (plan snapshots unchanged, test-enforced).
      coeff_min_samples: calibration rows below this sample count are
        treated as cold for planner ranking — a one-off measurement
        must not flip a strategy choice (the drift auditor's
        noise-band argument).
      coeff_replan_enable: close the loop (docs/COST_MODEL.md): a
        serve-side controller (serve/replan.py) watches the query
        event stream, and a firing DRIFT rank-order flag triggers a
        coefficient re-calibration + background re-planning of the
        affected cached plans under the new epoch — old plans keep
        serving, in-flight queries never block (the ``coeffv:``
        prefix). Requires ``coeff_planner_enable``. Off = zero
        controller objects (replan._CONSTRUCTED stays 0).
      coeff_replan_interval: queries between the controller's drift
        checks — the re-plan loop's cadence.
      coeff_replan_cooldown: checks a just-re-planned population sits
        out before its flags can fire again (hysteresis, the brownout
        dwell discipline): fresh samples under the NEW plans must
        accumulate before the loop may act on that population again,
        so a re-plan can never oscillate on its own stale evidence.
      spill_enable: the result cache's HBM → host RAM → disk spill
        hierarchy (serve/spill.py; docs/DURABILITY.md — the [P2]
        RDD-persist amortization rebuilt as explicit priced tiers).
        Off (the default) constructs ZERO spill objects and is
        bit-identical to the single-tier cache: LRU eviction drops
        entries exactly as before, plan snapshots unchanged
        (poisoned-init test-enforced, the brownout/breaker
        structural-zero contract). On: entries the byte budget evicts
        DEMOTE to a host-RAM numpy tier instead of dropping (and age
        host → disk under the host budget, as sha1-verified artifacts
        in ``state_dir`` — requires a result cache to spill FROM, so
        ``result_cache_max_bytes`` must be > 0, validated); a lower-
        tier hit THAWS the entry back to HBM paying only the priced
        transfer legs (parallel/coeffs.py ``spill:<leg>`` rows when
        the drift loop has calibrated them, analytic per-leg ms/MiB
        otherwise) — it never recomputes, and interior-substitution
        probes see the thawed entry as a laid-out leaf exactly like
        an HBM hit. Requires ``spill_enable`` for ``save_state()`` to
        persist result-cache entries (catalog/tables persist without
        it).
      spill_host_max_bytes: byte budget of the host-RAM tier. Past
        it, least-recently-used host entries age to disk when the
        disk tier exists (``state_dir`` set) AND the entry's hit
        count shows expected reuse (>= spill_disk_hits) — cold
        never-hit entries drop instead of paying disk IO on no
        evidence (docs/DURABILITY.md demotion policy).
      spill_disk_hits: minimum lifetime hit count an entry needs for
        the host tier to age it to DISK rather than drop it (the
        expected-reuse gate). 0 demotes everything the host tier
        evicts.
      state_dir: durable state directory — the disk spill tier
        (``<state_dir>/spill/`` sha1-verified artifacts) and the
        ``MatrelSession.save_state()``/``restore()`` snapshot root
        (``<state_dir>/state/`` checkpoint-format step dirs holding
        the catalog, the result-cache index with disk-tier entries by
        reference, the fleet directory, MQO template keys and the
        autotune/drift tables — docs/DURABILITY.md snapshot format).
        "" (the default) constructs nothing and disables the disk
        tier (host-only spill when spill_enable is on);
        ``save_state()``/``restore()`` then require an explicit
        directory argument.
    """

    block_size: int = 512
    mesh_shape: Optional[Tuple[int, int]] = None
    mesh_axis_names: Tuple[str, str] = ("x", "y")
    broadcast_threshold_bytes: int = 64 * 1024 * 1024
    strategy_override: str = "auto"
    sparsity_threshold: float = 0.05
    spgemm_density_threshold: float = 0.25
    spgemm_kernel_override: str = ""
    comm_alpha_bytes: float = 200_000.0
    default_dtype: str = "float32"
    matmul_precision: str = "highest"
    keep_input_dtype: bool = True
    use_pallas: bool = True
    pallas_interpret: bool = False
    chain_opt: bool = True
    rewrite_rules: bool = True
    donate_intermediates: bool = True
    join_pair_cap_entries: int = 1 << 26
    join_bruteforce_max_pairs: int = 1 << 28
    join_chunk_entries: int = 1 << 22
    plan_cache_max_plans: int = 64
    plan_cache_max_bytes: int = 4 << 30
    autotune: bool = False
    autotune_table_path: str = ""
    autotune_max_dim: int = 8192
    result_cache_max_bytes: int = 0
    result_cache_max_entries: int = 256
    serve_max_batch: int = 8
    serve_max_inflight: int = 2
    obs_level: str = "off"
    obs_event_log: str = ""
    obs_metrics_port: int = 0
    slo_targets: str = ""
    slo_fast_window_s: float = 60.0
    slo_slow_window_s: float = 1800.0
    slo_burn_threshold: float = 14.4
    slo_burn_exit: float = 1.0
    obs_flight_recorder: int = 0
    obs_flight_recorder_path: str = ""
    drift_table_path: str = ""
    verify_plans: str = "off"
    hbm_budget_bytes: int = 16 << 30
    reshard_peak_budget_bytes: int = 0
    axis_cost_weights: Tuple[float, float] = (1.0, 1.0)
    fault_inject: str = ""
    fault_inject_seed: int = 0
    retry_max_attempts: int = 0
    retry_backoff_ms: float = 25.0
    retry_backoff_mult: float = 2.0
    retry_jitter: float = 0.5
    deadline_ms: float = 0.0
    serve_queue_max: int = 0
    serve_tenant_weights: str = ""
    serve_tenant_queue_max: int = 0
    brownout_enable: bool = False
    brownout_window: int = 32
    brownout_dwell: int = 8
    brownout_wait_high_ms: float = 200.0
    brownout_wait_low_ms: float = 50.0
    brownout_depth_high: int = 64
    brownout_depth_low: int = 8
    brownout_miss_high: float = 0.25
    brownout_miss_low: float = 0.05
    breaker_threshold: int = 0
    breaker_cooldown_ms: float = 1000.0
    breaker_half_open_probes: int = 1
    precision_sla: str = "default"
    precision_enable_bf16: bool = True
    precision_enable_int: bool = True
    fusion_enable: bool = False
    cse_enable: bool = False
    cse_min_uses: int = 2
    cse_template_max: int = 64
    delta_patch_mode: str = "auto"
    delta_rank_max: int = 512
    fleet_slices: int = 0
    fleet_span_margin: float = 1.0
    fleet_directory_max: int = 4096
    fleet_replicate_hits: int = 3
    fleet_failover: bool = True
    fleet_placement_calibration: bool = True
    obs_provenance: int = 0
    obs_event_log_max_bytes: int = 0
    lockdep_enable: bool = False
    lockdep_raise: bool = False
    coeff_planner_enable: bool = False
    coeff_min_samples: int = 3
    coeff_replan_enable: bool = False
    coeff_replan_interval: int = 32
    coeff_replan_cooldown: int = 2
    spill_enable: bool = False
    spill_host_max_bytes: int = 2 << 30
    spill_disk_hits: int = 1
    state_dir: str = ""

    def __post_init__(self):
        # enablement is "anything != off", so an unvalidated typo/case
        # variant ("OFF", "of") would silently switch the production
        # query path onto the instrumented one — reject it at
        # construction (case-insensitively normalised)
        level = self.obs_level.lower()
        if level not in ("off", "on", "analyze"):
            raise ValueError(
                f"obs_level must be one of 'off'/'on'/'analyze', "
                f"got {self.obs_level!r}")
        object.__setattr__(self, "obs_level", level)
        # same typo hazard, opposite failure mode: a misspelled "eror"
        # would silently DISABLE the verifier's raise and ship the very
        # infeasible plan it exists to block
        vp = self.verify_plans.lower()
        if vp not in ("off", "warn", "error"):
            raise ValueError(
                f"verify_plans must be one of 'off'/'warn'/'error', "
                f"got {self.verify_plans!r}")
        object.__setattr__(self, "verify_plans", vp)
        # live telemetry plane (docs/OBSERVABILITY.md tier 3): an
        # out-of-range port would surface only as an OSError at the
        # first session construction; a malformed SLO spec must fail
        # HERE (the fault_inject/tenant-weights precedent — silently
        # monitoring nothing while the operator believes objectives
        # are in force is the worst failure an SLO knob can have);
        # un-separated burn thresholds would flap alerts on every
        # sample (the brownout hysteresis argument)
        if not (0 <= self.obs_metrics_port <= 65535):
            raise ValueError(
                f"obs_metrics_port must be a port in [0, 65535] "
                f"(0 disables the endpoint), "
                f"got {self.obs_metrics_port!r}")
        if self.slo_targets:
            parse_slo_targets(self.slo_targets)
        if not (0.0 < self.slo_fast_window_s < self.slo_slow_window_s):
            raise ValueError(
                "slo windows need 0 < slo_fast_window_s < "
                "slo_slow_window_s, got "
                f"({self.slo_fast_window_s!r}, "
                f"{self.slo_slow_window_s!r})")
        if not (0.0 < self.slo_burn_exit < self.slo_burn_threshold):
            raise ValueError(
                "slo burn thresholds need 0 < slo_burn_exit < "
                "slo_burn_threshold (the hysteresis separation), got "
                f"({self.slo_burn_exit!r}, "
                f"{self.slo_burn_threshold!r})")
        # a negative ring capacity would silently build a deque with
        # maxlen=None — an UNBOUNDED recorder, the opposite of the
        # always-cheap contract — reject it at construction
        if self.obs_flight_recorder < 0:
            raise ValueError(
                f"obs_flight_recorder must be >= 0 (ring capacity; "
                f"0 disables), got {self.obs_flight_recorder!r}")
        # a zero/negative admission width or in-flight bound would
        # deadlock the serve pipeline's coalescing loop (it always
        # admits at least the query it popped) — reject at construction
        if self.result_cache_max_entries < 1:
            raise ValueError(
                f"result_cache_max_entries must be >= 1, "
                f"got {self.result_cache_max_entries!r}")
        if self.serve_max_batch < 1:
            raise ValueError(
                f"serve_max_batch must be >= 1, got {self.serve_max_batch!r}")
        if self.serve_max_inflight < 1:
            raise ValueError(
                f"serve_max_inflight must be >= 1, "
                f"got {self.serve_max_inflight!r}")
        # a zero/negative weight would make an axis FREE (or negative)
        # and silently route every collective onto it; a 3-tuple would
        # desync from the 2D grid — reject both at construction. The
        # normalised float tuple is what every cache key embeds.
        w = tuple(self.axis_cost_weights)
        if len(w) != 2 or not all(
                isinstance(v, (int, float)) and v > 0.0 for v in w):
            raise ValueError(
                "axis_cost_weights must be two positive numbers "
                f"(per mesh axis), got {self.axis_cost_weights!r}")
        object.__setattr__(self, "axis_cost_weights",
                           (float(w[0]), float(w[1])))
        # resilience knobs: a malformed fault spec must fail HERE, not
        # silently inject nothing while a chaos test believes it is
        # injecting (the obs_level typo precedent); negative retry /
        # backoff / deadline values have no meaning and would corrupt
        # the backoff arithmetic silently
        if self.fault_inject:
            from matrel_tpu.resilience.faults import parse_spec
            parse_spec(self.fault_inject)
        if self.retry_max_attempts < 0:
            raise ValueError(
                f"retry_max_attempts must be >= 0, "
                f"got {self.retry_max_attempts!r}")
        if self.retry_backoff_ms < 0 or self.retry_backoff_mult < 1.0 \
                or not (0.0 <= self.retry_jitter <= 1.0):
            raise ValueError(
                "retry backoff needs retry_backoff_ms >= 0, "
                "retry_backoff_mult >= 1, retry_jitter in [0, 1]; got "
                f"({self.retry_backoff_ms!r}, "
                f"{self.retry_backoff_mult!r}, {self.retry_jitter!r})")
        if self.deadline_ms < 0:
            raise ValueError(
                f"deadline_ms must be >= 0 (0 disables), "
                f"got {self.deadline_ms!r}")
        # a negative reshard budget has no meaning — and would silently
        # read as "unbounded" in every fits() check while the caller
        # believes a cap is in force (the obs_level typo precedent)
        if self.reshard_peak_budget_bytes < 0:
            raise ValueError(
                f"reshard_peak_budget_bytes must be >= 0 (0 = legacy "
                f"single-shot reshards), "
                f"got {self.reshard_peak_budget_bytes!r}")
        if self.serve_queue_max < 0:
            raise ValueError(
                f"serve_queue_max must be >= 0 (0 = unbounded), "
                f"got {self.serve_queue_max!r}")
        # overload control plane (docs/OVERLOAD.md): a malformed tenant
        # weight spec must fail at construction (the fault_inject
        # precedent) — silently weighting nothing while the operator
        # believes fairness is in force is the worst failure mode a
        # fairness knob can have
        if self.serve_tenant_weights:
            parse_tenant_weights(self.serve_tenant_weights)
        if self.serve_tenant_queue_max < 0:
            raise ValueError(
                f"serve_tenant_queue_max must be >= 0 (0 = no "
                f"per-tenant cap), got {self.serve_tenant_queue_max!r}")
        # brownout hysteresis NEEDS separated thresholds: low == high
        # would flap the rung on every sample and low > high would
        # deadlock the ladder (enter and exit both impossible)
        if self.brownout_window < 1 or self.brownout_dwell < 1:
            raise ValueError(
                "brownout_window and brownout_dwell must be >= 1; got "
                f"({self.brownout_window!r}, {self.brownout_dwell!r})")
        for name, lo, hi in (
                ("wait", self.brownout_wait_low_ms,
                 self.brownout_wait_high_ms),
                ("depth", self.brownout_depth_low,
                 self.brownout_depth_high),
                ("miss", self.brownout_miss_low,
                 self.brownout_miss_high)):
            if not (0 <= lo < hi):
                raise ValueError(
                    f"brownout_{name} thresholds need 0 <= low < high "
                    f"(the hysteresis separation), got ({lo!r}, {hi!r})")
        if not (0.0 <= self.brownout_miss_high <= 1.0):
            raise ValueError(
                f"brownout_miss_high must be a rate in [0, 1], "
                f"got {self.brownout_miss_high!r}")
        if self.breaker_threshold < 0:
            raise ValueError(
                f"breaker_threshold must be >= 0 (0 disables "
                f"breakers), got {self.breaker_threshold!r}")
        if self.breaker_cooldown_ms <= 0 \
                or self.breaker_half_open_probes < 1:
            raise ValueError(
                "breakers need breaker_cooldown_ms > 0 and "
                "breaker_half_open_probes >= 1; got "
                f"({self.breaker_cooldown_ms!r}, "
                f"{self.breaker_half_open_probes!r})")
        # the SLA vocabulary gates NUMERICS, not just performance: an
        # unvalidated typo ("fasst") would silently run the default
        # path while the caller believes a bound was requested — or
        # worse, a misspelled "exact" would tier DOWN. Reject at
        # construction (case-insensitive, "bf16" normalised).
        object.__setattr__(self, "precision_sla",
                           normalize_sla(self.precision_sla))
        # IVM knobs (docs/IVM.md): a typo'd mode ("of", "forced")
        # would silently run "auto" while the operator believes the
        # ladder's escape hatch is in force — the obs_level precedent;
        # a non-positive rank bound would disable the factored form
        # while reading as "unlimited"
        mode = self.delta_patch_mode.lower()
        if mode not in ("auto", "force", "off"):
            raise ValueError(
                f"delta_patch_mode must be one of 'auto'/'force'/"
                f"'off', got {self.delta_patch_mode!r}")
        object.__setattr__(self, "delta_patch_mode", mode)
        if self.delta_rank_max < 1:
            raise ValueError(
                f"delta_rank_max must be >= 1, "
                f"got {self.delta_rank_max!r}")
        # multi-query-optimization knobs (docs/SERVING.md): a
        # min_uses of 1 would hoist EVERY interior of every batch —
        # pure overhead read as "more sharing"; a zero template bound
        # would evict each template at insert and turn steady-state
        # rebind traffic into a permanent recompile while the
        # operator believes templates are in force
        if self.cse_min_uses < 2:
            raise ValueError(
                f"cse_min_uses must be >= 2 (an interior used once "
                f"is not shared), got {self.cse_min_uses!r}")
        if self.cse_template_max < 1:
            raise ValueError(
                f"cse_template_max must be >= 1, "
                f"got {self.cse_template_max!r}")
        # same hazard for the kernel forcing knob: a typo'd override
        # would surface only as a mid-traffic ValueError on the first
        # dispatching query — or never, while the operator believes
        # the knob is in force. Validated against the vocabulary tuple
        # (the PRECISION_SLAS precedent — config cannot import the
        # registry, which needs jax; test_kernel_registry pins the
        # tuple == the registry's actual ids).
        if (self.spgemm_kernel_override
                and self.spgemm_kernel_override not in
                SPGEMM_KERNEL_IDS):
            raise ValueError(
                f"spgemm_kernel_override must be one of "
                f"{SPGEMM_KERNEL_IDS} (or '' to disable), got "
                f"{self.spgemm_kernel_override!r}")
        # fleet knobs (docs/FLEET.md): a negative slice count would
        # silently read as "off" while the operator believes a fleet
        # is serving (the obs_level typo precedent); a non-positive
        # span margin makes spanning unreachable while reading as
        # "neutral"; a zero directory bound would evict every
        # ownership record at insert and turn the hit-anywhere
        # protocol into a permanent miss
        if self.fleet_slices < 0:
            raise ValueError(
                f"fleet_slices must be >= 0 (0 disables the fleet), "
                f"got {self.fleet_slices!r}")
        if self.fleet_span_margin <= 0:
            raise ValueError(
                f"fleet_span_margin must be > 0, "
                f"got {self.fleet_span_margin!r}")
        if self.fleet_directory_max < 1:
            raise ValueError(
                f"fleet_directory_max must be >= 1, "
                f"got {self.fleet_directory_max!r}")
        if self.fleet_replicate_hits < 0:
            raise ValueError(
                f"fleet_replicate_hits must be >= 0 (0 disables "
                f"hot-entry replication), "
                f"got {self.fleet_replicate_hits!r}")
        # obs tier 4 (docs/OBSERVABILITY.md): a negative ledger
        # capacity would silently read as "off" while the operator
        # believes lineage is being captured (the fleet_slices
        # precedent); a negative rotation threshold likewise reads as
        # "never rotate" while the operator believes the disk is
        # bounded
        if self.obs_provenance < 0:
            raise ValueError(
                f"obs_provenance must be >= 0 (0 disables the "
                f"provenance ledger), got {self.obs_provenance!r}")
        if self.obs_event_log_max_bytes < 0:
            raise ValueError(
                f"obs_event_log_max_bytes must be >= 0 (0 disables "
                f"event-log rotation), "
                f"got {self.obs_event_log_max_bytes!r}")
        # concurrency sanitizer (docs/CONCURRENCY.md): lockdep_raise
        # without lockdep_enable would silently raise NOTHING while
        # the drill operator believes violations are fatal (the
        # obs_level typo precedent — a sanitizer that monitors
        # nothing while believed armed is its worst failure mode)
        if self.lockdep_raise and not self.lockdep_enable:
            raise ValueError(
                "lockdep_raise requires lockdep_enable (a raise mode "
                "with no instrumentation in force would silently "
                "check nothing)")
        # cost-model loop knobs (docs/COST_MODEL.md): a re-plan
        # controller with no coefficient-consulting planner would
        # re-calibrate a table nothing reads (the lockdep_raise
        # dependency precedent); degenerate cadence/sample bounds
        # would spin the check loop or let one noisy sample flip
        # strategy rankings
        if self.coeff_min_samples < 1:
            raise ValueError(
                f"coeff_min_samples must be >= 1, "
                f"got {self.coeff_min_samples!r}")
        if self.coeff_replan_enable and not self.coeff_planner_enable:
            raise ValueError(
                "coeff_replan_enable requires coeff_planner_enable "
                "(re-planning recalibrates coefficients the planner "
                "would otherwise never consult)")
        if self.coeff_replan_interval < 1:
            raise ValueError(
                f"coeff_replan_interval must be >= 1, "
                f"got {self.coeff_replan_interval!r}")
        if self.coeff_replan_cooldown < 0:
            raise ValueError(
                f"coeff_replan_cooldown must be >= 0, "
                f"got {self.coeff_replan_cooldown!r}")
        # durability knobs (docs/DURABILITY.md): a spill hierarchy
        # under a DISABLED result cache would demote nothing while the
        # operator believes the working set extends past HBM (the
        # lockdep_raise dependency precedent); a non-positive host
        # budget would bounce every demotion straight to disk/drop
        # while reading as "host tier in force"
        if self.spill_enable and self.result_cache_max_bytes <= 0:
            raise ValueError(
                "spill_enable requires result_cache_max_bytes > 0 "
                "(the spill hierarchy extends the result cache — with "
                "the cache off there is nothing to demote)")
        if self.spill_host_max_bytes < 1:
            raise ValueError(
                f"spill_host_max_bytes must be >= 1, "
                f"got {self.spill_host_max_bytes!r}")
        if self.spill_disk_hits < 0:
            raise ValueError(
                f"spill_disk_hits must be >= 0 (0 ages everything "
                f"the host tier evicts), got {self.spill_disk_hits!r}")

    def replace(self, **kw: Any) -> "MatrelConfig":
        return dataclasses.replace(self, **kw)

    @staticmethod
    def from_env(base: Optional["MatrelConfig"] = None) -> "MatrelConfig":
        """Build a config from MATREL_* environment variables."""
        cfg = base or MatrelConfig()
        overrides: dict = {}
        for f in dataclasses.fields(MatrelConfig):
            env_key = "MATREL_" + f.name.upper()
            if env_key not in os.environ:
                continue
            raw = os.environ[env_key]
            if f.type in ("int", int):
                overrides[f.name] = int(raw)
            elif f.type in ("float", float):
                overrides[f.name] = float(raw)
            elif f.type in ("bool", bool):
                overrides[f.name] = raw.lower() in ("1", "true", "yes", "on")
            elif f.name == "mesh_shape":
                parts = [int(p) for p in raw.replace("x", ",").split(",") if p]
                overrides[f.name] = tuple(parts)
            elif f.name == "axis_cost_weights":
                parts = [float(p)
                         for p in raw.replace("x", ",").split(",") if p]
                overrides[f.name] = tuple(parts)
            else:
                overrides[f.name] = raw
        return cfg.replace(**overrides) if overrides else cfg

    @staticmethod
    def from_dict(d: Mapping[str, Any], base: Optional["MatrelConfig"] = None) -> "MatrelConfig":
        cfg = base or MatrelConfig()
        valid = {f.name for f in dataclasses.fields(MatrelConfig)}
        unknown = set(d) - valid
        if unknown:
            raise KeyError(f"unknown MatrelConfig keys: {sorted(unknown)}")
        return cfg.replace(**dict(d))


#: The per-query accuracy-SLA vocabulary (docs/PRECISION.md): named
#: levels plus the explicit-dtype spellings that pin one tier.
PRECISION_SLAS = ("default", "exact", "high", "fast",
                  "float32", "bfloat16", "bf16x3", "int32", "int8")

#: The SpGEMM kernel-registry vocabulary (docs/SPARSE_KERNELS.md) —
#: what ``spgemm_kernel_override`` validates against at construction.
#: Config cannot import ops/kernel_registry (it needs jax), so the
#: tuple lives here and test_kernel_registry pins it equal to the
#: registry's actual ids; registering a new kernel extends BOTH.
SPGEMM_KERNEL_IDS = ("xla_gather", "pallas_generic", "pallas_band",
                     "pallas_cluster", "pallas_powerlaw")


def normalize_sla(sla) -> str:
    """Validate + normalise one precision-SLA value (config field or
    per-query ``precision=`` argument). None → "default"."""
    if sla is None:
        return "default"
    s = str(sla).lower().strip()
    if s in ("bf16", "bfloat16"):
        s = "bfloat16"
    if s == "f32":
        s = "float32"
    if s not in PRECISION_SLAS:
        raise ValueError(
            f"precision SLA must be one of {PRECISION_SLAS} (or 'bf16'/"
            f"'f32' aliases), got {sla!r}")
    return s


def parse_tenant_weights(spec) -> dict:
    """Validate + parse a ``serve_tenant_weights`` spec
    (``"gold:4,silver:2,bronze:1"``) into ``{tenant: float weight}``.
    Empty/None → {} (one implicit tenant, the historical FIFO).
    Raises ``ValueError`` on empty names, duplicate names, or
    non-positive weights — config.__post_init__ calls this so a typo
    fails at construction (the fault_inject precedent)."""
    if not spec:
        return {}
    out: dict = {}
    for part in (p.strip() for p in str(spec).split(",")):
        if not part:
            continue
        name, sep, w = part.partition(":")
        name = name.strip()
        if not sep or not name:
            raise ValueError(
                f"serve_tenant_weights entry {part!r} must be "
                f"'name:weight'")
        if name in out:
            raise ValueError(
                f"serve_tenant_weights names tenant {name!r} twice")
        try:
            weight = float(w)
        except ValueError:
            raise ValueError(
                f"serve_tenant_weights weight {w!r} (tenant "
                f"{name!r}) is not a number") from None
        if not weight > 0.0:
            raise ValueError(
                f"serve_tenant_weights weight for {name!r} must be "
                f"> 0, got {weight!r}")
        out[name] = weight
    if not out:
        raise ValueError(
            f"serve_tenant_weights {spec!r} names no tenants")
    return out


#: The SLO objective vocabulary (docs/OBSERVABILITY.md tier 3):
#: latency targets at named quantiles (milliseconds) plus availability.
SLO_OBJECTIVES = ("avail", "p50_ms", "p90_ms", "p95_ms", "p99_ms")


def parse_slo_targets(spec) -> dict:
    """Validate + parse an ``slo_targets`` spec
    (``"gold:p95_ms=50,avail=0.999;bronze:avail=0.99"``) into
    ``{tenant: {objective: float target}}``. Empty/None → {} (no
    objectives, no monitors). Raises ``ValueError`` on unknown
    objectives, duplicate tenants, availability targets outside (0, 1)
    or non-positive latency targets — config.__post_init__ calls this
    so a typo fails at construction (the tenant-weights precedent)."""
    if not spec:
        return {}
    out: dict = {}
    for tpart in (p.strip() for p in str(spec).split(";")):
        if not tpart:
            continue
        tenant, sep, objs = tpart.partition(":")
        tenant = tenant.strip()
        if not sep or not tenant:
            raise ValueError(
                f"slo_targets entry {tpart!r} must be "
                f"'tenant:objective=target[,objective=target...]'")
        if tenant in out:
            raise ValueError(
                f"slo_targets names tenant {tenant!r} twice")
        targets: dict = {}
        for opart in (p.strip() for p in objs.split(",")):
            if not opart:
                continue
            obj, osep, val = opart.partition("=")
            obj = obj.strip()
            if not osep or obj not in SLO_OBJECTIVES:
                raise ValueError(
                    f"slo_targets objective {opart!r} (tenant "
                    f"{tenant!r}) must be one of {SLO_OBJECTIVES} "
                    f"with '=target'")
            if obj in targets:
                raise ValueError(
                    f"slo_targets names objective {obj!r} twice for "
                    f"tenant {tenant!r}")
            try:
                target = float(val)
            except ValueError:
                raise ValueError(
                    f"slo_targets target {val!r} (tenant {tenant!r}, "
                    f"objective {obj!r}) is not a number") from None
            if obj == "avail":
                if not (0.0 < target < 1.0):
                    raise ValueError(
                        f"slo_targets avail target for {tenant!r} "
                        f"must be in (0, 1), got {target!r}")
            elif not target > 0.0:
                raise ValueError(
                    f"slo_targets latency target {obj} for "
                    f"{tenant!r} must be > 0 ms, got {target!r}")
            targets[obj] = target
        if not targets:
            raise ValueError(
                f"slo_targets entry {tpart!r} declares no objectives")
        out[tenant] = targets
    if not out:
        raise ValueError(f"slo_targets {spec!r} names no tenants")
    return out


_default_config = MatrelConfig.from_env()


def default_config() -> MatrelConfig:
    return _default_config


def set_default_config(cfg: MatrelConfig) -> None:
    global _default_config
    _default_config = cfg


def pallas_enabled(config: "MatrelConfig" = None) -> bool:
    """True when hand-written Pallas kernels should run: the config
    toggle is on AND the backend is a real TPU (CPU keeps the XLA
    paths), OR pallas_interpret forces them in interpret mode for
    testing. The single gate shared by every compact-executor call
    site; pair with ``pallas_interpret_mode`` for the interpret flag."""
    import jax
    cfg = config or default_config()
    if not cfg.use_pallas:
        return False
    return (jax.default_backend() in ("tpu", "axon")
            or cfg.pallas_interpret)


def pallas_interpret_mode(config: "MatrelConfig" = None) -> bool:
    """interpret= flag for pallas_call at the shared call sites: True
    only when the compact paths were forced onto a non-TPU backend."""
    import jax
    cfg = config or default_config()
    return cfg.pallas_interpret and jax.default_backend() not in (
        "tpu", "axon")


def resolve_interpret(interpret, config: "MatrelConfig" = None) -> bool:
    """The single None→config resolver for per-call ``interpret``
    parameters across every Pallas call site (ops/pallas_spmv.py,
    ops/spmm.py, workloads/pagerank.py): an explicit True/False wins;
    None defers to pallas_interpret_mode."""
    if interpret is not None:
        return bool(interpret)
    return pallas_interpret_mode(config)
