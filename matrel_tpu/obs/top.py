"""``python -m matrel_tpu top`` — the live operator console
(docs/OBSERVABILITY.md tier 3).

The serve plane has made second-to-second decisions (brownout rungs,
typed sheds, breaker trips, IVM patches) since rounds 12–14 with
nobody able to WATCH: every surface so far replays a log after the
fact. ``top`` renders the live view — per-tenant QPS, latency
p50/p95/p99, goodput, shed rate, SLO burn rates and active alerts,
plus the plane-wide rung / breaker / cache state — from either:

- ``--url`` (or ``--port``): poll a session's live metrics endpoint
  (``config.obs_metrics_port``; obs/export.py) — the operator tier;
- ``--log``: tail an event log and reconstruct the same view from the
  most recent ``overload``/``alert`` records — works post-hoc or
  against a host whose endpoint is off.

``--once`` renders a single frame and exits (scripting / tests);
otherwise it refreshes every ``--interval`` seconds until interrupted.
Plain ANSI, no curses — it must work over the dumbest SSH pipe a
production incident offers.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import List, Optional

from matrel_tpu.obs.events import read_events, resolve_path
from matrel_tpu.obs.metrics import percentile


def snapshot_from_url(url: str, timeout: float = 3.0) -> dict:
    """GET the endpoint's JSON snapshot. ``url`` is the exporter base
    (http://127.0.0.1:<port>); /json is appended."""
    base = url.rstrip("/")
    with urllib.request.urlopen(base + "/json",
                                timeout=timeout) as resp:
        snap = json.loads(resp.read().decode())
    snap["_source"] = base
    return snap


#: Log-mode trailing window (seconds of log time) the per-tenant
#: rates are computed over.
LOG_WINDOW_S = 60.0

#: Log-mode read bound: each refresh frame parses at most this much
#: of the file's tail — a live console over a multi-GB host log must
#: cost O(tail) per frame, not O(history). Alert last-states are
#: scoped to the same window (a console is a live view; `history`
#: owns the full replay).
LOG_TAIL_BYTES = 16 << 20


def snapshot_from_log(path: Optional[str] = None,
                      window_s: float = LOG_WINDOW_S,
                      tail_bytes: int = LOG_TAIL_BYTES) -> dict:
    """Reconstruct an endpoint-shaped snapshot from an event log's
    tail: the LAST ``overload`` record carries the instantaneous
    control-plane state (rung, depths, breaker set, and — when the
    SLO plane is active — its full snapshot), the trailing window of
    ``overload`` records gives per-tenant rates, and ``alert``
    records give last-known alert states. Timestamps are the LOG's
    own — a replay renders what the host saw, not what the reader's
    clock says."""
    p = resolve_path(path)
    events = read_events(p, tail_bytes=tail_bytes)
    ov = [e for e in events if e.get("kind") == "overload"]
    snap: dict = {"_source": p, "ts": (events[-1].get("ts")
                                       if events else None),
                  "slo": None, "brownout": None, "breakers": None,
                  "serve": None, "metrics": None,
                  "plan_cache": None, "result_cache": None,
                  "ivm": None, "drift": None}
    if ov:
        last = ov[-1]
        snap["slo"] = last.get("slo")
        # every overload record carries rung/rung_label at top level;
        # the nested "brownout" controller snapshot only exists when a
        # LoadController is configured — fall back so the header shows
        # the rung either way
        snap["brownout"] = (last.get("brownout")
                            or {"rung": last.get("rung"),
                                "rung_label": last.get("rung_label")})
        snap["breakers"] = last.get("breakers")
        snap["serve"] = {"queue_depth": last.get("queue_depth"),
                         "tenant_depths": last.get("tenant_depths"),
                         "deadline_misses": None, "inflight": None}
        # trailing-window per-tenant rates from the overload stream
        t_hi = last.get("ts") or 0.0
        recent = [e for e in ov
                  if (e.get("ts") or 0.0) >= t_hi - window_s]
        span = max(t_hi - (recent[0].get("ts") or t_hi), 1e-3) \
            if recent else 1e-3
        tenants: dict = {}
        for e in recent:
            for t, n in (e.get("admitted") or {}).items():
                row = tenants.setdefault(
                    t, {"admitted": 0, "sheds": 0, "waits": []})
                row["admitted"] += int(n)
            for t, n in (e.get("sheds") or {}).items():
                row = tenants.setdefault(
                    t, {"admitted": 0, "sheds": 0, "waits": []})
                row["sheds"] += int(n)
            for t, ws in (e.get("tenant_waits_ms") or {}).items():
                row = tenants.setdefault(
                    t, {"admitted": 0, "sheds": 0, "waits": []})
                row["waits"].extend(
                    float(w) for w in ws
                    if isinstance(w, (int, float)))
        snap["_log_tenants"] = {
            t: {"qps": round(row["admitted"] / span, 2),
                "shed_rate": (round(row["sheds"]
                                    / (row["admitted"] + row["sheds"]),
                                    4)
                              if row["admitted"] + row["sheds"]
                              else None),
                "p50": percentile(row["waits"], 0.50),
                "p95": percentile(row["waits"], 0.95),
                "p99": percentile(row["waits"], 0.99)}
            for t, row in tenants.items()}
        snap["_log_window_s"] = round(span, 1)
    # alert states: last transition wins per (tenant, objective)
    states: dict = {}
    for e in events:
        if e.get("kind") == "alert":
            states[(str(e.get("tenant")),
                    str(e.get("objective")))] = e
    snap["_log_alerts"] = [
        {"tenant": t, "objective": o, "state": e.get("state"),
         "burn_fast": e.get("burn_fast")}
        for (t, o), e in sorted(states.items())]
    # reconcile: alert transitions AFTER the last overload record are
    # newer truth than the snapshot it carried (the worker stops
    # emitting overload cycles once the queue drains, but the idle
    # tick keeps emitting alert clears) — without this the header
    # could show FIRING for an alert the log already cleared
    slo = snap.get("slo")
    if slo and states and ov:
        t_snap = ov[-1].get("ts") or 0.0
        for (t, o), e in states.items():
            st = ((slo.get("tenants") or {}).get(t, {})
                  .get("objectives") or {}).get(o)
            if st is not None and (e.get("ts") or 0.0) >= t_snap:
                st["state"] = ("firing" if e.get("state") == "firing"
                               else "ok")
                if e.get("burn_fast") is not None:
                    st["burn_fast"] = e["burn_fast"]
        slo["alerts_active"] = sum(
            1 for d in (slo.get("tenants") or {}).values()
            for st in (d.get("objectives") or {}).values()
            if st.get("state") == "firing")
    return snap


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------


def _f(v, nd=1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _tenant_rows(snap: dict) -> List[dict]:
    """Normalise either source into the table's rows. The SLO plane's
    snapshot is the richest source (sketch latencies, burns, states);
    the log fallback carries queue-wait percentiles instead."""
    rows: List[dict] = []
    slo = snap.get("slo")
    if slo and slo.get("tenants"):
        for t, d in sorted(slo["tenants"].items()):
            lat = d.get("latency_ms") or {}
            qps = d.get("qps")
            shed = d.get("shed_rate")
            burns = [(o, st) for o, st in sorted(
                (d.get("objectives") or {}).items())]
            worst = max((st.get("burn_fast") or 0.0)
                        for _, st in burns) if burns else None
            firing = [o for o, st in burns
                      if st.get("state") == "firing"]
            rows.append({
                "tenant": t, "qps": qps,
                "goodput": (round(qps * (1.0 - shed), 2)
                            if qps is not None and shed is not None
                            else qps),
                "p50": lat.get("p50"), "p95": lat.get("p95"),
                "p99": lat.get("p99"),
                "shed_rate": shed, "burn_fast": worst,
                "slo": (" ".join(f"FIRING:{o}" for o in firing)
                        or "ok")})
        return rows
    for t, d in sorted((snap.get("_log_tenants") or {}).items()):
        firing = [a["objective"]
                  for a in snap.get("_log_alerts") or ()
                  if a["tenant"] == t and a["state"] == "firing"]
        rows.append({
            "tenant": t or "(default)", "qps": d.get("qps"),
            "goodput": None,
            "p50": d.get("p50"), "p95": d.get("p95"),
            "p99": d.get("p99"), "shed_rate": d.get("shed_rate"),
            "burn_fast": None,
            "slo": (" ".join(f"FIRING:{o}" for o in firing)
                    or ("ok" if snap.get("_log_alerts") is not None
                        else "-"))})
    return rows


def render(snap: dict) -> str:
    """One frame of the console."""
    lines = []
    br = snap.get("brownout") or {}
    bk = snap.get("breakers") or {}
    slo = snap.get("slo") or {}
    alerts = (slo.get("alerts_active")
              if slo else sum(1 for a in snap.get("_log_alerts") or ()
                              if a["state"] == "firing"))
    open_breakers = bk.get("open") or ()
    lines.append(
        f"matrel_tpu top — {snap.get('_source', '?')}"
        + (f"   ts {snap['ts']}" if snap.get("ts") else ""))
    lines.append(
        f"rung: {br.get('rung_label', br.get('rung', 'off'))}   "
        f"breakers open: {len(open_breakers)}"
        + (f" ({', '.join(open_breakers)})" if open_breakers else "")
        + f"   active alerts: {alerts if alerts is not None else '-'}")
    sv = snap.get("serve") or {}
    pc = snap.get("plan_cache") or {}
    rc = snap.get("result_cache") or {}
    ivm = snap.get("ivm") or {}
    dr = snap.get("drift") or {}
    lines.append(
        f"queue depth: {_f(sv.get('queue_depth'))}   "
        f"inflight: {_f(sv.get('inflight'))}   "
        f"plan cache: {_f(pc.get('plans'))} plans   "
        f"result cache: {_f(rc.get('entries'))} entries"
        + (f"   ivm gen: {ivm.get('generation')}" if ivm else "")
        + (f"   DRIFT flags: {dr.get('flag_count')}"
           if dr.get("flag_count") else ""))
    fl = snap.get("fleet") or {}
    if fl.get("slices"):
        d = fl.get("directory") or {}
        pl = fl.get("placed") or {}
        lines.append(
            f"fleet: {len(fl['slices'])} slice(s) "
            f"({sum(1 for s in fl['slices'] if s.get('alive'))} "
            f"alive)   placed: slice={pl.get('slice', 0)} "
            f"span={pl.get('span', 0)}   dir hits: {d.get('hits', 0)}"
            f" ({d.get('remote_hits', 0)} remote)   "
            f"migrations: {fl.get('migrations', 0)}   "
            f"failovers: {fl.get('failovers', 0)}")
        for s in fl["slices"]:
            rc = s.get("result_cache") or {}
            slo = s.get("slo") or {}
            lines.append(
                f"  slice {s['id']}: "
                f"{'up' if s.get('alive') else 'DEAD'}   "
                f"dev {_f(s.get('devices'), 0)}   "
                f"queued {_f(s.get('queued'), 0)}   "
                f"submitted {_f(s.get('submitted'), 0)}   "
                f"rc {_f(rc.get('entries'), 0)} entries"
                + (f"   alerts {slo.get('alerts_active')}"
                   if slo else ""))
    rows = _tenant_rows(snap)
    if rows:
        header = (f"{'tenant':<14}{'qps':>8}{'goodput':>9}"
                  f"{'p50':>8}{'p95':>8}{'p99':>9}{'shed%':>8}"
                  f"{'burn':>7}  slo")
        lines += ["", header, "-" * len(header)]
        for r in rows:
            shed = (r["shed_rate"] * 100.0
                    if r["shed_rate"] is not None else None)
            lines.append(
                f"{r['tenant']:<14}{_f(r['qps']):>8}"
                f"{_f(r['goodput']):>9}{_f(r['p50']):>8}"
                f"{_f(r['p95']):>8}{_f(r['p99']):>9}"
                f"{_f(shed):>8}{_f(r['burn_fast']):>7}  {r['slo']}")
    la = snap.get("_log_alerts")
    if la:
        lines.append("")
        lines.append("alerts (last transition per objective):")
        for a in la:
            lines.append(
                f"  {a['tenant']}:{a['objective']} {a['state']}"
                + (f" (burn {_f(a['burn_fast'])})"
                   if a.get("burn_fast") is not None else ""))
    return "\n".join(lines)


def main(args) -> int:
    """CLI backend for ``python -m matrel_tpu top``."""
    url = args.url
    if not url and args.port:
        url = f"http://127.0.0.1:{args.port}"
    iterations = 1 if args.once else (args.iterations or 0)
    i = 0
    try:
        while True:
            if url:
                try:
                    snap = snapshot_from_url(url)
                except (OSError, ValueError) as ex:
                    print(f"top: endpoint {url} unreachable: {ex}")
                    return 1
            else:
                snap = snapshot_from_log(args.log)
            frame = render(snap)
            if not args.once and i > 0:
                # ANSI home+clear between frames; the first frame (and
                # --once) prints plainly so piping stays clean
                print("\x1b[2J\x1b[H", end="")
            print(frame, flush=True)
            i += 1
            if iterations and i >= iterations:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
