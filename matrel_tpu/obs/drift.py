"""Cost-model drift auditor — estimate vs. measurement, systematically.

The whole MatFast/MatRel thesis is cost-model-driven plan selection
(PAPER.md [P2]); MV106 checks the model against ITSELF (a stamped plan
vs the model's own cheaper alternative). This module is the EMPIRICAL
complement: it joins each matmul decision's estimated weighted
bytes/FLOPs (``planner.matmul_decisions`` — already in every query and
``analyze`` event) against measured per-op milliseconds
(``explain(analyze=True)``'s per-op tree, and single-matmul queries'
``execute_ms``), maintains per-(strategy, shape-class, backend)
calibration ratios in a JSON table persisted next to the autotune
tables, and flags strategy pairs whose ESTIMATED rank-order disagrees
with MEASURED rank-order — the "the model said cpmm was cheaper and it
was 3× slower" regression that otherwise only shows up as a slowly
rotting autotune table.

Shape classes are power-of-two buckets of max(n, k, m) — the same
granularity the autotune table keys measurements by, so a calibration
ratio and an autotune row describe the same population.

``python -m matrel_tpu history --drift`` is the CLI surface;
``make obs-report`` runs it over the repo log.
"""

from __future__ import annotations

import json
import logging
import math
import os
import time
from typing import Dict, List, Optional

_log = logging.getLogger("matrel_tpu.obs")

#: Table schema version (bump on reader-visible change, like events.py).
TABLE_SCHEMA = 1

#: Default table name — lives beside .matrel_autotune.json by the same
#: cwd-relative convention.
DEFAULT_TABLE = ".matrel_drift.json"

#: Measured must be at least this multiple SLOWER than a higher-
#: estimate alternative before the rank-order flag fires: estimates
#: are models and measurements are noisy; a bare inversion inside the
#: noise band would flag every near-tie.
RANK_FLAG_MARGIN = 1.25

#: Bounded per-key ratio memory in the persisted table (the metrics
#: registry's reservoir discipline: aggregatable, never unbounded).
_RECENT_MAX = 32


def table_path(config=None) -> str:
    """Config value → concrete path ('' → the default name)."""
    if config is None:
        from matrel_tpu.config import default_config
        config = default_config()
    return config.drift_table_path or DEFAULT_TABLE


def shape_class(dims) -> str:
    """Power-of-two bucket of max(n, k, m) — '<=1024' style classes so
    a 900×1000×1024 and a 1024³ multiply calibrate together (the
    autotune table's side-bucket granularity)."""
    top = max(int(d) for d in dims) if dims else 1
    return f"<={1 << max(0, math.ceil(math.log2(max(top, 1))))}"


def _strategy_key(d: dict) -> str:
    """Decision record → calibration strategy name. Pure-strategy
    matmuls use the stamped strategy; sparse/COO dispatches (which
    bypass the byte model) audit under their dispatch name so SpGEMM's
    est_saved_flops drift is visible without polluting strategy rows.

    A stamped precision tier joins the key (``rmm@bf16x3``): tiered
    passes retire MACs at a different MXU rate, so a bf16 ms_per_gflop
    blended into the f32 row — or a bf16 sample ranked against an f32
    one — would poison both the calibration and the rank-order flags.
    Untier records keep the historical bare-strategy key, so existing
    persisted tables merge unchanged. SpGEMM dispatches with a
    registry kernel stamp calibrate PER KERNEL (``spgemm:<kernel_id>``
    rows): the specialized variants retire the same estimated
    FLOPs/bytes at deliberately different rates, so one blended
    ``dispatch:spgemm`` row would mask exactly the per-kernel drift
    the registry's cost model needs audited; un-stamped spgemm
    records (pre-registry logs) keep the historical key.

    A fused-region anchor calibrates under ``fused:<region_sig>`` (the
    ``spgemm:<kernel_id>`` precedent): the region's measured ms covers
    the anchor PLUS its absorbed members, so blending it into the bare
    strategy row would drift every per-strategy flag by the epilogue's
    cost — and a miscalibrated fused estimate must be visible as a
    fused row, not as a poisoned strategy row."""
    if d.get("fused_region"):
        key = f"fused:{d['fused_region']}"
    elif d.get("dispatch") == "spgemm" and d.get("kernel_id"):
        key = f"spgemm:{d['kernel_id']}"
    elif d.get("dispatch"):
        key = f"dispatch:{d['dispatch']}"
    else:
        key = d.get("strategy", "?")
    tier = d.get("precision_tier")
    if tier:
        key += f"@{tier}"
    return key


def _est_bytes(d: dict):
    """The quantity the planner's ranking actually minimised for this
    decision: weighted cost on a non-uniform mesh, raw ICI bytes
    otherwise. None for dispatch records (no byte model)."""
    w = d.get("est_weighted_cost")
    if isinstance(w, (int, float)):
        return float(w)
    b = d.get("est_ici_bytes")
    return float(b) if isinstance(b, (int, float)) else None


def iter_samples(events: List[dict]):
    """(strategy, shape_class, backend, flops, est_bytes, measured_ms,
    source) samples from an event log.

    Two measurement sources, in decreasing fidelity:
    - ``analyze`` records: per-op EXCLUSIVE milliseconds joined to the
      decision by uid — the matmul's own time.
    - single-matmul ``query`` records: execute_ms attributed to the one
      matmul (includes pipeline overhead; still rank-usable within a
      backend). Batched roots and rc hits are excluded — their
      execute_ms is amortised/zero by construction.
    """
    for e in events:
        kind = e.get("kind")
        backend = e.get("backend") or "?"
        if kind == "analyze":
            per_op = {p.get("uid"): p for p in (e.get("per_op") or ())
                      if isinstance(p, dict)}
            # fused regions report ONE row at the region root with the
            # member uids listed (the ghost-row fix): an anchor matmul
            # absorbed into a region joins its decision to the region
            # row by MEMBERSHIP, so the fused:<sig> calibration row
            # gets the region's measured ms
            member_row = {}
            for p in per_op.values():
                for u in p.get("members") or ():
                    member_row[u] = p
            for d in e.get("matmuls") or ():
                op = per_op.get(d.get("uid"))
                if op is None and d.get("fused_region"):
                    op = member_row.get(d.get("uid"))
                if op is None or not isinstance(op.get("ms"),
                                                (int, float)):
                    continue
                yield _sample(d, float(op["ms"]), backend, "analyze")
        elif kind == "query":
            mm = e.get("matmuls") or ()
            ms = e.get("execute_ms")
            if (len(mm) == 1 and e.get("cache") != "rc_hit"
                    and not e.get("batch")
                    and isinstance(ms, (int, float)) and ms > 0):
                yield _sample(mm[0], float(ms), backend, "query")
        elif kind == "bench" and e.get("metric") == "reshard_sweep":
            # bench.py --reshard rows: both lowerings of each src->dst
            # move, measured with their modelled bytes — the
            # ``reshard:<kind>`` ms/MiB calibration rows, and the
            # population rank_flags compares so a reshard model whose
            # preferred lowering measures >= RANK_FLAG_MARGIN slower
            # raises a DRIFT flag like any miscalibrated strategy
            for row in e.get("rows") or ():
                if not isinstance(row, dict):
                    continue
                n = row.get("n")
                for variant, bytes_key, ms_key in (
                        (f"reshard:{row.get('kind', 'staged')}",
                         "staged_bytes", "staged_ms"),
                        ("reshard:oneshot", "naive_bytes", "naive_ms")):
                    b, ms = row.get(bytes_key), row.get(ms_key)
                    if not (isinstance(b, (int, float)) and b > 0
                            and isinstance(ms, (int, float)) and ms > 0):
                        continue
                    yield {"strategy": variant,
                           "class": shape_class([n] if n else ()),
                           "backend": backend, "tier": "",
                           "flops": 0.0, "est_bytes": float(b),
                           "ms": float(ms), "source": "bench"}
        elif kind == "spill":
            # live spill events (session._emit_spill_event): each
            # demotion/promotion records its priced transfer legs with
            # measured ms — the ``spill:<leg>`` ms/MiB calibration rows
            # the coefficient seam (coeffs.spill_leg_row) serves back
            # to the next pricing decision, closing the same loop the
            # reshard rows ride
            dims = e.get("dims") or ()
            for leg in e.get("legs") or ():
                if not isinstance(leg, dict):
                    continue
                name = leg.get("leg")
                b, ms = leg.get("bytes"), leg.get("ms")
                if not (name and isinstance(b, (int, float)) and b > 0
                        and isinstance(ms, (int, float)) and ms > 0):
                    continue
                yield {"strategy": f"spill:{name}",
                       "class": shape_class(dims),
                       "backend": backend, "tier": "",
                       "flops": 0.0, "est_bytes": float(b),
                       "ms": float(ms), "source": "spill"}
        elif kind == "bench" and e.get("metric") == "spill_sweep":
            # bench.py --spill rows: per-leg transfer timings at
            # controlled sizes — the seeded calibration a fresh table
            # starts from (the reshard_sweep precedent)
            for row in e.get("rows") or ():
                if not isinstance(row, dict):
                    continue
                name, n = row.get("leg"), row.get("n")
                b, ms = row.get("bytes"), row.get("ms")
                if not (name and isinstance(b, (int, float)) and b > 0
                        and isinstance(ms, (int, float)) and ms > 0):
                    continue
                yield {"strategy": f"spill:{name}",
                       "class": shape_class([n] if n else ()),
                       "backend": backend, "tier": "",
                       "flops": 0.0, "est_bytes": float(b),
                       "ms": float(ms), "source": "bench"}


def _sample(d: dict, ms: float, backend: str, source: str) -> dict:
    return {"strategy": _strategy_key(d),
            "class": shape_class(d.get("dims") or ()),
            "backend": backend,
            # the tier is ALSO a population dimension of its own:
            # rank_flags groups on it, so a bf16 sample is never
            # rank-compared against an f32 one (their ms/byte ratios
            # differ by the MXU-rate gap, not by model drift)
            "tier": d.get("precision_tier") or "",
            "flops": float(d.get("flops") or 0.0),
            "est_bytes": _est_bytes(d),
            "ms": ms,
            "source": source}


def _median(vals: List[float]):
    if not vals:
        return None
    s = sorted(vals)
    return s[len(s) // 2]


def calibrate(samples: List[dict]) -> Dict[str, dict]:
    """Per-(strategy, shape-class, backend) calibration rows:

    - ``ms_per_gflop``: median measured ms per estimated GFLOP — the
      compute-side calibration (a strategy whose ratio drifts up is
      losing MXU efficiency the FLOPs model can't see).
    - ``ms_per_est_mib``: median measured ms per estimated MiB moved —
      the comm-side calibration (None when the model estimated zero
      bytes, e.g. replicated-operand bmm). Divergence ACROSS strategies
      in one class is the drift signal: the model prices their bytes on
      one scale, so honest estimates give similar ratios.
    """
    acc: Dict[str, dict] = {}
    for s in samples:
        key = f"{s['strategy']}|{s['class']}|{s['backend']}"
        row = acc.setdefault(key, {"strategy": s["strategy"],
                                   "class": s["class"],
                                   "backend": s["backend"],
                                   "count": 0, "_gf": [], "_mib": [],
                                   "_ms": []})
        row["count"] += 1
        row["_ms"].append(s["ms"])
        if s["flops"] > 0:
            row["_gf"].append(s["ms"] / (s["flops"] / 1e9))
        eb = s["est_bytes"]
        if eb is not None and eb > 0:
            row["_mib"].append(s["ms"] / (eb / 2 ** 20))
    for row in acc.values():
        row["ms_median"] = round(_median(row.pop("_ms")), 4)
        gf = _median(row.pop("_gf"))
        mib = _median(row.pop("_mib"))
        row["ms_per_gflop"] = round(gf, 5) if gf is not None else None
        row["ms_per_est_mib"] = (round(mib, 5) if mib is not None
                                 else None)
    return acc


def rank_flags(samples: List[dict]) -> List[dict]:
    """Strategy pairs whose estimated and measured rank-orders
    DISAGREE within one (shape-class, backend) population: the model
    estimated strictly fewer bytes for A than B, but A measured at
    least RANK_FLAG_MARGIN× slower. The empirical complement of MV106
    (which can only compare the model against itself)."""
    groups: Dict[tuple, Dict[str, dict]] = {}
    for s in samples:
        if s["est_bytes"] is None:
            continue            # dispatch records have no byte ranking
        if s["strategy"].startswith("spill:"):
            # transfer legs are PRICED, never RANKED: the tier a value
            # ages to is fixed by adjacency, so "the model preferred
            # d2h over rmm" is not a choice anything makes — a disk
            # leg's honest 25x ms/MiB would flag as drift forever
            continue
        # tier joins the population key: rank-order is only meaningful
        # between strategies executing at the SAME precision tier
        g = groups.setdefault(
            (s["class"], s["backend"], s.get("tier") or ""), {})
        row = g.setdefault(s["strategy"], {"_ms": [], "_est": []})
        row["_ms"].append(s["ms"])
        row["_est"].append(s["est_bytes"])
    flags: List[dict] = []
    for (cls, backend, _tier), g in sorted(groups.items()):
        if len(g) < 2:
            continue
        meds = {name: (_median(row["_est"]), _median(row["_ms"]),
                       len(row["_ms"]))
                for name, row in g.items()}
        names = sorted(meds)
        for a in names:
            for b in names:
                if a == b:
                    continue
                est_a, ms_a, n_a = meds[a]
                est_b, ms_b, n_b = meds[b]
                if (est_a < est_b and ms_b > 0
                        and ms_a >= RANK_FLAG_MARGIN * ms_b):
                    flags.append({
                        "class": cls, "backend": backend,
                        "model_prefers": a, "measured_prefers": b,
                        "est_bytes": [est_a, est_b],
                        "measured_ms": [round(ms_a, 4),
                                        round(ms_b, 4)],
                        "samples": [n_a, n_b],
                        "slowdown": round(ms_a / ms_b, 2),
                    })
    return flags


# ---------------------------------------------------------------------------
# Persistence — the calibration table next to the autotune tables
# ---------------------------------------------------------------------------


def load_table(path: str) -> dict:
    """Persisted table or a fresh empty one. Corrupt/absent/foreign-
    schema files read as empty (the autotune load_table contract); a
    CORRUPT file additionally warns — the robust-reader discipline
    (docs/RESILIENCE.md): never crash the session over an auxiliary
    artifact, never silently eat one either."""
    try:
        with open(path) as f:
            t = json.load(f)
    except OSError:
        t = None              # absent: the normal first-run case
    except ValueError as e:
        _log.warning("drift table %s is corrupt (%s); rebuilding "
                     "from empty", path, e)
        t = None
    else:
        if (not isinstance(t, dict)
                or t.get("schema") != TABLE_SCHEMA
                or not isinstance(t.get("entries"), dict)):
            _log.warning("drift table %s has unexpected shape/schema; "
                         "rebuilding from empty", path)
            t = None
    if t is None:
        return {"schema": TABLE_SCHEMA, "entries": {}}
    return t


def update_table(path: str, calib: Dict[str, dict]) -> dict:
    """Merge one log's calibration rows into the persisted table
    (count-weighted blend of the ratios, bounded recent-ratio memory)
    and rewrite it atomically. Always writes — an empty log still
    stamps ``updated``, so `make obs-report` leaves a parseable
    artifact either way."""
    table = load_table(path)
    entries = table["entries"]
    for key, row in calib.items():
        old = entries.get(key)
        new = {k: row[k] for k in ("strategy", "class", "backend",
                                   "count", "ms_median",
                                   "ms_per_gflop", "ms_per_est_mib")}
        if old is not None:
            n_old = int(old.get("count") or 0)
            n_new = row["count"]
            for f in ("ms_per_gflop", "ms_per_est_mib"):
                ov, nv = old.get(f), row[f]
                if ov is not None and nv is not None:
                    new[f] = round((ov * n_old + nv * n_new)
                                   / max(n_old + n_new, 1), 5)
                elif nv is None:
                    new[f] = ov
            new["count"] = n_old + n_new
            recent = list(old.get("recent") or [])
        else:
            recent = []
        if row["ms_per_gflop"] is not None:
            recent.append(row["ms_per_gflop"])
        new["recent"] = recent[-_RECENT_MAX:]
        entries[key] = new
    table["updated"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(table, f, indent=1)
    os.replace(tmp, path)
    return table


# ---------------------------------------------------------------------------
# Report — `history --drift`
# ---------------------------------------------------------------------------


def report(events: List[dict],
           table_path_str: Optional[str] = None,
           persist: bool = True) -> str:
    """The drift-audit text: calibration rows, rank-order flags, and
    (when ``persist``) the table merge."""
    return audit(events, table_path_str, persist)[0]


def audit(events: List[dict],
          table_path_str: Optional[str] = None,
          persist: bool = True):
    """(report text, rank-order flags) — the machine-checkable face of
    the drift audit: ``history --drift --check`` exits nonzero when
    any flag fired, so ``make obs-report`` (and CI) gate on cost-model
    drift instead of a human reading the table (ROADMAP item 4's first
    consumable bite)."""
    samples = list(iter_samples(events))
    calib = calibrate(samples)
    flags = rank_flags(samples)
    lines = [f"drift audit: {len(samples)} sample(s) "
             f"({sum(1 for s in samples if s['source'] == 'analyze')} "
             f"analyze, "
             f"{sum(1 for s in samples if s['source'] == 'query')} "
             f"query) -> {len(calib)} calibration row(s)"]
    if calib:
        header = (f"{'strategy':<18}{'class':<10}{'backend':<9}"
                  f"{'n':>4}{'med ms':>10}{'ms/GFLOP':>12}"
                  f"{'ms/est MiB':>12}")
        lines += ["", header, "-" * len(header)]
        for key in sorted(calib):
            r = calib[key]
            lines.append(
                f"{r['strategy']:<18}{r['class']:<10}"
                f"{r['backend']:<9}{r['count']:>4}"
                f"{r['ms_median']:>10.3f}"
                + (f"{r['ms_per_gflop']:>12.4f}"
                   if r["ms_per_gflop"] is not None else f"{'-':>12}")
                + (f"{r['ms_per_est_mib']:>12.4f}"
                   if r["ms_per_est_mib"] is not None
                   else f"{'-':>12}"))
    if flags:
        lines.append("")
        for fl in flags:
            lines.append(
                f"DRIFT {fl['class']} {fl['backend']}: model prefers "
                f"{fl['model_prefers']} "
                f"(est {fl['est_bytes'][0]:.3g} < "
                f"{fl['est_bytes'][1]:.3g} bytes) but it measured "
                f"{fl['slowdown']}x slower than "
                f"{fl['measured_prefers']} "
                f"({fl['measured_ms'][0]} vs {fl['measured_ms'][1]} "
                f"ms; n={fl['samples']})")
    else:
        lines.append("rank-order: estimates agree with measurement "
                     "(no flags)")
    if persist:
        path = table_path_str or table_path()
        try:
            table = update_table(path, calib)
            lines.append(f"calibration table: {path} "
                         f"({len(table['entries'])} entries)")
        except OSError as e:     # auditing must not fail on a bad disk
            lines.append(f"calibration table NOT persisted: {e}")
    return "\n".join(lines), flags
