"""Query-lifecycle observability — the Spark UI / SparkListener analogue.

The reference inherits Spark's entire observability stack: the UI's
stage/task timelines, the JSON event log a history server replays, and
accumulator counters (both papers report their strategy wins off those
surfaces). This package is the TPU rebuild's equivalent, three layers:

- :mod:`matrel_tpu.obs.metrics` — process-wide metrics registry
  (counters / gauges / timing histograms; thread-safe, zero-dep), the
  accumulator analogue. ``utils/profiling.StepTimer`` is a view over it.
- :mod:`matrel_tpu.obs.events` — structured JSONL event log, the Spark
  event-log analogue: ``MatrelSession`` emits one record per query run
  (optimize/compile/execute phases, rewrite-rule hits, plan-cache
  hit/miss/evictions, per-matmul planner decisions with estimated ICI
  bytes + FLOPs); ``bench.py`` and ``tools/soak_guard.py`` emit theirs
  into the same log.
- :mod:`matrel_tpu.obs.analyze` + :mod:`matrel_tpu.obs.history` — the
  debugging surfaces: ``session.explain(expr, analyze=True)`` renders
  the physical tree with MEASURED per-op milliseconds next to the
  planner's estimates, and ``python -m matrel_tpu history`` aggregates
  an event-log file (the history-server analogue).

Instrumentation is off-hot-path by contract: event assembly happens
outside jitted code, per-op timing only under ``analyze=True``, and with
``config.obs_level == "off"`` (the default) the query path takes zero
extra syncs and appends zero events.
"""

from matrel_tpu.obs.events import EventLog, SCHEMA_VERSION, read_events
from matrel_tpu.obs.metrics import MetricsRegistry, REGISTRY

__all__ = [
    "EventLog", "MetricsRegistry", "REGISTRY", "SCHEMA_VERSION",
    "read_events",
]
