"""Query-lifecycle observability — the Spark UI / SparkListener analogue.

The reference inherits Spark's entire observability stack: the UI's
stage/task timelines, the JSON event log a history server replays, and
accumulator counters (both papers report their strategy wins off those
surfaces). This package is the TPU rebuild's equivalent, three layers:

- :mod:`matrel_tpu.obs.metrics` — process-wide metrics registry
  (counters / gauges / timing histograms; thread-safe, zero-dep), the
  accumulator analogue. ``utils/profiling.StepTimer`` is a view over it.
- :mod:`matrel_tpu.obs.events` — structured JSONL event log, the Spark
  event-log analogue: ``MatrelSession`` emits one record per query run
  (optimize/compile/execute phases, rewrite-rule hits, plan-cache
  hit/miss/evictions, per-matmul planner decisions with estimated ICI
  bytes + FLOPs); ``bench.py`` and ``tools/soak_guard.py`` emit theirs
  into the same log.
- :mod:`matrel_tpu.obs.analyze` + :mod:`matrel_tpu.obs.history` — the
  debugging surfaces: ``session.explain(expr, analyze=True)`` renders
  the physical tree with MEASURED per-op milliseconds next to the
  planner's estimates, and ``python -m matrel_tpu history`` aggregates
  an event-log file (the history-server analogue).

Tier 2 (round 9) adds the runtime-behaviour surfaces on top:

- :mod:`matrel_tpu.obs.trace` — structured tracing spans (parent-linked
  ``span`` records through admission → plan → verify → trace →
  execute; ``python -m matrel_tpu trace --export chrome`` renders them
  as a Perfetto timeline) and the bounded in-memory flight recorder
  (``config.obs_flight_recorder``) dumped as a post-mortem artifact on
  verification/compile/serve failures.
- :mod:`matrel_tpu.obs.drift` — the cost-model drift auditor
  (``history --drift``): estimated bytes/FLOPs joined to measured
  per-op times, calibration ratios persisted per (strategy,
  shape-class, backend), rank-order disagreements flagged.

Tier 3 (round 15) is the LIVE plane — the operator tier the reference
gets from Spark's live UI + metrics sink:

- :mod:`matrel_tpu.obs.metrics` gained :class:`QuantileSketch` — a
  bounded-memory, mergeable DDSketch-style quantile sketch with a
  proven relative-error bound backing every timing histogram, and
  :func:`percentile`, the ONE quantile definition history's replay,
  the endpoint and ``top`` all report through.
- :mod:`matrel_tpu.obs.slo` — declarative per-tenant SLOs
  (``config.slo_targets``) tracked by multi-window burn-rate
  monitors; alert transitions emit ``alert`` events that land in the
  flight-recorder ring regardless of ``obs_level``.
- :mod:`matrel_tpu.obs.export` — the in-process metrics endpoint
  (``config.obs_metrics_port``): ``/metrics`` Prometheus text +
  ``/json`` snapshot, zero threads at the default port 0.
- :mod:`matrel_tpu.obs.top` — ``python -m matrel_tpu top``, the live
  per-tenant QPS/latency/burn console.

Instrumentation is off-hot-path by contract: event assembly happens
outside jitted code, per-op timing only under ``analyze=True``, and with
``config.obs_level == "off"`` (the default) plus the flight recorder
off, the query path takes zero extra syncs, appends zero events and
creates zero span objects.
"""

from matrel_tpu.obs.events import EventLog, SCHEMA_VERSION, read_events
from matrel_tpu.obs.metrics import MetricsRegistry, REGISTRY
from matrel_tpu.obs.trace import (FlightRecorder, Span, Tracer,
                                  chrome_trace, span)

__all__ = [
    "EventLog", "FlightRecorder", "MetricsRegistry", "REGISTRY",
    "SCHEMA_VERSION", "Span", "Tracer", "chrome_trace", "read_events",
    "span",
]
