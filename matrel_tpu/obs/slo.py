"""Per-tenant SLO monitors — multi-window burn-rate alerting
(docs/OBSERVABILITY.md tier 3).

The drift auditor applies the estimated-vs-measured discipline offline;
this module moves it on-line for SERVING: declarative per-tenant
objectives (``config.slo_targets`` — latency quantile targets and
availability) are tracked continuously against the serve plane's
actual outcomes, and an alert fires WHILE the burn is happening, not
when a human reads ``history --summary`` tomorrow.

The alerting scheme is the Google-SRE multi-window burn rate:

- every objective reduces to a BAD-EVENT predicate plus an ERROR
  BUDGET fraction (``p95_ms=50`` → bad means "resolved slower than
  50 ms", budget 5%; ``avail=0.999`` → bad means "shed / deadline
  miss / terminal error", budget 0.1%);
- the **burn rate** of a window is the window's bad fraction divided
  by the budget — 1.0 means the budget is being consumed exactly at
  the sustainable rate, 14.4 (the default threshold) means 2% of a
  30-day budget per hour;
- an alert FIRES when BOTH the fast window (default 1 m) and the slow
  window (default 30 m) exceed ``slo_burn_threshold`` — the fast
  window gives detection latency, the slow window confirms the burn
  is sustained rather than one bad second;
- it CLEARS when the fast window's burn falls below ``slo_burn_exit``
  (< the fire threshold, validated — the separated-thresholds
  hysteresis the brownout controller established). An idle window
  burns nothing, so a drained plane always clears within one fast
  window.

Alert TRANSITIONS (fire and clear, never steady state) are emitted
through the session's funnel as ``alert`` events: they land in the
JSONL event log when ``obs_level`` is on and in the flight-recorder
ring whenever the ring exists — REGARDLESS of ``obs_level``, because
an alert transition is exactly the record a post-mortem needs.

The OFF contract is structural: :func:`from_config` returns None for
an empty ``slo_targets`` (the default) and no monitor, window or
sketch object is ever constructed (poisoned-``__init__`` test, the
brownout/breaker precedent). ``clock`` is injectable for
deterministic tests.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from matrel_tpu.config import parse_slo_targets
from matrel_tpu.obs.metrics import QuantileSketch
from matrel_tpu.utils import lockdep

#: The latency-objective vocabulary → (quantile, budget fraction).
#: ``avail`` is handled separately (its budget comes from the target).
_LATENCY_OBJECTIVES = {"p50_ms": 0.50, "p90_ms": 0.90,
                       "p95_ms": 0.95, "p99_ms": 0.99}

#: The pseudo-tenant ``register_delta`` patch latency reports under —
#: declare e.g. ``ivm:p95_ms=20`` to put the IVM patch path under an
#: objective (docs/IVM.md patch events are the offline view of the
#: same numbers).
IVM_TENANT = "ivm"


def from_config(config, emit: Optional[Callable] = None,
                clock: Optional[Callable[[], float]] = None
                ) -> Optional["SLOPlane"]:
    """None for the default config: the OFF path constructs nothing
    (the brownout/breaker structural-zero precedent)."""
    if not getattr(config, "slo_targets", ""):
        return None
    return SLOPlane(config, emit=emit, clock=clock)


class _Window:
    """Trailing-time good/bad counter: fixed-width time buckets in a
    bounded deque, expired buckets dropped on read. Bucket width is
    window/20 (clamped to >= 50 ms) — fine enough that the window
    slides smoothly, coarse enough that a sustained overload is a
    handful of buckets, not one entry per event."""

    __slots__ = ("seconds", "width", "_buckets", "_clock")

    def __init__(self, seconds: float, clock: Callable[[], float]):
        self.seconds = float(seconds)
        self.width = max(self.seconds / 20.0, 0.05)
        cap = int(self.seconds / self.width) + 2
        self._buckets: deque = deque(maxlen=cap)   # [idx, good, bad]
        self._clock = clock

    def add(self, good: int = 0, bad: int = 0) -> None:
        idx = int(self._clock() / self.width)
        if self._buckets and self._buckets[-1][0] == idx:
            b = self._buckets[-1]
            b[1] += good
            b[2] += bad
        else:
            self._buckets.append([idx, good, bad])

    def totals(self) -> Tuple[int, int]:
        """(good, bad) over the trailing window, expired dropped."""
        lo = int((self._clock() - self.seconds) / self.width)
        while self._buckets and self._buckets[0][0] <= lo:
            self._buckets.popleft()
        good = sum(b[1] for b in self._buckets)
        bad = sum(b[2] for b in self._buckets)
        return good, bad


class SLOMonitor:
    """One (tenant, objective): two burn-rate windows + the alert
    state machine. Not thread-safe on its own — the plane's lock
    covers it."""

    def __init__(self, tenant: str, objective: str, target: float,
                 config, clock: Callable[[], float]):
        self.tenant = tenant
        self.objective = objective
        self.target = float(target)
        if objective == "avail":
            self.budget = 1.0 - self.target
        else:
            self.budget = 1.0 - _LATENCY_OBJECTIVES[objective]
        self.threshold = float(config.slo_burn_threshold)
        self.exit = float(config.slo_burn_exit)
        self.fast = _Window(config.slo_fast_window_s, clock)
        self.slow = _Window(config.slo_slow_window_s, clock)
        self.firing = False
        self.fired = 0
        self.cleared = 0

    def record(self, good: int = 0, bad: int = 0) -> None:
        self.fast.add(good, bad)
        self.slow.add(good, bad)

    @staticmethod
    def _burn(good: int, bad: int, budget: float) -> float:
        n = good + bad
        if n == 0:
            return 0.0
        return (bad / n) / budget

    def evaluate(self) -> Optional[dict]:
        """Re-evaluate the state machine; returns the transition
        record on a fire/clear edge, None on steady state."""
        gf, bf = self.fast.totals()
        gs, bs = self.slow.totals()
        burn_fast = self._burn(gf, bf, self.budget)
        burn_slow = self._burn(gs, bs, self.budget)
        transition = None
        if (not self.firing and burn_fast >= self.threshold
                and burn_slow >= self.threshold):
            self.firing = True
            self.fired += 1
            transition = "firing"
        elif self.firing and burn_fast < self.exit:
            self.firing = False
            self.cleared += 1
            transition = "clear"
        if transition is None:
            return None
        n_slow = gs + bs
        return {"tenant": self.tenant, "objective": self.objective,
                "target": self.target, "state": transition,
                "burn_fast": round(burn_fast, 3),
                "burn_slow": round(burn_slow, 3),
                "attainment": (round(gs / n_slow, 5) if n_slow
                               else None),
                "window_fast_s": self.fast.seconds,
                "window_slow_s": self.slow.seconds}

    def status(self) -> dict:
        gf, bf = self.fast.totals()
        gs, bs = self.slow.totals()
        n_slow = gs + bs
        return {"target": self.target,
                "state": "firing" if self.firing else "ok",
                "burn_fast": round(self._burn(gf, bf, self.budget), 3),
                "burn_slow": round(self._burn(gs, bs, self.budget), 3),
                "attainment": (round(gs / n_slow, 5) if n_slow
                               else None),
                "fired": self.fired, "cleared": self.cleared}


class SLOPlane:
    """The session's live SLO tracker: monitors per declared (tenant,
    objective), one latency sketch + traffic window per tenant (the
    endpoint's per-tenant p50/p95/p99 and QPS), and the alert emission
    hook. Thread-safe: outcomes arrive from submit-side shed paths,
    the admission worker and ``register_delta`` concurrently.
    Transitions are emitted OUTSIDE the lock — the emit callback does
    I/O (event log, flight ring) and must not serialise recording."""

    def __init__(self, config, emit: Optional[Callable] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.targets = parse_slo_targets(config.slo_targets)
        self.emit = emit
        clk = clock or time.monotonic
        self._lock = lockdep.make_lock("obs.slo")
        self.monitors: Dict[Tuple[str, str], SLOMonitor] = {}
        for tenant, objs in self.targets.items():
            for obj, target in objs.items():
                self.monitors[(tenant, obj)] = SLOMonitor(
                    tenant, obj, target, config, clk)
        # per-tenant read surfaces for the endpoint/`top`: lifetime
        # latency sketch + a fast-window traffic counter (QPS), plus
        # lifetime outcome counters — only for DECLARED tenants, so an
        # undeclared tenant costs nothing per event
        self._latency: Dict[str, QuantileSketch] = {
            t: QuantileSketch() for t in self.targets}
        self._traffic: Dict[str, _Window] = {
            t: _Window(config.slo_fast_window_s, clk)
            for t in self.targets}
        self.counts: Dict[str, dict] = {
            t: {"ok": 0, "shed": 0, "miss": 0, "error": 0}
            for t in self.targets}

    def _key(self, tenant: Optional[str]) -> str:
        return tenant or ""

    # -- write side (the serve plane's outcome feed) -----------------------

    def record_ok(self, tenant: Optional[str],
                  latency_ms: Optional[float] = None) -> None:
        """One successfully served query: good for availability, and
        — when its resolution latency is known — good/bad against
        every latency objective of the tenant."""
        t = self._key(tenant)
        if t not in self.targets:
            return
        out: List[dict] = []
        with self._lock:
            self.counts[t]["ok"] += 1
            self._traffic[t].add(good=1)
            if latency_ms is not None:
                self._latency[t].add(float(latency_ms))
            for (mt, obj), mon in self.monitors.items():
                if mt != t:
                    continue
                if obj == "avail":
                    mon.record(good=1)
                elif latency_ms is not None:
                    if float(latency_ms) <= mon.target:
                        mon.record(good=1)
                    else:
                        mon.record(bad=1)
                tr = mon.evaluate()
                if tr is not None:
                    out.append(tr)
        self._emit(out)

    def record_bad(self, tenant: Optional[str],
                   kind: str = "error") -> None:
        """One refused/failed query (``kind`` in shed/miss/error):
        bad for availability. Latency objectives see nothing — a
        query that never resolved has no latency to judge."""
        t = self._key(tenant)
        if t not in self.targets:
            return
        out: List[dict] = []
        with self._lock:
            self.counts[t][kind] = self.counts[t].get(kind, 0) + 1
            self._traffic[t].add(bad=1)
            for (mt, obj), mon in self.monitors.items():
                if mt == t and obj == "avail":
                    mon.record(bad=1)
                    tr = mon.evaluate()
                    if tr is not None:
                        out.append(tr)
        self._emit(out)

    def record_shed(self, tenant: Optional[str]) -> None:
        self.record_bad(tenant, "shed")

    def record_miss(self, tenant: Optional[str]) -> None:
        self.record_bad(tenant, "miss")

    def observe_latency(self, tenant: Optional[str],
                        latency_ms: float) -> None:
        """A bare latency sample with no availability implication —
        the ``register_delta`` patch-latency feed (pseudo-tenant
        ``ivm``) and any future measurement-only source."""
        t = self._key(tenant)
        if t not in self.targets:
            return
        out: List[dict] = []
        with self._lock:
            self._latency[t].add(float(latency_ms))
            self._traffic[t].add(good=1)
            for (mt, obj), mon in self.monitors.items():
                if mt != t or obj == "avail":
                    continue
                mon.record(good=1 if float(latency_ms) <= mon.target
                           else 0,
                           bad=0 if float(latency_ms) <= mon.target
                           else 1)
                tr = mon.evaluate()
                if tr is not None:
                    out.append(tr)
        self._emit(out)

    def tick(self) -> None:
        """Idle re-evaluation: burn decays as the windows slide, so a
        drained plane must CLEAR without waiting for the next query —
        the admission worker calls this once per empty cycle, and the
        endpoint's snapshot path rides through it too."""
        out: List[dict] = []
        with self._lock:
            for mon in self.monitors.values():
                tr = mon.evaluate()
                if tr is not None:
                    out.append(tr)
        self._emit(out)

    def _emit(self, transitions: List[dict]) -> None:
        if not transitions or self.emit is None:
            return
        active = sum(1 for m in self.monitors.values() if m.firing)
        for tr in transitions:
            tr["active"] = active
            self.emit(tr)

    # -- read side (the endpoint / `top` / overload events) ----------------

    def firing(self) -> List[dict]:
        """Currently-firing (tenant, objective) pairs — evaluated
        fresh, so a drained plane reads clear."""
        self.tick()
        with self._lock:
            return [{"tenant": t, "objective": o,
                     "target": m.target}
                    for (t, o), m in sorted(self.monitors.items())
                    if m.firing]

    def snapshot(self) -> dict:
        """JSON-ready state for the endpoint / ``top`` / the overload
        event's ``slo`` field: per tenant the declared objectives
        (state, burns, attainment), the latency sketch's quantiles,
        fast-window QPS and lifetime outcome counters."""
        self.tick()
        with self._lock:
            tenants: dict = {}
            for t in sorted(self.targets):
                good, bad = self._traffic[t].totals()
                win = self._traffic[t].seconds
                tenants[t] = {
                    "objectives": {
                        o: m.status()
                        for (mt, o), m in sorted(self.monitors.items())
                        if mt == t},
                    "latency_ms": self._latency[t].summary(),
                    "qps": round((good + bad) / win, 3),
                    "shed_rate": (round(bad / (good + bad), 4)
                                  if good + bad else None),
                    "counts": dict(self.counts[t]),
                }
            return {"tenants": tenants,
                    "alerts_active": sum(
                        1 for m in self.monitors.values() if m.firing),
                    "alerts_fired": sum(
                        m.fired for m in self.monitors.values()),
                    "alerts_cleared": sum(
                        m.cleared for m in self.monitors.values())}
