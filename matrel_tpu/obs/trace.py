"""Structured tracing spans + the flight recorder — obs tier 2.

PR 1's event log says WHAT each query decided (strategies, estimated
bytes, cache outcomes); this module says WHERE THE TIME WENT: a
``span()`` context threaded through admission → plan → verify → trace →
execute, emitting parent-linked records into the same schema-versioned
event log, renderable as a Chrome/Perfetto timeline
(``python -m matrel_tpu trace --export chrome``) so serve-pipeline
overlap and admission-queue bubbles become visible.

Three cost tiers, strictly ordered:

- **Inactive** (``obs_level="off"``, flight recorder off — the bench
  default): :func:`span` returns a shared no-op singleton — no
  allocation, no clock reads, no stack bookkeeping. ``phase()`` (the
  executor's compile-phase form) still reads the clock because its
  durations feed ``plan.meta`` regardless of observability, exactly as
  the pre-span ``time.perf_counter()`` pairs did.
- **Flight recorder only** (``config.obs_flight_recorder > 0``,
  obs off): spans are timed and appended to a bounded in-memory ring —
  no file I/O, no event assembly — so a field failure can dump the last
  N records as a post-mortem artifact (the BENCH_r05 null-row lesson:
  today a relay-wedge failure leaves one error string).
- **Full** (``obs_level != "off"``): span records ALSO append to the
  JSONL event log (``kind: "span"``), where ``history`` and the chrome
  exporter read them back.

Activation is per-thread (``activate()``): the session activates its
tracer around each query/batch, and the serve admission worker
activates it in its own thread, so parent links never cross threads.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import List, Optional

from matrel_tpu.obs.events import SCHEMA_VERSION
from matrel_tpu.utils import lockdep

_SPAN_SEQ = itertools.count(1)

_tls = threading.local()


def _span_stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def active_tracer() -> Optional["Tracer"]:
    return getattr(_tls, "tracer", None)


class _Activation:
    """Context manager installing a tracer for the current thread.
    ``activate(None)`` is a sanctioned no-op (the session passes its
    tracer straight through; sessions without one pay two attribute
    writes per query)."""

    __slots__ = ("tracer", "_prev")

    def __init__(self, tracer: Optional["Tracer"]):
        self.tracer = tracer
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "tracer", None)
        _tls.tracer = self.tracer
        return self.tracer

    def __exit__(self, *exc):
        _tls.tracer = self._prev
        return False


def activate(tracer: Optional["Tracer"]) -> _Activation:
    return _Activation(tracer)


class _NoopSpan:
    """The inactive-path singleton: enters/exits without touching the
    clock or the span stack. ``dur_ms`` stays None — callers that need
    a duration unconditionally use :func:`phase` instead."""

    __slots__ = ()
    dur_ms = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def elapsed_ms(self):
        return None


_NOOP = _NoopSpan()


class Span:
    """One timed scope. Parent-linked through the per-thread stack;
    emitted through the owning tracer at exit (when there is one)."""

    __slots__ = ("name", "attrs", "tracer", "span_id", "parent_id",
                 "t0", "t0_epoch", "dur_ms")

    def __init__(self, name: str, tracer: Optional["Tracer"],
                 attrs: dict):
        self.name = name
        self.tracer = tracer
        self.attrs = attrs
        self.span_id = None
        self.parent_id = None
        self.t0 = None
        self.t0_epoch = None
        self.dur_ms = None

    def __enter__(self):
        if self.tracer is not None:
            self.span_id = next(_SPAN_SEQ)
            stack = _span_stack()
            self.parent_id = stack[-1] if stack else None
            stack.append(self.span_id)
        self.t0_epoch = time.time()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.dur_ms = (time.perf_counter() - self.t0) * 1e3
        if self.tracer is not None:
            stack = _span_stack()
            if stack and stack[-1] == self.span_id:
                stack.pop()
            rec = {"name": self.name,
                   "span_id": self.span_id,
                   "parent_id": self.parent_id,
                   "t0": round(self.t0_epoch, 6),
                   "dur_ms": round(self.dur_ms, 3),
                   "pid": os.getpid(),
                   "tid": threading.get_ident()}
            if exc_type is not None:
                # the error rides the span so a flight-recorder dump
                # shows WHICH scope died, not just that something did
                rec["error"] = repr(exc)[:200]
            if self.attrs:
                rec["attrs"] = self.attrs
            self.tracer.emit_span(rec)
        return False

    def set(self, **attrs) -> "Span":
        """Attach attributes discovered mid-scope (e.g. cache hit)."""
        self.attrs.update(attrs)
        return self

    def elapsed_ms(self) -> float:
        """Wall milliseconds since enter — readable BEFORE exit (the
        serve batch reports its wall while still inside the span)."""
        return (time.perf_counter() - self.t0) * 1e3


def span(name: str, **attrs):
    """A span that costs NOTHING when no tracer is active for this
    thread (the obs-off / recorder-off contract). Use everywhere the
    duration is purely observational."""
    tr = active_tracer()
    if tr is None:
        return _NOOP
    return Span(name, tr, attrs)


def phase(name: str, **attrs) -> Span:
    """A span that ALWAYS times (``dur_ms`` readable after exit) and
    emits only when a tracer is active — for the executor's compile
    phases, whose durations feed ``plan.meta`` regardless of
    observability (the pre-span behaviour, one mechanism)."""
    return Span(name, active_tracer(), attrs)


class Tracer:
    """Routes finished span records to the session's emission path
    (event log when obs is on, flight-recorder ring when configured —
    the session's ``_obs_emit`` decides). Never raises: a broken sink
    must not fail the scope it was observing."""

    __slots__ = ("_emit_fn",)

    def __init__(self, emit_fn):
        self._emit_fn = emit_fn

    def emit_span(self, rec: dict) -> None:
        try:
            self._emit_fn("span", rec)
        except Exception:  # matlint: disable=ML007 never-fail obs sink — a broken emitter must not fail the observed scope (and logging here could recurse per span)
            pass


class FlightRecorder:
    """Bounded in-memory ring of the last N span/event records —
    always-cheap (a deque append under a lock; no I/O, no assembly),
    independent of ``obs_level``. Dumped to a JSON artifact on
    ``VerificationError`` / compile failure / serve-batch failure or
    an explicit ``session.dump_flight_recorder()``, so a field failure
    leaves a post-mortem trail instead of a bare error string."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._buf: "collections.deque" = collections.deque(
            maxlen=self.capacity)
        self._lock = lockdep.make_lock("obs.flight_ring")
        self.dumps = 0

    def add(self, record: dict) -> None:
        with self._lock:
            self._buf.append(record)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._buf)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def dump(self, path: str, reason: str,
             error: Optional[str] = None) -> str:
        """Write the ring as one JSON artifact (atomic rename, same
        discipline as the autotune table). Returns the path."""
        artifact = {
            "schema": SCHEMA_VERSION,
            "kind": "flight_recorder",
            "dumped_at": round(time.time(), 3),
            "reason": reason,
            "error": error,
            "capacity": self.capacity,
            "records": self.snapshot(),
        }
        self.dumps += 1
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(artifact, f, default=repr)
        os.replace(tmp, path)
        return path


#: Default flight-recorder artifact name (cwd-relative, like the event
#: log's default).
DEFAULT_FLIGHT_PATH = ".matrel_flight.json"


# ---------------------------------------------------------------------------
# Chrome/Perfetto export — spans → trace_event JSON
# ---------------------------------------------------------------------------


def chrome_trace(events: List[dict], last: Optional[int] = None) -> dict:
    """Render span records as a Chrome ``trace_event`` JSON object
    (the "JSON Array Format" with complete "X" events) loadable in
    Perfetto / chrome://tracing. Nesting comes from per-tid timestamp
    containment — exactly how the spans nested live — and every event's
    args carry the explicit span/parent ids for cross-checking.

    ``last`` keeps only the most recent N ROOT spans (parent_id null)
    plus their descendants — "show me the last serve batch" without
    hand-filtering a long log."""
    spans = [e for e in events if e.get("kind") == "span"
             and isinstance(e.get("dur_ms"), (int, float))]
    spans.sort(key=lambda e: e.get("t0") or 0.0)
    if last is not None and last >= 0:
        # span ids are per-PROCESS sequences (a shared log mixes
        # sessions, drills and bench runs by design), so the root
        # selection and the descendant closure must key by
        # (pid, span_id) — a bare span_id would pull an unrelated
        # earlier process's spans into "the last batch"
        def sid(e):
            return (e.get("pid"), e.get("span_id"))

        roots = [sid(e) for e in spans
                 if e.get("parent_id") is None
                 and e.get("span_id") is not None]
        keep = set(roots[-last:] if last > 0 else [])
        # descend: children name their parent, so iterate to fixpoint
        # (span lists are small; the log reader already bounded them)
        grew = True
        while grew:
            grew = False
            for e in spans:
                if ((e.get("pid"), e.get("parent_id")) in keep
                        and sid(e) not in keep):
                    keep.add(sid(e))
                    grew = True
        spans = [e for e in spans if sid(e) in keep]
    trace_events = []
    for e in spans:
        t0 = e.get("t0")
        if not isinstance(t0, (int, float)):
            # older/foreign record: reconstruct start from the emission
            # timestamp (stamped at exit)
            t0 = float(e.get("ts", 0.0)) - e["dur_ms"] / 1e3
        args = {"span_id": e.get("span_id"),
                "parent_id": e.get("parent_id")}
        if e.get("attrs"):
            args.update(e["attrs"])
        if e.get("error"):
            args["error"] = e["error"]
        trace_events.append({
            "name": e.get("name", "span"),
            "cat": "matrel",
            "ph": "X",
            "ts": round(t0 * 1e6, 3),          # epoch microseconds
            "dur": round(e["dur_ms"] * 1e3, 3),
            "pid": e.get("pid", 0),
            "tid": e.get("tid", 0),
            "args": args,
        })
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def main(args) -> int:
    """CLI backend for ``python -m matrel_tpu trace --export chrome``.
    Path precedence matches ``history``: --log beats
    $MATREL_OBS_EVENT_LOG beats the cwd default."""
    from matrel_tpu.obs.events import read_events, resolve_path
    if args.export != "chrome":
        print(f"unknown export format {args.export!r} "
              f"(supported: chrome)")
        return 2
    path = resolve_path(args.log or os.environ.get(
        "MATREL_OBS_EVENT_LOG"))
    events = read_events(path)
    doc = chrome_trace(events, last=args.last)
    out_path = args.out or (path + ".chrome.json")
    if out_path == "-":
        print(json.dumps(doc))
        return 0
    with open(out_path, "w") as f:
        json.dump(doc, f)
    print(json.dumps({"spans": len(doc["traceEvents"]),
                      "log": path, "out": out_path}))
    return 0
